"""Commit-time collective-plan verifier.

``Session.commit`` is the one point where the whole communication plan is
visible *before* anything executes: every CommRequest is built, buckets are
formed, the selection table has resolved each request's algorithm. This pass
walks that committed state and statically checks the invariants PRs 2-10
established at runtime (TVM-style graph-level verification; NCCL's
collective-ordering deadlock model):

- **A101** issue-order consistency across overlapping process groups: under
  ``MLSL_MSG_PRIORITY`` a deferred large request's dispatch is released by a
  wall-clock flush window, so its wire order against an immediately
  dispatched request is rank-dependent on a multi-controller mesh — when the
  two groups' instance partitions differ and intersect, that inversion is
  the classic cross-replica deadlock.
- **A102/A103** worst-case concurrent in-flight collective programs vs the
  backend budget (the XLA:CPU rendezvous wedge documented in
  KNOWN_FAILURES.md — flagged before it hangs). On a two-tier world
  (comm/mesh.world_tiers) the count is ALSO taken per tier: programs whose
  groups span tiers contend for the DCN's far smaller concurrent-transfer
  tolerance, so they are budgeted separately at half the backend figure.
- **A110-A114** quantization geometry: bucket member slots on quant-block
  boundaries, coalesced totals on the ring-chunk unit, error-feedback
  lengths equal to the quant-ring geometry, ZeRO-1 shard boundaries on
  block boundaries, and (A114, the two-tier analog of A113) hier-routed
  compressed requests whose DCN-tier quant blocks would straddle the
  intra-slice shard boundary.
- **A115/A116** registry-codec wire geometry (mlsl_tpu.codecs, the
  A110-A114 siblings): per-chunk VQ index-table/codebook alignment — the
  index count must tile the chunk at the declared vector dim and the wire
  codebook must match k x dim (A115) — and prune mask coverage — the
  bit-packed mask must span the whole chunk with the keep-count inside it,
  or the rank-order decode gather desynchronizes (A116).
- **A121** the EF snapshot/rewind machinery's static preconditions on every
  retry/degrade path (degrade geometry covers every chunk program).
- **A120/A122** compiled-overlap donation hazards (``verify_overlap_plan``):
  aliased residual carry slots, units that cannot retire inside their stage
  window (a donated carry read after its emission window).
- **A130-A132** Pallas-ring static accounting (``verify_hop_trace``):
  per-hop semaphore signal/wait balance (sems must drain to zero at kernel
  exit), slot capacity vs the in-flight hop window, and a VMEM slot-buffer
  budget estimate.
- **A140/A141** elastic reshard coverage (``verify_reshard``): before an
  elastic shrink/grow moves ZeRO-1 optimizer state between world sizes
  (mlsl_tpu.elastic), the plan's source intervals must tile every real
  shard element exactly once (A140 — a gap drops state, an overlap
  double-applies it) and the target intervals must match the survivor
  world's ownership-chunk geometry (A141). Run unconditionally by the
  coordinator, not gated by MLSL_VERIFY.

Armed by ``MLSL_VERIFY=1`` at commit (``run_commit_verify``) and by
``python -m mlsl_tpu.analysis --graph``. Findings land in the shared
diagnostic format (analysis/diagnostics.py), the ``ANALYSIS`` stats line,
and trace instants; ``MLSL_VERIFY_SEVERITY`` picks raise-vs-warn.
"""

from __future__ import annotations

import time
from typing import FrozenSet, List, Optional, Set

import numpy as np

from mlsl_tpu.analysis.diagnostics import Report, record
from mlsl_tpu.log import MLSLError, log_warning
from mlsl_tpu.types import CompressionType

#: worst-case concurrent in-flight collective programs the backend tolerates.
#: XLA:CPU's thread-pool rendezvous wedges past ~dozens of concurrently
#: dispatched SPMD programs (measured in PR 2's bucket bench; the hang class
#: in KNOWN_FAILURES.md); real TPUs stream launches and tolerate far more.
INFLIGHT_BUDGET = {"cpu": 32}
INFLIGHT_BUDGET_DEFAULT = 512

#: VMEM budget (bytes) for the pallas-ring slot-buffer estimate (A132): a
#: conservative per-core figure — the kernel's comm slots, travelling
#: accumulator, and prefetch buffers must fit with headroom for the codec.
PALLAS_VMEM_BUDGET = 16 * 1024 * 1024


# ---------------------------------------------------------------------------
# Group overlap model
# ---------------------------------------------------------------------------


def _instances(group) -> Set[FrozenSet[int]]:
    """The group's instance partition: the set of member-world-rank sets."""
    from mlsl_tpu.comm.collectives import _member_world_table

    tbl = _member_world_table(group)
    return {frozenset(int(v) for v in row) for row in tbl}


def _partitions_conflict(i1, i2) -> bool:
    if i1 == i2:
        return False
    return any(a != b and a & b for a in i1 for b in i2)


def groups_conflict(g1, g2, _cache: Optional[dict] = None) -> bool:
    """True when the two groups' instance partitions differ AND intersect:
    a rank-dependent dispatch-order inversion between collectives on such
    groups is the cross-replica deadlock (two instances progress
    independently while sharing members). Identical partitions are safe —
    every member sees both collectives in its own (single) order.

    ``_cache`` (id(group) -> partition) amortizes the member-table walk
    across one verify run: the A101 scan compares O(L^2) request pairs on
    a graph whose few distinct groups repeat across every layer."""
    if getattr(g1, "is_self", False) or getattr(g2, "is_self", False):
        return False
    if _cache is None:
        _cache = {}
    i1 = _cache.get(id(g1))
    if i1 is None:
        i1 = _cache[id(g1)] = _instances(g1)
    i2 = _cache.get(id(g2))
    if i2 is None:
        i2 = _cache[id(g2)] = _instances(g2)
    return _partitions_conflict(i1, i2)


# ---------------------------------------------------------------------------
# The committed-graph walk
# ---------------------------------------------------------------------------


def _chunk_counts(req) -> List[int]:
    """Element count of each independently dispatched chunk program."""
    d = req.desc
    out = []
    for sl in req._chunk_slices:
        if sl == slice(None):
            out.append(d.count)
        else:
            out.append(int(sl.stop) - int(sl.start))
    return out or [d.count]


def _programs(req) -> int:
    return max(1, len(req._chunk_slices))


def _backward_entities(session) -> List[tuple]:
    """The backward dispatch window, in issue order (newest gradient first):
    one entry per dispatched entity — ``('bucket', bucket, anchor)`` once per
    coalesced bucket, ``('req', request, anchor)`` for individual sets."""
    out: List[tuple] = []
    seen_buckets: Set[int] = set()
    for op in reversed(session.operations):
        for ps in reversed(op.parameter_sets):
            if not ps.need_comm or ps.grad_req is None:
                continue
            anchor = f"graph:{op.name}/ps{ps.param_index}"
            b = ps.bucket
            if b is not None:
                if id(b) not in seen_buckets:
                    seen_buckets.add(id(b))
                    out.append(("bucket", b, f"graph:{b.req.name}"))
            else:
                out.append(("req", ps.grad_req, anchor))
    return out


def _inc_entities(session) -> List[tuple]:
    out: List[tuple] = []
    seen: Set[int] = set()
    for op in session.operations:
        for ps in op.parameter_sets:
            if not ps.need_comm or not ps.distributed_update:
                continue
            anchor = f"graph:{op.name}/ps{ps.param_index}/inc"
            b = ps.inc_bucket
            if b is not None:
                if id(b) not in seen:
                    seen.add(id(b))
                    out.append(("bucket", b, f"graph:{b.req.name}"))
            elif ps.inc_req is not None:
                out.append(("req", ps.inc_req, anchor))
    return out


def _entity_programs(kind: str, ent) -> int:
    """Worst-case concurrent programs one entity can put in flight: a bucket
    either dispatches its coalesced request OR (early-Wait fallback) every
    member's individual request — the worst case is the larger."""
    if kind == "req":
        return _programs(ent)
    coalesced = _programs(ent.req)
    fallback = sum(
        _programs(getattr(ps, ent.req_attr)) for ps in ent.members
        if getattr(ps, ent.req_attr) is not None
    )
    return max(coalesced, fallback)


def _entity_reqs(kind: str, ent) -> List[tuple]:
    """(request, anchor) pairs an entity can dispatch (bucket: the coalesced
    request AND the members' fallbacks — both are reachable paths)."""
    if kind == "req":
        return [(ent, None)]
    out = [(ent.req, None)]
    for ps in ent.members:
        r = getattr(ps, ent.req_attr)
        if r is not None:
            out.append((r, None))
    return out


def _platform(session) -> str:
    for op in session.operations:
        if op.distribution is not None:
            mesh = op.distribution.topology.mesh
            return mesh.devices.flat[0].platform
    return "cpu"


def verify_session(session, config=None) -> Report:
    """Statically verify one committed session's collective plan."""
    rep = Report("plan")
    cfg = config if config is not None else session.env.config
    back = _backward_entities(session)
    inc = _inc_entities(session)

    _check_inflight(rep, session, back, inc)
    _check_issue_order(rep, cfg, back)
    for kind, ent, anchor in back + inc:
        if kind == "bucket":
            _check_bucket_geometry(rep, ent, cfg, anchor)
        for req, _ in _entity_reqs(kind, ent):
            _check_request(rep, req, cfg,
                           anchor if kind == "req" else f"{anchor}/member")
    # activation edges dispatch sequentially (start -> wait per edge); their
    # requests still carry geometry/EF invariants worth pinning
    for op in session.operations:
        for act in list(op.inputs) + list(op.outputs):
            r = getattr(act, "comm_req", None)
            if r is not None and r.is_setup:
                _check_request(rep, r, cfg, f"graph:{op.name}/act")
    return rep


def _spans_tiers(group, tier_ids, cache=None) -> bool:
    """True when one of the group's instances has members in >= 2 tiers: its
    collectives put traffic on the DCN. ``cache`` memoizes per distinct
    group within one verify run (the A101 convention — the member table is
    O(W*G) to build and per-layer requests share a handful of groups)."""
    if getattr(group, "is_self", False):
        return False
    key = id(group)
    if cache is not None and key in cache:
        return cache[key]
    from mlsl_tpu.comm.collectives import _member_world_table

    try:
        tbl = _member_world_table(group)
    except Exception:
        return True  # unknowable layout: worst-case it as DCN-crossing
    spans = any(
        len({tier_ids[int(w)] for w in row}) > 1
        for row in np.atleast_2d(tbl)
    )
    if cache is not None:
        cache[key] = spans
    return spans


def _dcn_budget(budget: int) -> int:
    """The per-tier budget for DCN-crossing programs: the slow tier's
    rendezvous/transfer machinery tolerates far fewer concurrent
    collectives than the ICI — half the backend figure, floored so tiny
    budgets stay usable."""
    return max(budget // 2, 4)


def _check_inflight(rep: Report, session, back, inc) -> None:
    from mlsl_tpu.comm.mesh import world_tier_ids

    platform = _platform(session)
    budget = INFLIGHT_BUDGET.get(platform, INFLIGHT_BUDGET_DEFAULT)
    tier_ids = None
    for op in session.operations:
        if op.distribution is not None:
            devs = tuple(op.distribution.topology.mesh.devices.flat)
            tier_ids = world_tier_ids(devs)
            break
    for window, entities in (("backward", back), ("increment", inc)):
        n = sum(_entity_programs(k, e) for k, e, _ in entities)
        if n > budget:
            rep.add("MLSL-A102",
                    f"{window} window can put {n} collective programs in "
                    f"flight concurrently; the {platform} backend budget is "
                    f"{budget} (the rendezvous wedge class — raise "
                    "MLSL_GRAD_BUCKET_MB or window the dispatches)",
                    f"graph:{window}")
        elif n > budget // 2:
            rep.add("MLSL-A103",
                    f"{window} window reaches {n}/{budget} of the {platform} "
                    "in-flight collective budget", f"graph:{window}")
        if tier_ids is None:
            continue
        # two-tier shape: programs whose groups span tiers contend for the
        # DCN separately — the slow tier wedges at far lower concurrency
        dcn = _dcn_budget(budget)
        span_cache: dict = {}  # one member-table walk per distinct group
        n_dcn = sum(
            _entity_programs(k, e) for k, e, _ in entities
            if any(_spans_tiers(r.desc.group, tier_ids, span_cache)
                   for r, _ in _entity_reqs(k, e))
        )
        if n_dcn > dcn:
            rep.add("MLSL-A102",
                    f"{window} window can put {n_dcn} DCN-crossing "
                    f"collective programs in flight concurrently; the "
                    f"two-tier budget is {dcn} (half the {platform} figure "
                    "— route through the 'hier' lowering or raise "
                    "MLSL_GRAD_BUCKET_MB)", f"graph:{window}/dcn")
        elif n_dcn > dcn // 2:
            rep.add("MLSL-A103",
                    f"{window} window reaches {n_dcn}/{dcn} of the "
                    "DCN-crossing in-flight budget on this two-tier world",
                    f"graph:{window}/dcn")


def _check_issue_order(rep: Report, cfg, back) -> None:
    """A101: deferral-window order inversion on conflicting groups."""
    if not getattr(cfg, "msg_priority", False):
        return
    threshold = getattr(cfg, "msg_priority_threshold", 0)
    open_deferred: List[tuple] = []
    cache: dict = {}  # one partition computation per distinct group
    for kind, ent, anchor in back:
        for req, _ in _entity_reqs(kind, ent):
            d = req.desc
            if d.kind == "barrier":
                open_deferred.clear()  # a barrier flushes the stack
                continue
            if req._payload > threshold:
                open_deferred.append((req, anchor))
                continue
            for dref, danchor in open_deferred:
                if groups_conflict(dref.desc.group, d.group, cache):
                    rep.add(
                        "MLSL-A101",
                        f"immediate dispatch of '{req.name or req.uid}' can "
                        f"land before OR after the deferred flush of "
                        f"'{dref.name or dref.uid}' (flush window "
                        f"{cfg.msg_priority_flush_ms}ms) while their groups' "
                        "instance partitions overlap but differ — wire "
                        "order becomes rank-dependent, the cross-replica "
                        "deadlock", anchor)


def _expected_err_len(req, cfg) -> Optional[List[int]]:
    """Per-chunk expected error-feedback length for a compressed request, or
    None when the wire family owns its own layout (top-k, user dlopen
    codec — registry codecs DO declare theirs: g x chunk entry residual)."""
    d = req.desc
    if d.compression != CompressionType.QUANTIZATION:
        return None
    if req.algo.startswith("codec:"):
        # registry codec on the compressed-ring transport (comm/codec.py):
        # entry EF, one residual row per hop — err_len = g * chunk
        g = 1 if d.group.is_self else d.group.size
        rs = d.kind == "reduce_scatter"
        return [g * (n // g if rs else -(-n // g)) for n in _chunk_counts(req)]
    if req.algo not in ("quant_ring", "pallas_ring", "hier"):
        return None
    # effective block: a desc-level override or a calibrated int8 cell may
    # widen it per-request (comm/request.py setup) — the session block is
    # only the fallback
    block = (getattr(req, "_eff_quant_block", 0)
             or getattr(cfg, "quant_block_elems", 256))
    out = []
    for n in _chunk_counts(req):
        if req.algo == "pallas_ring":
            from mlsl_tpu.ops import ring_kernels as rk

            out.append(rk.quant_geometry(d.kind, d.group, n, block)[3])
        elif req.algo == "hier":
            from mlsl_tpu.comm.algos import hier

            out.append(hier.quant_geometry(d.kind, d.group, n, block)[2])
        else:
            from mlsl_tpu.comm.quant_ring import ring_geometry

            out.append(ring_geometry(d.kind, d.group, n, block)[3])
    return out


def _check_request(rep: Report, req, cfg, anchor: str) -> None:
    """Per-request invariants: EF geometry (A112) and the snapshot/rewind
    machinery's static preconditions (A121), plus pallas accounting."""
    d = req.desc
    compressed = req._quant_fn is not None or req._quant_fns is not None
    if compressed:
        # -- A121: every retry/degrade path rewinds from a snapshot whose
        # geometry covers every chunk program (request._ef_restore /
        # _take_residuals preconditions)
        geoms = req._degrade_geoms
        chunks = _chunk_counts(req)
        if req._err_layout not in ("ring", "flat", "hier"):
            rep.add("MLSL-A121",
                    f"compressed request '{req.name or req.uid}' has no "
                    "_err_layout: the degrade flush cannot invert its "
                    "residual", anchor)
        if req._err_layout == "hier" and getattr(
                req, "_hier_meta", None) is None:
            rep.add("MLSL-A121",
                    f"hier-routed request '{req.name or req.uid}' carries "
                    "no intra-tier position table: the degrade flush "
                    "cannot re-place its per-shard residual", anchor)
        if geoms is None or len(geoms) != len(chunks):
            rep.add("MLSL-A121",
                    f"degrade geometry of '{req.name or req.uid}' covers "
                    f"{0 if geoms is None else len(geoms)} chunk(s) but the "
                    f"request dispatches {len(chunks)}: a degraded retry "
                    "would flush the wrong residual slices", anchor)
        else:
            for (n, _el), c in zip(geoms, chunks):
                if int(n) != int(c):
                    rep.add("MLSL-A121",
                            f"degrade geometry count {n} != chunk count {c} "
                            f"on '{req.name or req.uid}'", anchor)
        # -- A112: EF length vs the ring geometry
        expected = _expected_err_len(req, cfg)
        if expected is not None:
            actual = (list(req._err_lens) if req._err_lens is not None
                      else [req._err_len])
            if len(actual) == len(expected):
                for a, e in zip(actual, expected):
                    if int(a) != int(e):
                        rep.add("MLSL-A112",
                                f"err_len {a} != quant-ring geometry {e} on "
                                f"'{req.name or req.uid}' (block="
                                f"{getattr(req, '_eff_quant_block', 0) or getattr(cfg, 'quant_block_elems', '?')})",
                                anchor)
            else:
                rep.add("MLSL-A112",
                        f"'{req.name or req.uid}' carries {len(actual)} "
                        f"residual length(s) for {len(expected)} chunk "
                        "program(s)", anchor)
    if compressed and req.algo == "hier":
        # -- A114 (the A113 class on the two-tier shape): the compressed
        # DCN tier quantizes each member's 1/L shard against the shared
        # per-block scale — a residual/shard length off the block grid means
        # a quant block straddles the intra-slice shard boundary, breaking
        # scale locality AND the flush_residual slice placement
        block = getattr(cfg, "quant_block_elems", 256)
        actual = (list(req._err_lens) if req._err_lens is not None
                  else [req._err_len])
        from mlsl_tpu.comm.algos import hier

        tiers = hier.tier_structure(d.group)
        for slen, n in zip(actual, _chunk_counts(req)):
            if int(slen) % int(block):
                rep.add("MLSL-A114",
                        f"hier compressed-tier shard length {slen} is not "
                        f"on the {block}-elem quant block grid on "
                        f"'{req.name or req.uid}': a DCN-tier block "
                        "straddles the intra-slice shard boundary", anchor)
            elif tiers is not None and int(slen) * tiers[1] < int(n):
                rep.add("MLSL-A114",
                        f"hier shard length {slen} x L={tiers[1]} does not "
                        f"cover chunk count {n} on "
                        f"'{req.name or req.uid}': the tail of the payload "
                        "would never cross the DCN", anchor)
    geoms = getattr(req, "_codec_geoms", None)
    if compressed and geoms is not None:
        # -- A115/A116 (the A110-A114 siblings for registry codecs): each
        # chunk's pinned wire geometry must be self-consistent — a tampered
        # VQ index table or codebook no longer covers the chunk (A115), a
        # prune mask shorter than the chunk silently drops tail gradients
        # and desynchronizes the rank-decode (A116)
        for gm in geoms:
            name = str(gm.get("codec", ""))
            chunk = int(gm.get("chunk", 0))
            if name == "vq":
                dim = int(gm.get("dim", 0) or 0)
                k = int(gm.get("k", 0) or 0)
                idx = int(gm.get("idx_elems", -1))
                cbe = int(gm.get("codebook_elems", -1))
                want_idx = -(-chunk // dim) if dim > 0 else -1
                if dim <= 0 or idx != want_idx:
                    rep.add("MLSL-A115",
                            f"VQ index table of '{req.name or req.uid}' "
                            f"carries {idx} indices for a {chunk}-elem chunk "
                            f"at dim={dim} (expected {want_idx}): decode "
                            "would mis-tile the vectors", anchor)
                elif cbe != k * dim:
                    rep.add("MLSL-A115",
                            f"VQ codebook of '{req.name or req.uid}' "
                            f"carries {cbe} elems for k={k} x dim={dim}: "
                            "the wire codebook and the index range "
                            "disagree", anchor)
                elif int(gm.get("wire_len", -1)) != idx + 4 * cbe + 4:
                    rep.add("MLSL-A115",
                            f"VQ wire length {gm.get('wire_len')} of "
                            f"'{req.name or req.uid}' != indices {idx} + "
                            f"codebook {4 * cbe} + scale 4 bytes", anchor)
            elif name in ("prune", "topk"):
                k = int(gm.get("k", 0) or 0)
                mask_len = int(gm.get("mask_len", -1))
                if mask_len != chunk:
                    rep.add("MLSL-A116",
                            f"prune mask of '{req.name or req.uid}' covers "
                            f"{mask_len} elems of a {chunk}-elem chunk: the "
                            "tail would silently drop from every round",
                            anchor)
                elif not 0 < k <= chunk:
                    rep.add("MLSL-A116",
                            f"prune keep-count {k} of "
                            f"'{req.name or req.uid}' is outside the "
                            f"{chunk}-elem chunk", anchor)
                elif int(gm.get("wire_len", -1)) != -(-mask_len // 8) + 4 * k:
                    rep.add("MLSL-A116",
                            f"prune wire length {gm.get('wire_len')} of "
                            f"'{req.name or req.uid}' != packed mask "
                            f"{-(-mask_len // 8)} + {4 * k} value bytes: "
                            "the rank-decode gather desynchronizes", anchor)
    if req.algo in ("pallas_ring", "pallas_ring2d"):
        # the 2D snake ring runs the identical kernel program over the
        # snake-ordered neighbour tables, so the 1D accounting mirror IS
        # its accounting mirror (same hop/slot schedule, different peers)
        _check_pallas_request(rep, req, cfg, anchor)
    elif req.algo == "pallas_rhd":
        _check_pallas_rhd_request(rep, req, cfg, anchor)
    elif req.algo == "pallas_a2a":
        _check_pallas_a2a_request(rep, req, cfg, anchor)


# ---------------------------------------------------------------------------
# Bucket geometry (A110/A111/A113 + the request-level A112 above)
# ---------------------------------------------------------------------------


def _check_bucket_geometry(rep: Report, bucket, cfg, anchor: str) -> None:
    if bucket.compression != CompressionType.QUANTIZATION:
        return
    from mlsl_tpu.comm.quant_ring import ring_aligned_rc

    block = getattr(cfg, "quant_block_elems", 256)
    d = bucket.req.desc
    group = d.group
    g = 1 if group.is_self else group.size
    for i, (ps, off, slot) in enumerate(
            zip(bucket.members, bucket.offsets, bucket.slots)):
        if off % block or slot % block:
            req = getattr(ps, bucket.req_attr, None)
            rep.add("MLSL-A110",
                    f"member '{getattr(req, 'name', None) or i}' slot "
                    f"[{off}, {off + slot}) is not on the {block}-elem quant "
                    "block grid: a block would straddle members and break "
                    "per-member scale locality", f"{anchor}/member{i}")
    if bucket.kind == "reduce_scatter":
        recv = d.count // g
        if recv % block:
            rep.add("MLSL-A113",
                    f"ZeRO-1 shard length {recv} is not block-aligned "
                    f"(block={block}): a quant block straddles the shard "
                    "boundary", anchor)
        if ring_aligned_rc(group, recv, block) != recv:
            rep.add("MLSL-A111",
                    f"per-rank shard {recv} is not ring-chunk aligned "
                    "(quant_ring.ring_aligned_rc): hops would pad "
                    "internally and miss the packed-scale kernel path",
                    anchor)
    else:
        rc = -(-d.count // g)
        if ring_aligned_rc(group, rc, block) != rc or d.count != g * rc:
            rep.add("MLSL-A111",
                    f"coalesced total {d.count} (per-rank slice {rc}) is "
                    "not ring-chunk aligned (quant_ring.ring_aligned_rc)",
                    anchor)


# ---------------------------------------------------------------------------
# Compiled-overlap plan (A120/A122, + A112 via the shared geometry)
# ---------------------------------------------------------------------------


def verify_overlap_plan(plan, block: Optional[int] = None) -> Report:
    """Statically verify a comm/overlap.OverlapPlan + its staged schedule:
    donated-carry aliasing (A120), stage-window retirement (A122), and the
    residual geometry the donated EF carry must match (A112)."""
    rep = Report("plan")
    seen_keys: Set[str] = set()
    for u in plan.units:
        anchor = f"graph:overlap/{'+'.join(u.names)}"
        if u.key is not None:
            if u.key in seen_keys:
                rep.add("MLSL-A120",
                        f"residual carry key '{u.key}' aliased by two "
                        "units: both would donate and read the same EF "
                        "slot", anchor)
            seen_keys.add(u.key)
            if plan.err_lens.get(u.key) != u.err_len:
                rep.add("MLSL-A120",
                        f"plan residual table says {plan.err_lens.get(u.key)}"
                        f" elems for '{u.key}' but the unit carries "
                        f"{u.err_len}: the donated carry would be read at "
                        "the wrong geometry", anchor)
            if block is not None:
                if u.algo == "hier":
                    from mlsl_tpu.comm.algos import hier

                    exp = hier.quant_geometry("allreduce", plan.group,
                                              u.total, block)[2]
                else:
                    from mlsl_tpu.comm.quant_ring import ring_geometry

                    exp = ring_geometry("allreduce", plan.group, u.total,
                                        block)[3]
                if exp != u.err_len:
                    rep.add("MLSL-A112",
                            f"unit err_len {u.err_len} != quant-ring "
                            f"geometry {exp} (block={block})", anchor)
        need = -(-u.nphases // plan.stages) if u.nphases else 0
        if u.nphases and u.per_tick < max(1, need):
            rep.add("MLSL-A122",
                    f"unit advances {u.per_tick} phase(s)/tick but needs "
                    f"{need} to retire inside its {plan.stages}-stage "
                    "window: its carry stays live past the stage boundary",
                    anchor)
    _simulate_schedule(rep, plan)
    return rep


def _simulate_schedule(rep: Report, plan) -> None:
    """Integer replay of overlap.emit_schedule's tick loop: every unit must
    retire (all phases emitted exactly once) within the bounded tick budget,
    or its donated carry outlives the emission window (A120)."""
    inflight: List[list] = []   # [unit, phase_idx]
    retired: Set[int] = set()
    total_ticks = 0
    budget = len(plan.units) + sum(u.nphases for u in plan.units) + \
        plan.stages + 4

    def tick():
        nonlocal total_ticks
        total_ticks += 1
        for ent in inflight:
            u = ent[0]
            for _ in range(max(0, u.per_tick)):
                if ent[1] < u.nphases:
                    ent[1] += 1
        for ent in [e for e in inflight if e[1] >= e[0].nphases]:
            inflight.remove(ent)
            retired.add(ent[0].index)

    for u in plan.units:
        inflight.append([u, 0])
        tick()
    while inflight and total_ticks < budget:
        tick()
    for ent in inflight:
        rep.add("MLSL-A120",
                f"unit {'+'.join(ent[0].names)} never retires "
                f"({ent[1]}/{ent[0].nphases} phases after {total_ticks} "
                "ticks): its donated carry is read after the emission "
                "window", f"graph:overlap/{'+'.join(ent[0].names)}")


# ---------------------------------------------------------------------------
# Pallas-ring static accounting (A130/A131/A132)
# ---------------------------------------------------------------------------


def verify_hop_trace(events: List[tuple], *, slots: int, ndirs: int,
                     total_hops: int, anchor: str = "graph:pallas_ring",
                     report: Optional[Report] = None) -> Report:
    """Check one kernel build's semaphore accounting. ``events`` is the
    ordered ``('wait', dir, hop)`` / ``('free', dir, use_hop)`` trace
    (ops/ring_kernels.static_accounting mirrors the kernel's slot_wait/
    slot_free emission). Invariants: every wait's matching free (of hop
    ``h - slots``) precedes it in program order — the peer's symmetric SPMD
    program emits that signal strictly before this rank can block on it —
    and every semaphore drains to zero at kernel exit (signals == waits per
    direction)."""
    rep = report if report is not None else Report("plan")
    if slots < 2:
        rep.add("MLSL-A131",
                f"{slots} comm slot(s): the ring needs a double buffer — "
                "hop h's send would overwrite the slot hop h-1 is still "
                "accumulating from", anchor)
    freed: List[Set[int]] = [set() for _ in range(ndirs)]
    waits = [0] * ndirs
    frees = [0] * ndirs
    for ev in events:
        kind, d, hop = ev[0], int(ev[1]), int(ev[2])
        if kind == "free":
            frees[d] += 1
            freed[d].add(hop)
        elif kind == "wait":
            waits[d] += 1
            need = hop - slots
            if need < 0 or need not in freed[d]:
                rep.add("MLSL-A130",
                        f"hop {hop} (dir {d}) waits on slot {hop % slots} "
                        f"but hop {need}'s free signal is not emitted "
                        "before it: the capacity semaphore deadlocks",
                        anchor)
    for d in range(ndirs):
        if waits[d] != frees[d]:
            rep.add("MLSL-A130",
                    f"dir {d}: {frees[d]} free signal(s) vs {waits[d]} "
                    "wait(s) — the capacity semaphore does not drain to "
                    "zero at kernel exit", anchor)
    return rep


def _check_pallas_request(rep: Report, req, cfg, anchor: str) -> None:
    from mlsl_tpu.ops import ring_kernels as rk

    d = req.desc
    slots = rk.env_slots(getattr(cfg, "pallas_ring_slots", None))
    bidir = rk.env_bidir(getattr(cfg, "pallas_ring_bidir", None))
    quantized = d.compression == CompressionType.QUANTIZATION
    block = getattr(cfg, "quant_block_elems", 256)
    for n in _chunk_counts(req):
        if quantized:
            g, _, chunk, _ = rk.quant_geometry(d.kind, d.group, n, block)
        else:
            g, _, chunk = rk.dense_geometry(d.kind, d.group, n)
        if g <= 1:
            continue
        mode = d.kind
        events, total_hops, ndirs = rk.static_accounting(
            mode, g, slots, bidir=bidir
        )
        verify_hop_trace(events, slots=slots, ndirs=ndirs,
                         total_hops=total_hops,
                         anchor=f"{anchor}/pallas", report=rep)
        # VMEM estimate: travelling accumulator + local prefetch + send
        # image (f32-ish working set ~3 chunks) plus (slots+1) wire-sized
        # slot buffers per direction-split payload
        if quantized:
            wire = chunk + 4 * (chunk // max(block, 1))
        else:
            wire = chunk * 4
        est = 3 * 4 * chunk + (slots + 1) * wire
        if est > PALLAS_VMEM_BUDGET:
            rep.add("MLSL-A132",
                    f"estimated VMEM working set {est / 2**20:.1f} MiB "
                    f"(chunk {chunk} elems x {slots} slots) exceeds the "
                    f"{PALLAS_VMEM_BUDGET // 2**20} MiB budget: shrink the "
                    "chunk (MLSL_LARGE_MSG_SIZE_MB) or the slot count",
                    f"{anchor}/pallas")


def _check_pallas_rhd_request(rep: Report, req, cfg, anchor: str) -> None:
    """A130-A132 for the recursive-halving/doubling latency kernel: replay
    its static_accounting mirror per chunk program and bound the scratch the
    build actually allocates (acc + recv slots, ops/rhd_kernels._rhd_call)."""
    from mlsl_tpu.ops import rhd_kernels as rhd
    from mlsl_tpu.ops import ring_kernels as rk

    d = req.desc
    slots = rk.env_slots(getattr(cfg, "pallas_ring_slots", None))
    g = 1 if d.group.is_self else int(d.group.size)
    if g <= 1:
        return
    for n in _chunk_counts(req):
        events, total_hops, ndirs = rhd.static_accounting(g, slots)
        verify_hop_trace(events, slots=slots, ndirs=ndirs,
                         total_hops=total_hops,
                         anchor=f"{anchor}/pallas_rhd", report=rep)
        m, m_rows = rhd.geometry(g, int(n))
        c, _k, r = rhd._split(g)
        slots_eff = min(max(slots, 2), max(rhd.rounds(g), 1))
        buf_rows = m_rows if r else max(m_rows // 2, 8)
        est = 4 * 128 * (m_rows + slots_eff * buf_rows)
        if est > PALLAS_VMEM_BUDGET:
            rep.add("MLSL-A132",
                    f"estimated rhd VMEM working set {est / 2**20:.1f} MiB "
                    f"(m={m} elems x {slots_eff} slots) exceeds the "
                    f"{PALLAS_VMEM_BUDGET // 2**20} MiB budget: this payload "
                    "belongs to the ring class, lower "
                    "MLSL_PALLAS_RHD_MAX_BYTES", f"{anchor}/pallas_rhd")


def _check_pallas_a2a_request(rep: Report, req, cfg, anchor: str) -> None:
    """A130-A132 for the fused alltoall: replay its accounting mirror and
    bound the codec scratch (local + staging chunks plus per-slot wire
    images, ops/a2a_kernels._a2a_call)."""
    from mlsl_tpu.ops import a2a_kernels as a2a
    from mlsl_tpu.ops import ring_kernels as rk

    d = req.desc
    slots = rk.env_slots(getattr(cfg, "pallas_ring_slots", None))
    g = 1 if d.group.is_self else int(d.group.size)
    if g <= 1:
        return
    quantized = a2a.quant_enabled(cfg)
    block = getattr(cfg, "quant_block_elems", 256)
    for n in _chunk_counts(req):
        events, total_hops, ndirs = a2a.static_accounting(g, slots)
        verify_hop_trace(events, slots=slots, ndirs=ndirs,
                         total_hops=total_hops,
                         anchor=f"{anchor}/pallas_a2a", report=rep)
        # an alltoall desc's count is the PER-DESTINATION slice (the
        # send_count the lax body rides); the kernel exchanges g of them
        _rc, chunk, rows = a2a.geometry(g, g * int(n), block, quantized)
        wire = chunk + 4 * rows if quantized else chunk * 4
        est = 2 * 4 * chunk + (slots + 1) * wire
        if est > PALLAS_VMEM_BUDGET:
            rep.add("MLSL-A132",
                    f"estimated a2a VMEM working set {est / 2**20:.1f} MiB "
                    f"(chunk {chunk} elems x {slots} slots) exceeds the "
                    f"{PALLAS_VMEM_BUDGET // 2**20} MiB budget: shrink the "
                    "per-destination slice or the slot count",
                    f"{anchor}/pallas_a2a")


# ---------------------------------------------------------------------------
# Elastic reshard coverage (A140/A141)
# ---------------------------------------------------------------------------


def verify_reshard(plan: dict, report: Optional[Report] = None) -> Report:
    """Prove an elastic reshard plan (mlsl_tpu.elastic.build_reshard_plan)
    moves every ZeRO-1 shard element exactly once BEFORE it executes.

    Per layer: ``sources`` are (old-rank, lo, hi) intervals over the old
    padded flat layout that must tile ``[0, count)`` exactly — a gap loses
    optimizer state, an overlap double-applies it (A140) — and ``targets``
    are (new-rank, lo, hi) intervals that must tile ``[0, padded_new)`` in
    ownership-chunk geometry (k_new per rank; A141 when the target geometry
    disagrees with the survivor world's shard math). The coordinator runs
    this unconditionally (not gated by MLSL_VERIFY): a covering bug here
    silently corrupts the training state it exists to carry."""
    rep = report if report is not None else Report("plan")
    d_old = int(plan.get("d_old", 0))
    d_new = int(plan.get("d_new", 0))
    if d_old < 1 or d_new < 1:
        rep.add("MLSL-A141",
                f"reshard world sizes invalid: d_old={d_old}, d_new={d_new}",
                "graph:reshard")
        return rep
    for layer in plan.get("layers", ()):
        name = layer.get("name", "?")
        anchor = f"graph:reshard/{name}"
        count = int(layer["count"])
        padded_old = int(layer["padded_old"])
        padded_new = int(layer["padded_new"])
        k_old = int(layer["k_old"])
        k_new = int(layer["k_new"])
        if padded_old != k_old * d_old or padded_old < count:
            rep.add("MLSL-A141",
                    f"source geometry: padded_old {padded_old} != "
                    f"k_old {k_old} x d_old {d_old} (count {count})", anchor)
        if padded_new != k_new * d_new or padded_new < count:
            rep.add("MLSL-A141",
                    f"target geometry: padded_new {padded_new} != "
                    f"k_new {k_new} x d_new {d_new} (count {count}) — the "
                    "survivor world's ownership chunks cannot hold this "
                    "layer", anchor)
        # -- A140: sources tile [0, count) exactly once ---------------------
        src = sorted(
            (int(lo), int(hi), int(r)) for r, lo, hi in layer["sources"]
        )
        pos = 0
        for lo, hi, r in src:
            if lo < pos:
                rep.add("MLSL-A140",
                        f"source interval [{lo}, {hi}) of old rank {r} "
                        f"overlaps coverage up to {pos}: an element would "
                        "be applied twice", anchor)
            elif lo > pos:
                rep.add("MLSL-A140",
                        f"coverage gap [{pos}, {lo}) before old rank {r}'s "
                        "interval: those shard elements would be dropped",
                        anchor)
            if not (0 <= lo <= hi <= padded_old) or (
                    hi > lo and (k_old < 1  # no chunk can own an interval
                                 or lo // k_old != (hi - 1) // k_old
                                 or lo // k_old != r)):
                rep.add("MLSL-A140",
                        f"source interval [{lo}, {hi}) does not lie inside "
                        f"old rank {r}'s owned chunk "
                        f"[{r * k_old}, {(r + 1) * k_old})", anchor)
            pos = max(pos, hi)
        if pos != count:
            rep.add("MLSL-A140",
                    f"sources cover [0, {pos}) but the layer holds {count} "
                    "real elements", anchor)
        # -- targets tile [0, padded_new) in ownership-chunk geometry -------
        tgt = sorted(
            (int(lo), int(hi), int(r)) for r, lo, hi in layer["targets"]
        )
        pos = 0
        for i, (lo, hi, r) in enumerate(tgt):
            if lo != pos or r != i or hi - lo != k_new:
                rep.add("MLSL-A141",
                        f"target interval [{lo}, {hi}) of new rank {r} is "
                        f"not the ownership chunk "
                        f"[{i * k_new}, {(i + 1) * k_new})", anchor)
            pos = hi
        if pos != padded_new or len(tgt) != d_new:
            rep.add("MLSL-A141",
                    f"targets cover [0, {pos}) across {len(tgt)} rank(s); "
                    f"the survivor world needs [0, {padded_new}) across "
                    f"{d_new}", anchor)
    return rep


# ---------------------------------------------------------------------------
# The commit hook
# ---------------------------------------------------------------------------


def enforce(rep: Report, cfg, what: str, t0: Optional[float] = None) -> Report:
    """The one severity gate every MLSL_VERIFY entry point shares: record
    the verdict (stats line, trace instants, supervisor.status 'analysis'
    key), log each finding, then apply ``MLSL_VERIFY_SEVERITY`` — ``error``
    (default) raises MLSLError naming every error-severity code; ``warn``
    logs and continues."""
    record(rep, time.perf_counter() - t0 if t0 is not None else 0.0)
    for d in rep.diagnostics:
        log_warning("MLSL_VERIFY: %s", d.format())
    if rep.errors and getattr(cfg, "verify_severity", "error") == "error":
        raise MLSLError(
            f"MLSL_VERIFY rejected the {what}: "
            + "; ".join(d.format() for d in rep.errors)
            + " (set MLSL_VERIFY_SEVERITY=warn to log instead)"
        )
    return rep


def run_commit_verify(session) -> Report:
    """Session.commit's MLSL_VERIFY=1 entry point."""
    cfg = session.env.config
    t0 = time.perf_counter()
    return enforce(verify_session(session, cfg), cfg,
                   "collective plan at commit", t0)
