"""Static lockset + lock-order analyzer: the A21x rule family.

The package's value proposition is *asynchronous* communication — endpoint
threads driving the network behind Start/Wait/Test handles — so by PR 19
eighteen modules spawn or coordinate threads. The A2xx linter
(``analysis/lint.py``) checks single-site idioms; this pass models the
*interaction*: which locks exist, which functions may acquire them, and what
happens while they are held.

Rules (docs/DESIGN.md "Static analysis" for the table):

- **A210** lock-order cycle: the may-hold-while-acquiring graph (direct
  ``with A: with B:`` nesting plus call edges into functions that may
  acquire) contains a cycle — two threads taking the locks in opposite
  orders deadlock. A self-edge on a non-reentrant ``Lock`` is the
  single-thread special case.
- **A211** lock held across a blocking operation: device dispatch
  (``_dispatch``/``block_until_ready``), no-timeout ``join()``/``get()``/
  ``put()``/``wait()``, ``time.sleep``, and socket I/O (``send_frame``,
  ``accept``/``recv``/``sendall``) stall every other thread that needs the
  lock for the full blocking duration — the control plane's miss budget is
  the canonical victim (a held lock across a TCP send gets the *sender*
  declared dead).
- **A212** module-level mutable state written from a ``threading.Thread``
  target with no lock held: the cross-thread race the GIL does not fix for
  read-modify-write sequences. ``core/stats``/``obs/metrics``/``obs/tracer``
  are allowlisted — their lock-free single-writer discipline is the
  documented design (and A203/A207 pin its mutation scope).
- **A213** ``Condition.wait`` without an enclosing ``while``: wakeups are
  spurious and racy by contract; an ``if`` check runs the body on a stale
  predicate.
- **A214** (warn) ``daemon=True`` thread never joined anywhere in its
  module: daemon threads die mid-critical-section at interpreter exit,
  leaking locks and half-written state. Join in a shutdown path or carry a
  same-line pragma with the reason.

Same pragma grammar as the linter (``# mlsl-lint: disable=A211 -- why``).
stdlib-only on purpose: runs as a pre-commit gate without importing jax.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from mlsl_tpu.analysis.diagnostics import Report, normalize_code
from mlsl_tpu.analysis.lint import (
    _parse_pragmas,
    _rule_path,
    package_root,
)

#: constructors that create a lock object, -> kind. Both the raw threading
#: primitives and the witness factories (analysis/witness.py) count: routing
#: a lock through the witness must not blind the static pass.
_LOCK_CTORS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "named_lock": "lock",
    "named_rlock": "rlock",
    "named_condition": "condition",
}

#: attribute calls that block for an unbounded time only when called with
#: zero positional args and no timeout kwarg (Thread.join / Queue.get /
#: Queue.put / Event.wait — a 1-arg .join is str.join, a 1-arg .get is
#: dict.get, a with-timeout wait is bounded)
_BLOCKING_IF_NO_TIMEOUT = {"join", "get", "put", "wait"}

#: attribute calls that block regardless of arguments (socket I/O)
_BLOCKING_ALWAYS = {"accept", "recv", "recv_into", "sendall"}

#: plain / module-qualified calls that block (device dispatch markers from
#: the A202 rule, the control channel's retried TCP send, sleeps)
_BLOCKING_CALLS = {"_dispatch", "_dispatch_items", "block_until_ready",
                   "send_frame", "create_connection"}

#: modules whose module-level counters are lock-free BY DESIGN (documented
#: single-writer / GIL-atomic disciplines, pinned by A203/A207); A212 skips
#: them instead of demanding locks the design deliberately omits
_A212_ALLOWED_FILES = {"core/stats.py", "obs/metrics.py", "obs/tracer.py"}

#: device-kernel modules: ``.wait()``/``.get()`` there are Pallas semaphore/
#: ref ops traced into the compiled program, not host-thread blocking
_DEVICE_CODE_FILES = {"ops/ring_kernels.py"}

#: fixpoint bound for the transitive may-acquire/may-block propagation
_MAX_PASSES = 12

LockKey = Tuple[str, Optional[str], str]   # (rule_path, owner class, attr)
FnKey = Tuple[str, Optional[str], str]     # (rule_path, class, name)


class _LockDef:
    __slots__ = ("key", "kind", "lineno")

    def __init__(self, key: LockKey, kind: str, lineno: int):
        self.key = key
        self.kind = kind
        self.lineno = lineno


class _Fn:
    """Per-function facts gathered by the held-set-aware walk."""

    __slots__ = ("key", "node", "acquires", "calls", "blocking",
                 "global_writes", "cond_waits", "nest_edges")

    def __init__(self, key: FnKey, node: ast.AST):
        self.key = key
        self.node = node
        #: lock keys this function itself acquires (any position)
        self.acquires: Set[LockKey] = set()
        #: (callee ref, held set, lineno)
        self.calls: List[Tuple[tuple, FrozenSet[LockKey], int]] = []
        #: (marker name, held set, lineno)
        self.blocking: List[Tuple[str, FrozenSet[LockKey], int]] = []
        #: (global name, held set, lineno)
        self.global_writes: List[Tuple[str, FrozenSet[LockKey], int]] = []
        #: (lineno, inside a while loop?)
        self.cond_waits: List[Tuple[int, bool]] = []
        #: direct with-nesting edges (outer key, inner key, lineno)
        self.nest_edges: List[Tuple[LockKey, LockKey, int]] = []


class _Module:
    """One parsed file: lock inventory, import map, per-function facts."""

    def __init__(self, path: str, src: str):
        self.path = path                       # rule path (package-relative)
        self.src = src
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        self.line_pragmas: Dict[int, Set[str]] = {}
        self.file_pragmas: Set[str] = set()
        #: (owner class or None, attr) -> _LockDef
        self.locks: Dict[Tuple[Optional[str], str], _LockDef] = {}
        #: condition constructed over an existing lock: cond key -> lock key
        self.cond_alias: Dict[LockKey, LockKey] = {}
        self.funcs: Dict[FnKey, _Fn] = {}
        self.by_name: Dict[str, List[FnKey]] = {}
        #: import alias -> target rule path ('stats_mod' -> 'core/stats.py')
        self.imports: Dict[str, str] = {}
        #: module-level names bound to mutable containers
        self.mutable_globals: Set[str] = set()
        #: thread-target function names -> spawn lineno
        self.thread_targets: List[Tuple[str, int]] = []
        #: daemon spawns: (binding name or None, lineno)
        self.daemon_spawns: List[Tuple[Optional[str], int]] = []
        #: names that have .join( called on them somewhere in the module
        self.joined_names: Set[str] = set()
        try:
            self.tree = ast.parse(src)
        except SyntaxError as e:
            self.syntax_error = e
            return
        self.line_pragmas, self.file_pragmas = _parse_pragmas(src)
        self._scan_imports()
        self._scan_locks()
        self._scan_globals()
        self._scan_threads()
        self._scan_functions()

    # -- inventory ---------------------------------------------------------

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("mlsl_tpu."):
                        rel = a.name[len("mlsl_tpu."):].replace(".", "/")
                        self.imports[a.asname or a.name.split(".")[-1]] = \
                            rel + ".py"
            elif isinstance(node, ast.ImportFrom):
                if not node.module or not node.module.startswith("mlsl_tpu"):
                    continue
                base = node.module[len("mlsl_tpu"):].lstrip(".")
                for a in node.names:
                    sub = (base + "/" if base else "") + a.name
                    self.imports[a.asname or a.name] = \
                        sub.replace(".", "/") + ".py"

    @staticmethod
    def _ctor_kind(call: ast.Call) -> Optional[str]:
        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        return _LOCK_CTORS.get(name or "")

    def _scan_locks(self) -> None:
        """Every ``X = threading.Lock()``-shaped binding, module-level or
        ``self.attr`` inside a class body, plus Condition-over-lock
        aliases."""

        def visit(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and \
                        isinstance(child.value, ast.Call):
                    kind = self._ctor_kind(child.value)
                    if kind:
                        for t in child.targets:
                            owner_attr = self._binding(t, cls)
                            if owner_attr is None:
                                continue
                            key = (self.path,) + owner_attr
                            self.locks[owner_attr] = _LockDef(
                                key, kind, child.lineno)
                            if kind == "condition" and child.value.args:
                                base = self._binding_of_expr(
                                    child.value.args[0], cls)
                                if base is not None:
                                    self.cond_alias[key] = \
                                        (self.path,) + base
                visit(child, cls)

        visit(self.tree, None)

    def _binding(self, target: ast.AST,
                 cls: Optional[str]) -> Optional[Tuple[Optional[str], str]]:
        """A lock binding target -> (owner, attr): ``self.x`` inside class C
        is (C, 'x'); a module-level name is (None, name)."""
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            return (cls, target.attr)
        if isinstance(target, ast.Name) and cls is None:
            return (None, target.id)
        return None

    def _binding_of_expr(self, expr: ast.AST, cls: Optional[str]
                         ) -> Optional[Tuple[Optional[str], str]]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return (cls, expr.attr)
        if isinstance(expr, ast.Name):
            return (None, expr.id)
        return None

    def _scan_globals(self) -> None:
        mutable_ctors = {"dict", "list", "set", "deque", "defaultdict",
                         "OrderedDict", "Counter"}
        for child in ast.iter_child_nodes(self.tree):
            if not isinstance(child, (ast.Assign, ast.AnnAssign)):
                continue
            targets = child.targets if isinstance(child, ast.Assign) \
                else [child.target]
            v = child.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id in mutable_ctors)
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    self.mutable_globals.add(t.id)

    def _scan_threads(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name == "Thread":
                    self._note_thread(node)
                elif isinstance(f, ast.Attribute) and f.attr == "join":
                    recv = f.value
                    if isinstance(recv, ast.Attribute):
                        self.joined_names.add(recv.attr)
                    elif isinstance(recv, ast.Name):
                        self.joined_names.add(recv.id)

    def _note_thread(self, node: ast.Call) -> None:
        daemon = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True for kw in node.keywords)
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self":
                self.thread_targets.append((v.attr, node.lineno))
            elif isinstance(v, ast.Name):
                self.thread_targets.append((v.id, node.lineno))
        if daemon:
            self.daemon_spawns.append((self._thread_binding(node),
                                       node.lineno))

    def _thread_binding(self, call: ast.Call) -> Optional[str]:
        """The name the Thread object is bound to (``self._t = Thread(...)``
        -> '_t'), found by matching the call node back to its Assign."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                t = node.targets[0]
                if isinstance(t, ast.Attribute):
                    return t.attr
                if isinstance(t, ast.Name):
                    return t.id
        return None

    # -- per-function facts ------------------------------------------------

    def _scan_functions(self) -> None:
        def visit(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key: FnKey = (self.path, cls, child.name)
                    fn = _Fn(key, child)
                    self.funcs[key] = fn
                    self.by_name.setdefault(child.name, []).append(key)
                    self._walk_fn(child, cls, fn)
                    visit(child, cls)   # nested defs get their own entry
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                else:
                    visit(child, cls)

        visit(self.tree, None)

    def _lock_key_of(self, expr: ast.AST, cls: Optional[str]
                     ) -> Optional[Tuple[LockKey, str]]:
        """Resolve a with-context / receiver expression to a known lock
        (following Condition-over-lock aliases) -> (key, kind)."""
        binding = self._binding_of_expr(expr, cls)
        if binding is None:
            return None
        d = self.locks.get(binding)
        if d is None and binding[0] is not None:
            # method of another class in this module, or an attr assigned in
            # a helper: fall back to a unique same-attr match
            matches = [x for (o, a), x in self.locks.items()
                       if a == binding[1]]
            d = matches[0] if len(matches) == 1 else None
        if d is None:
            return None
        key = self.cond_alias.get(d.key, d.key)
        return key, d.kind

    def _walk_fn(self, fn_node: ast.AST, cls: Optional[str], fn: _Fn) -> None:
        declared_global: Set[str] = {
            n for node in ast.walk(fn_node)
            if isinstance(node, ast.Global) for n in node.names}

        def walk(node, held: List[LockKey], in_while: bool):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # nested defs are separate functions
            if isinstance(node, ast.With):
                entered: List[LockKey] = []
                for item in node.items:
                    got = self._lock_key_of(item.context_expr, cls)
                    if got is None:
                        continue
                    key, _kind = got
                    fn.acquires.add(key)
                    for h in held + entered:
                        if h != key:
                            fn.nest_edges.append((h, key, node.lineno))
                    entered.append(key)
                for b in node.body:
                    walk(b, held + entered, in_while)
                return
            if isinstance(node, ast.While):
                for child in ast.iter_child_nodes(node):
                    walk(child, held, True)
                return
            if isinstance(node, ast.Call):
                self._note_call(node, cls, fn, held, in_while)
            self._note_write(node, cls, fn, held, declared_global)
            for child in ast.iter_child_nodes(node):
                walk(child, held, in_while)

        for stmt in ast.iter_child_nodes(fn_node):
            walk(stmt, [], False)

    def _note_call(self, call: ast.Call, cls: Optional[str], fn: _Fn,
                   held: List[LockKey], in_while: bool) -> None:
        f = call.func
        hset = frozenset(held)
        # a positional arg makes join/get/wait bounded or non-queue
        # (str.join(it), dict.get(k), Event.wait(t)); put(item) still blocks
        # and is only bounded by an explicit timeout/block kwarg
        kwargs = {kw.arg for kw in call.keywords}
        if isinstance(f, ast.Attribute) and f.attr == "put":
            has_timeout = bool(kwargs & {"timeout", "block"})
        else:
            has_timeout = bool(call.args) or bool(kwargs & {"timeout",
                                                            "block"})
        if isinstance(f, ast.Attribute):
            recv_lock = self._lock_key_of(f.value, cls)
            if f.attr in ("acquire",) and recv_lock is not None:
                fn.acquires.add(recv_lock[0])
            if f.attr == "wait":
                if recv_lock is not None and recv_lock[1] == "condition":
                    fn.cond_waits.append((call.lineno, in_while))
                    return   # Condition.wait releases its lock: not A211
            if f.attr in _BLOCKING_ALWAYS and held:
                fn.blocking.append((f.attr, hset, call.lineno))
            elif f.attr in _BLOCKING_IF_NO_TIMEOUT and held \
                    and not has_timeout:
                fn.blocking.append((f.attr, hset, call.lineno))
            if f.attr in _BLOCKING_CALLS and held:
                fn.blocking.append((f.attr, hset, call.lineno))
            # sleep: time.sleep / bare sleep
            if f.attr == "sleep" and isinstance(f.value, ast.Name) \
                    and f.value.id == "time" and held:
                fn.blocking.append(("time.sleep", hset, call.lineno))
            # call-graph edge
            if isinstance(f.value, ast.Name):
                if f.value.id == "self":
                    fn.calls.append((("self", f.attr), hset, call.lineno))
                elif f.value.id in self.imports:
                    fn.calls.append((("import", f.value.id, f.attr),
                                     hset, call.lineno))
        elif isinstance(f, ast.Name):
            if f.id in _BLOCKING_CALLS and held:
                fn.blocking.append((f.id, hset, call.lineno))
            if f.id in self.imports:
                # from mlsl_tpu.x import fn; fn(...)
                fn.calls.append((("import_fn", f.id), hset, call.lineno))
            else:
                fn.calls.append((("local", f.id), hset, call.lineno))

    def _note_write(self, node: ast.AST, cls: Optional[str], fn: _Fn,
                    held: List[LockKey], declared_global: Set[str]) -> None:
        hset = frozenset(held)

        def global_name(expr) -> Optional[str]:
            if isinstance(expr, ast.Name) and \
                    expr.id in self.mutable_globals:
                return expr.id
            return None

        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                name = None
                if isinstance(t, ast.Subscript):
                    name = global_name(t.value)
                elif isinstance(t, ast.Name) and t.id in declared_global \
                        and t.id in self.mutable_globals:
                    name = t.id
                if name:
                    fn.global_writes.append((name, hset, node.lineno))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and node.func.attr in (
                    "append", "appendleft", "extend", "update", "add",
                    "setdefault", "pop", "popleft", "clear", "remove",
                    "discard"):
            name = global_name(node.func.value)
            if name:
                fn.global_writes.append((name, hset, node.lineno))


# ---------------------------------------------------------------------------
# whole-package analysis
# ---------------------------------------------------------------------------


def _resolve(mods: Dict[str, _Module], mod: _Module, caller: FnKey,
             ref: tuple) -> List[FnKey]:
    """A recorded call ref -> candidate function keys (under-approximate:
    unresolvable receivers contribute no edges)."""
    kind = ref[0]
    if kind == "self":
        name = ref[1]
        cls = caller[1]
        exact = (mod.path, cls, name)
        if exact in mod.funcs:
            return [exact]
        return mod.by_name.get(name, [])
    if kind == "local":
        return mod.by_name.get(ref[1], [])
    if kind == "import":
        target = mods.get(mod.imports.get(ref[1], ""))
        if target is None:
            return []
        return [k for k in target.by_name.get(ref[2], ())
                if k[1] is None]  # module-qualified -> module-level fns
    if kind == "import_fn":
        # from mlsl_tpu.pkg import name -- the import maps name to either a
        # module (pkg/name.py) or a module-level function in pkg/__init__.py
        tpath = mod.imports.get(ref[1], "")
        parent = os.path.dirname(tpath)
        fname = os.path.basename(tpath)[:-3] if tpath.endswith(".py") else ""
        init = (parent + "/" if parent else "") + "__init__.py"
        target = mods.get(init)
        if target is not None:
            return [k for k in target.by_name.get(fname, ()) if k[1] is None]
        return []
    return []


def _fixpoint_may_acquire(mods: Dict[str, _Module]
                          ) -> Dict[FnKey, Set[LockKey]]:
    may: Dict[FnKey, Set[LockKey]] = {}
    for m in mods.values():
        for key, fn in m.funcs.items():
            may[key] = set(fn.acquires)
    for _ in range(_MAX_PASSES):
        changed = False
        for m in mods.values():
            for key, fn in m.funcs.items():
                acc = may[key]
                before = len(acc)
                for ref, _held, _line in fn.calls:
                    for callee in _resolve(mods, m, key, ref):
                        acc |= may.get(callee, set())
                if len(acc) != before:
                    changed = True
        if not changed:
            break
    return may


def _fixpoint_may_block(mods: Dict[str, _Module]
                        ) -> Dict[FnKey, Optional[Tuple[str, str]]]:
    """fn -> (marker, anchor 'path:line') of one blocking site reachable
    from it (its own, or transitively through resolvable calls), or None."""
    may: Dict[FnKey, Optional[Tuple[str, str]]] = {}
    # blocking is recorded in fn.blocking only when a lock was held at the
    # site; for propagation what matters is that the callee CAN block at
    # all, so rescan every call node with the same marker logic (minus the
    # held filter, minus Condition.wait — that releases its lock)
    for m in mods.values():
        for key, fn in m.funcs.items():
            may[key] = None
            if m.path in _DEVICE_CODE_FILES:
                continue
            cls = key[1]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                kwargs = {kw.arg for kw in node.keywords}
                if name == "put":
                    has_timeout = bool(kwargs & {"timeout", "block"})
                else:
                    has_timeout = bool(node.args) or bool(
                        kwargs & {"timeout", "block"})
                if name == "wait" and isinstance(f, ast.Attribute):
                    got = m._lock_key_of(f.value, cls)
                    if got is not None and got[1] == "condition":
                        continue
                if name in _BLOCKING_CALLS or name in _BLOCKING_ALWAYS or (
                        name in _BLOCKING_IF_NO_TIMEOUT and not has_timeout):
                    may[key] = (name or "?", f"{m.path}:{node.lineno}")
                    break
                if name == "sleep" and isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "time":
                    may[key] = ("time.sleep", f"{m.path}:{node.lineno}")
                    break
    for _ in range(_MAX_PASSES):
        changed = False
        for m in mods.values():
            for key, fn in m.funcs.items():
                if may[key] is not None:
                    continue
                for ref, _held, _line in fn.calls:
                    for callee in _resolve(mods, m, key, ref):
                        if may.get(callee) is not None:
                            may[key] = may[callee]
                            changed = True
                            break
                    if may[key] is not None:
                        break
        if not changed:
            break
    return may


def _lock_name(key: LockKey) -> str:
    path, owner, attr = key
    return f"{path}:{owner + '.' if owner else ''}{attr}"


def _find_cycles(edges: Dict[Tuple[LockKey, LockKey], int]
                 ) -> List[Tuple[List[LockKey], int]]:
    """Cycles in the acquisition-order graph -> (cycle node list, anchor
    line). Each strongly-connected component with a cycle reports once."""
    graph: Dict[LockKey, Set[LockKey]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[LockKey, int] = {}
    low: Dict[LockKey, int] = {}
    on_stack: Set[LockKey] = set()
    stack: List[LockKey] = []
    sccs: List[List[LockKey]] = []
    counter = [0]

    def strongconnect(v: LockKey) -> None:
        # iterative Tarjan: (node, iterator) frames
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    out = []
    for comp in sccs:
        cyclic = len(comp) > 1 or (comp[0], comp[0]) in edges
        if not cyclic:
            continue
        comp = sorted(comp)
        anchor = min(line for (a, b), line in edges.items()
                     if a in comp and b in comp)
        out.append((comp, anchor))
    return out


def analyze_sources(files: Dict[str, str]) -> Report:
    """Run the A21x pass over ``{rule_path: source}``. Cross-module edges
    resolve through the package-internal import graph; anything that cannot
    be resolved contributes no edge (under-approximation: this pass must
    never cry wolf on the shipped tree)."""
    rep = Report("locks")
    mods: Dict[str, _Module] = {}
    for path, src in sorted(files.items()):
        mods[path] = _Module(path, src)

    def emit(mod: _Module, code: str, message: str, lineno: int) -> None:
        code = normalize_code(code)
        if code in mod.file_pragmas or \
                code in mod.line_pragmas.get(lineno, ()):
            return
        rep.add(code, message, f"{mod.path}:{lineno}")

    may_acquire = _fixpoint_may_acquire(mods)
    may_block = _fixpoint_may_block(mods)

    # -- A210: acquisition-order graph + cycles ---------------------------
    edges: Dict[Tuple[LockKey, LockKey], int] = {}
    edge_mod: Dict[Tuple[LockKey, LockKey], _Module] = {}
    for m in mods.values():
        for key, fn in m.funcs.items():
            for a, b, line in fn.nest_edges:
                edges.setdefault((a, b), line)
                edge_mod.setdefault((a, b), m)
            for ref, held, line in fn.calls:
                if not held:
                    continue
                targets: Set[LockKey] = set()
                for callee in _resolve(mods, m, key, ref):
                    targets |= may_acquire.get(callee, set())
                for h in held:
                    for t in targets:
                        if t != h:
                            edges.setdefault((h, t), line)
                            edge_mod.setdefault((h, t), m)
    for cycle, anchor in _find_cycles(edges):
        names = " -> ".join(_lock_name(k) for k in cycle)
        mod = next((edge_mod[(a, b)] for (a, b) in edges
                    if a in cycle and b in cycle
                    and edges[(a, b)] == anchor), None)
        if mod is None:
            continue
        emit(mod, "A210",
             f"lock-order cycle: {names} — threads taking these locks in "
             "opposite orders deadlock; pick one order and hold to it",
             anchor)

    # -- A211 / A212 / A213 per-function facts ----------------------------
    seen_211: Set[Tuple[str, int]] = set()
    for m in mods.values():
        if m.syntax_error is not None:
            continue   # the linter's A200 owns unparseable files
        reachable = _thread_reachable(mods, m)
        for key, fn in m.funcs.items():
            for marker, held, line in fn.blocking:
                if not held or (m.path, line) in seen_211:
                    continue
                seen_211.add((m.path, line))
                emit(m, "A211",
                     f"'{marker}' can block while "
                     f"{_held_names(held)} is held — every thread needing "
                     "the lock stalls for the full blocking duration",
                     line)
            for ref, held, line in fn.calls:
                if not held or (m.path, line) in seen_211:
                    continue
                for callee in _resolve(mods, m, key, ref):
                    blk = may_block.get(callee)
                    if blk is None:
                        continue
                    seen_211.add((m.path, line))
                    emit(m, "A211",
                         f"call into '{callee[2]}' (which can block: "
                         f"'{blk[0]}' at {blk[1]}) while "
                         f"{_held_names(held)} is held", line)
                    break
            for line, in_while in fn.cond_waits:
                if not in_while:
                    emit(m, "A213",
                         "Condition.wait outside a while loop: wakeups are "
                         "spurious by contract — re-check the predicate in "
                         "a loop", line)
            if m.path in _A212_ALLOWED_FILES:
                continue
            if key in reachable:
                for name, held, line in fn.global_writes:
                    if held:
                        continue
                    emit(m, "A212",
                         f"module-level mutable '{name}' written from "
                         f"thread-reachable '{key[2]}' with no lock held — "
                         "a cross-thread read-modify-write race", line)

        # -- A214: daemon spawns never joined -----------------------------
        for binding, line in m.daemon_spawns:
            if binding is not None and binding in m.joined_names:
                continue
            who = f"'{binding}'" if binding else "an unbound Thread"
            emit(m, "A214",
                 f"daemon thread {who} is never joined in this module: at "
                 "interpreter exit it dies mid-critical-section, leaking "
                 "locks and half-written state — join it in a shutdown "
                 "path (or pragma with the reason)", line)
    return rep


def _held_names(held: FrozenSet[LockKey]) -> str:
    return "/".join(sorted(_lock_name(k) for k in held))


def _thread_reachable(mods: Dict[str, _Module], m: _Module) -> Set[FnKey]:
    """Function keys reachable (resolvable calls, bounded) from any of this
    module's Thread targets."""
    frontier: List[FnKey] = []
    for name, _line in m.thread_targets:
        frontier.extend(m.by_name.get(name, []))
    seen: Set[FnKey] = set()
    depth = 0
    while frontier and depth < _MAX_PASSES:
        nxt: List[FnKey] = []
        for key in frontier:
            if key in seen:
                continue
            seen.add(key)
            mod = mods.get(key[0])
            fn = mod.funcs.get(key) if mod else None
            if fn is None:
                continue
            for ref, _held, _line in fn.calls:
                nxt.extend(_resolve(mods, mod, key, ref))
        frontier = nxt
        depth += 1
    return seen


def analyze_source(src: str, relpath: str = "<string>") -> Report:
    """Single-file convenience (the fixture tests): whole-package analysis
    over a one-file package."""
    return analyze_sources({_rule_path(relpath): src})


def analyze_tree(root: Optional[str] = None) -> Report:
    """Analyze every ``.py`` under ``root`` (default: the installed package)
    as one program — the form the lint gate and ``--concurrency`` run."""
    root = os.path.abspath(root or package_root())
    files: Dict[str, str] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build", ".git",
                                    "node_modules", ".ruff_cache")
                       and not (d == "fixtures"
                                and os.path.basename(dirpath) == "tests")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                files[_rule_path(rel)] = f.read()
    return analyze_sources(files)
