"""Explicit-state protocol model checker: the A15x rule family.

The control plane (PR 17) and the elastic coordinator (PR 11) are
distributed state machines — membership epochs fenced by leadership,
preemption notices retried toward a moving leader, drain verdicts that must
survive the decider's own death. Runtime tests sample a handful of
interleavings; this pass enumerates *all* of them over small declarative
models of those protocols and checks the safety properties the runtime
story depends on:

- **A150** reachable deadlock: a state with no enabled transition that the
  model does not accept as a completed run.
- **A151** invariant violation (the flagship: *dual coordinator* — two live
  ranks simultaneously holding committed leadership at the same epoch).
- **A152** lost drain-ack: a completed run in which a preemption notice was
  raised by a still-live rank but its drain never reached the acked state.
- **A153** (warn) exploration truncated at the state/depth bound: the
  verdict covers only the explored prefix.

Models are *mirrors*, not imports: they re-state the commit/fence/drain
rules of ``control/plane.py`` (leadership = lowest surviving rank; a commit
is applied iff its epoch is strictly newer AND its sender is the lowest
rank net of the removals it carries; notices are re-sent toward the
current leader view until a drain is ordered; drain acks are re-sent until
acknowledged) in ~40 lines of transition function. Keeping them here keeps
``analysis/`` import-light (the ``static_accounting``-next-to-the-kernel
precedent was considered and rejected: plane.py must not import a model
checker); the cross-check is the fixture suite pinning each code plus the
commit-gate run proving the SHIPPED models safe.

Wired at ``Session.commit`` next to the A1xx plan verifier (same
``MLSL_VERIFY`` gate, same ``plan.enforce`` severity behavior), and into
``python -m mlsl_tpu.analysis --concurrency``. The exploration result is
memoized process-wide: the models are constants, so one exhaustive run per
process covers every commit.

stdlib-only, like the rest of ``analysis/``.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from mlsl_tpu.analysis.diagnostics import Report

ENV_MAX_STATES = "MLSL_PROTOCOL_MAX_STATES"
ENV_MAX_DEPTH = "MLSL_PROTOCOL_MAX_DEPTH"

#: exhaustive-exploration bounds: the shipped models reach quiescence well
#: inside both (the stated bound the acceptance story quotes); a model that
#: hits either reports A153 and the verdict covers only the prefix
DEFAULT_MAX_STATES = 200_000
DEFAULT_MAX_DEPTH = 64


class Model:
    """A small declarative protocol model.

    ``transitions(state) -> [(label, next_state)]`` (self-loops are ignored
    by the explorer); ``invariant(state)`` returns ``None`` or
    ``(code, message)``; ``done(state)`` says whether a transition-free
    state is an accepted completed run; ``quiescence(state)`` runs extra
    checks on completed runs and returns ``None`` or ``(code, message)``.
    States must be hashable.
    """

    def __init__(self, name: str, initial: Iterable,
                 transitions: Callable,
                 invariant: Optional[Callable] = None,
                 done: Optional[Callable] = None,
                 quiescence: Optional[Callable] = None):
        self.name = name
        self.initial = list(initial)
        self.transitions = transitions
        self.invariant = invariant or (lambda s: None)
        self.done = done or (lambda s: True)
        self.quiescence = quiescence or (lambda s: None)


def _trace(parents: Dict, state) -> str:
    """Reconstruct the (label) path from an initial state, newest last."""
    labels: List[str] = []
    while True:
        got = parents.get(state)
        if got is None:
            break
        state, label = got
        labels.append(label)
    labels.reverse()
    if len(labels) > 12:
        labels = labels[:4] + [f"... {len(labels) - 8} steps ..."] + \
            labels[-4:]
    return " -> ".join(labels) if labels else "<initial>"


def explore(model: Model,
            max_states: Optional[int] = None,
            max_depth: Optional[int] = None) -> Report:
    """Exhaustive BFS over ``model``'s reachable states. Every finding is
    anchored ``model:<name>`` with a counterexample trace in the message."""
    if max_states is None:
        max_states = int(os.environ.get(ENV_MAX_STATES, DEFAULT_MAX_STATES))
    if max_depth is None:
        max_depth = int(os.environ.get(ENV_MAX_DEPTH, DEFAULT_MAX_DEPTH))
    rep = Report("protocol")
    anchor = f"model:{model.name}"
    visited = set(model.initial)
    frontier = list(model.initial)
    parents: Dict = {}
    depth = 0
    truncated = False
    # one report per code keeps the output readable; every violating state
    # would otherwise repeat the same story
    seen_codes = set()

    def emit(code: str, message: str, state) -> None:
        if code in seen_codes:
            return
        seen_codes.add(code)
        rep.add(code, f"{message} [trace: {_trace(parents, state)}]", anchor)

    while frontier:
        if depth >= max_depth:
            truncated = True
            break
        nxt: List = []
        for s in frontier:
            viol = model.invariant(s)
            if viol is not None:
                emit(viol[0], viol[1], s)
            moves = [(lb, t2) for lb, t2 in model.transitions(s) if t2 != s]
            if not moves:
                if not model.done(s):
                    emit("A150",
                         "reachable deadlock: no transition enabled and the "
                         "run is not complete", s)
                else:
                    q = model.quiescence(s)
                    if q is not None:
                        emit(q[0], q[1], s)
                continue
            for label, t in moves:
                if t in visited:
                    continue
                if len(visited) >= max_states:
                    truncated = True
                    break
                visited.add(t)
                parents[t] = (s, label)
                nxt.append(t)
        frontier = nxt
        depth += 1
    if truncated:
        rep.add("A153",
                f"exploration truncated at {len(visited)} states / depth "
                f"{depth} (bounds: {max_states} states, {max_depth} deep): "
                "the verdict covers only the explored prefix", anchor)
    rep.explored_states = len(visited)   # type: ignore[attr-defined]
    rep.explored_depth = depth           # type: ignore[attr-defined]
    return rep


# ---------------------------------------------------------------------------
# The shipped models
# ---------------------------------------------------------------------------

_RANKS = (0, 1, 2)

# membership/drain state:
# (crashed fs, epochs 3-tuple, removed 3-tuple of fs, detected 3-tuple of
#  fs, msgs fs, notice_rank, drain_state, crash_budget)
# drain_state: 0 notice unserved / 1 drain ordered / 2 drained locally
#              (ack in flight) / 3 acked. -1 = no notice in this run.
_D_NONE, _D_UNSERVED, _D_ORDERED, _D_DRAINED, _D_ACKED = -1, 0, 1, 2, 3


def _leader_view(state, r: int) -> int:
    """plane.py's candidate rule: the lowest rank not removed or locally
    suspected."""
    _, _, removed, detected, _, _, _, _ = state
    alive_known = [p for p in _RANKS
                   if p not in removed[r] and p not in detected[r]]
    return min(alive_known) if alive_known else r


def _committed_leader(state, r: int) -> int:
    """Leadership by committed membership only (the A151 invariant uses
    this: commits are what carries authority)."""
    _, _, removed, _, _, _, _, _ = state
    alive = [p for p in _RANKS if p not in removed[r]]
    return min(alive) if alive else r


def _membership_transitions(state) -> List[Tuple[str, tuple]]:
    (crashed, epochs, removed, detected, msgs, notice_rank, drain,
     budget) = state
    out: List[Tuple[str, tuple]] = []
    live = [r for r in _RANKS if r not in crashed]

    def repl(seq, i, v):
        t = list(seq)
        t[i] = v
        return tuple(t)

    # 1. crash (at most `budget` in a run)
    if budget > 0:
        for r in live:
            out.append((f"crash({r})",
                        (crashed | {r}, epochs, removed, detected, msgs,
                         notice_rank, drain, budget - 1)))
    # 2. heartbeat-miss detection: a live rank locally suspects a corpse
    for p in live:
        for c in crashed:
            if c in detected[p] or c in removed[p]:
                continue
            out.append((f"detect({p},{c})",
                        (crashed, epochs, removed,
                         repl(detected, p, detected[p] | {c}), msgs,
                         notice_rank, drain, budget)))
    # 3. act on detection: the view-leader commits the loss epoch (the
    #    barrier's corroborated union — in-model every detection IS
    #    corroborated); a non-leader's proposal is subsumed by the
    #    leader's own detection transition
    for p in live:
        pend = detected[p] - removed[p]
        if not pend:
            continue
        if _leader_view(state, p) != p:
            continue
        new_removed = removed[p] | pend
        new_epoch = epochs[p] + 1
        commit_msgs = msgs | {
            ("commit", p, q, (new_epoch, frozenset(new_removed)))
            for q in _RANKS if q != p
        }
        out.append((f"commit({p},e{new_epoch})",
                    (crashed, repl(epochs, p, new_epoch),
                     repl(removed, p, new_removed),
                     repl(detected, p, detected[p] - new_removed),
                     commit_msgs, notice_rank, drain, budget)))
    # 4. preemption notice: re-sent toward the current leader view until a
    #    drain is ordered (plane retries next tick; the target moves as
    #    deaths are detected)
    if drain == _D_UNSERVED and notice_rank not in crashed:
        tgt = _leader_view(state, notice_rank)
        m = ("notice", notice_rank, tgt, None)
        if m not in msgs:
            out.append((f"send_notice({notice_rank}->{tgt})",
                        (crashed, epochs, removed, detected, msgs | {m},
                         notice_rank, drain, budget)))
    # 4b. drain-ack re-send (the heartbeat-carried status): until acked,
    #     the drained rank keeps telling its current leader view
    if drain == _D_DRAINED and notice_rank not in crashed:
        tgt = _leader_view(state, notice_rank)
        m = ("drained", notice_rank, tgt, None)
        if m not in msgs:
            out.append((f"resend_drained({notice_rank}->{tgt})",
                        (crashed, epochs, removed, detected, msgs | {m},
                         notice_rank, drain, budget)))
    # 5. message delivery (any order; delivery to a corpse consumes the
    #    frame — TCP to a dead host is an error at the sender, the retry
    #    is modeled by the re-send transitions above)
    for m in msgs:
        kind, src, dst, data = m
        rest = msgs - {m}
        if dst in crashed:
            out.append((f"lose({kind}->{dst})",
                        (crashed, epochs, removed, detected, rest,
                         notice_rank, drain, budget)))
            continue
        if kind == "commit":
            e, rem = data
            # plane._fence: strictly newer epoch AND the sender must lead
            # the world net of the removals it announces
            if e > epochs[dst] and src == min(set(_RANKS) - rem):
                out.append((f"apply_commit({dst},e{e})",
                            (crashed, repl(epochs, dst, e),
                             repl(removed, dst, frozenset(rem)),
                             repl(detected, dst, detected[dst] - rem),
                             rest, notice_rank, drain, budget)))
            else:
                out.append((f"reject_commit({dst},e{e})",
                            (crashed, epochs, removed, detected, rest,
                             notice_rank, drain, budget)))
        elif kind == "notice":
            nd = _D_ORDERED if drain == _D_UNSERVED else drain
            extra = ({("drain", dst, notice_rank, None)}
                     if drain == _D_UNSERVED else set())
            out.append((f"decide_drain({dst})",
                        (crashed, epochs, removed, detected, rest | extra,
                         notice_rank, nd, budget)))
        elif kind == "drain":
            nd = _D_DRAINED if drain == _D_ORDERED else drain
            extra = ({("drained", dst, _leader_view(state, dst), None)}
                     if drain == _D_ORDERED else set())
            out.append((f"execute_drain({dst})",
                        (crashed, epochs, removed, detected, rest | extra,
                         notice_rank, nd, budget)))
        elif kind == "drained":
            nd = _D_ACKED if drain in (_D_DRAINED, _D_ORDERED) else drain
            out.append((f"ack_drain({dst})",
                        (crashed, epochs, removed, detected, rest,
                         notice_rank, nd, budget)))
    return out


def _membership_invariant(state):
    crashed, epochs, removed, _, _, _, _, _ = state
    leaders = [r for r in _RANKS if r not in crashed
               and _committed_leader(state, r) == r]
    for i, a in enumerate(leaders):
        for b in leaders[i + 1:]:
            if epochs[a] == epochs[b]:
                return ("A151",
                        f"dual coordinator: ranks {a} and {b} both hold "
                        f"committed leadership at epoch {epochs[a]}")
    return None


def _membership_done(state) -> bool:
    crashed, epochs, removed, detected, msgs, notice_rank, drain, _ = state
    if msgs:
        return False
    live = [r for r in _RANKS if r not in crashed]
    if not live:
        return True
    # converged membership: every survivor agrees, and agrees with reality
    if any(removed[r] != frozenset(crashed) for r in live):
        return False
    if any(epochs[r] != epochs[live[0]] for r in live):
        return False
    if any(detected[r] - removed[r] for r in live):
        return False
    return True


def _membership_quiescence(state):
    crashed, _, _, _, _, notice_rank, drain, _ = state
    if notice_rank >= 0 and notice_rank not in crashed \
            and drain != _D_ACKED:
        return ("A152",
                f"lost drain-ack: rank {notice_rank}'s preemption notice "
                f"ended the run at drain state {drain} (never acked by a "
                "live coordinator)")
    return None


def membership_drain_model() -> Model:
    """The control-plane membership/heartbeat/drain mirror: 3 ranks, at
    most one crash, at most one preemption notice per run."""
    empty = frozenset()
    base = (frozenset(), (0, 0, 0), (empty,) * 3, (empty,) * 3,
            frozenset(), _D_NONE, _D_NONE, 1)
    inits = [base]
    for r in _RANKS:
        inits.append((frozenset(), (0, 0, 0), (empty,) * 3, (empty,) * 3,
                      frozenset(), r, _D_UNSERVED, 1))
    return Model("control.membership_drain", inits,
                 _membership_transitions,
                 invariant=_membership_invariant,
                 done=_membership_done,
                 quiescence=_membership_quiescence)


# elastic shrink/grow state:
# (world, cap, op, audit_fails) — op: '' | 'shrink' | 'grow'
def _elastic_transitions(state) -> List[Tuple[str, tuple]]:
    world, cap, op, fails = state
    out: List[Tuple[str, tuple]] = []
    if op == "":
        if world > 1:
            out.append(("device_loss", (world, cap, "shrink", fails)))
        if world < cap:
            out.append(("grow_request", (world, cap, "grow", 0)))
    elif op == "shrink":
        out.append(("reshard_commit", (world - 1, cap, "", fails)))
    elif op == "grow":
        # the admit audit can pass, fail-then-retry once, or abandon
        out.append(("admit_pass", (world + 1, cap, "", 0)))
        if fails < 1:
            out.append(("admit_fail_retry", (world, cap, "grow", fails + 1)))
        out.append(("admit_abandon", (world, cap, "", 0)))
    return out


def _elastic_invariant(state):
    world, cap, _, _ = state
    if world < 1 or world > cap:
        return ("A151",
                f"elastic world size {world} outside [1, {cap}]: the "
                "capacity budget / last-replica floor was violated")
    return None


def elastic_model() -> Model:
    """The elastic coordinator mirror: capacity-bounded shrink/grow with a
    bounded admit-audit retry. Every state with an in-flight op can finish
    it, so the model is deadlock-free by the A150 check (quiescent states
    are the op=='' ones, all accepted)."""
    return Model("elastic.shrink_grow", [(3, 3, "", 0)],
                 _elastic_transitions,
                 invariant=_elastic_invariant,
                 done=lambda s: s[2] == "")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

#: process-wide memo: the models are constants, one exhaustive run covers
#: every commit in the process (the <5%-of-commit overhead bound)
_memo: Dict[Tuple[int, int], Report] = {}


def check_protocols(max_states: Optional[int] = None,
                    max_depth: Optional[int] = None) -> Report:
    """Explore every shipped model; one combined 'protocol' report."""
    if max_states is None:
        max_states = int(os.environ.get(ENV_MAX_STATES, DEFAULT_MAX_STATES))
    if max_depth is None:
        max_depth = int(os.environ.get(ENV_MAX_DEPTH, DEFAULT_MAX_DEPTH))
    key = (max_states, max_depth)
    got = _memo.get(key)
    if got is not None:
        return got
    rep = Report("protocol")
    explored = []
    states = depth = 0
    for model in (membership_drain_model(), elastic_model()):
        sub = explore(model, max_states, max_depth)
        rep.extend(sub)
        states += sub.explored_states
        depth = max(depth, sub.explored_depth)
        explored.append(
            f"{model.name}: {sub.explored_states} states / depth "
            f"{sub.explored_depth}")
    rep.explored = "; ".join(explored)       # type: ignore[attr-defined]
    rep.explored_states = states             # type: ignore[attr-defined]
    rep.explored_depth = depth               # type: ignore[attr-defined]
    _memo[key] = rep
    return rep


def reset() -> None:
    """Drop the memoized verdict (tests that vary the bounds)."""
    _memo.clear()


def run_commit_protocol_check(session) -> Report:
    """Session.commit's protocol-model entry point: same MLSL_VERIFY gate
    and severity behavior as the A1xx plan verifier."""
    from mlsl_tpu.analysis.plan import enforce

    cfg = session.env.config
    t0 = time.perf_counter()
    return enforce(check_protocols(), cfg,
                   "control/elastic protocol models at commit", t0)
