"""CLI for the static-analysis passes: the pre-commit gate.

Usage::

    python -m mlsl_tpu.analysis                 # lint + lock analysis
    python -m mlsl_tpu.analysis --lint --root . # lint an arbitrary tree
    python -m mlsl_tpu.analysis --graph         # build + verify a demo graph
    python -m mlsl_tpu.analysis --concurrency   # lock analyzer + protocol
                                                # model checker only
    python -m mlsl_tpu.analysis --json          # machine-readable findings

Exits nonzero when any error-severity finding survives — wire it as a
pre-commit hook (scripts/run_lint.sh runs it after ruff). ``--concurrency``
is stricter: it exits nonzero on *any* finding, warnings included, because
its consumers (run_lint.sh --concurrency, CI concurrency jobs) treat an
unproven interleaving as a failure.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def _demo_graph_report():
    """Build a small representative committed graph on the current backend
    (a 3-layer net with a plain, a quantized, and a ZeRO-1 parameter set)
    and run the plan verifier over it — the ``--graph`` smoke path that
    exercises every pass a real commit would."""
    # multi-device CPU simulation when nothing provides devices (the same
    # trick tests/conftest.py uses); harmless if a backend already exists
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    from mlsl_tpu.analysis import plan as plan_mod
    from mlsl_tpu.core.environment import Environment
    from mlsl_tpu.types import CompressionType, OpType

    env = Environment.get_env().init()
    try:
        n = len(env.devices)
        dist = env.create_distribution(n, 1)
        s = env.create_session()
        s.set_global_minibatch_size(max(8, n))
        prev = None
        for i, (comp, du) in enumerate([
            (CompressionType.NONE, False),
            (CompressionType.QUANTIZATION, False),
            (CompressionType.NONE, True),
        ]):
            r = s.create_operation_reg_info(OpType.CC)
            r.set_name(f"demo{i}")
            if i:
                r.add_input(8, 4)
            r.add_output(8, 4)
            r.add_parameter_set(256, 4, distributed_update=du,
                                compression_type=comp)
            op = s.get_operation(s.add_operation(r, dist))
            if prev is not None:
                prev.set_next(op, 0, 0)
            prev = op
        s.commit()
        from mlsl_tpu.analysis.diagnostics import record

        rep = plan_mod.verify_session(s)
        record(rep)
        return rep
    finally:
        env.finalize()


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mlsl_tpu.analysis",
        description="MLSL static analysis: plan verifier + concurrency "
                    "linter (exit 1 on error-severity findings)",
    )
    p.add_argument("--lint", action="store_true",
                   help="run the AST linter (the default when no pass is "
                        "selected)")
    p.add_argument("--graph", action="store_true",
                   help="build a representative demo graph and run the "
                        "commit-time plan verifier over it")
    p.add_argument("--concurrency", action="store_true",
                   help="run the lock-order analyzer and the protocol model "
                        "checker only (exit 1 on ANY finding, warnings "
                        "included)")
    p.add_argument("--root", default=None,
                   help="lint root (default: the installed mlsl_tpu package)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON")
    p.add_argument("--codes", action="store_true",
                   help="print the diagnostic-code table and exit")
    args = p.parse_args(argv)

    from mlsl_tpu.analysis.diagnostics import CODES, Report, record

    if args.codes:
        for code, (sev, title) in sorted(CODES.items()):
            print(f"{code}  {sev:<5}  {title}")
        return 0

    reports: List[Report] = []
    if args.lint or not (args.graph or args.concurrency):
        from mlsl_tpu.analysis import lint, locks

        rep = lint.lint_tree(args.root)
        record(rep)
        reports.append(rep)
        # the lint gate includes the whole-package lockset/lock-order pass:
        # the commit bar is 0 errors across BOTH
        lrep = locks.analyze_tree(args.root)
        record(lrep)
        reports.append(lrep)
    if args.concurrency:
        from mlsl_tpu.analysis import locks, protocol

        lrep = locks.analyze_tree(args.root)
        record(lrep)
        reports.append(lrep)
        prep = protocol.check_protocols()
        record(prep)
        reports.append(prep)
    if args.graph:
        reports.append(_demo_graph_report())

    rc = 0
    for rep in reports:
        if args.json:
            print(rep.to_json())
        elif rep.diagnostics:
            print(rep.format())
        print(rep.summary(), file=sys.stderr)
        if rep.errors:
            rc = 1
        elif args.concurrency and rep.diagnostics:
            rc = 1  # --concurrency: warnings fail too
    return rc


if __name__ == "__main__":
    sys.exit(main())
