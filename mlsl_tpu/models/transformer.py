"""Decoder-only transformer with dp x sp x tp hybrid parallelism, MLSL in the loop.

The scaling design (SURVEY.md §2 parallelism table + §5.7):
- batch over the 'data' axis (DP), sequence over the 'seq' axis (SP, ring or Ulysses
  attention), heads/hidden over the 'model' axis (TP — the reference's feature-map
  sharding, src/mlsl_impl.cpp:36-66, applied to attention heads and MLP width);
- TP activation reductions are lax.psum over 'model' inside the forward (the
  reference's needReduce -> AllReduce case 2);
- parameter-gradient sync across data x seq goes through ParameterSet requests exactly
  like the ResNet trainer — TP-sharded leaves ride the same distributed buffers, with
  each model-axis slot carrying that rank's shard;
- gradients of replicated params (embeddings, layer norms, head) are psum'd over
  'model' inside the grad program (their forward is used by every TP branch).

Compute is bf16 on the MXU; params and reductions f32.
"""

# mlsl-lint: disable-file=A201 -- the hybrid TP/SP forward embeds its
# activation reductions in-graph by design (the needReduce -> AllReduce
# cases above); they fuse with the surrounding matmuls and are not request
# collectives the engine could route

from __future__ import annotations

import dataclasses
from typing import Dict, List

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mlsl_tpu.comm.collectives import _BUF_SPEC
from mlsl_tpu.comm.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.models.moe import init_moe_params, moe_ffn, mxu_einsum
from mlsl_tpu.models.train import (
    _leaf_buf_spec,
    build_owned_increment_fn,
    build_owned_opt_increment_fn,
    init_shard_opt_state,
    smap,
    _unflatten_like,
)
from mlsl_tpu.comm.mesh import NUM_GRID_AXES
from mlsl_tpu.parallel.sequence import (
    ring_attention, ulysses_attention, zigzag_perm, zigzag_ring_attention,
)
from mlsl_tpu.types import CompressionType, DataType, OpType


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 8
    head_dim: int = 8
    n_blocks: int = 2
    seq_len: int = 64
    mlp_ratio: int = 4
    attention: str = "ring"  # 'ring' | 'zigzag' | 'ulysses'. 'zigzag' is the
    # load-balanced causal ring (parallel/sequence.py): the trainer feeds
    # tokens/labels in zigzag sequence order and the position embedding rows
    # follow, so training is mathematically identical to 'ring' at ~2x fewer
    # attention block-FLOPs on the ring hops.
    dtype: str = "bfloat16"  # MXU compute dtype; 'float32' for exactness tests
    remat: bool = False      # jax.checkpoint each block: save only the block
    # input, recompute internals (incl. ring-attention hops' collectives) in
    # the backward — O(n_blocks) residual streams instead of O(n_blocks *
    # per-block intermediates) of saved activations; the long-context trade
    remat_policy: str = "full"  # 'full' | 'dots' (with remat=True): 'dots'
    # applies jax.checkpoint_policies.checkpoint_dots — matmul/attention
    # outputs are saved and only elementwise/softmax work replays in the
    # backward, trading O(blocks * S * d) extra saved bytes for nearly all
    # of full remat's recomputed MXU FLOPs
    n_experts: int = 0       # >0: MoE FFN with expert parallelism over 'model'
    moe_top_k: int = 1       # 1 = switch routing; 2 = GShard-style top-2
    moe_aux_weight: float = 0.01
    capacity_factor: float = 2.0
    sharded_vocab: bool = False  # shard the LM head over 'model'; CE via collectives


def init_params(key, cfg: TransformerConfig) -> Dict:
    ks = iter(jax.random.split(key, 8 + 8 * cfg.n_blocks))
    dm, h, dh = cfg.d_model, cfg.n_heads, cfg.head_dim
    f = cfg.mlp_ratio * dm
    std = 0.02
    params = {
        "embed": {
            "tok": jax.random.normal(next(ks), (cfg.vocab, dm)) * std,
            "pos": jax.random.normal(next(ks), (cfg.seq_len, dm)) * std,
        },
        "final": {
            "ln_scale": jnp.ones((dm,)),
            "ln_bias": jnp.zeros((dm,)),
            "head": jax.random.normal(next(ks), (dm, cfg.vocab)) * std,
        },
    }
    for i in range(cfg.n_blocks):
        params[f"blk{i}.ln"] = {
            "ln1_scale": jnp.ones((dm,)), "ln1_bias": jnp.zeros((dm,)),
            "ln2_scale": jnp.ones((dm,)), "ln2_bias": jnp.zeros((dm,)),
        }
        params[f"blk{i}.attn"] = {
            "wqkv": jax.random.normal(next(ks), (dm, 3, h, dh)) * std,
            "wo": jax.random.normal(next(ks), (h, dh, dm)) * std,
        }
        if cfg.n_experts > 0:
            params[f"blk{i}.mlp"] = init_moe_params(
                next(ks), dm, f, cfg.n_experts, std
            )
        else:
            params[f"blk{i}.mlp"] = {
                "w1": jax.random.normal(next(ks), (dm, f)) * std,
                "b1": jnp.zeros((f,)),
                "w2": jax.random.normal(next(ks), (f, dm)) * std,
                "b2": jnp.zeros((dm,)),
            }
    return params


def param_specs(cfg: TransformerConfig) -> Dict:
    """PartitionSpec pytree: which leaves are TP-sharded over 'model'."""
    specs = {
        "embed": {"tok": P(), "pos": P()},
        "final": {
            "ln_scale": P(),
            "ln_bias": P(),
            # large-vocab: the head shards over 'model'; CE is computed from the
            # per-shard logits with pmax/psum (never materializing full-V logits)
            "head": P(None, MODEL_AXIS) if cfg.sharded_vocab else P(),
        },
    }
    for i in range(cfg.n_blocks):
        specs[f"blk{i}.ln"] = {
            "ln1_scale": P(), "ln1_bias": P(), "ln2_scale": P(), "ln2_bias": P(),
        }
        specs[f"blk{i}.attn"] = {
            "wqkv": P(None, None, MODEL_AXIS, None),
            "wo": P(MODEL_AXIS, None, None),
        }
        if cfg.n_experts > 0:
            # expert parallelism: experts sharded over the model axis
            specs[f"blk{i}.mlp"] = {
                "wg": P(),
                "w1": P(MODEL_AXIS, None, None),
                "w2": P(MODEL_AXIS, None, None),
            }
        else:
            specs[f"blk{i}.mlp"] = {
                "w1": P(None, MODEL_AXIS),
                "b1": P(MODEL_AXIS),
                "w2": P(MODEL_AXIS, None),
                "b2": P(),
            }
    return specs


def layer_names(cfg: TransformerConfig) -> List[str]:
    names = ["embed"]
    for i in range(cfg.n_blocks):
        names += [f"blk{i}.ln", f"blk{i}.attn", f"blk{i}.mlp"]
    names.append("final")
    return names


def get_layer(params, name):
    return params[name]


def _ln(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * scale + bias


def forward_local(params, tokens, cfg: TransformerConfig, sp: int, tp: int,
                  comm=None):
    """SPMD forward on local shards (call inside shard_map).

    tokens: (Bl, Sl) int32. params: LOCAL shards per param_specs. Returns
    (final hidden states (Bl, Sl, d_model) f32 — post final-LN, replicated over
    'model' (psum'd), sharded over data/seq — and the MoE aux-loss total, 0.0
    without experts). The LM head is applied by the loss (local_loss), which owns
    the replicated-vs-vocab-sharded distinction.

    ``comm``: optional (model ProcessGroup, mlsl Config) pair; with it the MoE
    dispatch/combine exchanges route through the collective engine's selection
    table (comm/algos.inline_alltoall) instead of pinning the lax baseline.
    """
    emb = params["embed"]
    cdt = jnp.dtype(cfg.dtype)
    aux_total = jnp.float32(0.0)
    s_idx = lax.axis_index(SEQ_AXIS) if sp > 1 else 0
    sl = tokens.shape[1]
    if cfg.attention == "zigzag" and sp > 1:
        # zigzag layout: tokens/labels arrive zigzag-ordered (shard_tokens),
        # so the position rows follow the SAME permutation — zigzag_perm is
        # the single source of truth for the layout, derived from the RUN-TIME
        # global length sp*sl (shard_tokens permutes whatever length it is
        # fed, which may be shorter than cfg.seq_len). Slice this shard's
        # window of the constant index vector first, then gather only the sl
        # needed rows.
        perm = jnp.asarray(zigzag_perm(sp * sl, sp))
        idx = lax.dynamic_slice_in_dim(perm, s_idx * sl, sl, axis=0)
        pos = emb["pos"][idx]
    else:
        pos = lax.dynamic_slice_in_dim(emb["pos"], s_idx * sl, sl, axis=0)
    h = (emb["tok"][tokens] + pos[None]).astype(cdt)

    if cfg.attention == "zigzag":
        def attn_fn(q, k, v, ax, n, causal=True):
            mlsl_assert(causal, "zigzag attention is causal-only "
                                "(use attention='ring' for non-causal)")
            if n > 1:
                return zigzag_ring_attention(q, k, v, ax, n)
            return ring_attention(q, k, v, ax, n, causal=True)
    else:
        attn_fn = ring_attention if cfg.attention == "ring" else ulysses_attention
    def block_body(h, lnp, ap, mp):
        a = _ln(h.astype(jnp.float32), lnp["ln1_scale"], lnp["ln1_bias"]).astype(cdt)
        qkv = jnp.einsum("bsd,dchx->bcshx", a, ap["wqkv"].astype(cdt))
        q, k, v = (
            jnp.moveaxis(qkv[:, c], 2, 1) for c in range(3)
        )  # (Bl, Hl, Sl, Dh)
        attn = attn_fn(q, k, v, SEQ_AXIS, sp, causal=True)
        # bf16 operands, f32 accumulate/output: keeps the projection on the MXU's
        # native path while the residual add and TP psum stay f32.
        o = mxu_einsum("bhsx,hxd->bsd", attn.astype(cdt), ap["wo"].astype(cdt))
        o = lax.psum(o, MODEL_AXIS) if tp > 1 else o      # TP reduction (case-2 analog)
        h = (h.astype(jnp.float32) + o).astype(cdt)

        a = _ln(h.astype(jnp.float32), lnp["ln2_scale"], lnp["ln2_bias"]).astype(cdt)
        if cfg.n_experts > 0:
            bl, sl_, dm = a.shape
            o2d, aux = moe_ffn(
                a.reshape(bl * sl_, dm).astype(jnp.float32),
                mp, MODEL_AXIS, tp, cfg.capacity_factor, cfg.moe_top_k,
                compute_dtype=cdt,
                group=comm[0] if comm else None,
                config=comm[1] if comm else None,
            )
            h = (h.astype(jnp.float32) + o2d.reshape(bl, sl_, dm)).astype(cdt)
        else:
            aux = jnp.float32(0.0)
            f = jax.nn.gelu(
                jnp.einsum("bsd,df->bsf", a, mp["w1"].astype(cdt))
                + mp["b1"].astype(cdt)
            )
            o = mxu_einsum("bsf,fd->bsd", f, mp["w2"].astype(cdt))
            o = lax.psum(o, MODEL_AXIS) if tp > 1 else o
            h = (h.astype(jnp.float32) + o + mp["b2"]).astype(cdt)
        return h, aux

    # cfg.remat: save only each block's input residual stream; the backward
    # replays the block (incl. the ring hops' collectives) instead of keeping
    # qkv/attn/gelu intermediates alive — the O(sqrt)-style memory trade that
    # makes long sequences fit (docs/DESIGN.md long-context section)
    mlsl_assert(cfg.remat_policy in ("full", "dots"),
                "unknown remat_policy %r", cfg.remat_policy)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            blk = jax.checkpoint(
                block_body, policy=jax.checkpoint_policies.checkpoint_dots
            )
        else:
            blk = jax.checkpoint(block_body)
    else:
        blk = block_body
    for i in range(cfg.n_blocks):
        h, aux = blk(
            h, params[f"blk{i}.ln"], params[f"blk{i}.attn"], params[f"blk{i}.mlp"]
        )
        aux_total = aux_total + aux

    fin = params["final"]
    h = _ln(h.astype(jnp.float32), fin["ln_scale"], fin["ln_bias"])
    return h, aux_total


def _sharded_vocab_ce(h, head_local, labels, vocab_local: int):
    """CE over a model-axis-sharded vocabulary: per-shard logits + pmax/psum
    log-sum-exp; the (tokens, V) logits matrix never exists on any device."""
    logits_l = h @ head_local                                  # (B, S, Vl)
    # the stability max cancels analytically in d(lse)/d(logits) (= softmax), so
    # stop_gradient is exact; pmax has no JVP rule, so the cross-shard max rides
    # a (small) all_gather of the per-shard maxima instead
    mx = lax.stop_gradient(
        jnp.max(lax.all_gather(jnp.max(logits_l, axis=-1), MODEL_AXIS, axis=0), axis=0)
    )                                                          # (B, S)
    se = lax.psum(
        jnp.sum(jnp.exp(logits_l - mx[..., None]), axis=-1), MODEL_AXIS
    )
    lse = jnp.log(se) + mx
    off = lax.axis_index(MODEL_AXIS) * vocab_local
    local_label = jnp.clip(labels - off, 0, vocab_local - 1)
    in_range = jnp.logical_and(labels >= off, labels < off + vocab_local)
    picked = jnp.take_along_axis(logits_l, local_label[..., None], axis=-1)[..., 0]
    label_logit = lax.psum(jnp.where(in_range, picked, 0.0), MODEL_AXIS)
    return jnp.sum(lse - label_logit)


def local_loss(params, tokens, labels, cfg, sp, tp, comm=None):
    """Sum (not mean) of CE over the LOCAL token shard — the reduction across
    data/seq shards belongs to the MLSL gradient requests. Owns the LM head:
    replicated (dense softmax) or model-axis vocab-sharded (pmax/psum CE, full-V
    logits never materialize). Returns (ce_sum, aux)."""
    h, aux = forward_local(params, tokens, cfg, sp, tp, comm=comm)
    head = params["final"]["head"].astype(jnp.float32)
    if cfg.sharded_vocab and tp > 1:
        return _sharded_vocab_ce(h, head, labels, head.shape[-1]), aux
    logp = jax.nn.log_softmax(h @ head)
    ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return jnp.sum(ce), aux


# -- decode mode (mlsl_tpu.serve): prefill + paged single-token steps ---------
#
# The serving engine (serve/engine.py) compiles these bodies as model-axis
# shard_map programs (dp = sp = 1): a per-sequence prefill, and the batched
# decode step over the paged KV pool. KV pages shard over 'model' on the
# heads dim (the wqkv spec); TP output reductions route through the
# collective engine's selection table (algos.inline_allreduce) when a
# (model group, config) pair is passed, so the µs-class decode allreduces
# are pallas_rhd-eligible and breaker degradation to lax stays intact.
#
# Bit-exactness contract (tests/test_serve.py): attention math runs in f32
# over f32-at-rest KV in BOTH paths, and the engine pins the paged decode's
# gathered-context extent (max_pages * page_elems) to the prefill length, so
# every reduction has the same extent in both programs — masked-out page
# slots contribute exact float zeros and the paged step reproduces the
# unpaged full-context forward bit for bit.


def _decode_reduce(x, tp: int, comm):
    """TP output reduction for the decode path: selection-table routed when
    a (model group, config) pair is supplied, lax baseline otherwise."""
    if tp <= 1:
        return x
    if comm is not None:
        from mlsl_tpu.comm import algos

        return algos.inline_allreduce(
            x, MODEL_AXIS, group=comm[0], config=comm[1]
        )
    return lax.psum(x, MODEL_AXIS)


def _causal_attn_f32(q, k, v, scale):
    """Plain causal attention on one sequence (sp=1): (Hl, S, Dh) f32 ->
    (Hl, S, Dh) f32. The prefill twin of the decode step's masked softmax."""
    s = jnp.einsum("hsx,htx->hst", q * scale, k)
    n = q.shape[1]
    mask = jnp.tril(jnp.ones((n, n), bool))
    s = jnp.where(mask[None], s, -jnp.inf)
    return jnp.einsum("hst,htx->hsx", jax.nn.softmax(s, axis=-1), v)


def kv_block_quant(x):
    """Symmetric int8 over the trailing (head_dim) lane dim — the
    ops/quant_kernels blockwise-ref contract with block = head_dim, applied
    per (token, head) row. Returns (q int8, scales f32 without the lane
    dim); dequantize is ``q * scales[..., None]`` (the dequantize oracle
    tests/test_serve.py pins against)."""
    amax = jnp.max(jnp.abs(x), axis=-1)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(x / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def prefill_local(params, tokens, length, cfg: TransformerConfig, tp: int,
                  comm=None, dtype=None):
    """Decode-mode prefill over one sequence (call inside shard_map).

    tokens: (S,) int32, padded past ``length`` with any value — padded
    positions' K/V are computed but land on the KV cache's reserved garbage
    page (serve/kv_cache.py) and are masked out of every decode read.
    Returns (next-token logits (V,) f32 read at position length-1,
    k, v: (n_blocks, S, Hl, Dh) f32 local head shards).
    """
    mlsl_assert(cfg.n_experts == 0, "decode mode serves dense-MLP models")
    mlsl_assert(not cfg.sharded_vocab,
                "decode mode serves a replicated LM head")
    cdt = jnp.dtype(dtype or cfg.dtype)
    emb = params["embed"]
    n = tokens.shape[0]
    h = (emb["tok"][tokens] + emb["pos"][:n]).astype(cdt)
    scale = 1.0 / float(np.sqrt(cfg.head_dim))
    ks, vs = [], []
    for i in range(cfg.n_blocks):
        lnp = params[f"blk{i}.ln"]
        ap = params[f"blk{i}.attn"]
        mp = params[f"blk{i}.mlp"]
        a = _ln(h.astype(jnp.float32),
                lnp["ln1_scale"], lnp["ln1_bias"]).astype(cdt)
        qkv = jnp.einsum("sd,dchx->cshx", a, ap["wqkv"].astype(cdt))
        q, k, v = (
            jnp.moveaxis(qkv[c], 1, 0).astype(jnp.float32) for c in range(3)
        )  # (Hl, S, Dh) f32 — the at-rest KV dtype
        ks.append(jnp.moveaxis(k, 0, 1))   # (S, Hl, Dh): page layout
        vs.append(jnp.moveaxis(v, 0, 1))
        attn = _causal_attn_f32(q, k, v, scale)
        o = mxu_einsum("hsx,hxd->sd", attn.astype(cdt), ap["wo"].astype(cdt))
        o = _decode_reduce(o, tp, comm)
        h = (h.astype(jnp.float32) + o).astype(cdt)

        a = _ln(h.astype(jnp.float32),
                lnp["ln2_scale"], lnp["ln2_bias"]).astype(cdt)
        f = jax.nn.gelu(
            jnp.einsum("sd,df->sf", a, mp["w1"].astype(cdt))
            + mp["b1"].astype(cdt)
        )
        o = mxu_einsum("sf,fd->sd", f, mp["w2"].astype(cdt))
        o = _decode_reduce(o, tp, comm)
        h = (h.astype(jnp.float32) + o + mp["b2"]).astype(cdt)

    fin = params["final"]
    h = _ln(h.astype(jnp.float32), fin["ln_scale"], fin["ln_bias"])
    last = lax.dynamic_slice_in_dim(h, length - 1, 1, axis=0)[0]
    logits = last @ fin["head"].astype(jnp.float32)
    return logits, jnp.stack(ks), jnp.stack(vs)


def decode_local(params, tokens, positions, pt, kpool, vpool,
                 cfg: TransformerConfig, tp: int, comm=None, dtype=None,
                 kscale=None, vscale=None):
    """One continuous-batching decode step (call inside shard_map).

    tokens: (B,) int32 the token each slot feeds; positions: (B,) int32 the
    index that token occupies (its K/V is written there, and it attends over
    indices <= it); pt: (B, M) int32 page tables (0 = the reserved garbage
    page — inactive slots carry all-zero tables and positions and their
    writes land there); kpool/vpool: (n_blocks, Np, page, Hl, Dh) KV pools,
    int8 with kscale/vscale (n_blocks, Np, page, Hl) for the quantized
    variant (kv_block_quant codec). Returns (logits (B, V) f32, kpool,
    vpool[, kscale, vscale]) — the engine donates the pools.
    """
    mlsl_assert(cfg.n_experts == 0, "decode mode serves dense-MLP models")
    mlsl_assert(not cfg.sharded_vocab,
                "decode mode serves a replicated LM head")
    cdt = jnp.dtype(dtype or cfg.dtype)
    quant = kscale is not None
    page = kpool.shape[2]
    t_ctx = pt.shape[1] * page
    emb = params["embed"]
    h = (emb["tok"][tokens] + emb["pos"][positions]).astype(cdt)  # (B, dm)
    scale = 1.0 / float(np.sqrt(cfg.head_dim))
    b = tokens.shape[0]
    pages_b = jnp.take_along_axis(
        pt, (positions // page)[:, None], axis=1
    )[:, 0]                                                       # (B,)
    offs_b = positions % page
    mask = jnp.arange(t_ctx)[None, :] <= positions[:, None]       # (B, T)
    for i in range(cfg.n_blocks):
        lnp = params[f"blk{i}.ln"]
        ap = params[f"blk{i}.attn"]
        mp = params[f"blk{i}.mlp"]
        a = _ln(h.astype(jnp.float32),
                lnp["ln1_scale"], lnp["ln1_bias"]).astype(cdt)
        qkv = jnp.einsum("bd,dchx->bchx", a, ap["wqkv"].astype(cdt))
        q = qkv[:, 0].astype(jnp.float32)                         # (B, Hl, Dh)
        knew = qkv[:, 1].astype(jnp.float32)
        vnew = qkv[:, 2].astype(jnp.float32)
        if quant:
            kq, ksc = kv_block_quant(knew)
            vq, vsc = kv_block_quant(vnew)
            kpool = kpool.at[i, pages_b, offs_b].set(kq)
            vpool = vpool.at[i, pages_b, offs_b].set(vq)
            kscale = kscale.at[i, pages_b, offs_b].set(ksc)
            vscale = vscale.at[i, pages_b, offs_b].set(vsc)
            kseq = kpool[i][pt].astype(jnp.float32) \
                * kscale[i][pt][..., None]
            vseq = vpool[i][pt].astype(jnp.float32) \
                * vscale[i][pt][..., None]
        else:
            kpool = kpool.at[i, pages_b, offs_b].set(knew)
            vpool = vpool.at[i, pages_b, offs_b].set(vnew)
            kseq = kpool[i][pt]                 # (B, M, page, Hl, Dh)
            vseq = vpool[i][pt]
        kseq = kseq.reshape(b, t_ctx, *kseq.shape[-2:])           # (B, T, Hl, Dh)
        vseq = vseq.reshape(b, t_ctx, *vseq.shape[-2:])
        s = jnp.einsum("bhx,bthx->bht", q * scale, kseq)
        s = jnp.where(mask[:, None, :], s, -jnp.inf)
        attn = jnp.einsum(
            "bht,bthx->bhx", jax.nn.softmax(s, axis=-1), vseq
        )                                                         # (B, Hl, Dh)
        o = mxu_einsum("bhx,hxd->bd", attn.astype(cdt), ap["wo"].astype(cdt))
        o = _decode_reduce(o, tp, comm)
        h = (h.astype(jnp.float32) + o).astype(cdt)

        a = _ln(h.astype(jnp.float32),
                lnp["ln2_scale"], lnp["ln2_bias"]).astype(cdt)
        f = jax.nn.gelu(
            jnp.einsum("bd,df->bf", a, mp["w1"].astype(cdt))
            + mp["b1"].astype(cdt)
        )
        o = mxu_einsum("bf,fd->bd", f, mp["w2"].astype(cdt))
        o = _decode_reduce(o, tp, comm)
        h = (h.astype(jnp.float32) + o + mp["b2"]).astype(cdt)

    fin = params["final"]
    h = _ln(h.astype(jnp.float32), fin["ln_scale"], fin["ln_bias"])
    logits = h @ fin["head"].astype(jnp.float32)
    if quant:
        return logits, kpool, vpool, kscale, vscale
    return logits, kpool, vpool


class HybridTrainer:
    """dp x sp x tp training with per-layer MLSL gradient sync over data x seq."""

    def __init__(self, env, cfg: TransformerConfig, dp: int, sp: int, tp: int,
                 batch: int = None, lr: float = 0.1, seed: int = 0,
                 distributed_update: bool = False,
                 compression=None,
                 devices=None,
                 optimizer=None,
                 donate_params: bool = True):
        """optimizer: optional optax.GradientTransformation; state lives per
        layer over each rank's flat local (TP-sharded) parameter vector, or the
        owned gradient shard under distributed_update (ZeRO-1). Elementwise/
        shard-local transforms only (adam, momentum, ...); params-consuming
        transforms see the flat local param vector on the plain path.

        donate_params: EVERY update path (fused no-comm, graph barrier
        update, optax update, ZeRO-1 increment apply) donates the parameter
        and optimizer-state buffers to XLA so the update is in-place in HBM —
        after step() returns, any EXTERNAL reference to the previous
        ``trainer.params`` tree points at deleted buffers (reading it raises).
        Pass donate_params=False to keep old param trees readable (e.g. EMA
        snapshots, debugging diffs) at the cost of double-buffering the
        weights."""
        self.env = env
        self.cfg = cfg
        self.dp, self.sp, self.tp = dp, sp, tp
        self.batch = batch if batch is not None else dp
        mlsl_assert(self.batch % dp == 0, "batch %d %% dp %d", self.batch, dp)
        self.lr = lr
        from mlsl_tpu.optim import ShardedAdafactor

        mlsl_assert(
            not isinstance(optimizer, ShardedAdafactor),
            "ShardedAdafactor's cross-shard factored stats are implemented for "
            "DataParallelTrainer's distributed update; pass "
            "optimizer.as_optax() to HybridTrainer (plain path only)",
        )
        self.optimizer = optimizer
        self.donate_params = bool(donate_params)
        self.dist = env.create_distribution(
            dp, tp, seq_parts=sp, devices=devices
        )
        mlsl_assert(
            self.dist.replica_count == 1,
            "device count must equal dp*sp*tp (got %d replicas)",
            self.dist.replica_count,
        )
        mlsl_assert(cfg.n_heads % tp == 0, "heads %d %% tp %d", cfg.n_heads, tp)
        mlsl_assert(cfg.seq_len % sp == 0, "seq %d %% sp %d", cfg.seq_len, sp)
        if cfg.sharded_vocab:
            mlsl_assert(
                cfg.vocab % tp == 0, "vocab %d %% tp %d (sharded head)",
                cfg.vocab, tp,
            )
        if cfg.n_experts > 0:
            local_tokens = (self.batch // dp) * (cfg.seq_len // sp)
            mlsl_assert(
                cfg.n_experts % tp == 0,
                "n_experts %d must be divisible by tp %d (experts shard over "
                "the model axis)", cfg.n_experts, tp,
            )
            mlsl_assert(
                local_tokens % tp == 0,
                "local token count %d (batch/dp * seq/sp) must be divisible by "
                "tp %d for expert-parallel routing", local_tokens, tp,
            )
        self.mesh = self.dist.topology.mesh
        self.session = env.create_session()
        self.session.set_global_minibatch_size(self.batch)

        self.specs = param_specs(cfg)
        params = init_params(jax.random.PRNGKey(seed), cfg)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params,
            self.specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.layers = layer_names(cfg)
        self._replicated = {
            name: all(s == P() for s in jax.tree.leaves(
                self.specs[name], is_leaf=lambda x: isinstance(x, P))
            )
            for name in self.layers
        }

        # local (per-device) flat size of each layer = Operation kernel count
        self.local_counts = {}
        for name in self.layers:
            n = 0
            for leaf, spec in zip(
                jax.tree.leaves(params[name]),
                jax.tree.leaves(self.specs[name], is_leaf=lambda x: isinstance(x, P)),
            ):
                size = int(np.prod(leaf.shape))
                for dim_spec, dim in zip(spec, leaf.shape):
                    if dim_spec == MODEL_AXIS:
                        size //= tp
                n += size
            self.local_counts[name] = n

        self.distributed_update = bool(distributed_update)
        comp = CompressionType(compression) if compression is not None else CompressionType.NONE
        self.ops = {}
        for name in self.layers:
            reg = self.session.create_operation_reg_info(OpType.CC)
            reg.set_name(name)
            reg.add_input(tp, 1)   # placeholder activations (graph comm is unused
            reg.add_output(tp, 1)  # here; grads flow through the parameter sets)
            # MLSL kernel counts are global: the ParameterSet partitions them over the
            # model group, recovering the per-device length local_counts[name]
            reg.add_parameter_set(
                self.local_counts[name] * tp, 1, DataType.FLOAT,
                distributed_update=self.distributed_update,
                compression_type=comp,
            )
            self.ops[name] = self.session.get_operation(
                self.session.add_operation(reg, self.dist)
            )
        self.session.commit()
        self.padded_counts = {
            name: self.ops[name].get_parameter_set(0).get_local_kernel_count()
            for name in self.layers
        }

        self._opt_state = None
        self._du_opt_state = None
        if optimizer is not None:
            topo = self.dist.topology
            if self.distributed_update:
                self._du_opt_state = {
                    n: init_shard_opt_state(
                        topo, optimizer,
                        self.ops[n].get_parameter_set(0).owned_kernel_count,
                    )
                    for n in self.layers
                }
            else:
                self._opt_state = {
                    n: init_shard_opt_state(topo, optimizer, self.local_counts[n])
                    for n in self.layers
                }

        self._grad_fn = self._build_grad_fn()
        self._update_fn = self._build_update_fn()
        self._du_inc_fn = self._build_du_inc_fn() if self.distributed_update else None
        self._du_apply_fn = (
            self._build_du_apply_fn() if self.distributed_update else None
        )
        # When no ParameterSet needs gradient comm (grad group of one: dp=sp=1;
        # TP-only grids qualify — TP grad psums live inside the loss body), fuse
        # loss+grad+update into ONE donated jit: skips the flatten/unflatten
        # round trip through per-layer buffers and lets XLA update params in
        # place — the same shortcut DataParallelTrainer takes.
        needs_comm = any(
            self.ops[n].get_parameter_set(0).need_comm for n in self.layers
        )
        self._needs_comm = needs_comm
        self._fused_fn = (
            self._build_fused_fn()
            if (not needs_comm and not self.distributed_update)
            else None
        )

    # -- compiled programs -------------------------------------------------

    def compiled_step(self, tokens, labels):
        """Lower+compile the fused train step for (tokens, labels) and return
        the jax Compiled object (cost_analysis, memory_analysis, as_text) —
        the profiling surface for benchmarks. None on the per-layer graph
        path, where the step is many programs, not one."""
        if self._fused_fn is None:
            return None
        if self.optimizer is None:
            return self._fused_fn.lower(self.params, tokens, labels).compile()
        return self._fused_fn.lower(
            self.params, self._opt_state, tokens, labels
        ).compile()

    def _token_spec(self):
        return P((DATA_AXIS,), (SEQ_AXIS,))

    def _scaled_loss_fn(self):
        """Per-device loss whose autodiff yields d(global CE sum)/d(local leaf).

        SPMD autodiff semantics: differentiating a per-device scalar seeds
        cotangent 1 on EVERY device, so the computed gradient is d(sum of all
        devices' losses)/d(local leaf). The CE loss is replicated over the model
        axis (logits are psum'd), so that sum counts the true loss tp times —
        scale it by 1/tp. The MoE aux loss is per-slice (DEVICE-VARYING over
        model), so the natural sum over model ranks is already the total. The
        synced gradient is later divided by batch*seq_len (the CE-mean
        normalizer); pre-scaling aux by tokens-per-slice makes the effective
        objective mean_CE + moe_aux_weight * mean_aux, independent of token
        count. Shared by the graph and fused paths — the two must not diverge.
        """
        cfg, sp, tp = self.cfg, self.sp, self.tp
        tokens_per_slice = (self.batch // self.dp) * (cfg.seq_len // self.sp) / tp
        aux_w = cfg.moe_aux_weight * tokens_per_slice

        # the model group + config thread the MoE alltoalls through the
        # selection table; the group is a static trace-time object, so the
        # choice is baked per compiled step like every engine decision
        comm = (self.dist.model_group, self.env.config) if self.tp > 1 else None

        def scaled_loss(p, t, l):
            ce, aux = local_loss(p, t, l, cfg, sp, tp, comm=comm)
            return ce / tp + aux_w * aux, ce

        return scaled_loss

    def _flat_opt_layer_update(self, params_sub, state_sub, flat_grad):
        """One layer's optax update on the rank's flat local parameter vector
        (shared by the graph update path and the fused path; the flat state
        layout keeps checkpoints interchangeable between them). Inputs are
        LOCAL (grid dims stripped); returns (new subtree, new local state)."""
        flat_p = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32)
             for l in jax.tree.leaves(params_sub)]
        )
        updates, ns = self.optimizer.update(flat_grad, state_sub, flat_p)
        new_sub = jax.tree.map(
            lambda p, uu: (p + uu).astype(p.dtype),
            params_sub,
            _unflatten_like(params_sub, updates),
        )
        return new_sub, ns

    def _build_grad_fn(self):
        cfg, sp, tp = self.cfg, self.sp, self.tp
        layers, padded = self.layers, self.padded_counts
        specs = self.specs
        scaled_loss = self._scaled_loss_fn()

        def body(params, tokens, labels):
            (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(
                params, tokens, labels
            )
            flat = {}
            for name in layers:
                parts = []
                leaf_specs = jax.tree.leaves(
                    specs[name], is_leaf=lambda x: isinstance(x, P)
                )
                for leaf, spec in zip(jax.tree.leaves(grads[name]), leaf_specs):
                    g = leaf.reshape(-1).astype(jnp.float32)
                    if tp > 1 and MODEL_AXIS not in spec:
                        g = lax.psum(g, MODEL_AXIS)
                    parts.append(g)
                g = jnp.concatenate(parts)
                flat[name] = jnp.pad(g, (0, padded[name] - g.shape[0]))[
                    None, None, None, None
                ]
            return loss[None, None, None, None, None], flat

        sm = smap(
            body,
            self.mesh,
            in_specs=(self.specs, self._token_spec(), self._token_spec()),
            out_specs=(_BUF_SPEC, {n: _BUF_SPEC for n in layers}),
            check=False,
        )
        return jax.jit(sm)

    def _build_update_fn(self):
        if self.optimizer is not None:
            return self._build_opt_update_fn()
        layers, lr = self.layers, self.lr
        counts = self.local_counts
        # synced grads are sums of d(CE sum)/dw over all data x seq shards; SGD on the
        # mean loss divides by the total token count
        norm = self.batch * self.cfg.seq_len

        def update(params, reduced):
            def body(params, *flat_grads):
                new = dict(params)
                for name, g in zip(layers, flat_grads):
                    g = g.reshape(-1)[: counts[name]] / norm
                    sub = params[name]
                    new[name] = jax.tree.map(
                        lambda p, gg: (p - lr * gg).astype(p.dtype),
                        sub,
                        _unflatten_like(sub, g),
                    )
                return new

            sm = smap(
                body,
                self.mesh,
                in_specs=(self.specs,) + tuple(_BUF_SPEC for _ in layers),
                out_specs=self.specs,
                check=False,
            )
            return sm(params, *[reduced[n] for n in layers])

        # donated params: in-place HBM update (same contract as the fused path)
        return jax.jit(
            update, donate_argnums=(0,) if self.donate_params else ()
        )

    def _build_fused_fn(self):
        """One donated jit: loss + grads (+ in-body TP psum for replicated
        leaves) + update, bypassing the per-layer buffer round trip. Optimizer
        state keeps the flat per-layer layout of _build_opt_update_fn, so
        checkpoints are interchangeable with the graph path."""

        cfg, sp, tp = self.cfg, self.sp, self.tp
        lr, layers, specs = self.lr, self.layers, self.specs
        norm = self.batch * cfg.seq_len
        optimizer = self.optimizer
        scaled_loss = self._scaled_loss_fn()

        def synced_layer_grads(params, grads, name):
            leaf_specs = jax.tree.leaves(
                specs[name], is_leaf=lambda x: isinstance(x, P)
            )
            out = []
            for leaf, spec in zip(jax.tree.leaves(grads[name]), leaf_specs):
                g = leaf.astype(jnp.float32)
                if tp > 1 and MODEL_AXIS not in spec:
                    g = lax.psum(g, MODEL_AXIS)
                out.append(g / norm)
            return out

        tok = self._token_spec()
        if optimizer is None:
            def body(params, tokens, labels):
                (_, loss), grads = jax.value_and_grad(
                    scaled_loss, has_aux=True
                )(params, tokens, labels)
                new = dict(params)
                for name in layers:
                    subl, treedef = jax.tree.flatten(params[name])
                    gl = synced_layer_grads(params, grads, name)
                    new[name] = jax.tree.unflatten(
                        treedef,
                        [(p - lr * g).astype(p.dtype) for p, g in zip(subl, gl)],
                    )
                return loss[None, None, None, None, None], new

            sm = smap(
                body, self.mesh,
                in_specs=(specs, tok, tok),
                out_specs=(_BUF_SPEC, specs),
                check=False,
            )
            return jax.jit(
                sm, donate_argnums=(0,) if self.donate_params else ()
            )

        def body(params, states, tokens, labels):
            (_, loss), grads = jax.value_and_grad(scaled_loss, has_aux=True)(
                params, tokens, labels
            )
            new, new_states = dict(params), {}
            grid1 = (1,) * NUM_GRID_AXES
            for name in layers:
                gl = jnp.concatenate(
                    [g.reshape(-1) for g in synced_layer_grads(params, grads, name)]
                )
                local = jax.tree.map(
                    lambda l: l.reshape(l.shape[NUM_GRID_AXES:]), states[name]
                )
                new[name], ns = self._flat_opt_layer_update(
                    params[name], local, gl
                )
                new_states[name] = jax.tree.map(
                    lambda l: l.reshape(grid1 + l.shape), ns
                )
            return loss[None, None, None, None, None], new, new_states

        state_specs = {
            n: jax.tree.map(_leaf_buf_spec, self._opt_state[n]) for n in layers
        }
        sm = smap(
            body, self.mesh,
            in_specs=(specs, state_specs, tok, tok),
            out_specs=(_BUF_SPEC, specs, state_specs),
            check=False,
        )
        return jax.jit(
            sm, donate_argnums=(0, 1) if self.donate_params else ()
        )

    def _build_opt_update_fn(self):
        """optax path: each layer's optimization variable is the rank's flat
        local (TP-sharded) parameter vector; state buffers mirror it."""
        layers, counts = self.layers, self.local_counts
        norm = self.batch * self.cfg.seq_len
        optimizer = self.optimizer

        def update(params, states, reduced):
            state_specs = {
                n: jax.tree.map(_leaf_buf_spec, states[n]) for n in layers
            }

            def body(params, states, *flat_grads):
                new, new_states = dict(params), {}
                grid1 = (1,) * NUM_GRID_AXES
                for name, g in zip(layers, flat_grads):
                    gl = g.reshape(-1)[: counts[name]] / norm
                    local = jax.tree.map(
                        lambda l: l.reshape(l.shape[NUM_GRID_AXES:]), states[name]
                    )
                    new[name], ns = self._flat_opt_layer_update(
                        params[name], local, gl
                    )
                    new_states[name] = jax.tree.map(
                        lambda l: l.reshape(grid1 + l.shape), ns
                    )
                return new, new_states

            sm = smap(
                body,
                self.mesh,
                in_specs=(self.specs, state_specs)
                + tuple(_BUF_SPEC for _ in layers),
                out_specs=(self.specs, state_specs),
                check=False,
            )
            return sm(params, states, *[reduced[n] for n in layers])

        return jax.jit(
            update, donate_argnums=(0, 1) if self.donate_params else ()
        )

    def _build_du_inc_fn(self):
        """distributed update: owned-shard gradient -> owned-shard increment."""
        if self.optimizer is not None:
            return build_owned_opt_increment_fn(
                self.mesh, self.optimizer, self.batch * self.cfg.seq_len
            )
        return build_owned_increment_fn(
            self.mesh, self.lr, self.batch * self.cfg.seq_len
        )

    def _build_du_apply_fn(self):
        """Apply all-gathered increments: params += inc (per model shard)."""
        layers, counts = self.layers, self.local_counts

        def body(params, *flat_incs):
            new = dict(params)
            for name, inc in zip(layers, flat_incs):
                inc = inc.reshape(-1)[: counts[name]]
                sub = params[name]
                new[name] = jax.tree.map(
                    lambda p, dd: (p + dd).astype(p.dtype),
                    sub,
                    _unflatten_like(sub, inc),
                )
            return new

        sm = smap(
            body, self.mesh,
            in_specs=(self.specs,) + tuple(_BUF_SPEC for _ in layers),
            out_specs=self.specs,
            check=False,
        )
        jitted = jax.jit(
            sm, donate_argnums=(0,) if self.donate_params else ()
        )

        def apply(params, incs):
            return jitted(params, *[incs[n] for n in layers])

        return apply

    # -- step --------------------------------------------------------------

    def shard_tokens(self, tokens: np.ndarray, labels: np.ndarray):
        if self.cfg.attention == "zigzag" and self.sp > 1:
            # feed the sequence in zigzag order; CE is position-wise, so a
            # consistent (tokens, labels) permutation leaves the loss and the
            # parameter trajectory identical to the contiguous layout
            perm = zigzag_perm(tokens.shape[1], self.sp)
            tokens = np.asarray(tokens)[:, perm]
            labels = np.asarray(labels)[:, perm]
        sharding = NamedSharding(self.mesh, self._token_spec())
        return (
            jax.device_put(jnp.asarray(tokens), sharding),
            jax.device_put(jnp.asarray(labels), sharding),
        )

    def step_accum(self, batches):
        """Gradient accumulation: k local fwd/bwd passes over (tokens, labels)
        pairs, one gradient sync + update (Caffe iter_size pattern). The
        effective objective is the mean over all k micro-batches."""
        mlsl_assert(len(batches) >= 1, "step_accum needs at least one batch")
        if getattr(self, "_accum_fns", None) is None:
            def add(a, b):
                return jax.tree.map(jnp.add, a, b)

            def scale(tree, k):
                return jax.tree.map(lambda g: g / k, tree)

            self._accum_fns = (jax.jit(add), jax.jit(scale, static_argnums=1))
        add_fn, scale_fn = self._accum_fns
        total, loss_sum = None, None
        for tokens, labels in batches:
            loss, grads = self._grad_fn(self.params, tokens, labels)
            total = grads if total is None else add_fn(total, grads)
            loss_sum = loss if loss_sum is None else loss_sum + loss
        k = len(batches)
        return self._sync_and_update(scale_fn(total, k), loss_sum) / k

    def step(self, tokens, labels):
        if self._fused_fn is not None:
            if self.optimizer is None:
                loss, self.params = self._fused_fn(self.params, tokens, labels)
            else:
                loss, self.params, self._opt_state = self._fused_fn(
                    self.params, self._opt_state, tokens, labels
                )
            return jnp.sum(loss[:, :, :, 0]) / (self.batch * self.cfg.seq_len)
        loss, grads = self._grad_fn(self.params, tokens, labels)
        return self._sync_and_update(grads, loss)

    def _sync_and_update(self, grads, loss):
        for name in reversed(self.layers):
            self.ops[name].get_parameter_set(0).start_gradient_comm(grads[name])
        if self.distributed_update:
            # ZeRO-1: update only the owned shard, all-gather the increments
            incs = {}
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                owned = ps.wait_gradient_comm()
                src = grads[name] if owned is None else owned
                if self.optimizer is None:
                    inc = self._du_inc_fn(src)
                else:
                    inc, self._du_opt_state[name] = self._du_inc_fn(
                        src, self._du_opt_state[name]
                    )
                if owned is None:  # degenerate grad group: full local increment
                    incs[name] = inc
                else:
                    ps.start_increment_comm(inc)
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                inc = ps.wait_increment_comm()
                if inc is not None:
                    incs[name] = inc
            self.params = self._du_apply_fn(self.params, incs)
        else:
            reduced = {}
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                out = ps.wait_gradient_comm()
                reduced[name] = out if out is not None else grads[name]
            if self.optimizer is None:
                self.params = self._update_fn(self.params, reduced)
            else:
                self.params, self._opt_state = self._update_fn(
                    self.params, self._opt_state, reduced
                )
        # loss buffer holds per-(data,seq)-shard partial CE sums (replicated over the
        # model axis -> take slot 0); mean = total / (batch * seq_len)
        return jnp.sum(loss[:, :, :, 0]) / (self.batch * self.cfg.seq_len)
