"""Minimal MLP classifier — the small end-to-end test/dry-run model."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LAYERS = ["l1", "l2"]


def init(key, din=8, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "l1": {"w": jax.random.normal(k1, (din, dh)) * 0.3, "b": jnp.zeros((dh,))},
        "l2": {"w": jax.random.normal(k2, (dh, dout)) * 0.3, "b": jnp.zeros((dout,))},
    }


def loss_fn(params, batch):
    x, y = batch
    h = jnp.tanh(x @ params["l1"]["w"] + params["l1"]["b"])
    logits = h @ params["l2"]["w"] + params["l2"]["b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def get_layer(params, name):
    return params[name]
