"""Mixture-of-experts FFN with expert parallelism over the model axis.

Expert parallelism is the modern descendant of the reference's redistribution
machinery: tokens move to the device holding their expert and back — two AlltoAlls
over the model group (exactly the reference's case-4/5 AlltoAll redistribution,
src/mlsl_impl.cpp:203-226, applied per token instead of per feature block).

Switch-style top-1 routing (GShard dispatch algebra): each device routes its local
tokens, builds a capacity-bounded dispatch tensor, all_to_all's token buffers to the
expert owners, applies that device's expert FFNs, and returns the outputs for
gate-weighted combination. Tokens over capacity are dropped (the residual connection
carries them). Routing gradients flow through the gate probability (argmax is
non-differentiable by construction).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.comm import algos
from mlsl_tpu.log import mlsl_assert


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int, std=0.02) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "wg": jax.random.normal(k1, (d_model, n_experts)) * std,   # replicated
        "w1": jax.random.normal(k2, (n_experts, d_model, d_ff)) * std,  # sharded[0]
        "w2": jax.random.normal(k3, (n_experts, d_ff, d_model)) * std,  # sharded[0]
    }


def _route(x, wg, n_experts: int, capacity: int, top_k: int = 1):
    """-> (dispatch (T, E, C) f32, combine (T, E, C) f32, aux_loss scalar).

    top_k=1 is switch routing; top_k=2 is GShard-style with the two gate
    probabilities renormalized over the selected pair. Capacity positions are
    assigned choice-major (all first choices queue before any second choice,
    GShard's priority rule), so over-capacity drops hit second choices first."""
    logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = lax.top_k(probs, top_k)                          # (T, K)
    if top_k == 1:
        # switch routing: the RAW probability gates the output — renormalizing
        # would make the gate identically 1.0 and kill the router's task-loss
        # gradient (d(v/v)/dv == 0)
        gates = topv
    else:
        denom = jnp.sum(topv, axis=-1, keepdims=True)
        gates = topv / jnp.maximum(denom, 1e-9)                   # GShard renorm

    dispatch = jnp.zeros((x.shape[0], n_experts, capacity), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    # running per-expert queue length, carried across choices (choice-major)
    taken = jnp.zeros((n_experts,), jnp.float32)
    onehot_first = None
    for c in range(top_k):
        onehot = jax.nn.one_hot(topi[:, c], n_experts, dtype=jnp.float32)  # (T, E)
        if c == 0:
            onehot_first = onehot
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot + taken[None, :] * onehot
        keep = (pos < capacity).astype(jnp.float32) * onehot
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        d_c = keep[:, :, None] * slot
        dispatch = dispatch + d_c
        combine = combine + d_c * gates[:, c][:, None, None]
        taken = taken + jnp.sum(onehot, axis=0)
    # load-balancing auxiliary loss on the FIRST choice (switch/GShard convention)
    frac_tokens = jnp.mean(onehot_first, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def mxu_einsum(spec: str, a, b):
    """Einsum with f32 accumulation from (possibly) bf16 operands.

    On TPU this is the MXU-native contract (bf16 in, f32 out). The CPU backend
    cannot execute mixed bf16->f32 dots ("Unsupported element type for
    DotThunk"), so there the dot runs in the operand dtype and the result is
    cast — bf16 on CPU is a simulation path, not a precision contract."""
    if jax.default_backend() == "cpu":
        return jnp.einsum(spec, a, b).astype(jnp.float32)
    return jnp.einsum(spec, a, b, preferred_element_type=jnp.float32)


def _expert_ffn(buf, w1, w2, compute_dtype=jnp.float32):
    """buf: (..., El, C, D); w1: (El, D, F); w2: (El, F, D).

    With a bf16 compute_dtype the expert matmuls run bf16-in/f32-accumulate
    (MXU-native); dispatch, combine and the gate always stay f32 for routing
    stability."""
    cdt = jnp.dtype(compute_dtype)
    h = jax.nn.gelu(mxu_einsum("...ecd,edf->...ecf", buf.astype(cdt), w1.astype(cdt)))
    return mxu_einsum("...ecf,efd->...ecd", h.astype(cdt), w2.astype(cdt))


def moe_ffn(
    x: jax.Array,
    params: Dict,
    axis: str,
    ep: int,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    compute_dtype=jnp.float32,
    group=None,
    config=None,
) -> Tuple[jax.Array, jax.Array]:
    """SPMD MoE feed-forward (call inside shard_map over ``axis`` of size ep).

    x: (T, D) tokens REPLICATED over the expert axis (the transformer's post-psum
    residual stream). Each rank routes its 1/ep token slice, token buffers
    all_to_all to the expert owners, expert outputs return and combine, and an
    all-gather reassembles the replicated output — so routing, expert compute and
    capacity competition are all sharded over the ep axis.
    params['w1'/'w2']: this rank's expert shard (El = E/ep experts); 'wg'
    replicated. -> (out (T, D) f32 replicated, aux-loss scalar for this slice).

    ``group``/``config``: the expert-axis ProcessGroup and mlsl Config, when
    the caller has them (HybridTrainer threads its model group). With both,
    the dispatch/combine exchanges route through the collective engine's
    selection table (MLSL_ALGO > tuned profile > inline lax) — a forced or
    tuned ``pallas_a2a`` cell lowers them to the fused quantized alltoall
    kernel. Without, the lax baseline applies unchanged.
    """
    t, d = x.shape
    el = params["w1"].shape[0]
    n_experts = el * ep
    if ep == 1:
        return _moe_slice(x, params, n_experts, capacity_factor, top_k,
                          compute_dtype)

    mlsl_assert(
        t % ep == 0,
        "moe_ffn: token count %d not divisible by ep=%d (trailing tokens would be "
        "silently dropped)", t, ep,
    )
    me = lax.axis_index(axis)
    tl = t // ep
    xs = lax.dynamic_slice_in_dim(x, me * tl, tl, axis=0)         # (Tl, D) distinct
    capacity = max(1, int(tl * capacity_factor * top_k / n_experts))
    dispatch, combine, aux = _route(xs, params["wg"], n_experts, capacity, top_k)
    buf = jnp.einsum("tec,td->ecd", dispatch, xs.astype(jnp.float32))
    # Cast to the compute dtype BEFORE the wire: the experts downcast anyway, so
    # a bf16 dispatch alltoall moves half the bytes for identical inputs (the
    # return path stays f32 — combine consumes it in f32).
    buf = buf.reshape(ep, el, capacity, d).astype(compute_dtype)
    # expert dispatch/combine exchanges route through the collective engine
    # (comm/algos inline helpers): the engine owns the call site, so the
    # lint gate, stats attribution, and future tiered alltoall lowerings
    # all apply here without touching the routing math
    recv = algos.inline_alltoall(buf, axis, split_axis=0, concat_axis=0,
                                 group=group, config=config)
    y = _expert_ffn(recv, params["w1"], params["w2"], compute_dtype)  # (ep, El, C, D)
    back = algos.inline_alltoall(y, axis, split_axis=0, concat_axis=0,
                                 group=group, config=config)
    y_full = back.reshape(n_experts, capacity, d)
    out_slice = jnp.einsum("tec,ecd->td", combine, y_full)         # (Tl, D)
    out = algos.inline_allgather(out_slice, axis, gather_axis=0,
                                 tiled=True)                       # (T, D)
    return out, aux


def _moe_slice(xs, params, n_experts: int, capacity_factor: float, top_k: int = 1,
               compute_dtype=jnp.float32):
    capacity = max(1, int(xs.shape[0] * capacity_factor * top_k / n_experts))
    dispatch, combine, aux = _route(xs, params["wg"], n_experts, capacity, top_k)
    buf = jnp.einsum("tec,td->ecd", dispatch, xs.astype(jnp.float32))
    y = _expert_ffn(buf, params["w1"], params["w2"], compute_dtype)
    return jnp.einsum("tec,ecd->td", combine, y), aux


def moe_ffn_dense(x, wg, w1, w2, ep: int = 1, capacity_factor: float = 1.25,
                  top_k: int = 1):
    """Single-device oracle reproducing the sharded semantics: tokens are routed in
    ep independent slices (capacity competition is per slice). w1: (E, D, F)."""
    t, d = x.shape
    e = w1.shape[0]
    params = {"wg": wg, "w1": w1, "w2": w2}
    outs, auxes = [], []
    tl = t // ep
    for s in range(ep):
        o, a = _moe_slice(x[s * tl : (s + 1) * tl], params, e, capacity_factor, top_k)
        outs.append(o)
        auxes.append(a)
    return jnp.concatenate(outs, axis=0), jnp.stack(auxes).mean()
