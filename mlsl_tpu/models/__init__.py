"""Model zoo: pure-JAX models whose training drives the Session/Operation graph."""
