"""ResNet-50 in pure JAX (no flax/haiku) — the flagship benchmark model.

The reference's headline workload is Caffe ResNet-50 data-parallel training with
per-layer gradient sync through the Session/Operation graph (BASELINE.json config 5).
This is a from-scratch TPU-idiomatic implementation: NHWC layout (TPU-native),
bfloat16 activations with float32 params, lax.conv_general_dilated on the MXU, and a
flat per-layer parameter list that maps 1:1 onto MLSL Operations.

Train-mode batch norm computes batch statistics on the local shard (per-device BN, the
standard data-parallel practice; the reference likewise keeps BN local to each worker).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Params = Dict[str, Any]

STAGES = (3, 4, 6, 3)          # ResNet-50 bottleneck counts
WIDTHS = (256, 512, 1024, 2048)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    std = np.sqrt(2.0 / fan_in)
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * std


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}


def init_resnet50(key, num_classes: int = 1000) -> Params:
    keys = iter(jax.random.split(key, 128))
    params: Params = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, 64), "bn": _bn_init(64)}}
    cin = 64
    for si, (blocks, width) in enumerate(zip(STAGES, WIDTHS)):
        mid = width // 4
        stage = []
        for bi in range(blocks):
            block = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid),
                "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid),
                "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, width),
                "bn3": _bn_init(width),
            }
            if bi == 0:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, width)
                block["bn_proj"] = _bn_init(width)
            stage.append(block)
            cin = width
        params[f"stage{si}"] = stage
    params["fc"] = {
        "w": jax.random.normal(next(keys), (2048, num_classes), jnp.float32) * 0.01,
        "b": jnp.zeros((num_classes,), jnp.float32),
    }
    return params


def _bn(x, p, eps=1e-5):
    # Folded BN, one-pass statistics: mean and E[x^2] accumulate in f32 off
    # the bf16 input in a SINGLE read of the activation (XLA fuses both
    # reductions into one convert_reduce pass). The centered two-pass form
    # read every activation twice — BN-stat traffic dominated the profiled
    # step (benchmarks/profile_step.py: 19.7 ms of 50.5 at batch 128 on v5e);
    # one-pass cut the measured train step 58.8 -> 49.2 ms. E[x^2]-E[x]^2 can
    # cancel to a small negative on near-constant channels, so the variance
    # is clamped at 0 — normalization then degrades to rsqrt(eps)-scaling,
    # exactly what true-variance BN does on such channels (flax BatchNorm's
    # use_fast_variance default takes the same trade). Normalization folds
    # into per-channel (a, b) so the apply is one fused multiply-add; output
    # returns to the compute dtype so downstream convs stay on the MXU's
    # bf16 path.
    mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
    msq = jnp.mean(lax.square(x.astype(jnp.float32)), axis=(0, 1, 2))
    var = jnp.maximum(msq - lax.square(mean), 0.0)
    a = lax.rsqrt(var + eps) * p["scale"]
    b = p["bias"] - mean * a
    return (x * a + b).astype(x.dtype)


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _stem_conv(x, w):
    """7x7-stride-2 'SAME' stem conv, optionally in space-to-depth form.

    The direct form contracts over 7*7*3 = 147 input taps — poor MXU lane
    utilization at 3 input channels (MLPerf ResNet submissions on TPU use
    the same space-to-depth rewrite). With MLSL_RESNET_S2D=1 the input is
    rearranged to (H/2, W/2, 12) 2x2 phases and the kernel zero-padded to
    8x8 and resampled into 2x2 phases of 4x4x12, giving a stride-1 conv
    with identical outputs for even H, W:
        y[i,j] = sum_u x[2i+u-2] w[u]   (u in [0,7), SAME pad (2,3))
      = sum_{k,a} x2[i+k-1, a] w[2k+a]  (k in [0,4), a in {0,1}, pad (1,2))
    Parameters stay in the canonical (7,7,3,64) shape — the rewrite is a
    trace-time reparametrization, so checkpoints and grad sync see the
    same tree either way.
    """
    n, h, wd, c = x.shape
    if not _use_s2d_stem() or h % 2 or wd % 2:
        return _conv(x, w, stride=2)
    x2 = x.reshape(n, h // 2, 2, wd // 2, 2, c)
    x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, wd // 2, 4 * c)
    wp = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    kh, kw, cin, co = wp.shape
    w2 = wp.reshape(kh // 2, 2, kw // 2, 2, cin, co)
    w2 = w2.transpose(0, 2, 1, 3, 4, 5).reshape(kh // 2, kw // 2, 4 * cin, co)
    return lax.conv_general_dilated(
        x2,
        w2.astype(x.dtype),
        window_strides=(1, 1),
        padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _use_s2d_stem() -> bool:
    """MLSL_RESNET_S2D: '1' forces the space-to-depth stem, '0' forces the
    direct conv; unset defaults to on for TPU backends (measured on v5e at
    batch 256: median MFU 0.2835 -> 0.287; identical math, pinned by
    test_s2d_stem_matches_direct_conv)."""
    import os

    v = os.environ.get("MLSL_RESNET_S2D", "").strip().lower()
    if v in ("0", "false", "off"):
        return False
    if v in ("1", "true", "on"):
        return True
    from mlsl_tpu.sysinfo import on_tpu

    return on_tpu()


def _bottleneck(x, block, stride):
    y = jax.nn.relu(_bn(_conv(x, block["conv1"]), block["bn1"]))
    y = jax.nn.relu(_bn(_conv(y, block["conv2"], stride), block["bn2"]))
    y = _bn(_conv(y, block["conv3"]), block["bn3"])
    if "proj" in block:
        x = _bn(_conv(x, block["proj"], stride), block["bn_proj"])
    return jax.nn.relu(x + y)


def apply_resnet50(params: Params, x: jax.Array) -> jax.Array:
    """x: (N, H, W, 3) -> logits (N, num_classes). Compute in bf16, params f32."""
    x = x.astype(jnp.bfloat16)
    x = _stem_conv(x, params["stem"]["conv"])
    x = jax.nn.relu(_bn(x, params["stem"]["bn"]))
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for si, blocks in enumerate(STAGES):
        stage = params[f"stage{si}"]
        for bi in range(blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            x = _bottleneck(x, stage[bi], stride)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # pool accumulates in f32
    return x @ params["fc"]["w"] + params["fc"]["b"]


def loss_fn(params: Params, batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
    x, labels = batch
    logits = apply_resnet50(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def layer_names(params: Params) -> List[str]:
    """Flat per-layer names in forward order — one MLSL Operation per entry."""
    names = ["stem"]
    for si, blocks in enumerate(STAGES):
        names += [f"stage{si}.{bi}" for bi in range(blocks)]
    names.append("fc")
    return names


def layer_param_counts(params: Params) -> Dict[str, int]:
    """name -> total parameter element count (the Operation's kernel count)."""
    counts = {}
    counts["stem"] = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params["stem"]))
    for si, blocks in enumerate(STAGES):
        for bi in range(blocks):
            counts[f"stage{si}.{bi}"] = sum(
                int(np.prod(l.shape)) for l in jax.tree.leaves(params[f"stage{si}"][bi])
            )
    counts["fc"] = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params["fc"]))
    return counts


def layer_subtree(params: Params, name: str):
    if name in ("stem", "fc"):
        return params[name]
    stage, block = name.split(".")
    return params[stage][int(block)]
