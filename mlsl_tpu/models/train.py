"""MLSL-driven data-parallel training: the Session/Operation graph in the loop.

This is the BASELINE config-5 workload shape (Caffe ResNet-50 per-layer grad sync,
reference canonical loop tests/examples/mlsl_test/mlsl_test.cpp:660-698) done the TPU
way:

- one jitted shard_map computes *local* (unsynced) gradients per device — the analog of
  each MPI rank's backprop producing local gradients;
- each model layer is an MLSL Operation whose ParameterSet carries the gradient
  collective; StartGradientComm is issued per layer in reverse (backprop) order so the
  newest-first priority scheduler sees the same stream the reference's eplib does;
- WaitGradientComm + a jitted update apply SGD, with the distributed-update
  (ReduceScatter / local update / AllGather-increment) path supported per layer.

Gradients cross the framework boundary as distributed buffers (R, D, S, M, count): the
device-local flattened layer gradient is the shard — no host round-trips in the loop.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mlsl_tpu import chaos
from mlsl_tpu.comm.collectives import _BUF_SPEC
from mlsl_tpu.comm.mesh import (
    DATA_AXIS,
    GRID_AXES,
    NUM_GRID_AXES,
    SEQ_AXIS,
)
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.obs import metrics as obs_metrics
from mlsl_tpu.obs import tracer as obs_trace
from mlsl_tpu.types import CompressionType, DataType, OpType


from mlsl_tpu.comm.collectives import smap  # noqa: F401  (canonical home)


def _flatten_layer(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])


def build_owned_increment_fn(mesh, lr: float, norm: float, with_scale: bool = False):
    """Jitted fn: owned-shard gradient buffer -> owned-shard SGD increment
    (-lr * g / norm), shared by every distributed-update trainer. With
    with_scale the fn takes an extra replicated scalar multiplied into the
    gradient (global-norm clipping)."""

    def body(g, s):
        return (-lr * s * g.reshape(g.shape[NUM_GRID_AXES:]) / norm)[
            None, None, None, None
        ]

    if with_scale:
        def inc_s(g, s):
            return smap(
                body, mesh, in_specs=(_BUF_SPEC, P()), out_specs=_BUF_SPEC
            )(g, s)

        return jax.jit(inc_s)

    def inc(g):
        return smap(
            lambda g: body(g, 1.0), mesh, in_specs=_BUF_SPEC, out_specs=_BUF_SPEC
        )(g)

    return jax.jit(inc)


def build_owned_norm_fn(mesh, norm: float, grad_axes=(DATA_AXIS, SEQ_AXIS)):
    """Jitted fn: dict of owned-shard gradient buffers -> global L2 norm of the
    mean gradient (replicated scalar). Owned shards partition the parameters
    across the gradient group, so sq-sum locally + psum = the full norm — the
    cross-shard reduction ZeRO-1 global-norm clipping needs."""

    def gnorm(owned):
        names = sorted(owned)

        def body(*gs):
            local = sum(jnp.sum((g / norm) ** 2) for g in gs)
            # mlsl-lint: disable=A201 -- the global-norm reduction is part
            # of the clip math inside the compiled step, not a request
            return jnp.sqrt(jax.lax.psum(local, grad_axes))

        sm = smap(
            body, mesh,
            in_specs=tuple(_BUF_SPEC for _ in names),
            out_specs=P(),
            check=False,
        )
        return sm(*[owned[n] for n in names])

    return jax.jit(gnorm)


def _leaf_buf_spec(leaf) -> P:
    """PartitionSpec for a distributed buffer with arbitrary payload rank."""
    return P(*GRID_AXES, *([None] * (leaf.ndim - NUM_GRID_AXES)))


def _clip_scale(sq_norm, clip: float):
    """Scale factor applying an L2 gradient clip: min(1, clip / norm)."""
    return jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(sq_norm), 1e-12))


def init_shard_opt_state(topo, optimizer, count: int):
    """Optimizer state over a flat (count,) per-rank shard, as distributed
    buffers (scalar leaves ride as payload shape (1,))."""
    state = optimizer.init(jnp.zeros((count,), jnp.float32))
    grid = topo.grid_shape

    def bufferize(leaf):
        arr = np.asarray(leaf)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        return topo.shard_buffer(
            np.ascontiguousarray(np.broadcast_to(arr, grid + arr.shape))
        )

    return jax.tree.map(bufferize, state)


def build_owned_opt_increment_fn(mesh, optimizer, norm: float,
                                 with_scale: bool = False):
    """Jitted (owned-shard grad buffer, state buffers[, scale]) -> (increment
    buffer, new state buffers): the optax analog of build_owned_increment_fn.
    The transform sees each rank's flat (owned,) shard, so only elementwise/
    shard-local transforms are correct here (see DataParallelTrainer)."""

    def body(g, state, s):
        gl = s * g.reshape(g.shape[NUM_GRID_AXES:]) / norm
        local = jax.tree.map(
            lambda l: l.reshape(l.shape[NUM_GRID_AXES:]), state
        )
        updates, new_state = optimizer.update(gl, local)
        grid1 = (1,) * NUM_GRID_AXES
        return (
            updates.reshape(grid1 + updates.shape),
            jax.tree.map(lambda l: l.reshape(grid1 + l.shape), new_state),
        )

    if with_scale:
        def inc_s(g, state, s):
            state_specs = jax.tree.map(_leaf_buf_spec, state)
            sm = smap(
                body, mesh,
                in_specs=(_BUF_SPEC, state_specs, P()),
                out_specs=(_BUF_SPEC, state_specs),
                check=False,
            )
            return sm(g, state, s)

        return jax.jit(inc_s)

    def inc(g, state):
        state_specs = jax.tree.map(_leaf_buf_spec, state)
        sm = smap(
            lambda g, st: body(g, st, 1.0), mesh,
            in_specs=(_BUF_SPEC, state_specs),
            out_specs=(_BUF_SPEC, state_specs),
            check=False,
        )
        return sm(g, state)

    return jax.jit(inc)


def build_local_grads(loss_fn, layers, get_layer, padded):
    """The local-gradient core shared by the host ``_grad_fn`` and the
    compiled overlap engine's fused program: ``(params, x, y) -> (scalar
    loss, {layer: padded flat grad})`` on already-squeezed local shards.
    ONE implementation on purpose — the flatten/pad policy is what the
    compiled-vs-host lockstep parity pins, so it must never diverge."""

    def local_grads(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, (x, y))
        flat = {}
        for name in layers:
            g = _flatten_layer(get_layer(grads, name))
            flat[name] = jnp.pad(g, (0, padded[name] - g.shape[0]))
        return loss, flat

    return local_grads


def _unflatten_like(tree, flat: jax.Array):
    leaves, treedef = jax.tree.flatten(tree)
    out, off = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[off : off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


class DataParallelTrainer:
    """Trains a model with per-layer MLSL gradient sync.

    model contract:
      params: pytree; loss_fn(params, batch) -> scalar;
      layers: ordered list of names; get_layer(params, name) -> subtree (its flattened
      size is the Operation's kernel count).

    Attribute contract: ``trainer.params`` is replaced every step, and the
    previous value's buffers are DONATED to XLA (in-place HBM update) on the
    fused, per-layer, and distributed-update paths — a reference held across a
    step() becomes unreadable. Snapshot with ``jax.device_get(trainer.params)``
    or construct with donate_params=False (the overlap_updates path never
    donates). Optimizer state follows the same donation contract.
    """

    def __init__(
        self,
        env,
        dist,
        session,
        params,
        loss_fn: Callable,
        layers: List[str],
        get_layer: Callable,
        distributed_update: bool = False,
        compression: CompressionType = CompressionType.NONE,
        lr: float = 0.05,
        donate_params: bool = True,
        overlap_updates: bool = False,
        overlap_compiled: Optional[bool] = None,
        force_graph_path: bool = False,
        optimizer=None,
        clip_global_norm: Optional[float] = None,
    ):
        """optimizer: an optax.GradientTransformation (e.g. optax.adam(lr)).
        None keeps the built-in SGD (p - lr * mean_grad). With
        distributed_update=True the optimizer state lives ONLY on each rank's
        owned gradient shard (ZeRO-1 proper: Adam moments sharded over the data
        group, reference owned-kernel math src/mlsl_impl.cpp:401-435). The
        sharded path runs the transform on each rank's flat (owned,) shard, so
        a black-box optax transform is correct only if it is elementwise/
        shard-local (adam, sgd with momentum, rmsprop, ...); params-consuming
        (weight decay) or shape-dependent black-box transforms would silently
        see per-shard views. The shape-dependent cases this framework supports
        cross-shard have dedicated implementations: pass
        mlsl_tpu.optim.ShardedAdafactor for factored-stats Adafactor under
        ZeRO-1, and clip_global_norm= (below) for global-norm clipping.

        overlap_compiled: arm the compiled overlap engine (comm/overlap.py;
        None = the MLSL_OVERLAP_COMPILED config default): ONE single-dispatch
        donation-enabled step program with every layer's gradient collective
        emitted in-graph, newest-first, staged over MLSL_OVERLAP_STAGES unit
        starts — XLA's latency-hiding scheduler overlaps the comm instead of
        the host Start/Wait loop. SGD only (an optax optimizer, ZeRO-1, or
        overlap_updates impose their own schedules — asserted when requested
        explicitly); TOPK/custom-codec/color-group graphs fall back to the
        host path, which stays the default and the parity oracle
        (tests/test_overlap_compiled.py). With the sentinel quality gate
        armed the engine runs the two-program split (grad program + one
        compiled comm/update program) so the gate keeps its host-side
        gradient boundary.

        clip_global_norm: clip the (mean) gradient to this global L2 norm
        BEFORE the optimizer — on every path, including ZeRO-1, where the norm
        is assembled from per-rank owned-shard partials via a psum over the
        gradient group (the cross-shard reduction a black-box optax
        clip_by_global_norm cannot perform there)."""
        from mlsl_tpu.optim import ShardedAdafactor

        self.env = env
        self.dist = dist
        self.session = session
        self.loss_fn = loss_fn
        self.layers = layers
        self.get_layer = get_layer
        self.lr = lr
        self.optimizer = optimizer
        # ShardedAdafactor is a config marker: the plain/fused paths run its
        # optax equivalent; distributed update runs the cross-shard factored
        # implementation (mlsl_tpu/optim.py) with identical numerics.
        self._af_cfg = optimizer if isinstance(optimizer, ShardedAdafactor) else None
        self._optax_opt = (
            optimizer.as_optax() if self._af_cfg is not None else optimizer
        )
        self.clip_global_norm = clip_global_norm
        self.mesh = dist.topology.mesh
        mlsl_assert(
            not (optimizer is not None and overlap_updates),
            "overlap_updates is not supported with an optax optimizer "
            "(per-layer state slicing would impose its own schedule)",
        )
        # Normalizer must match the reduction group (grad_group = data x seq); this
        # trainer only shards the batch, so it requires seq_parts == 1 and the two
        # coincide (HybridTrainer handles sequence-parallel grids).
        mlsl_assert(
            dist.get_process_count_model() == 1
            and dist.replica_count == 1
            and dist.get_seq_parts() == 1,
            "DataParallelTrainer requires model=seq=1 and replica_count == 1 "
            "(got model=%d, seq=%d, replicas=%d)",
            dist.get_process_count_model(),
            dist.get_seq_parts(),
            dist.replica_count,
        )
        self.data_size = dist.get_process_count_data()

        # Register one Operation per layer (reference per-layer Caffe graph).
        self.ops = {}
        self.layer_counts = {}
        for name in layers:
            count = int(
                sum(np.prod(l.shape) for l in jax.tree.leaves(get_layer(params, name)))
            )
            self.layer_counts[name] = count
            reg = session.create_operation_reg_info(OpType.CC)
            reg.set_name(name)
            reg.add_input(1, 1)
            reg.add_output(1, 1)
            reg.add_parameter_set(
                count, 1, DataType.FLOAT,
                distributed_update=distributed_update,
                compression_type=compression,
            )
            self.ops[name] = session.get_operation(session.add_operation(reg, dist))
        session.commit()
        # distributed update pads the local kernel count so every data rank owns an
        # equal shard (reference src/mlsl_impl.cpp:403-405); grads buffers must match.
        self.padded_counts = {
            name: self.ops[name].get_parameter_set(0).get_local_kernel_count()
            for name in layers
        }

        # When Commit shows no parameter set needs communication (single data rank),
        # the per-layer Start/Wait structure buys nothing — fuse the entire step into
        # one XLA program (with donated, in-place-updated params) so the framework
        # beats a monolithic jit rather than matching it.
        needs_comm = any(
            self.ops[n].get_parameter_set(0).need_comm for n in layers
        )
        # Integrity sentinel (mlsl_tpu.sentinel): the step quality gate and
        # the cross-replica consistency audit, armed from Config
        # (MLSL_SENTINEL_*). Public: FaultTolerantLoop drives the audit
        # cadence and verified-checkpoint fingerprints through it.
        self.sentinel = None
        cfg = env.config
        if cfg is not None:
            from mlsl_tpu import sentinel as sentinel_mod

            if sentinel_mod.armed(cfg):
                self.sentinel = sentinel_mod.Sentinel.from_config(
                    cfg, self.mesh
                )
        # Straggler sentinel (obs/straggler.py): per-replica step-time skew
        # watch, armed from Config (MLSL_STRAGGLER_*). This process feeds
        # its own replica id; FaultTolerantLoop polls shed_candidate()
        # between steps and hands a confirmed straggler to the elastic
        # coordinator.
        self.straggler = None
        if cfg is not None:
            from mlsl_tpu.obs import straggler as straggler_mod

            if straggler_mod.armed(cfg):
                self.straggler = straggler_mod.StragglerSentinel(
                    skew=cfg.straggler_skew,
                    every=cfg.straggler_every,
                    sustain=cfg.straggler_sustain,
                    shed=cfg.straggler_shed,
                )
        # straggler attribution: the pod rank when a control plane is armed
        # (pod-wide peer medians need pod-unique replica ids — remote ranks'
        # samples arrive over heartbeat frames under THEIR rank), else
        # jax.process_index() as before
        from mlsl_tpu import control as control_mod

        self._replica_id = control_mod.replica_id(jax.process_index())
        self._gnorm_fn = None       # lazy telemetry grad-norm program
        self._stall_ms_seen = 0.0   # FEED stall total at the last sample
        # force_graph_path bypasses the fused shortcut so the per-layer
        # Start/Wait machinery can be measured even when no comm is needed
        # (bench.py times it against the fused program on one chip). An
        # armed quality gate does the same: the gate screens at the
        # gradient boundary, which the fused program never exposes.
        use_fused = (
            not needs_comm and not force_graph_path
            and not (self.sentinel is not None and self.sentinel.gate_armed)
        )
        self.donate_params = bool(donate_params)
        sharding = NamedSharding(self.mesh, P())
        # Donation happens on the fused and barrier-update paths; the
        # overlap_updates per-layer path never donates (but the fused shortcut
        # can still engage under overlap_updates on a no-comm grid). Make the
        # owning copy exactly when some donating program will consume
        # self.params — device_put alone can alias the caller's on-device
        # arrays, and donating an aliased buffer deletes the caller's tree.
        will_donate = donate_params and (use_fused or not overlap_updates)
        if not will_donate:
            self.params = jax.device_put(params, sharding)
        else:
            self.params = jax.tree.map(
                lambda x: jax.device_put(jnp.array(x, copy=True), sharding), params
            )
        # Optimizer state: replicated alongside the params on the plain path;
        # per-layer buffers over each rank's OWNED gradient shard under
        # distributed update (ZeRO-1: moments sharded over the data group).
        self._opt_state = None
        self._du_opt_state = None
        self._af_layouts = {}
        self._du_inc_fns = None
        self._needs_comm = needs_comm
        self._accum_fns = None
        self._du_norm_fn = None
        if optimizer is not None:
            if distributed_update and needs_comm:
                self._du_opt_state = {
                    n: self._init_owned_opt_state(n) for n in layers
                }
            else:
                # No gradient comm (single data rank, fused or forced graph
                # path): owned == full, replicated state drives the plain
                # update.
                self._opt_state = jax.device_put(
                    self._optax_opt.init(self.params), sharding
                )
        self._grad_fn = self._build_grad_fn()
        self._update_fn = self._build_update_fn()
        self._du_inc_fn = self._build_du_inc_fn() if distributed_update else None
        self._du_apply_fn = self._build_du_apply_fn() if distributed_update else None
        self.distributed_update = distributed_update
        self._fused_fn = self._build_fused_fn() if use_fused else None
        # Test-driven overlap (the reference's canonical loop polls
        # TestGradientComm and updates each layer as its collective lands,
        # tests/examples/mlsl_test/mlsl_test.cpp:660-698): per-layer jitted
        # updates dispatched on completion instead of one barrier-then-update.
        mlsl_assert(
            not (overlap_updates and distributed_update),
            "overlap_updates is not supported together with distributed_update "
            "(the increment all-gather imposes its own schedule)",
        )
        self.overlap_updates = overlap_updates
        self._layer_update_fns = (
            {n: self._build_layer_update_fn(n) for n in layers}
            if self.overlap_updates
            else None
        )
        # Compiled overlap engine (comm/overlap.py): the in-graph per-layer
        # comm schedule. Explicitly requesting it alongside a mode that
        # imposes its own schedule is a usage error; the env-armed default
        # (MLSL_OVERLAP_COMPILED=1) silently skips those graphs instead, so
        # one exported knob doesn't break unrelated trainers.
        if overlap_compiled:
            mlsl_assert(
                optimizer is None,
                "overlap_compiled is not supported with an optax optimizer "
                "(per-layer fused updates would impose their own state "
                "slicing)",
            )
            mlsl_assert(
                not distributed_update,
                "overlap_compiled is not supported with distributed_update "
                "(the increment all-gather imposes its own schedule)",
            )
            mlsl_assert(
                not overlap_updates,
                "overlap_compiled replaces overlap_updates (the schedule "
                "lives in the compiled program, not the host poll loop)",
            )
        want_overlap = (
            overlap_compiled if overlap_compiled is not None
            else bool(cfg is not None and cfg.overlap_compiled)
        )
        self._overlap = None
        if (
            want_overlap
            and optimizer is None
            and not distributed_update
            and not overlap_updates
            and self._fused_fn is None
        ):
            from mlsl_tpu.comm import overlap as overlap_mod

            # may return None (TOPK / custom codec / color groups ride the
            # host path)
            self._overlap = overlap_mod.engine_for_trainer(self, cfg)
        # monotonically increasing step() counter — trace spans
        # (mlsl_tpu.obs) carry it so a timeline row maps back to a step
        self._step_no = 0

    # -- compiled pieces ---------------------------------------------------

    def _init_owned_opt_state(self, name: str):
        """Optimizer state over this layer's owned shard (ZeRO-1)."""
        from mlsl_tpu import optim

        ps = self.ops[name].get_parameter_set(0)
        if self._af_cfg is not None:
            layout = optim.build_adafactor_layout(
                [tuple(l.shape)
                 for l in jax.tree.leaves(self.get_layer(self.params, name))],
                self.padded_counts[name],
                self.data_size,
                self._af_cfg.min_dim_size_to_factor,
            )
            self._af_layouts[name] = layout
            return optim.init_adafactor_state(
                self.dist.topology, layout, self._af_cfg, self.data_size
            )
        return init_shard_opt_state(
            self.dist.topology, self.optimizer, ps.owned_kernel_count
        )

    def _build_grad_fn(self):
        layers = self.layers
        core = build_local_grads(
            self.loss_fn, layers, self.get_layer, self.padded_counts
        )

        def local_grads(params, batch):
            # per-device: local-batch loss -> local grads (NO cross-device sync here;
            # the MLSL requests own the reduction)
            x, y = batch
            x = x.reshape(x.shape[NUM_GRID_AXES:])  # strip grid block dims
            y = y.reshape(y.shape[NUM_GRID_AXES:])
            loss, flat = core(params, x, y)
            return (
                loss[None, None, None, None, None],
                {n: g[None, None, None, None] for n, g in flat.items()},
            )

        sm = smap(
            local_grads,
            self.mesh,
            in_specs=(P(), (_BUF_SPEC, _BUF_SPEC)),
            out_specs=(_BUF_SPEC, {n: _BUF_SPEC for n in layers}),
            check=False,
        )
        return jax.jit(sm)

    def _build_update_fn(self):
        if self.optimizer is not None:
            return self._build_opt_update_fn()
        layers, get_layer = self.layers, self.get_layer
        data_size, lr = self.data_size, self.lr
        counts = self.layer_counts
        clip = self.clip_global_norm

        def update(params, reduced: Dict[str, jax.Array]):
            def body(params, *flat_grads):
                cscale = (
                    _clip_scale(
                        sum(
                            jnp.sum((g.reshape(-1)[: counts[n]] / data_size) ** 2)
                            for n, g in zip(layers, flat_grads)
                        ),
                        clip,
                    )
                    if clip is not None
                    else 1.0
                )
                new = params
                for name, g in zip(layers, flat_grads):
                    g = g.reshape(-1)[: counts[name]] / data_size * cscale
                    sub = get_layer(new, name)
                    new_sub = jax.tree.map(
                        lambda p, gg: p - lr * gg,
                        sub,
                        _unflatten_like(sub, g),
                    )
                    new = _set_layer(new, name, new_sub)
                return new

            sm = smap(
                body,
                self.mesh,
                in_specs=(P(),) + tuple(_BUF_SPEC for _ in layers),
                out_specs=P(),
                check=False,
            )
            return sm(params, *[reduced[n] for n in layers])

        # donated params: the update is in-place in HBM (same contract as the
        # fused path — see the class docstring)
        return jax.jit(
            update, donate_argnums=(0,) if self.donate_params else ()
        )

    def _build_opt_update_fn(self):
        """optax path: reduced per-layer gradient buffers -> (params, opt_state)."""
        import optax

        layers, get_layer = self.layers, self.get_layer
        data_size, counts = self.data_size, self.layer_counts
        optimizer = self._optax_opt
        clip = self.clip_global_norm

        def update(params, opt_state, reduced: Dict[str, jax.Array]):
            def body(params, opt_state, *flat_grads):
                grads = jax.tree.map(jnp.zeros_like, params)
                for name, g in zip(layers, flat_grads):
                    g = g.reshape(-1)[: counts[name]] / data_size
                    sub = get_layer(params, name)
                    grads = _set_layer(grads, name, _unflatten_like(sub, g))
                if clip is not None:
                    cscale = _clip_scale(
                        sum(jnp.sum(g ** 2) for g in jax.tree.leaves(grads)), clip
                    )
                    grads = jax.tree.map(lambda g: g * cscale, grads)
                updates, new_state = optimizer.update(grads, opt_state, params)
                # Apply only to registered layers: leaves outside `layers`
                # (frozen params) must stay untouched even under
                # params-consuming transforms like weight decay, matching the
                # SGD path's semantics.
                new_params = params
                for name in layers:
                    new_params = _set_layer(
                        new_params, name,
                        optax.apply_updates(
                            get_layer(params, name), get_layer(updates, name)
                        ),
                    )
                return new_params, new_state

            sm = smap(
                body,
                self.mesh,
                in_specs=(P(), P()) + tuple(_BUF_SPEC for _ in layers),
                out_specs=(P(), P()),
                check=False,
            )
            return sm(params, opt_state, *[reduced[n] for n in layers])

        return jax.jit(
            update, donate_argnums=(0, 1) if self.donate_params else ()
        )

    def _build_du_inc_fn(self):
        """distributed-update: owned-shard gradient -> owned-shard increment."""
        from mlsl_tpu import optim

        with_scale = self.clip_global_norm is not None
        if self.optimizer is None:
            return build_owned_increment_fn(
                self.mesh, self.lr, self.data_size, with_scale=with_scale
            )
        if self._af_cfg is not None:
            self._du_inc_fns = {
                name: optim.build_adafactor_inc_fn(
                    self.mesh,
                    self.dist.topology,
                    self._af_cfg,
                    self._af_layouts[name],
                    self.data_size,
                    with_scale=with_scale,
                )
                for name in self._af_layouts
            }
            return None
        return build_owned_opt_increment_fn(
            self.mesh, self.optimizer, self.data_size, with_scale=with_scale
        )

    def _build_du_apply_fn(self):
        layers, get_layer = self.layers, self.get_layer

        def apply(params, incs: Dict[str, jax.Array]):
            def body(params, *flat_incs):
                new = params
                for name, inc in zip(layers, flat_incs):
                    inc = inc.reshape(-1)[: self.layer_counts[name]]
                    sub = get_layer(new, name)
                    new_sub = jax.tree.map(
                        lambda p, dd: p + dd, sub, _unflatten_like(sub, inc)
                    )
                    new = _set_layer(new, name, new_sub)
                return new

            sm = smap(
                body,
                self.mesh,
                in_specs=(P(),) + tuple(_BUF_SPEC for _ in layers),
                out_specs=P(),
                check=False,
            )
            return sm(params, *[incs[n] for n in layers])

        return jax.jit(
            apply, donate_argnums=(0,) if self.donate_params else ()
        )

    def _build_layer_update_fn(self, name: str):
        data_size, lr = self.data_size, self.lr
        count = self.layer_counts[name]

        def update_layer(sub, g):
            def body(sub, g):
                g = g.reshape(-1)[:count] / data_size
                return jax.tree.map(
                    lambda p, gg: p - lr * gg, sub, _unflatten_like(sub, g)
                )

            sm = smap(
                body, self.mesh, in_specs=(P(), _BUF_SPEC), out_specs=P(),
                check=False,
            )
            return sm(sub, g)

        return jax.jit(update_layer)

    def _build_fused_fn(self):
        loss_fn, lr = self.loss_fn, self.lr
        optimizer = self._optax_opt
        clip = self.clip_global_norm

        def _clipped(grads):
            if clip is None:
                return grads
            cscale = _clip_scale(
                sum(jnp.sum(g.astype(jnp.float32) ** 2)
                    for g in jax.tree.leaves(grads)), clip
            )
            return jax.tree.map(lambda g: g * cscale, grads)

        # Donating the params lets XLA update weights in place (the trainer owns
        # self.params and always replaces it) — halves parameter HBM traffic in the
        # optimizer tail, something a caller-owned raw-JAX step cannot safely do.
        if optimizer is None:
            @functools.partial(jax.jit, donate_argnums=(0,) if self.donate_params else ())
            def fused(params, batch):
                x, y = batch
                x = x.reshape(x.shape[NUM_GRID_AXES:])
                y = y.reshape(y.shape[NUM_GRID_AXES:])
                loss, grads = jax.value_and_grad(loss_fn)(params, (x, y))
                grads = _clipped(grads)
                return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)

            return fused

        import optax

        @functools.partial(jax.jit, donate_argnums=(0, 1) if self.donate_params else ())
        def fused_opt(params, opt_state, batch):
            x, y = batch
            x = x.reshape(x.shape[NUM_GRID_AXES:])
            y = y.reshape(y.shape[NUM_GRID_AXES:])
            loss, grads = jax.value_and_grad(loss_fn)(params, (x, y))
            updates, new_state = optimizer.update(_clipped(grads), opt_state, params)
            return loss, optax.apply_updates(params, updates), new_state

        return fused_opt

    # -- AOT warm-up (MLSL_PRECOMPILE) --------------------------------------

    def precompile(self, batch) -> None:
        """Warm every compiled program one step() dispatches, so step 0 of the
        timed loop contains no compilation: the session's collective plans
        (Session.precompile_collectives — also run automatically at Commit
        under MLSL_PRECOMPILE=1, and idempotent here) plus this trainer's
        model-side programs. Donating programs are exercised on copies — a
        donated warm argument must never consume the live params/opt state.
        ``batch`` is a shard_batch() result; its values are read, not trained
        on (params are unchanged afterwards)."""
        self.session.precompile_collectives()
        copy = lambda tree: jax.tree.map(jnp.copy, tree)
        if self._fused_fn is not None:
            # the fused step never dispatches _grad_fn — warming it here would
            # ADD a full-model compile to startup, the exact stall this exists
            # to remove
            if self.optimizer is None:
                out = self._fused_fn(copy(self.params), batch)
            else:
                out = self._fused_fn(copy(self.params), copy(self._opt_state), batch)
            jax.block_until_ready(out)
            return
        if self._overlap is not None:
            # The engine warms the program step() dispatches on donation-safe
            # copies: the fused single program, or (gate armed) _grad_fn +
            # the split sync program. A gate-unarmed step_accum still pays
            # its first-use sync-program compile — the same contract as the
            # host path, whose accum add/scale jits are likewise not warmed.
            self._overlap.precompile(batch)
            return
        loss, grads = self._grad_fn(self.params, batch)
        if self.overlap_updates:
            for name in self.layers:  # per-layer update fns never donate
                self._layer_update_fns[name](
                    self.get_layer(self.params, name), grads[name]
                )
        elif not (self.distributed_update and self._needs_comm):
            if self.optimizer is None:
                self._update_fn(copy(self.params), grads)
            else:
                self._update_fn(copy(self.params), copy(self._opt_state), grads)
        else:
            topo = self.dist.topology
            grid = topo.grid_shape
            owned = {
                name: topo.shard_buffer(np.zeros(
                    (*grid,
                     self.ops[name].get_parameter_set(0).owned_kernel_count
                     * self.ops[name].get_parameter_set(0).kernel_size),
                    np.float32,
                ))
                for name in self.layers
            }
            scale_args = ()
            if self.clip_global_norm is not None:
                if self._du_norm_fn is None:
                    self._du_norm_fn = build_owned_norm_fn(
                        self.mesh, self.data_size
                    )
                scale_args = (_clip_scale(
                    self._du_norm_fn(owned) ** 2, self.clip_global_norm
                ),)
            incs = {}
            for name in self.layers:
                if self.optimizer is None:
                    self._du_inc_fn(owned[name], *scale_args)
                elif self._du_inc_fns is not None:
                    self._du_inc_fns[name](
                        owned[name], copy(self._du_opt_state[name]),
                        self.get_layer(self.params, name), *scale_args
                    )
                else:
                    self._du_inc_fn(
                        owned[name], copy(self._du_opt_state[name]), *scale_args
                    )
                incs[name] = topo.shard_buffer(np.zeros(
                    (*grid, self.padded_counts[name]), np.float32
                ))
            self._du_apply_fn(copy(self.params), incs)
        jax.block_until_ready(loss)

    # -- data placement ----------------------------------------------------

    def shard_batch(self, x: np.ndarray, y: np.ndarray):
        """Global batch (B, ...) -> distributed buffers (R, D, S, M, localB, ...)."""
        topo = self.dist.topology
        r, d, s, m = topo.grid_shape
        local_b = x.shape[0] // (r * d)
        xs = np.broadcast_to(
            x.reshape(r, d, 1, 1, local_b, *x.shape[1:]),
            (r, d, s, m, local_b, *x.shape[1:]),
        )
        ys = np.broadcast_to(
            y.reshape(r, d, 1, 1, local_b, *y.shape[1:]),
            (r, d, s, m, local_b, *y.shape[1:]),
        )
        return topo.shard_buffer(xs), topo.shard_buffer(ys)

    def shard_batch_local(self, x: np.ndarray, y: np.ndarray):
        """Multi-host batch placement: x/y are THIS process's contiguous rows of
        the global batch (global_batch / process_count rows each); no host
        materializes the full batch. Requires the data-rank count (r*d) to be
        divisible by the process count with replica-major contiguity (r == 1 or
        process_count dividing r)."""
        topo = self.dist.topology
        r, d, s, m = topo.grid_shape
        nproc = jax.process_count()
        mlsl_assert(
            (r * d) % nproc == 0 and (r == 1 or r % nproc == 0),
            "data ranks (r=%d x d=%d) must split contiguously over %d processes",
            r, d, nproc,
        )
        rd_local = (r * d) // nproc
        local_b = x.shape[0] // rd_local
        r_loc = max(1, r // nproc)
        d_loc = rd_local // r_loc
        xs = np.broadcast_to(
            x.reshape(r_loc, d_loc, 1, 1, local_b, *x.shape[1:]),
            (r_loc, d_loc, s, m, local_b, *x.shape[1:]),
        )
        ys = np.broadcast_to(
            y.reshape(r_loc, d_loc, 1, 1, local_b, *y.shape[1:]),
            (r_loc, d_loc, s, m, local_b, *y.shape[1:]),
        )
        gx = (r, d, s, m, local_b, *x.shape[1:])
        gy = (r, d, s, m, local_b, *y.shape[1:])
        return topo.shard_buffer_local(xs, gx), topo.shard_buffer_local(ys, gy)

    def feed(self, source, *, depth: Optional[int] = None, **kw):
        """Build the wire-compressed prefetching device feed for this
        trainer's topology: an :class:`mlsl_tpu.data.AsyncLoader` over a
        :class:`mlsl_tpu.data.DeviceFeed` whose decoded batches are the SAME
        distributed buffers :meth:`shard_batch` produces — ``step`` consumes
        them unchanged, but batches cross the h2d link in the configured
        wire dtype and epoch replays can serve straight from the HBM cache.

        Defaults come from the environment's Config (``MLSL_FEED_*``,
        docs/TUNING.md §12); any DeviceFeed kwarg (wire, cache_mb, epochs,
        shuffle_seed, normalize, augment, ...) can be overridden here.
        Remember to ``close()`` the returned loader."""
        from mlsl_tpu.data import AsyncLoader, DeviceFeed

        cfg = self.env.config
        kw.setdefault("wire", cfg.feed_wire_dtype if cfg else None)
        kw.setdefault("cache_mb", cfg.feed_cache_mb if cfg else None)
        kw.setdefault("retries", cfg.feed_retries if cfg else None)
        kw.setdefault("quant_block", cfg.quant_block_elems if cfg else None)
        if depth is None:
            depth = cfg.feed_depth if cfg else None
        dev_feed = DeviceFeed(source, self.dist.topology, **kw)
        return AsyncLoader(dev_feed, depth=depth)

    # -- silent-corruption chaos sites + the sentinel quality gate ---------

    def _chaos_state_sites(self) -> None:
        """``train.params`` / ``train.opt_state`` silent-corruption sites:
        a fired ``silent`` plan flips/perturbs ONE replica's copy of live
        state without raising (sentinel.corrupt_silent) — the SDC class only
        the consistency audit can catch. Called at step entry."""
        from mlsl_tpu import sentinel as sentinel_mod

        p = chaos.inject("train.params", step=self._step_no)
        if p is not None and p.kind == "silent":
            self.params = sentinel_mod.corrupt_silent(self.params, p)
        if self._opt_state is not None or self._du_opt_state:
            # only consult the site when there IS state to corrupt: firing
            # (and burning a plan's xN budget) against a stateless SGD
            # trainer would make a soak's "every fire was detected"
            # accounting vacuous
            p = chaos.inject("train.opt_state", step=self._step_no)
            if p is not None and p.kind == "silent":
                if self._opt_state is not None:
                    self._opt_state = sentinel_mod.corrupt_silent(
                        self._opt_state, p
                    )
                else:
                    name = sorted(self._du_opt_state)[
                        chaos._rng.randrange(len(self._du_opt_state))
                    ]
                    self._du_opt_state[name] = sentinel_mod.corrupt_silent(
                        self._du_opt_state[name], p
                    )

    def _screen(self, loss, grads):
        """``train.grads`` silent site + the step quality gate, between the
        gradient program and any gradient comm. -> (grads, proceed): proceed
        False means the gate chose ``skip_step`` — the caller returns the
        loss without syncing or updating, so no comm starts, error-feedback
        residuals never advance, and the step behaves exactly as if it had
        not run (lockstep-twin parity, tests/test_sentinel.py)."""
        if chaos._plans:
            p = chaos.inject("train.grads", step=self._step_no)
            if p is not None and p.kind == "silent":
                from mlsl_tpu import sentinel as sentinel_mod

                grads = sentinel_mod.corrupt_silent(grads, p)
        if self.sentinel is not None and self.sentinel.gate_armed:
            if not self.sentinel.gate(loss, grads, self.params,
                                      self._step_no):
                return grads, False
        m = obs_metrics._registry
        if m is not None and self._step_no % m.every == 0:
            # telemetry cadence: the (local) gradient norm, recorded here
            # because only the host grad paths expose a gradient boundary
            self._record_grad_norm(m, grads)
        return grads, True

    # -- the training step (reference loop mlsl_test.cpp:660-698) ----------

    def step(self, batch) -> jax.Array:
        """One training step. With the telemetry plane disarmed this is a
        zero-overhead passthrough (two module/attr None-checks); armed, the
        step wall time feeds the ``mlsl_step_ms`` histogram and the
        straggler sentinel, and every ``MLSL_METRICS_EVERY`` steps the
        cadence tick samples loss/grad-norm/input-stall plus every counter
        family (``_sample_telemetry``)."""
        m = obs_metrics._registry
        if m is None and self.straggler is None:
            return self._step_impl(batch)
        t0 = time.perf_counter()
        loss = self._step_impl(batch)
        self._post_step_telemetry(m, loss, t0)
        return loss

    def step_accum(self, batches) -> jax.Array:
        m = obs_metrics._registry
        if m is None and self.straggler is None:
            return self._step_accum_impl(batches)
        t0 = time.perf_counter()
        loss = self._step_accum_impl(batches)
        self._post_step_telemetry(m, loss, t0)
        return loss

    def _post_step_telemetry(self, m, loss, t0: float) -> None:
        """Armed-path epilogue: step wall time into the histogram + the
        straggler feed, cadence tick every ``m.every`` steps."""
        step_ms = (time.perf_counter() - t0) * 1e3
        if m is not None:
            m.observe("mlsl_step_ms", step_ms)
            if self._step_no % m.every == 0:
                self._sample_telemetry(m, loss)
        strag = self.straggler
        if strag is not None:
            strag.observe(self._replica_id, step_ms)
            strag.maybe_audit(self._step_no)

    def _sample_telemetry(self, m, loss) -> None:
        """One cadence tick (``MLSL_METRICS_EVERY``): the scalars that cost
        a device sync or IO live here, NOT per step — loss readback (one
        host sync), the input-stall delta since the last tick, a gauge
        snapshot of every core/stats counter family, one timestamped sample
        per series, and the JSONL append."""
        try:
            # per-device loss buffers (the step's native shape) read back as
            # the device mean — the same scalar the examples log
            m.set("mlsl_loss", float(np.asarray(loss).mean()))
        except (TypeError, ValueError):  # non-numeric custom loss: skip
            pass
        from mlsl_tpu.core import stats as stats_mod

        stall = float(stats_mod.FEED_COUNTERS["stall_ms"])
        m.set("mlsl_input_stall_ms", max(0.0, stall - self._stall_ms_seen))
        self._stall_ms_seen = stall
        m.sample_families()
        m.write_jsonl(records=m.sample())

    def _record_grad_norm(self, m, grads) -> None:
        """Telemetry grad-norm at the cadence tick (host grad paths only —
        the fused/unsplit-overlap programs expose no gradient boundary).
        One small jitted program, built lazily on first use."""
        if self._gnorm_fn is None:
            def sq(tree):
                leaves = jax.tree.leaves(tree)
                return sum(jnp.sum(jnp.square(g)) for g in leaves)

            self._gnorm_fn = jax.jit(sq)
        try:
            m.set("mlsl_grad_norm",
                  float(jnp.sqrt(self._gnorm_fn(grads))))
        except (TypeError, ValueError):  # pragma: no cover - odd dtypes
            pass

    def _step_accum_impl(self, batches) -> jax.Array:
        """Gradient accumulation (the Caffe iter_size pattern the reference's
        per-layer sync was built around): k local fwd/bwd passes, ONE gradient
        sync + update. Each entry of ``batches`` is a shard_batch() result with
        the same local minibatch size; the effective loss is the mean over all
        k micro-batches. Returns the mean loss."""
        mlsl_assert(len(batches) >= 1, "step_accum needs at least one batch")
        self._step_no += 1
        if chaos._plans:
            self._chaos_state_sites()
        if self._accum_fns is None:
            def add(a, b):
                return jax.tree.map(jnp.add, a, b)

            def scale(tree, k):
                return jax.tree.map(lambda g: g / k, tree)

            self._accum_fns = (jax.jit(add), jax.jit(scale, static_argnums=1))
        add_fn, scale_fn = self._accum_fns
        tr = obs_trace._tracer
        t0 = tr.now() if tr is not None else 0
        total, loss_sum = None, None
        for b in batches:
            loss, grads = self._grad_fn(self.params, b)
            total = grads if total is None else add_fn(total, grads)
            loss_sum = loss if loss_sum is None else loss_sum + loss
        k = len(batches)
        if tr is not None:
            tr.complete("step.grad", "step", t0, step=self._step_no,
                        micro_batches=k)
        loss = loss_sum / k
        grads, proceed = self._screen(loss, scale_fn(total, k))
        if not proceed:
            return loss
        if self._overlap is not None:
            # accumulated grads ride the engine's split comm/update program
            # (one compiled dispatch for the whole sync, residuals threaded)
            self._overlap.step(None, grads=grads, loss=loss)
            return loss
        return self._sync_and_update(grads, loss)

    def _step_impl(self, batch) -> jax.Array:
        self._step_no += 1
        if chaos._plans:
            self._chaos_state_sites()
        tr = obs_trace._tracer
        t0 = tr.now() if tr is not None else 0
        if self._fused_fn is not None:
            if self.optimizer is None:
                loss, self.params = self._fused_fn(self.params, batch)
            else:
                loss, self.params, self._opt_state = self._fused_fn(
                    self.params, self._opt_state, batch
                )
            if tr is not None:
                tr.complete("step.fused", "step", t0, step=self._step_no)
            return loss
        if self._overlap is not None:
            return self._overlap_step(batch)
        loss, grads = self._grad_fn(self.params, batch)
        if tr is not None:
            # host-side dispatch of the local-gradient program (async: device
            # compute overlaps the comm Starts that follow)
            tr.complete("step.grad", "step", t0, step=self._step_no)
        grads, proceed = self._screen(loss, grads)
        if not proceed:
            return loss
        return self._sync_and_update(grads, loss)

    def _overlap_step(self, batch) -> jax.Array:
        """One compiled-overlap step (comm/overlap.py). With the sentinel
        quality gate armed the two-program split runs — the gate screens at
        the host gradient boundary and a ``skip_step`` verdict never
        dispatches the comm program, so EF residuals and data order stay
        lockstep with the host path; unarmed, the fused single-dispatch
        program carries the whole step (like the no-comm fused shortcut, it
        exposes no gradient boundary)."""
        if self.sentinel is not None and self.sentinel.gate_armed:
            tr = obs_trace._tracer
            t0 = tr.now() if tr is not None else 0
            loss, grads = self._grad_fn(self.params, batch)
            if tr is not None:
                tr.complete("step.grad", "step", t0, step=self._step_no)
            grads, proceed = self._screen(loss, grads)
            if not proceed:
                return loss
            self._overlap.step(batch, grads=grads, loss=loss)
            return loss
        return self._overlap.step(batch)

    def _sync_and_update(self, grads, loss) -> jax.Array:
        # Start gradient comms newest-gradient-first (reverse layer order), the
        # stream shape eplib's priority allreduce was built for.
        tr = obs_trace._tracer
        t0 = tr.now() if tr is not None else 0
        for name in reversed(self.layers):
            self.ops[name].get_parameter_set(0).start_gradient_comm(grads[name])
        if tr is not None:
            tr.complete("step.sync_start", "step", t0, step=self._step_no,
                        layers=len(self.layers))
            t0 = tr.now()

        if self.overlap_updates:
            # poll Test and update each layer the moment its collective lands
            new_params = self.params

            def apply(name, g):
                nonlocal new_params
                sub = self._layer_update_fns[name](
                    self.get_layer(new_params, name), g
                )
                new_params = _set_layer(new_params, name, sub)

            pending = list(self.layers)
            while pending:
                still = []
                for name in pending:
                    ps = self.ops[name].get_parameter_set(0)
                    done, out = ps.test_gradient_comm()
                    if done:
                        apply(name, out if out is not None else grads[name])
                    else:
                        still.append(name)
                if still and len(still) == len(pending):
                    # nothing landed this pass: block on one to avoid spinning
                    name = still.pop()
                    ps = self.ops[name].get_parameter_set(0)
                    out = ps.wait_gradient_comm()
                    apply(name, out if out is not None else grads[name])
                pending = still
            self.params = new_params
        elif not (self.distributed_update and self._needs_comm):
            reduced = {}
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                out = ps.wait_gradient_comm()
                reduced[name] = out if out is not None else grads[name]
            if self.optimizer is None:
                self.params = self._update_fn(self.params, reduced)
            else:
                self.params, self._opt_state = self._update_fn(
                    self.params, self._opt_state, reduced
                )
        else:
            incs = {}
            owned_all, scale_args = {}, ()
            if self.clip_global_norm is not None:
                # Global-norm clipping needs every owned shard before any
                # increment: wait all, psum the shard norms, then scale.
                for name in self.layers:
                    ps = self.ops[name].get_parameter_set(0)
                    owned_all[name] = ps.wait_gradient_comm()
                    mlsl_assert(
                        owned_all[name] is not None,
                        "distributed update requires dataParts>1",
                    )
                if self._du_norm_fn is None:
                    self._du_norm_fn = build_owned_norm_fn(
                        self.mesh, self.data_size
                    )
                cscale = _clip_scale(
                    self._du_norm_fn(owned_all) ** 2, self.clip_global_norm
                )
                scale_args = (cscale,)
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                if name in owned_all:
                    owned = owned_all[name]
                else:
                    owned = ps.wait_gradient_comm()
                    mlsl_assert(
                        owned is not None, "distributed update requires dataParts>1"
                    )
                if self.optimizer is None:
                    inc_local = self._du_inc_fn(owned, *scale_args)
                elif self._du_inc_fns is not None:
                    # sharded adafactor: factored stats need the replicated
                    # layer subtree (per-leaf shapes / parameter scale)
                    inc_local, self._du_opt_state[name] = self._du_inc_fns[name](
                        owned, self._du_opt_state[name],
                        self.get_layer(self.params, name), *scale_args
                    )
                else:
                    inc_local, self._du_opt_state[name] = self._du_inc_fn(
                        owned, self._du_opt_state[name], *scale_args
                    )
                ps.start_increment_comm(inc_local)
            for name in self.layers:
                ps = self.ops[name].get_parameter_set(0)
                incs[name] = ps.wait_increment_comm()
            self.params = self._du_apply_fn(self.params, incs)
        if tr is not None:
            # wait-all + parameter update phase (whatever path ran above)
            tr.complete("step.update", "step", t0, step=self._step_no)
        return loss


def _set_layer(params, name: str, subtree):
    """Functional update of a layer subtree addressed by resnet-style names."""
    if isinstance(params, dict) and name in params:
        new = dict(params)
        new[name] = subtree
        return new
    stage, block = name.split(".")
    new = dict(params)
    lst = list(new[stage])
    lst[int(block)] = subtree
    new[stage] = lst
    return new
