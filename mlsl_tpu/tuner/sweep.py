"""The autotuner sweep: measure candidate algorithms x knobs on the live mesh.

The reference picks dispatch policy from a static Xeon-vs-Phi / NIC matrix
(src/sysinfo.hpp, AutoConfig); EQuARX and DynamiQ both argue the selection
that matters is MEASURED, on the actual interconnect, per (collective, size,
group shape). This module is that measurement:

- **algorithm cells**: for every engine kind x payload size x group shape,
  build each eligible algorithm's program (comm/algos.build — the same cache
  the dispatch path uses, so the sweep's winners are already warm) and time
  best-of-N executions on zero buffers (the isolation-stats methodology:
  repeated replay, warmup discarded, min taken — core/stats.py).
- **knob derivation**: the dispatch floor (a tiny allreduce's wall time) and
  the peak algbw together give the bandwidth/latency crossover every
  scheduling knob encodes:
    msg_priority_threshold — defer messages whose wire time exceeds the
        dispatch floor (smaller ones are latency-bound; deferral only adds
        queue overhead);
    grad_bucket_mb — coalesce until one bucket's wire time is >= 16x the
        dispatch floor (per-member dispatch overhead amortized to <= 6%);
    large_msg_size_mb / large_msg_chunks — set only when a measured split
        of the largest swept payload actually beats the single-shot
        dispatch (on sim meshes it never does, and the knob stays unset);
    quant_block_elems — argmin over the quant-ring block palette at a
        bandwidth-sized payload (swept when ``quant=True``).

Sweep geometry defaults to the two shapes every training topology exercises
— the full 1D ring and (when the world factors) a 2D sub-torus — and is
overridable for tests/benches via arguments or MLSL_TUNE_SIZES (KiB, comma
separated) / MLSL_TUNE_ITERS.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from mlsl_tpu.log import log_debug, log_info

#: payload sizes swept by default (bytes); spans latency-bound to
#: bandwidth-bound on every backend we run on
DEFAULT_SIZES = (16 * 1024, 256 * 1024, 2 * 1024 * 1024)
DEFAULT_ITERS = 5
WARMUP = 2

#: quant-ring block palette (elements) swept for the quant knob cell
QUANT_BLOCKS = (128, 256, 512)


def _env_sizes() -> Optional[Tuple[int, ...]]:
    v = os.environ.get("MLSL_TUNE_SIZES")
    if not v:
        return None
    return tuple(int(float(s) * 1024) for s in v.split(",") if s.strip())


def _time_fn(fn, args, iters: int) -> float:
    """Best-of-``iters`` wall seconds for one compiled collective (min, not
    mean: the minimum is the least-noise estimator for a deterministic
    program under scheduler jitter — same reasoning as the bench harness).

    Times the program BENEATH the chaos instrumentation (``_mlsl_inner``,
    the same bypass the precompile warm uses): an armed MLSL_CHAOS budget
    must fire at the training step it targets, not be spent — or wedge
    init — inside the MLSL_TUNE sweep's hundreds of measurement calls."""
    import jax

    fn = getattr(fn, "_mlsl_inner", fn)
    for _ in range(WARMUP):
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _sweep_topologies(devices) -> List[tuple]:
    """(topology, group, shape) candidates: the 1D world ring plus a 2D
    factoring when the world splits into a real sub-torus."""
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.comm import algos

    n = len(devices)
    out = []
    if n > 1:
        t1 = Topology(n, 1, devices=devices)
        g1 = ProcessGroup(t1, ("data",))
        out.append((t1, g1, algos.group_shape(g1)))
    if n >= 4 and n % 2 == 0:
        t2 = Topology(n // 2, 2, devices=devices)
        g2 = ProcessGroup(t2, ("data", "model"))
        out.append((t2, g2, algos.group_shape(g2)))
    return out


def run_sweep(
    devices=None,
    sizes: Optional[Sequence[int]] = None,
    iters: Optional[int] = None,
    quant: bool = False,
) -> "TunedProfile":
    """Measure and return a TunedProfile for the current device world (not
    yet saved — the caller owns persistence)."""
    import jax

    from mlsl_tpu import sysinfo
    from mlsl_tpu.comm import algos
    from mlsl_tpu.tuner.profile import TunedProfile
    from mlsl_tpu.types import ReductionType

    devices = tuple(devices) if devices is not None else tuple(jax.devices())
    sizes = tuple(sizes) if sizes is not None else (_env_sizes() or DEFAULT_SIZES)
    iters = int(iters if iters is not None else
                os.environ.get("MLSL_TUNE_ITERS", DEFAULT_ITERS))
    t_start = time.perf_counter()

    cells: List[dict] = []
    floor_s = None
    algbw = 0.0
    largest = {}

    for topo, group, shape in _sweep_topologies(devices):
        G = group.size

        def buf_for(elems):
            return topo.shard_buffer(
                np.zeros((*topo.grid_shape, elems), dtype=np.float32)
            )

        # dispatch floor: one tiny allreduce on the first (1D) shape
        if floor_s is None:
            fn = algos.build("allreduce", group, np.float32, "lax",
                             op=ReductionType.SUM)
            floor_s = _time_fn(fn, (buf_for(256),), iters)

        for kind in algos.ENGINE_KINDS:
            for size_b in sorted(sizes):
                # elements padded so reduce_scatter counts divide the group
                elems = max(-(-(size_b // 4) // G) * G, G)
                if kind == "alltoall":
                    # an exchange, not a reduction: the per-destination
                    # slice rides send_count and there is no op to sweep
                    kw = dict(send_count=elems // G)
                    cand_op = None
                else:
                    kw = dict(op=ReductionType.SUM)
                    if kind == "reduce_scatter":
                        kw["recv_count"] = elems // G
                    cand_op = ReductionType.SUM
                args = (buf_for(elems),)
                measured = {}
                for algo in algos.candidates(kind, group, cand_op):
                    if algo.startswith("pallas"):
                        # never time the interpreter (a correctness vehicle
                        # whose simulated DMAs are world gathers — it can
                        # only lose, at enormous sweep wall-time)
                        from mlsl_tpu.ops import ring_kernels

                        if ring_kernels.interpret_mode():
                            continue
                    fn = algos.build(kind, group, np.float32, algo, **kw)
                    measured[algo] = _time_fn(fn, args, iters)
                best = min(measured, key=measured.get)
                payload = elems * 4
                cells.append({
                    "kind": kind,
                    "shape": list(shape),
                    "compression": "none",
                    "payload_bytes": payload,   # what was actually measured
                    "max_bytes": payload * 2,   # the band this cell covers
                    "algo": best,
                    "us": {a: round(s * 1e6, 2) for a, s in measured.items()},
                })
                log_debug(
                    "tune: %s shape=%s %dB -> %s (%s)", kind, shape, payload,
                    best, cells[-1]["us"],
                )
                if kind == "allreduce":
                    bw = payload / measured["lax"]
                    if bw > algbw:
                        algbw = bw
                    if payload > largest.get("bytes", 0):
                        largest = {"bytes": payload, "group": group,
                                   "topo": topo, "kw": kw}

        # open the top band: the largest swept size's winner covers payloads
        # beyond the sweep range (bandwidth-bound behavior extrapolates;
        # latency-bound does not)
        for kind in algos.ENGINE_KINDS:
            tops = [c for c in cells
                    if c["kind"] == kind and c["shape"] == list(shape)]
            if tops:
                tops[-1]["max_bytes"] = None

    knobs: dict = {}
    if floor_s and algbw > 0:
        mib = 1024 * 1024
        knobs["msg_priority_threshold"] = int(
            min(max(floor_s * algbw, 4096), 16 * mib)
        )
        knobs["grad_bucket_mb"] = int(
            min(max(round(16 * floor_s * algbw / mib), 1), 64)
        )
        # chunk-split probe on the largest swept allreduce: sequential
        # quarter-slice dispatches vs the single shot
        if largest:
            from mlsl_tpu.types import ReductionType as RT

            grp, topo = largest["group"], largest["topo"]
            elems = largest["bytes"] // 4
            fn = algos.build("allreduce", grp, np.float32, "lax",
                            op=RT.SUM)
            fn = getattr(fn, "_mlsl_inner", fn)  # chaos bypass, as above
            full = topo.shard_buffer(
                np.zeros((*topo.grid_shape, elems), dtype=np.float32)
            )
            single = _time_fn(fn, (full,), iters)
            q = elems // 4

            def chunked():
                outs = [fn(full[..., i * q:(i + 1) * q]) for i in range(4)]
                return jax.block_until_ready(outs)

            t_chunk = _time_fn(chunked, (), iters)
            if t_chunk < single * 0.9:
                knobs["large_msg_size_mb"] = max(largest["bytes"] // (2 * mib), 1)
                knobs["large_msg_chunks"] = 4
            knobs["_measured"] = {
                "dispatch_floor_us": round(floor_s * 1e6, 2),
                "algbw_gbps": round(algbw / 1e9, 4),
                "large_single_us": round(single * 1e6, 2),
                "large_chunked_us": round(t_chunk * 1e6, 2),
            }

    if quant:
        knobs.update(_sweep_quant_block(devices, iters))
        # lowering cells measured at the block the SAME sweep just picked
        # (the geometry runtime requests will actually run)
        cells.extend(_sweep_quant_lowering(
            devices, iters, block=int(knobs.get("quant_block_elems", 256))
        ))
    knobs.update(_sweep_overlap_stages(devices, iters))

    prof = TunedProfile(
        # keyed to the world the sweep MEASURED (the active device set):
        # an elastic shrink re-sweeps over survivors, and its profile must
        # not transfer back to the full world, nor vice versa
        fingerprint=sysinfo.topology_fingerprint(devices),
        cells=cells,
        knobs=knobs,
        created=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    )
    log_info(
        "tuner sweep: %d cells, %d knobs in %.1fs",
        len(cells), len([k for k in knobs if not k.startswith("_")]),
        time.perf_counter() - t_start,
    )
    return prof


#: staging depths swept for the compiled-overlap knob cell
OVERLAP_STAGE_CANDIDATES = (1, 2, 4)


def _sweep_overlap_stages(devices, iters: int) -> dict:
    """Staging-depth cell for the compiled overlap engine (comm/overlap.py):
    time the staged multi-tensor reduce — a 12-tensor backward-shaped
    stream, the same program shape the engine's comm segment emits — at
    each candidate depth on the 1D ring and take the argmin. On sim meshes
    the depths usually tie (the CPU backend serializes collectives anyway);
    on a real torus the depth controls how many layers' phases interleave
    in the scheduled program."""
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.comm import overlap

    n = len(devices)
    if n <= 1:
        return {}
    topo = Topology(n, 1, devices=devices)
    group = ProcessGroup(topo, ("data",))
    counts = [16 * 1024] * 12
    bufs = [
        topo.shard_buffer(np.zeros((*topo.grid_shape, c), dtype=np.float32))
        for c in counts
    ]
    measured = {}
    for stages in OVERLAP_STAGE_CANDIDATES:
        fn, _ = overlap.build_multi_reduce(group, counts, stages=stages)
        measured[stages] = _time_fn(lambda: fn(bufs), (), iters)
    best = min(measured, key=measured.get)
    return {
        "overlap_stages": int(best),
        "_overlap_measured": {
            str(s): round(t * 1e6, 2) for s, t in measured.items()
        },
    }


def _sweep_quant_lowering(devices, iters: int, block: int = 256) -> list:
    """Quantized-wire lowering cells: time the composed quant ring ('lax')
    against the fused pallas kernel ('pallas_ring') and the two-tier
    hierarchical wire ('hier') per payload size on the 1D ring, so the
    selection table can route QUANTIZATION requests to the lowering that
    measures faster per (kind x size x topology) cell. The pallas contender
    joins only where the kernel can run on this backend (on-TPU: never
    measured under the interpreter, a correctness vehicle, not a
    contender); the hier contender joins only on a tiered world
    (MLSL_MESH_TIERS / multislice). Note the CPU-mesh hier timing carries
    no DCN model — on a real pod the DCN link decides, which is what the
    hier cell measures there."""
    import jax

    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.comm import algos, quant_ring
    from mlsl_tpu.comm.algos import hier
    from mlsl_tpu.ops import ring_kernels as rk

    n = len(devices)
    if n <= 1:
        return []
    topo = Topology(n, 1, devices=devices)
    group = ProcessGroup(topo, ("data",))
    rings = [("lax", "lax")]
    if rk.eligible_quant(group, block) and not rk.interpret_mode():
        rings.append(("pallas", "pallas_ring"))
    if hier.eligible_quant(group, block):
        rings.append(("hier", "hier"))
    if len(rings) == 1:
        return []
    shape = list(algos.group_shape(group))
    cells = []
    sizes = _env_sizes() or DEFAULT_SIZES
    for size_b in sorted(sizes):
        elems = max(-(-(size_b // 4) // n) * n, n)
        buf = topo.shard_buffer(
            np.zeros((*topo.grid_shape, elems), dtype=np.float32)
        )
        measured = {}
        for ring, name in rings:
            fn, err_len = quant_ring.build_quantized_collective(
                "allreduce", group, elems, block, ring=ring
            )
            err = topo.shard_buffer(
                np.zeros((*topo.grid_shape, err_len), dtype=np.float32)
            )
            measured[name] = _time_fn(fn, (buf, err), iters)
        best = min(measured, key=measured.get)
        payload = elems * 4
        cells.append({
            "kind": "allreduce",
            "shape": shape,
            "compression": "quantization",
            "payload_bytes": payload,
            "max_bytes": payload * 2,
            "algo": best,
            "us": {a: round(s * 1e6, 2) for a, s in measured.items()},
        })
        log_debug("tune: quant allreduce %dB -> %s (%s)", payload, best,
                  cells[-1]["us"])
    if cells:
        cells[-1]["max_bytes"] = None  # open top band
    return cells


def _sweep_quant_block(devices, iters: int) -> dict:
    """Block-size cell for the int8 quant ring: argmin over the palette at a
    bandwidth-sized payload on the 1D ring."""
    from mlsl_tpu.comm.mesh import ProcessGroup, Topology
    from mlsl_tpu.comm import quant_ring

    n = len(devices)
    if n <= 1:
        return {}
    topo = Topology(n, 1, devices=devices)
    group = ProcessGroup(topo, ("data",))
    elems = max(256 * 1024 // 4, n) // n * n
    measured = {}
    for block in QUANT_BLOCKS:
        fn, err_len = quant_ring.build_quantized_collective(
            "allreduce", group, elems, block
        )
        buf = topo.shard_buffer(
            np.zeros((*topo.grid_shape, elems), dtype=np.float32)
        )
        err = topo.shard_buffer(
            np.zeros((*topo.grid_shape, err_len), dtype=np.float32)
        )
        measured[block] = _time_fn(fn, (buf, err), iters)
    best = min(measured, key=measured.get)
    return {
        "quant_block_elems": int(best),
        "_quant_measured": {
            str(b): round(s * 1e6, 2) for b, s in measured.items()
        },
    }
