"""Tuner profile: the persisted selection table + tuned knob set.

A profile is one JSON document keyed by a ``sysinfo`` topology fingerprint
(platform, chip generation, world size, host spread). Cells map
(kind, group shape, compression, payload band) -> algorithm name; knobs are
whole-config values (chunk/bucket/priority/quant-block) the sweep measured.
Both carry the raw measurements they were derived from, so an operator can
audit WHY a cell picked its algorithm (docs/TUNING.md §10).

Load contract (the config-validation satellite): a missing or corrupt file
is an immediate ``MLSLError`` — pointing MLSL_TUNE_PROFILE at garbage must
fail at init, not deep in dispatch. A well-formed profile whose fingerprint
disagrees with the probed hardware is STALE: rejected with a warning and the
untuned defaults keep running (measurements do not transfer across
machines).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

from mlsl_tpu.log import MLSLError

PROFILE_VERSION = 1
DEFAULT_PROFILE_FILE = "mlsl_tune_profile.json"

#: knob name -> minimum legal value: the Config fields a profile's knob
#: table may set (anything else under "knobs" is measurement metadata,
#: ignored on apply). Checked at LOAD time — a profile file with a
#: nonsensical knob must fail with an MLSLError naming the file, not deep
#: inside the first collective that consumes the knob (the same
#: fail-at-init contract as Config.validate()).
KNOB_RANGES = {
    "msg_priority_threshold": 1,
    "grad_bucket_mb": 0,
    "large_msg_size_mb": 0,
    "large_msg_chunks": 1,
    "quant_block_elems": 1,
    # pallas-ring comm slots per direction (ops/ring_kernels.py): profiles
    # may carry a measured double-buffer depth for this machine's ICI; an
    # exported MLSL_PALLAS_RING_SLOTS always wins
    "pallas_ring_slots": 2,
    # latency-class allreduce payload band (ops/rhd_kernels.py): profiles
    # may carry the measured rhd/ring crossover in bytes for this fabric
    # (0 = derive from msg_priority_threshold); an exported
    # MLSL_PALLAS_RHD_MAX_BYTES always wins
    "pallas_rhd_max_bytes": 0,
    # fused-alltoall wire codec (ops/a2a_kernels.py): 1 = int8 blockwise,
    # 0 = dense f32 variant of the same kernel. Carried as 0/1 (the range
    # table rejects bools); an exported MLSL_PALLAS_A2A_QUANT always wins
    "pallas_a2a_quant": 0,
    # compiled-overlap staging depth (comm/overlap.py): profiles may carry
    # the measured number of unit-starts a layer's reduce phases spread
    # over; an exported MLSL_OVERLAP_STAGES always wins
    "overlap_stages": 1,
    # feed-pipeline prefetch depth (mlsl_tpu.data): profiles may carry the
    # depth benchmarks/input_pipeline_bench.py measured best for this
    # machine's h2d link; an exported MLSL_FEED_DEPTH always wins
    "feed_depth": 1,
    # integrity-sentinel audit interval (mlsl_tpu.sentinel): profiles may
    # carry the interval benchmarks/sentinel_overhead_bench.py measured to
    # keep gate+audit overhead under its budget on this machine; an
    # exported MLSL_SENTINEL_EVERY always wins (0 = audit off)
    "sentinel_every": 0,
    # telemetry sampler cadence (obs/metrics.py): profiles may carry the
    # cadence benchmarks/metrics_overhead_bench.py measured to keep the
    # armed-path cost under its 2% budget on this machine; an exported
    # MLSL_METRICS_EVERY always wins
    "metrics_every": 1,
    # straggler audit window (obs/straggler.py): an exported
    # MLSL_STRAGGLER_EVERY always wins; floor = the judgeable minimum
    # (MIN_WINDOW_SAMPLES — below it no replica is ever judged)
    "straggler_every": 3,
    # heartbeat miss budget (control/plane.py): profiles may carry the
    # consecutive-miss count measured to cover this pod's worst GC/compile
    # pause without false-declaring a host dead (each extra miss delays
    # real-failure detection by one MLSL_HEARTBEAT_INTERVAL_S); an exported
    # MLSL_HEARTBEAT_MISSES always wins
    "heartbeat_misses": 1,
    # codec-lab knobs (mlsl_tpu.codecs; docs/TUNING.md §22): calibration
    # may carry whole-run codec parameters alongside the per-set assignment
    # table; exported MLSL_VQ_* / MLSL_PRUNE_RATIO always win
    "vq_dim": 1,
    "vq_codebook": 2,
    "prune_ratio": 1e-4,
    # serving decode-slot ceiling (serve/engine.py): profiles may carry the
    # batch benchmarks/serving_bench.py measured to maximize tokens/s while
    # holding p99 TPOT on this chip; an exported MLSL_SERVE_MAX_BATCH
    # always wins
    "serve_max_batch": 1,
    # KV page granularity in tokens (serve/kv_cache.py): profiles may carry
    # the page size measured to balance HBM tail waste against page-table
    # gather cost; an exported MLSL_SERVE_KV_PAGE_ELEMS always wins
    "serve_kv_page_elems": 1,
    # paged-KV HBM budget in MiB (serve/kv_cache.py): profiles may carry
    # the budget measured to fit this chip's free HBM after weights; an
    # exported MLSL_SERVE_KV_CACHE_MB always wins
    "serve_kv_cache_mb": 1,
    # admission queue depth (serve/engine.py): profiles may carry the depth
    # measured to absorb offered-load bursts without breaching TTFT; an
    # exported MLSL_SERVE_QUEUE_DEPTH always wins
    "serve_queue_depth": 1,
}

#: string-valued knobs -> allowed values: same load-time validation contract
#: as KNOB_RANGES, for knobs that pick a variant rather than a magnitude
KNOB_CHOICES = {
    # DCN-tier codec for the 'hier' lowering (comm/algos/hier.py): profiles
    # tuned on a two-tier mesh may carry the codec that measured best on
    # its DCN; an exported MLSL_HIER_DCN_CODEC always wins. Registry codecs
    # (mlsl_tpu.codecs) are legal DCN members since the codec-lab PR.
    "hier_dcn_codec": ("int8", "f32", "topk", "vq", "prune"),
}


def default_profile_path() -> str:
    """Where an unnamed profile lands: ``MLSL_STATS_DIR`` (default CWD), the
    same routing contract as mlsl_stats.log (core/stats.stats_path)."""
    d = os.environ.get("MLSL_STATS_DIR")
    return os.path.join(d, DEFAULT_PROFILE_FILE) if d else DEFAULT_PROFILE_FILE


@dataclasses.dataclass
class TunedProfile:
    """In-memory form of one profile document."""

    fingerprint: dict
    cells: List[dict] = dataclasses.field(default_factory=list)
    knobs: dict = dataclasses.field(default_factory=dict)
    created: str = ""
    # codec-lab calibration table (tuner/calibrate.py; docs/TUNING.md §22):
    # request name -> {"codec": registry name, "block": int8 block or 0,
    # "params": codec knobs, "nsr": measured noise-to-signal, "wire_bytes":
    # per-round compressed image}. Absent in pre-codec-lab profiles — the
    # loader tolerates a missing section (older files keep loading).
    codecs: dict = dataclasses.field(default_factory=dict)

    # -- selection ---------------------------------------------------------

    def select(
        self,
        kind: str,
        shape: Tuple[int, ...],
        compression,
        payload_bytes: int,
    ) -> Optional[str]:
        """Tuned algorithm for (kind, group shape, compression, payload), or
        None when no cell covers it (the caller falls back to the heuristic
        default). Cells are size-banded: the matching cell is the smallest
        ``max_bytes`` band that still covers the payload; a cell with
        ``max_bytes: null`` is the open top band."""
        comp = _comp_name(compression)
        shape = tuple(int(s) for s in shape)
        best = None
        best_cap = None
        for cell in self.cells:
            if cell.get("kind") != kind or _comp_name(cell.get("compression", "none")) != comp:
                continue
            if tuple(int(s) for s in cell.get("shape", ())) != shape:
                continue
            cap = cell.get("max_bytes")
            if cap is not None and payload_bytes > cap:
                continue
            if best is None or (cap is not None and (best_cap is None or cap < best_cap)):
                best, best_cap = cell, cap
        return best.get("algo") if best else None

    def matches(self, fingerprint: dict) -> bool:
        return dict(self.fingerprint) == dict(fingerprint)

    # -- persistence -------------------------------------------------------

    def to_doc(self) -> dict:
        doc = {
            "version": PROFILE_VERSION,
            "fingerprint": self.fingerprint,
            "created": self.created,
            "cells": self.cells,
            "knobs": self.knobs,
        }
        if self.codecs:
            doc["codecs"] = self.codecs
        return doc

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_doc(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)  # atomic: a reader never sees a half-written file
        return path


def _comp_name(compression) -> str:
    if isinstance(compression, str):
        return compression
    from mlsl_tpu.types import CompressionType

    try:
        return CompressionType(compression).name.lower()
    except ValueError:
        return str(compression)


def load_profile(path: str) -> TunedProfile:
    """Parse a profile file; MLSLError on missing/corrupt/unknown-version —
    the fail-at-init contract for MLSL_TUNE_PROFILE."""
    if not os.path.exists(path):
        raise MLSLError(
            f"MLSL_TUNE_PROFILE points at a missing file: {path} "
            f"(run MLSL_TUNE=1 or scripts/run_tune.sh to produce one)"
        )
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise MLSLError(
            f"MLSL_TUNE_PROFILE file {path} is unreadable or corrupt: {e!r}"
        ) from e
    if not isinstance(doc, dict) or "fingerprint" not in doc or "cells" not in doc:
        raise MLSLError(
            f"MLSL_TUNE_PROFILE file {path} is not a tuner profile "
            f"(missing fingerprint/cells)"
        )
    if doc.get("version") != PROFILE_VERSION:
        raise MLSLError(
            f"MLSL_TUNE_PROFILE file {path} has unsupported version "
            f"{doc.get('version')!r} (this build reads version {PROFILE_VERSION})"
        )
    cells = doc["cells"]
    if not isinstance(cells, list) or not all(isinstance(c, dict) for c in cells):
        raise MLSLError(f"MLSL_TUNE_PROFILE file {path} has a malformed cell table")
    from mlsl_tpu.comm import algos

    for cell in cells:
        if cell.get("algo") not in algos.ALGORITHMS:
            raise MLSLError(
                f"MLSL_TUNE_PROFILE file {path} names unknown algorithm "
                f"{cell.get('algo')!r} (registry: {', '.join(algos.ALGORITHMS)})"
            )
    knobs = doc.get("knobs", {}) or {}
    for name, lo in KNOB_RANGES.items():
        v = knobs.get(name)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)) or v < lo:
            raise MLSLError(
                f"MLSL_TUNE_PROFILE file {path} has invalid knob "
                f"{name}={v!r} (expected a number >= {lo})"
            )
    for name, allowed in KNOB_CHOICES.items():
        v = knobs.get(name)
        if v is not None and v not in allowed:
            raise MLSLError(
                f"MLSL_TUNE_PROFILE file {path} has invalid knob "
                f"{name}={v!r} (expected one of {', '.join(allowed)})"
            )
    codec_cells = doc.get("codecs", {}) or {}
    if not isinstance(codec_cells, dict) or not all(
        isinstance(k, str) and isinstance(v, dict) and isinstance(v.get("codec"), str)
        for k, v in codec_cells.items()
    ):
        raise MLSLError(
            f"MLSL_TUNE_PROFILE file {path} has a malformed codecs table "
            f"(expected request name -> {{'codec': name, ...}})"
        )
    from mlsl_tpu import codecs as codecs_mod

    for rname, cell in codec_cells.items():
        if cell["codec"] not in codecs_mod.names():
            raise MLSLError(
                f"MLSL_TUNE_PROFILE file {path} assigns unknown codec "
                f"{cell['codec']!r} to {rname!r} "
                f"(registry: {', '.join(codecs_mod.names())})"
            )
    return TunedProfile(
        fingerprint=doc["fingerprint"],
        cells=cells,
        knobs=knobs,
        created=str(doc.get("created", "")),
        codecs=codec_cells,
    )
