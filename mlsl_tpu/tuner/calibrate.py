"""Convergence-aware codec calibration (MLSL_TUNE_CODEC=1).

The codec lab's measurement half (docs/TUNING.md §22): at Session.commit,
BEFORE gradient buckets form, replay a short deterministic gradient sample
through every registry codec's encode/decode round-trip per ParameterSet and
measure its quantization-noise-to-signal ratio (NSR — noise power over
signal power) plus the layer's norm spectrum. The solver then picks, per
set, the cheapest (fewest wire bytes) codec x block cell whose NSR stays
under the convergence budget ``MLSL_CODEC_NSR_BUDGET`` — int8 at the
session block is always a candidate, so a set never calibrates WORSE than
the seed wire. The assignment persists into the topology-keyed tuned
profile (tuner/profile.py ``codecs`` section) and applies to the live
session by re-running each affected request's setup().

Precedence stays the codec-lab contract (codecs.assigned): an exported
MLSL_CODEC pins every set and calibration writes the profile WITHOUT
touching the live assignment; the sentinel's loss z-score screen guards the
calibrated sets online and demotes a mis-calibrated one back to int8
(CommRequest.demote_codec — one DEGRADE-ladder rung, exactly-once EF
flush).

The gradient sample is synthetic but layer-shaped: per-set deterministic
(seeded by the request name, stable across processes so every rank solves
the same table), scaled by 1/sqrt(kernel_size) with a heavy sparse tail —
the magnitude mixture pruning-style codecs are sensitive to. A calibration
run measures sensitivity, not loss: the online guardrail owns convergence.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from mlsl_tpu.log import log_info, log_warning
from mlsl_tpu.tuner.profile import TunedProfile, default_profile_path, load_profile

#: cap on the per-set sample length: NSR converges well before this, and
#: calibrating a billion-element set must not dominate commit time
SAMPLE_CAP = 65536

#: int8 block palette the solver searches (the session block is always
#: included on top of these)
INT8_BLOCKS = (128, 256, 512)

#: prune keep-ratio palette
PRUNE_RATIOS = (0.01, 0.05, 0.1, 0.25)

#: VQ vector-dimension palette (codebook size rides MLSL_VQ_CODEBOOK)
VQ_DIMS = (4, 8)


#: element count above which the surrogate models a wide conv/embedding
#: layer: mostly-dead ReLU backprop -> 90% exact zeros (the regime where
#: importance-weighted pruning beats the dense int8 wire)
WIDE_LAYER_ELEMS = 16384


def gradient_sample(name: str, n: int, kernel_size: int = 1) -> np.ndarray:
    """Deterministic layer-shaped gradient surrogate: dense Gaussian body at
    the 1/sqrt(fan) scale + a sparse heavy tail (1% of entries, 8x scale) +
    ReLU-style exact zeros (half the entries; 90% for wide layers — dead
    units backprop nothing). Seeded by the request name via crc32 —
    identical on every process, so distributed ranks derive identical
    assignments without a collective."""
    m = min(int(n), SAMPLE_CAP)
    rng = np.random.default_rng(zlib.crc32(name.encode("utf-8")) & 0xFFFFFFFF)
    scale = 1.0 / float(np.sqrt(max(1, kernel_size)))
    x = rng.normal(0.0, scale, size=m).astype(np.float32)
    spikes = rng.random(m) < 0.01
    x[spikes] *= 8.0
    sparsity = 0.9 if n >= WIDE_LAYER_ELEMS else 0.5
    x[rng.random(m) < sparsity] = 0.0
    return x


def norm_spectrum(x: np.ndarray) -> dict:
    """The per-layer norm statistics the profile records next to the NSR:
    enough for an operator to audit WHY a cell picked its codec."""
    ax = np.abs(x)
    return {
        "l2": float(np.linalg.norm(x)),
        "linf": float(ax.max(initial=0.0)),
        "mean_abs": float(ax.mean()) if x.size else 0.0,
        # tail mass: fraction of the l1 norm carried by the top 1% — the
        # signal pruning-class codecs feed on
        "top1pct_mass": float(
            np.sort(ax)[::-1][: max(1, x.size // 100)].sum() / max(ax.sum(), 1e-30)
        ),
    }


def measure_nsr(codec, x: np.ndarray) -> float:
    """Noise-to-signal power of one encode/decode round trip on the sample."""
    import jax.numpy as jnp

    n = int(x.shape[0])
    xhat = np.asarray(codec.decode(codec.encode(jnp.asarray(x)), n))
    sig = float(np.sum(np.square(x, dtype=np.float64)))
    if sig == 0.0:
        return 0.0
    noise = float(np.sum(np.square((xhat - x).astype(np.float64))))
    return noise / sig


def candidate_cells(config, name: str, n: int, x: np.ndarray) -> List[dict]:
    """The per-set search space: every cell carries the measured NSR and the
    full-payload wire bytes the solver ranks on."""
    from mlsl_tpu import codecs as codecs_mod
    from mlsl_tpu.codecs import vq as vq_mod

    cells: List[dict] = []

    def add(codec_name: str, codec, block: int = 0, params: Optional[dict] = None):
        cells.append({
            "codec": codec_name,
            "block": int(block),
            "params": params or {},
            "nsr": measure_nsr(codec, x),
            "wire_bytes": int(codec.wire_len(n)),
        })

    session_block = int(getattr(config, "quant_block_elems", 256) or 256)
    for block in sorted({*INT8_BLOCKS, session_block}):
        add("int8", codecs_mod.get("int8", block=block), block=block)
    for ratio in PRUNE_RATIOS:
        add("prune", codecs_mod.get("prune", ratio=ratio),
            params={"ratio": float(ratio)})
    k = int(getattr(config, "vq_codebook", 16) or 16)
    for dim in VQ_DIMS:
        cb = vq_mod.learn_codebook(x, k=k, dim=dim)
        add("vq", codecs_mod.get("vq", dim=dim, k=k, codebook=cb),
            params={"vq_dim": int(dim), "vq_codebook": k,
                    "codebook": cb.tolist()})
    return cells


def solve(cells: List[dict], budget: float) -> Optional[dict]:
    """Cheapest cell whose NSR meets the budget; int8 breaks wire-byte ties
    (the seed wire is the proven rung). None when nothing fits — the caller
    keeps the uncalibrated default rather than assigning a breach."""
    fits = [c for c in cells if c["nsr"] <= budget]
    if not fits:
        return None
    return min(fits, key=lambda c: (c["wire_bytes"], c["codec"] != "int8"))


def calibrate_session(session) -> Dict[str, dict]:
    """Session.commit hook (MLSL_TUNE_CODEC=1): measure -> solve -> persist
    -> apply. Returns the assignment table (request name -> cell)."""
    from mlsl_tpu.core import stats as stats_mod
    from mlsl_tpu.types import CompressionType

    cfg = session.env.config
    budget = float(getattr(cfg, "codec_nsr_budget", 0.02))
    table: Dict[str, dict] = {}
    targets: List[Tuple[str, object]] = []
    for op in session.operations:
        for ps in op.parameter_sets:
            req = ps.grad_req
            if (
                req is None
                or req.desc.compression != CompressionType.QUANTIZATION
            ):
                continue
            n = int(req.desc.count)
            x = gradient_sample(req.name, n, ps.kernel_size)
            cell = solve(candidate_cells(cfg, req.name, n, x), budget)
            if cell is None:
                log_warning(
                    "codec calibration: no codec meets NSR budget %.4g for "
                    "%s; keeping the uncalibrated default", budget, req.name,
                )
                continue
            table[req.name] = dict(cell, spectrum=norm_spectrum(x))
            targets.append((req.name, req))
    stats_mod.record_codec("calibrations")
    if not table:
        return table

    _persist(cfg, table)

    explicit = getattr(cfg, "_explicit", ()) or ()
    if "codec" in explicit:
        # an exported MLSL_CODEC wins over calibration (docs/TUNING.md §22):
        # the profile above still records the measurement for later runs
        log_info(
            "codec calibration: %d cell(s) measured but MLSL_CODEC=%s is "
            "exported — live assignment unchanged", len(table), cfg.codec,
        )
        return table
    cfg.codec_assignment = dict(table)
    for name, req in targets:
        if name in table:
            req.setup()  # re-route onto the calibrated codec
            stats_mod.record_codec("assignments")
    log_info(
        "codec calibration: %d set(s) assigned under NSR budget %.4g (%s)",
        len(table), budget,
        ", ".join(f"{k}->{v['codec']}" for k, v in sorted(table.items())),
    )
    return table


def _persist(cfg, table: Dict[str, dict]) -> None:
    """Merge the assignment into the topology-keyed tuned profile (create it
    when absent, reject-and-rewrite when stale) — atomic save, same file the
    algorithm sweep owns. Cells keep their measurements (NSR, spectrum,
    codebook): profiles are audit documents (docs/TUNING.md §10)."""
    from mlsl_tpu import sysinfo
    from mlsl_tpu.log import MLSLError

    path = getattr(cfg, "tune_profile", "") or default_profile_path()
    fp = sysinfo.topology_fingerprint()
    profile = None
    try:
        profile = load_profile(path)
    except MLSLError:
        profile = None  # absent or unreadable: start a fresh document
    if profile is not None and not profile.matches(fp):
        log_warning(
            "codec calibration: existing profile %s was measured on a "
            "different topology; rewriting its codec table for this one",
            path,
        )
        profile = None
    if profile is None:
        profile = TunedProfile(fingerprint=fp)
    profile.codecs = dict(profile.codecs or {}, **table)
    profile.save(path)
    log_info("codec calibration: %d cell(s) -> %s", len(table), path)
