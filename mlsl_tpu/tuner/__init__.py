"""Topology-aware autotuner: measured algorithm + knob selection.

The missing half of the algorithm engine (comm/algos): the engine provides
CHOICE, this package provides the MEASUREMENT that justifies one. A sweep
(`sweep.run_sweep`) times every eligible algorithm per (kind, payload, group
shape) on the live mesh and derives the chunk/bucket/priority knobs from the
measured dispatch floor and algbw; the result persists as a JSON profile
(`profile.TunedProfile`) keyed by a ``sysinfo`` topology fingerprint, and
``init_profile`` loads it at Environment.init so every subsequent
CommRequest.setup consults the tuned table.

Operator surface (docs/TUNING.md §10):
    MLSL_TUNE=1          run the sweep at init and persist + use the profile
    MLSL_TUNE_PROFILE=f  profile path (read when MLSL_TUNE=0, written when 1);
                         default mlsl_tune_profile.json in MLSL_STATS_DIR/CWD
    MLSL_TUNE_SIZES      swept payloads, KiB, comma separated (tests/benches)
    MLSL_TUNE_ITERS      timing iterations per cell

Selection precedence stays: explicit config (MLSL_ALGO / exported MLSL_*
knobs) > tuned profile > heuristic defaults. Tuned knobs never override a
knob the user exported explicitly (the Config._explicit contract shared with
sysinfo.auto_config), and with neither MLSL_TUNE nor MLSL_TUNE_PROFILE set
this package never runs — untuned behavior is bit-for-bit unchanged.
"""

from __future__ import annotations

from mlsl_tpu.log import log_info, log_warning
from mlsl_tpu.tuner.profile import (  # noqa: F401  (public API)
    DEFAULT_PROFILE_FILE,
    KNOB_CHOICES,
    KNOB_RANGES,
    TunedProfile,
    default_profile_path,
    load_profile,
)
from mlsl_tpu.tuner.sweep import run_sweep  # noqa: F401

#: Config fields a profile's knob table may set (anything else in ``knobs``
#: is measurement metadata, ignored on apply); numeric ranges / string
#: choices enforced at load (profile.KNOB_RANGES / KNOB_CHOICES)
TUNABLE_KNOBS = tuple(KNOB_RANGES) + tuple(KNOB_CHOICES)


def apply_knobs(config, profile: TunedProfile) -> None:
    """Apply a profile's tuned knobs to the config — except knobs the user
    exported explicitly (Config._explicit), which always win (the same
    contract as sysinfo.auto_config and the reference's AutoConfig)."""
    explicit = getattr(config, "_explicit", set())
    for name in TUNABLE_KNOBS:
        if name in profile.knobs and name not in explicit:
            setattr(config, name, profile.knobs[name])
    # codec-lab calibration table (tuner/calibrate.py): per-request codec
    # assignment rides the same precedence — an exported MLSL_CODEC pins
    # every set to one codec and the calibrated table stays unapplied
    if profile.codecs and "codec" not in explicit:
        config.codec_assignment = dict(profile.codecs)


def init_profile(config, devices=None) -> None:
    """Environment.init hook: resolve the tuned profile for this process.

    - MLSL_TUNE=1: run the sweep on the live device world, persist the
      profile (atomic write), and use it.
    - MLSL_TUNE_PROFILE set (no sweep): load it. Missing/corrupt/unknown
      version raises MLSLError here — at init, where the operator can see it
      — never deep in dispatch. A well-formed profile whose topology
      fingerprint disagrees with the probed hardware is stale: rejected with
      a warning, untuned defaults keep running.
    - neither: config.tuned_profile stays None and nothing changes.
    """
    from mlsl_tpu import sysinfo

    config.tuned_profile = None
    if config.tune:
        import os

        path = config.tune_profile or default_profile_path()
        # MLSL_TUNE_QUANT=1 adds the int8-ring block-palette cell — opt-in
        # because it only pays off for quantized training and costs extra
        # sweep time on every tuned init
        quant = os.environ.get("MLSL_TUNE_QUANT", "").strip().lower() not in (
            "", "0", "false", "no", "off",
        )
        profile = run_sweep(devices=devices, quant=quant)
        profile.save(path)
        log_info("tuner: profile written to %s (%d cells)", path,
                 len(profile.cells))
        config.tuned_profile = profile
    elif config.tune_profile:
        import os

        if not os.path.exists(config.tune_profile) and getattr(
            config, "tune_codec", False
        ):
            # MLSL_TUNE_CODEC=1 pointed at a not-yet-written profile: codec
            # calibration CREATES it at Session.commit (tuner/calibrate.py),
            # so a missing file is the expected first-run state, not the
            # fail-at-init operator error the plain load path reports
            log_info(
                "tuner: profile %s absent; codec calibration will write it "
                "at commit", config.tune_profile,
            )
            return
        profile = load_profile(config.tune_profile)  # MLSLError on bad file
        # fingerprint the ACTIVE world, not the physical machine: every
        # re-init re-checks here — including FaultTolerantLoop recovery
        # rebuilds and elastic reshard re-inits over a survivor subset,
        # where a profile measured at the old world size is stale and must
        # be rejected with a warning, never silently honored (the
        # world-size-change regression, tests/test_elastic.py)
        fp = sysinfo.topology_fingerprint(devices)
        if not profile.matches(fp):
            log_warning(
                "tuner: profile %s was measured on a different topology "
                "(profile %r vs probed %r); rejecting it — rerun MLSL_TUNE=1 "
                "on this machine/world", config.tune_profile,
                profile.fingerprint, fp,
            )
            return
        config.tuned_profile = profile
    if config.tuned_profile is not None:
        apply_knobs(config, config.tuned_profile)
