"""Straggler sentinel: cross-replica step-time/wait-latency skew detection.

The integrity sentinel (mlsl_tpu.sentinel) catches replicas whose *state*
diverges; nothing catches a replica whose *speed* diverges — a thermally
throttled chip, a host with a noisy neighbor, a degrading ICI link. In a
synchronous data-parallel step every replica waits for the slowest one, so a
persistent straggler taxes the whole world its full skew, and before this
module the only evidence was a post-hoc log read. The sentinel closes the
loop from measurement to action:

1. **Measure** — :meth:`observe` feeds one replica's step wall time (and
   optionally its request wait latency) into per-replica
   :class:`~mlsl_tpu.obs.metrics.Histogram` pairs, windowed per audit
   interval. Each process feeds its OWN replica id (the trainer wires
   ``jax.process_index()``); on the single-controller proof world that is
   one replica, and tests/soaks feed multiple ids explicitly — the compare
   path is id-agnostic by design, so the multi-host plumb (ROADMAP #4's
   remaining work) only has to deliver observations, not new logic.
2. **Compare** — every ``MLSL_STRAGGLER_EVERY`` observed steps per replica
   (the window closes when the fastest-reporting replica has a full one),
   :meth:`maybe_audit` takes each replica's window median and compares it
   to the median-of-medians baseline. A replica past
   ``MLSL_STRAGGLER_SKEW`` x baseline is suspect; ``MLSL_STRAGGLER_SUSTAIN``
   consecutive suspect audits make it a confirmed straggler (one slow GC
   pause must not shed a replica).
3. **Act** — a confirmed straggler fires a DEGRADE-style event
   (core/stats.record_straggler: STRAGGLER line + counters + an obs
   timeline instant) and, when ``MLSL_STRAGGLER_SHED`` arms it, is exposed
   as :meth:`shed_candidate` — FaultTolerantLoop hands it to the elastic
   coordinator (``ElasticCoordinator.shed``) as a synthetic DEVICE_LOSS, so
   the same shrink/budget/grow machinery that answers a preemption answers
   a chronic straggler.

Wait latency rides along because it separates the two straggler classes:
a slow-compute replica has high step time and LOW wait (everyone waits for
it); a slow-link replica has high wait. The fired event carries both.

Process-wide module state mirrors the other sentinels: the armed instance
registers itself so ``supervisor.status()['straggler']`` (and /healthz)
reports it without a trainer handle.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from mlsl_tpu.log import log_warning
from mlsl_tpu.obs import metrics as metrics_mod
from mlsl_tpu.obs import tracer as obs

ENV_SKEW = "MLSL_STRAGGLER_SKEW"
ENV_EVERY = "MLSL_STRAGGLER_EVERY"
ENV_SUSTAIN = "MLSL_STRAGGLER_SUSTAIN"
ENV_SHED = "MLSL_STRAGGLER_SHED"

DEFAULT_EVERY = 20
DEFAULT_SUSTAIN = 2
#: minimum per-replica observations inside a window before it may be judged
#: (a replica that contributed one sample to this window is data, not a
#: distribution)
MIN_WINDOW_SAMPLES = 3


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class StragglerSentinel:
    """Per-replica skew monitor. Constructed by the trainer when
    ``MLSL_STRAGGLER_SKEW`` arms it (models/train.py), or explicitly by
    tests/soaks."""

    def __init__(self, skew: Optional[float] = None,
                 every: Optional[int] = None,
                 sustain: Optional[int] = None,
                 shed: Optional[bool] = None):
        from mlsl_tpu.config import _env_bool, _env_float, _env_int

        if skew is None:
            skew = _env_float(ENV_SKEW, 0.0)
        if every is None:
            every = _env_int(ENV_EVERY, DEFAULT_EVERY)
        if sustain is None:
            sustain = _env_int(ENV_SUSTAIN, DEFAULT_SUSTAIN)
        if shed is None:
            shed = _env_bool(ENV_SHED, False)
        self.skew = float(skew)
        # a window below the judgeable minimum would close before any
        # replica reaches MIN_WINDOW_SAMPLES and silently disable detection
        # (Config.validate enforces the same floor for the env knob)
        self.every = max(int(every), MIN_WINDOW_SAMPLES)
        self.sustain = max(int(sustain), 1)
        self.shed = bool(shed)
        # lifetime distributions (scrape surface): per-replica histograms in
        # the process registry when armed, so /metrics exposes
        # mlsl_replica_step_ms{replica=...} without extra bookkeeping
        self._win_step: Dict[int, List[float]] = {}
        self._win_wait: Dict[int, List[float]] = {}
        self._suspect_streak: Dict[int, int] = {}
        self._remote_replicas: set = set()
        self._audits = 0
        self._flagged: Dict[int, dict] = {}
        self._candidate: Optional[int] = None
        self._lock = threading.Lock()
        _set_active(self)

    # -- feed --------------------------------------------------------------

    def observe(self, replica: int, step_ms: float,
                wait_ms: Optional[float] = None) -> None:
        """One replica-step observation (trainer hot path; cheap: two list
        appends, plus registry histogram upserts when metrics is armed)."""
        replica = int(replica)
        with self._lock:
            self._win_step.setdefault(replica, []).append(float(step_ms))
            if wait_ms is not None:
                self._win_wait.setdefault(replica, []).append(float(wait_ms))
        m = metrics_mod._registry
        if m is not None:
            m.observe("mlsl_replica_step_ms", step_ms, replica=replica)
            if wait_ms is not None:
                m.observe("mlsl_replica_wait_ms", wait_ms, replica=replica)

    def observe_remote(self, replica: int, samples) -> None:
        """Feed a REMOTE rank's step times (delivered over control-plane
        heartbeat frames — ROADMAP #2b closed: the multi-host plumb only
        had to deliver observations). Runs on the control listener thread:
        host-side list appends under the same lock as :meth:`observe`, no
        device work (the A202 contract). Remote ranks are tracked so
        /healthz shows the audit baseline truly spans the pod."""
        replica = int(replica)
        with self._lock:
            self._remote_replicas.add(replica)
        for ms in samples:
            self.observe(replica, float(ms))

    # -- compare -----------------------------------------------------------

    def maybe_audit(self, step: int) -> Optional[dict]:
        """Run the cross-replica comparison when a full window has
        accumulated; returns the audit verdict dict when an audit ran (None
        otherwise). Called by the trainer each step. ``every`` is
        observations PER REPLICA (= steps, at one observe per step): the
        window closes when the fastest-reporting replica has a full one —
        counting TOTAL observations would shrink every replica's window as
        the world grows, until past ``every/MIN_WINDOW_SAMPLES`` replicas
        nobody ever reaches the judgeable minimum and detection silently
        turns off."""
        with self._lock:
            if not self._win_step or max(
                    len(v) for v in self._win_step.values()) < self.every:
                return None
        return self.audit_now(step)

    def audit_now(self, step: int = 0) -> dict:
        """One cross-replica comparison over the current windows (the
        windows reset afterwards). With fewer than two replicas reporting
        there is no baseline — the audit records itself and clears, firing
        nothing (zero false positives on a world that cannot skew)."""
        from mlsl_tpu.core import stats as stats_mod

        with self._lock:
            win_step = {r: v for r, v in self._win_step.items()
                        if len(v) >= MIN_WINDOW_SAMPLES}
            win_wait = {r: list(v) for r, v in self._win_wait.items()}
            self._win_step = {}
            self._win_wait = {}
            self._audits += 1
            # a replica absent from (or data-starved in) this window was
            # not JUDGED, so it cannot extend a suspect streak — without
            # this, two suspect audits any distance apart would read as
            # "consecutive" and confirm a replica that was slow twice in a
            # month (the one-GC-pause class sustain exists to filter)
            for r in list(self._suspect_streak):
                if r not in win_step:
                    self._suspect_streak.pop(r)
        stats_mod.record_straggler("audits")
        verdict = {"step": step, "replicas": sorted(win_step),
                   "suspects": [], "confirmed": []}
        if len(win_step) < 2:
            return verdict
        medians = {r: _median(v) for r, v in win_step.items()}
        verdict["baseline_ms"] = _median(list(medians.values()))
        for r, med in medians.items():
            # a replica is judged against its PEERS' median, never a pool
            # that includes itself — with two replicas a 3x straggler would
            # otherwise drag the baseline up and read as only 1.5x
            peers = [m for rr, m in medians.items() if rr != r]
            baseline = _median(peers)
            if baseline <= 0:
                continue
            ratio = med / baseline
            if self.skew > 0 and ratio > self.skew:
                verdict["suspects"].append(r)
                with self._lock:
                    streak = self._suspect_streak.get(r, 0) + 1
                    self._suspect_streak[r] = streak
                if streak >= self.sustain:
                    self._fire(r, step, med, baseline, ratio,
                               _median(win_wait.get(r, [])))
                    verdict["confirmed"].append(r)
            else:
                with self._lock:
                    self._suspect_streak.pop(r, None)
        return verdict

    # -- act ---------------------------------------------------------------

    def _fire(self, replica: int, step: int, med_ms: float,
              baseline_ms: float, ratio: float, wait_med_ms: float) -> None:
        from mlsl_tpu.core import stats as stats_mod

        detail = (f"replica={replica} step={step} p50={med_ms:.2f}ms "
                  f"baseline={baseline_ms:.2f}ms skew={ratio:.2f}x "
                  f"wait_p50={wait_med_ms:.2f}ms "
                  f"({'shed-armed' if self.shed else 'observe-only'})")
        sets_candidate = False
        with self._lock:
            # the write must hold the lock: status() (the /healthz scrape
            # thread) iterates _flagged under it, and an unlocked insert
            # here would 500 the scrape mid-incident
            first = replica not in self._flagged
            self._flagged[replica] = {
                "step": step, "skew": round(ratio, 3),
                "p50_ms": round(med_ms, 3),
                "baseline_ms": round(baseline_ms, 3),
                "wait_p50_ms": round(wait_med_ms, 3),
            }
            if self.shed and self._candidate is None:
                self._candidate = replica
                sets_candidate = True
        # one FLAGS event per confirmation that is NEWS: the first time a
        # replica is confirmed, or a re-confirmation that arms a fresh shed
        # candidate (post clear_candidate). Shed-armed with the candidate
        # still pending un-consumed (no elastic coordinator in the loop)
        # must NOT re-record every audit — flags counts stragglers, not
        # audit intervals, and one chronic straggler must not fill the log
        if not (first or sets_candidate):
            return
        stats_mod.record_straggler("flags", detail)
        log_warning("straggler sentinel: %s", detail)
        tr = obs._tracer
        if tr is not None:
            # DEGRADE-style timeline annotation: the straggler interval
            # starts here; a shed (resilience loop) closes it with an
            # elastic.shrink span
            tr.instant("straggler.flag", "straggler", replica=replica,
                       step=step, skew=round(ratio, 3),
                       p50_ms=round(med_ms, 3),
                       baseline_ms=round(baseline_ms, 3))

    def shed_candidate(self) -> Optional[int]:
        """The confirmed straggler awaiting an elastic shed (None when shed
        is unarmed or nothing is confirmed). FaultTolerantLoop polls this
        between steps and hands it to ``ElasticCoordinator.shed``."""
        return self._candidate

    def clear_candidate(self) -> None:
        """The loop took (or refused) the candidate; a later audit must
        re-confirm before another shed fires."""
        with self._lock:
            self._candidate = None
            self._suspect_streak.clear()

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """JSON-serializable summary for supervisor.status()['straggler']
        (the /healthz contract). ``state`` uses its own vocabulary
        ('watching'/'flagged') — stats.print_ lists it in the DEGRADE line
        only when flagged, the elastic/'full' lesson."""
        with self._lock:
            return {
                "state": "flagged" if self._flagged else "watching",
                "skew_threshold": self.skew,
                "every": self.every,
                "sustain": self.sustain,
                "shed_armed": self.shed,
                "audits": self._audits,
                "flagged": {str(r): dict(v)
                            for r, v in self._flagged.items()},
                "shed_candidate": self._candidate,
                "remote_replicas": sorted(self._remote_replicas),
            }


#: the armed process-wide instance (the sentinel/elastic registry pattern:
#: supervisor.status() must report it with no trainer handle in scope)
_active: Optional[StragglerSentinel] = None


def _set_active(s: Optional[StragglerSentinel]) -> None:
    global _active
    _active = s


def get_active() -> Optional[StragglerSentinel]:
    return _active


def reset() -> None:
    """Drop the active instance (tests)."""
    _set_active(None)


def armed(config=None) -> bool:
    """Is the straggler sentinel armed (MLSL_STRAGGLER_SKEW > 0 /
    Config.straggler_skew)?"""
    if config is not None:
        return float(getattr(config, "straggler_skew", 0.0) or 0.0) > 0
    try:
        return float(os.environ.get(ENV_SKEW) or 0.0) > 0
    except ValueError:
        return False


def status() -> dict:
    """Module-level summary for supervisor.status()."""
    if _active is None:
        return {"state": "off"}
    return _active.status()
