"""Opt-in HTTP scrape surface for the telemetry plane (stdlib-only).

``MLSL_METRICS_PORT=<port>`` (or :func:`start_server`) runs one daemon
``ThreadingHTTPServer`` thread serving:

- ``/metrics`` — the registry in Prometheus text exposition format
  (``obs/metrics.py to_prometheus``); scrape it with any Prometheus-
  compatible collector.
- ``/healthz`` — ``supervisor.status()`` rendered VERBATIM as JSON: breaker
  states, sentinel/analysis verdicts, elastic world state, straggler state,
  registry summary. tests/test_metrics.py pins JSON round-trip
  serializability so a non-serializable field fails in tier-1, not in a
  production scrape.
- ``/statusz`` — human one-screen summary (plain text): world/health header
  plus the per-series table the trace_view ``--metrics`` mode renders.

Design constraints (why this is not a web framework):

- The handler thread only READS process-wide state (registry snapshots,
  breaker status dicts) — it never dispatches device programs (the A202
  hazard: a second thread launching SPMD programs wedges the XLA:CPU
  rendezvous) and never blocks the training loop.
- Port 0 binds an ephemeral port (tests); the bound port is on
  ``MetricsServer.port``.
- Serving failures return 500 with the error text instead of killing the
  thread; request logging routes to log_debug (a scrape every few seconds
  must not spam stderr).
- The server is process-wide like the tracer: ``Environment.finalize`` does
  NOT stop it (a recovery teardown/rebuild cycle must not drop the scrape
  surface mid-incident); :func:`stop_server` stops it explicitly.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mlsl_tpu.log import log_debug, log_warning
from mlsl_tpu.obs import metrics as metrics_mod

ENV_PORT = "MLSL_METRICS_PORT"
ENV_ADDR = "MLSL_METRICS_ADDR"
DEFAULT_ADDR = ""  # all interfaces: the scrape surface is for remote collectors


def healthz_doc() -> dict:
    """The /healthz body: ``supervisor.status()`` verbatim (lazy import —
    supervisor sits above obs in the import graph), plus — on the pod
    LEADER only — a ``pod`` key merging every member's last pushed status
    snapshot and heartbeat age (mlsl_tpu.control): one scrape of the leader
    answers for the whole pod, which is the point of electing one."""
    from mlsl_tpu import control as control_mod
    from mlsl_tpu import supervisor

    doc = supervisor.status()
    plane = control_mod.get_active()
    if plane is not None and plane.is_leader():
        doc["pod"] = plane.pod_status()
    return doc


def statusz_text() -> str:
    """The /statusz body: one screen of human-readable health."""
    lines = ["mlsl_tpu statusz", "================", ""]
    try:
        doc = healthz_doc()
        elastic = doc.get("elastic", {})
        lines.append(
            f"world: {elastic.get('active_size')}/{elastic.get('world_size')}"
            f" devices ({elastic.get('state', '?')})"
        )
        breakers = ", ".join(
            f"{name}:{st['state']}"
            for name, st in sorted(doc.items())
            # breaker-shaped entries only: elastic is on the world line,
            # straggler and control have their own lines below — listing
            # 'watching'/'member' here would read a healthy sentinel as a
            # degraded subsystem
            if isinstance(st, dict) and "state" in st
            and name not in ("elastic", "straggler", "control")
        )
        if breakers:
            lines.append(f"subsystems: {breakers}")
        ctl = doc.get("control", {})
        if ctl.get("state", "off") != "off":
            lines.append(
                f"pod: {ctl.get('state')} rank={ctl.get('rank')} "
                f"epoch={ctl.get('epoch')} leader={ctl.get('leader')} "
                f"alive={ctl.get('alive')} dead={ctl.get('dead')}"
            )
        strag = doc.get("straggler", {})
        if strag.get("state", "off") != "off":
            lines.append(
                f"straggler: {strag.get('state')} "
                f"(flagged={strag.get('flagged')}, "
                f"audits={strag.get('audits')})"
            )
        mets = doc.get("metrics", {})
        lines.append(
            f"metrics: {'armed' if mets.get('armed') else 'off'}"
            + (f" ({mets.get('series')} series, "
               f"{mets.get('samples_taken')} samples)"
               if mets.get("armed") else "")
        )
    except Exception as e:  # the summary must render even half-initialized
        lines.append(f"status unavailable: {type(e).__name__}: {e}")
    reg = metrics_mod._registry
    if reg is not None:
        lines += ["", "series:",
                  metrics_mod.render_summary(
                      metrics_mod.summarize_jsonl(
                          reg.jsonl_snapshot().splitlines()))]
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "mlsl-metrics/1"

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                reg = metrics_mod._registry
                body = reg.to_prometheus() if reg is not None else ""
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/healthz":
                body = json.dumps(healthz_doc())
                ctype = "application/json"
            elif path in ("/", "/statusz"):
                body = statusz_text()
                ctype = "text/plain; charset=utf-8"
            else:
                self._respond(404, "text/plain", f"no such endpoint: {path}\n")
                return
            self._respond(200, ctype, body)
        except Exception as e:
            self._respond(500, "text/plain",
                          f"{type(e).__name__}: {e}\n")

    def _respond(self, code: int, ctype: str, body: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-body; nothing to recover

    def log_message(self, fmt, *args):  # noqa: A003 - handler API
        log_debug("metrics server: " + fmt, *args)


class MetricsServer:
    """One ThreadingHTTPServer on a daemon thread."""

    def __init__(self, port: int, addr: Optional[str] = None):
        if addr is None:
            addr = os.environ.get(ENV_ADDR, DEFAULT_ADDR)
        self._httpd = ThreadingHTTPServer((addr, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.addr = addr
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mlsl-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        if self._thread.is_alive():  # pragma: no cover - defensive
            log_warning("metrics server thread did not stop within 5s")


#: the process-wide server (one scrape surface per process, like the tracer)
_server: Optional[MetricsServer] = None


def get_server() -> Optional[MetricsServer]:
    return _server


def start_server(port: Optional[int] = None,
                 addr: Optional[str] = None) -> Optional[MetricsServer]:
    """Start the scrape surface (idempotent; the first successful start
    wins). ``port`` defaults to MLSL_METRICS_PORT (unset/0 there = do not
    serve); an EXPLICIT ``port=0`` binds an ephemeral port (tests read it
    back from ``MetricsServer.port``). The registry is armed alongside — a
    scrape surface over a disabled registry would answer every /metrics
    with an empty document. Failures (port in use) log a warning and return
    None: telemetry must never take the training job down."""
    global _server
    if _server is not None:
        return _server
    if port is None:
        env_port = os.environ.get(ENV_PORT)
        if not env_port:
            return None
        try:
            port = int(env_port)
        except ValueError:
            log_warning("invalid %s=%r; metrics server not started",
                        ENV_PORT, env_port)
            return None
        if port <= 0:
            return None
    if int(port) < 0:
        return None
    metrics_mod.enable()
    try:
        _server = MetricsServer(int(port), addr=addr)
    except OSError as e:
        log_warning("metrics server failed to bind port %s: %s — telemetry "
                    "endpoints disabled for this run", port, e)
        return None
    log_debug("metrics server listening on %s:%d", _server.addr or "0.0.0.0",
              _server.port)
    return _server


def stop_server() -> None:
    global _server
    if _server is not None:
        _server.stop()
        _server = None
