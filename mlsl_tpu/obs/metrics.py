"""Process-wide typed time-series metrics: the telemetry plane's data model.

The span tracer (``obs/tracer.py``) answers *which request stalled and when*;
the ``core/stats.py`` counter families answer *how much, in total, since
start*. Neither gives a scrape surface or a trend: there is no way to ask a
running trainer "what is step p99 right now" without stopping it and reading
a log. This module closes that gap with a typed registry —

- :class:`Counter` — monotone total (dispatches, bytes, events);
- :class:`Gauge`   — last-written scalar (loss, budget remaining);
- :class:`Histogram` — fixed-bucket latency/size distribution with
  bucket-interpolated p50/p95/p99 (step_ms, dispatch→wait latency, achieved
  algbw);

each retaining a bounded ring of timestamped samples (``MLSL_METRICS_RETENTION``
samples per series, the tracer's deque(maxlen) discipline: a week-long run
keeps the trailing window, not an unbounded log). The sampler
(:func:`sample_families`) snapshots every existing ``core/stats`` counter
family (BUCKET/ALGO/FEED/SENTINEL/DEGRADE/OVERLAP/ELASTIC/ANALYSIS/CHKP/
STRAGGLER/CODEC) into gauges, so one registry covers the whole stack; the trainer
feeds per-step scalars on the ``MLSL_METRICS_EVERY`` cadence
(models/train.py) and the request layer feeds per-request latency on every
completed wait (comm/request.py).

Exports: :meth:`MetricsRegistry.to_prometheus` (text exposition format —
``obs/serve.py`` serves it on ``/metrics``) and
:meth:`MetricsRegistry.jsonl_snapshot` (JSON-lines, one line per live
series, appended to ``mlsl_metrics.jsonl`` under ``MLSL_STATS_DIR`` on each
sampler tick; ``scripts/trace_view.py --metrics`` summarizes the file).

Hot-path contract (the tracer/chaos precedent, pinned by tracemalloc in
tests/test_metrics.py and benchmarks/metrics_overhead_bench.py):
instrumented code reads the module global once per operation —
``m = metrics._registry`` / ``if m is not None:`` — so the disabled path is
ONE attribute load and a None test with zero allocations. Series internals
deliberately carry distinctive ``_m*`` names (``_mval``/``_mcounts``/
``_msum``/``_mn``/``_msamples``/``_mseries``): lint rule A207
(analysis/lint.py) rejects any mutation of them outside this module's
record/observe/sample paths — the A203 single-mutation-discipline contract,
extended to the registry.

Thread-safety: series creation takes the registry lock; the record paths are
lock-free (int/float upserts and deque appends under the GIL — a racing
increment can lose a count, never corrupt a structure; the same trade the
tracer and ALGO_COUNTERS already make).
"""

from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_METRICS = "MLSL_METRICS"
ENV_EVERY = "MLSL_METRICS_EVERY"
ENV_RETENTION = "MLSL_METRICS_RETENTION"

DEFAULT_EVERY = 20
DEFAULT_RETENTION = 512

#: default histogram bucket upper bounds, ms-scale (latency series); an
#: explicit ``buckets=`` at first creation wins (algbw series pass GB/s-scale
#: bounds). Fixed buckets keep ``observe`` O(log B) with zero allocations
#: beyond the deque sample ring.
DEFAULT_BUCKETS_MS = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

#: GB/s-scale bounds for the achieved-algbw series (ICI sits at tens-of-GB/s,
#: DCN and the CPU proof mesh orders below)
ALGBW_BUCKETS_GBPS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 25.0,
    50.0, 100.0, 200.0, 400.0,
)

COUNTER, GAUGE, HISTOGRAM = "counter", "gauge", "histogram"

LabelsT = Tuple[Tuple[str, str], ...]


def _labels_key(labels: dict) -> LabelsT:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotone total. ``inc`` is the only mutation path (A207)."""

    __slots__ = ("name", "labels", "_mval", "_msamples")
    kind = COUNTER

    def __init__(self, name: str, labels: LabelsT, retention: int):
        self.name = name
        self.labels = labels
        self._mval = 0.0
        self._msamples = collections.deque(maxlen=retention)

    def inc(self, v: float = 1.0) -> None:
        self._mval += v

    @property
    def value(self) -> float:
        return self._mval

    def record_sample(self, ts: float) -> dict:
        snap = {"t": ts, "value": self._mval}
        self._msamples.append(snap)
        return snap

    def snapshot(self) -> dict:
        return {"value": self._mval}


class Gauge:
    """Last-written scalar. ``set`` is the only mutation path (A207)."""

    __slots__ = ("name", "labels", "_mval", "_msamples")
    kind = GAUGE

    def __init__(self, name: str, labels: LabelsT, retention: int):
        self.name = name
        self.labels = labels
        self._mval = 0.0
        self._msamples = collections.deque(maxlen=retention)

    def set(self, v: float) -> None:
        self._mval = float(v)

    @property
    def value(self) -> float:
        return self._mval

    def record_sample(self, ts: float) -> dict:
        snap = {"t": ts, "value": self._mval}
        self._msamples.append(snap)
        return snap

    def snapshot(self) -> dict:
        return {"value": self._mval}


class Histogram:
    """Fixed-bucket distribution; ``observe`` is the only mutation path
    (A207). ``buckets`` are upper bounds; one overflow bucket (+Inf) rides at
    the end. Percentiles interpolate linearly inside the winning bucket —
    exact enough for p50/p95/p99 dashboards at ~16 buckets, allocation-free
    on the observe path."""

    __slots__ = ("name", "labels", "buckets", "_mcounts", "_msum", "_mn",
                 "_msamples")
    kind = HISTOGRAM

    def __init__(self, name: str, labels: LabelsT, retention: int,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.labels = labels
        self.buckets = tuple(float(b) for b in (buckets or DEFAULT_BUCKETS_MS))
        self._mcounts = [0] * (len(self.buckets) + 1)
        self._msum = 0.0
        self._mn = 0
        self._msamples = collections.deque(maxlen=retention)

    def observe(self, v: float) -> None:
        self._mcounts[bisect.bisect_left(self.buckets, v)] += 1
        self._msum += v
        self._mn += 1

    @property
    def count(self) -> int:
        return self._mn

    @property
    def sum(self) -> float:
        return self._msum

    def percentile(self, pct: float) -> float:
        """Bucket-interpolated percentile over everything observed so far.
        0.0 with no observations; the overflow bucket reports its lower
        bound (the largest finite boundary)."""
        n = self._mn
        if n <= 0:
            return 0.0
        rank = pct / 100.0 * n
        acc = 0
        for i, c in enumerate(self._mcounts):
            if c == 0:
                continue
            if acc + c >= rank:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                frac = (rank - acc) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            acc += c
        return self.buckets[-1]

    def record_sample(self, ts: float) -> dict:
        snap = {
            "t": ts, "n": self._mn, "sum": round(self._msum, 6),
            "p50": round(self.percentile(50), 6),
            "p95": round(self.percentile(95), 6),
            "p99": round(self.percentile(99), 6),
        }
        self._msamples.append(snap)
        return snap

    def snapshot(self) -> dict:
        return {
            "n": self._mn, "sum": self._msum,
            "p50": self.percentile(50), "p95": self.percentile(95),
            "p99": self.percentile(99),
            "buckets": list(zip(self.buckets, self._mcounts)),
            "overflow": self._mcounts[-1],
        }


class MetricsRegistry:
    """The process-wide series table. One instance per process (module
    global ``_registry``); instrumented code never constructs one."""

    def __init__(self, every: int = DEFAULT_EVERY,
                 retention: int = DEFAULT_RETENTION):
        self.every = max(int(every), 1)
        self.retention = max(int(retention), 2)
        self.created_at = time.time()
        self.samples_taken = 0
        self.last_sample_at: Optional[float] = None
        self._mseries: Dict[Tuple[str, LabelsT], object] = {}
        self._lock = threading.Lock()

    # -- series access (get-or-create; creation under the lock) -----------

    def _get(self, cls, name: str, labels: dict, **kw):
        key = (name, _labels_key(labels))
        s = self._mseries.get(key)
        if s is None:
            with self._lock:
                s = self._mseries.get(key)
                if s is None:
                    s = cls(name, key[1], self.retention, **kw)
                    self._mseries[key] = s
        return s

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    # -- hot-path shorthands ----------------------------------------------

    def inc(self, name: str, v: float = 1.0, **labels) -> None:
        self.counter(name, **labels).inc(v)

    def set(self, name: str, v: float, **labels) -> None:
        self.gauge(name, **labels).set(v)

    def observe(self, name: str, v: float,
                buckets: Optional[Tuple[float, ...]] = None,
                **labels) -> None:
        self.histogram(name, buckets=buckets, **labels).observe(v)

    # -- queries ------------------------------------------------------------

    def series(self) -> List[object]:
        return list(self._mseries.values())

    def find(self, name: str, **labels):
        return self._mseries.get((name, _labels_key(labels)))

    def status(self) -> dict:
        """Registry summary for supervisor.status()['metrics'] — deliberately
        NOT breaker-shaped (no 'state' key: the DEGRADE-line and abort-log
        consumers iterate breaker entries by that key)."""
        return {
            "armed": True,
            "series": len(self._mseries),
            "every": self.every,
            "retention": self.retention,
            "samples_taken": self.samples_taken,
            "last_sample_at": self.last_sample_at,
        }

    # -- sampling ------------------------------------------------------------

    def sample_families(self) -> None:
        """Snapshot every core/stats counter family into gauges: one
        registry covers the whole stack's totals, time-stamped on the
        sampler cadence so trends (and the straggler/SLA dashboards) see
        rates, not just lifetime sums. Lazy import: core.stats imports
        obs.tracer through the obs package, so a module-level import here
        would cycle."""
        from mlsl_tpu.core import stats as st

        for fam, d in (
            ("bucket", st.BUCKET_COUNTERS),
            ("feed", st.FEED_COUNTERS),
            ("sentinel", st.SENTINEL_COUNTERS),
            ("degrade", st.DEGRADE_COUNTERS),
            ("overlap", st.OVERLAP_COUNTERS),
            ("elastic", st.ELASTIC_COUNTERS),
            ("analysis", st.ANALYSIS_COUNTERS),
            ("chkp", st.CHKP_COUNTERS),
            ("straggler", st.STRAGGLER_COUNTERS),
            ("serve", st.SERVE_COUNTERS),
            ("codec", st.CODEC_COUNTERS),
            ("lockwitness", st.LOCKWITNESS_COUNTERS),
        ):
            for k, v in d.items():
                self.set(f"mlsl_{fam}_{k}", float(v))
        for (kind, algo), n in list(st.ALGO_COUNTERS.items()):
            self.set("mlsl_algo_dispatches", float(n), kind=kind, algo=algo)
        for subsystem, n in list(st.DEGRADE_FALLBACKS.items()):
            self.set("mlsl_degrade_fallback", float(n), subsystem=subsystem)
        for codec, n in list(st.CODEC_WIRE_BYTES.items()):
            self.set("mlsl_codec_wire_bytes", float(n), codec=codec)

    def sample(self, ts: Optional[float] = None) -> List[dict]:
        """One sampler tick: append a timestamped sample to every live
        series' ring and return the JSONL-shaped records."""
        ts = time.time() if ts is None else ts
        out = []
        for (name, labels), s in list(self._mseries.items()):
            rec = {"series": name, "kind": s.kind}
            if labels:
                rec["labels"] = dict(labels)
            rec.update(s.record_sample(round(ts, 3)))
            out.append(rec)
        self.samples_taken += 1
        self.last_sample_at = ts
        return out

    # -- exports -------------------------------------------------------------

    def jsonl_snapshot(self) -> str:
        """Current value of every series, one JSON object per line (the
        ``mlsl_metrics.jsonl`` record shape; does not advance the rings)."""
        ts = round(time.time(), 3)
        lines = []
        for (name, labels), s in sorted(self._mseries.items()):
            rec = {"t": ts, "series": name, "kind": s.kind}
            if labels:
                rec["labels"] = dict(labels)
            for k, v in s.snapshot().items():
                if k != "buckets":  # bucket arrays stay scrape-only
                    rec[k] = round(v, 6) if isinstance(v, float) else v
            lines.append(json.dumps(rec))
        return "\n".join(lines) + ("\n" if lines else "")

    def write_jsonl(self, path: Optional[str] = None,
                    records: Optional[List[dict]] = None) -> Optional[str]:
        """Append a snapshot (or the given sampler records) to the metrics
        JSONL file (``MLSL_STATS_DIR``-routed like mlsl_stats.log). Returns
        the path, or None when the write failed (IO must never take the
        training loop down — the tracer-exporter contract)."""
        if path is None:
            path = jsonl_path()
        try:
            with open(path, "a") as f:
                if records is None:
                    f.write(self.jsonl_snapshot())
                else:
                    for rec in records:
                        f.write(json.dumps(rec) + "\n")
            return path
        except OSError:
            return None

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (served on ``/metrics``).
        Series names are sanitized to the metric-name grammar; histogram
        series render the standard ``_bucket``/``_sum``/``_count`` triple
        with cumulative ``le`` bounds."""
        by_name: Dict[str, List[Tuple[LabelsT, object]]] = {}
        for (name, labels), s in sorted(self._mseries.items()):
            by_name.setdefault(name, []).append((labels, s))
        lines: List[str] = []
        for name, entries in by_name.items():
            pname = _prom_name(name)
            kind = entries[0][1].kind
            lines.append(f"# TYPE {pname} {kind}")
            for labels, s in entries:
                lab = _prom_labels(labels)
                if kind == HISTOGRAM:
                    acc = 0
                    for bound, c in zip(s.buckets, s._mcounts):
                        acc += c
                        lines.append(
                            f"{pname}_bucket"
                            f"{_prom_labels(labels, ('le', _fmt(bound)))}"
                            f" {acc}"
                        )
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(labels, ('le', '+Inf'))} {s._mn}"
                    )
                    lines.append(f"{pname}_sum{lab} {_fmt(s._msum)}")
                    lines.append(f"{pname}_count{lab} {s._mn}")
                else:
                    lines.append(f"{pname}{lab} {_fmt(s._mval)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in ("_", ":")
        if i == 0 and ch.isdigit():
            ok = False
        out.append(ch if ok else "_")
    return "".join(out)


def _prom_labels(labels: LabelsT, extra: Optional[Tuple[str, str]] = None
                 ) -> str:
    items = list(labels)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (
            _prom_name(k),
            str(v).replace("\\", "\\\\").replace('"', '\\"'),
        )
        for k, v in items
    )
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def jsonl_path() -> str:
    """Where the sampler's JSON-lines snapshots land: MLSL_STATS_DIR
    (default CWD), the mlsl_stats.log routing contract."""
    d = os.environ.get("MLSL_STATS_DIR")
    name = "mlsl_metrics.jsonl"
    return os.path.join(d, name) if d else name


#: THE hot-path guard: None = disabled. Instrumented code reads this once
#: per operation (``m = metrics._registry``) and does nothing when None.
_registry: Optional[MetricsRegistry] = None


def enabled() -> bool:
    return _registry is not None


def get_registry() -> Optional[MetricsRegistry]:
    return _registry


def enable(every: Optional[int] = None,
           retention: Optional[int] = None) -> MetricsRegistry:
    """Arm the registry (idempotent). Knobs default to MLSL_METRICS_EVERY /
    MLSL_METRICS_RETENTION. An EXPLICIT knob always binds, even when the
    registry is already armed — MLSL_METRICS=1 arms at import with the env
    defaults, and Environment.init re-enables with the validated (possibly
    tuner-profiled) Config values, which must not be silently dropped.
    ``retention`` applies to series created afterwards (existing rings keep
    their maxlen — a ring cannot be resized in place)."""
    global _registry
    if _registry is None:
        if every is None:
            every = int(os.environ.get(ENV_EVERY) or DEFAULT_EVERY)
        if retention is None:
            retention = int(os.environ.get(ENV_RETENTION)
                            or DEFAULT_RETENTION)
        _registry = MetricsRegistry(every=every, retention=retention)
    else:
        if every is not None:
            _registry.every = max(int(every), 1)
        if retention is not None:
            _registry.retention = max(int(retention), 2)
    return _registry


def disable() -> None:
    """Disarm; the series table is dropped (export first if needed)."""
    global _registry
    _registry = None


def status() -> dict:
    """Module-level summary for supervisor.status()['metrics']."""
    if _registry is None:
        return {"armed": False}
    return _registry.status()


# -- JSONL summarization (trace_view --metrics / the statusz text) -----------


def summarize_jsonl(lines) -> Dict[Tuple[str, str], dict]:
    """Aggregate a metrics JSONL stream into per-series summaries:
    ``{(series, labels_repr): {kind, n_samples, last, p50, p95, p99, max}}``.
    Gauge/counter percentiles are over the sampled VALUES (the time series);
    histogram lines carry their own percentiles — the summary reports the
    latest plus the max-seen p99."""
    acc: Dict[Tuple[str, str], dict] = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        name = rec.get("series")
        if not name:
            continue
        lkey = ",".join(
            f"{k}={v}" for k, v in sorted((rec.get("labels") or {}).items())
        )
        ent = acc.setdefault((name, lkey), {
            "kind": rec.get("kind", "?"), "n_samples": 0, "values": [],
            "last": None, "p99_max": 0.0,
        })
        ent["n_samples"] += 1
        if rec.get("kind") == HISTOGRAM:
            ent["last"] = {k: rec.get(k) for k in
                           ("n", "sum", "p50", "p95", "p99")}
            ent["p99_max"] = max(ent["p99_max"], float(rec.get("p99") or 0.0))
        else:
            v = rec.get("value")
            if v is not None:
                ent["values"].append(float(v))
                ent["last"] = float(v)
    for ent in acc.values():
        vals = sorted(ent.pop("values"))
        if vals:
            ent["min"] = vals[0]
            ent["max"] = vals[-1]
            for pct, key in ((50, "p50"), (95, "p95"), (99, "p99")):
                k = max(0, min(len(vals) - 1,
                               int(round(pct / 100.0 * (len(vals) - 1)))))
                ent[key] = vals[k]
    return acc


def render_summary(acc: Dict[Tuple[str, str], dict], top: int = 0) -> str:
    """Terminal table for :func:`summarize_jsonl` output (shared by
    trace_view --metrics and the statusz renderer)."""
    rows = []
    for (name, lkey), ent in sorted(acc.items()):
        label = f"{name}{{{lkey}}}" if lkey else name
        if ent["kind"] == HISTOGRAM and isinstance(ent.get("last"), dict):
            last = ent["last"]
            rows.append(
                f"  {label:<44} hist  n={last.get('n', 0):>8} "
                f"p50={last.get('p50', 0):>10.3f} "
                f"p95={last.get('p95', 0):>10.3f} "
                f"p99={last.get('p99', 0):>10.3f} "
                f"p99_max={ent.get('p99_max', 0):>10.3f}"
            )
        else:
            p50 = ent.get("p50", ent.get("last") or 0.0)
            p99 = ent.get("p99", ent.get("last") or 0.0)
            rows.append(
                f"  {label:<44} {ent['kind']:<5} "
                f"last={ent.get('last') if ent.get('last') is not None else 0:>10.3f} "
                f"p50={p50:>10.3f} p99={p99:>10.3f} "
                f"({ent['n_samples']} samples)"
            )
    if top:
        rows = rows[:top]
    return "\n".join(rows)


# Arm from the environment at import (the MLSL_TRACE/MLSL_CHAOS contract):
# instrumented modules import this module, so MLSL_METRICS=1 on the launch
# command works with no code changes. The truthy table is the tracer's —
# MLSL_TRACE and MLSL_METRICS must parse a value identically.
from mlsl_tpu.obs.tracer import _env_truthy  # noqa: E402

if _env_truthy(os.environ.get(ENV_METRICS)):
    enable()
