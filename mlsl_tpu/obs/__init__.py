"""mlsl_tpu.obs — structured comm-timeline tracing (docs/DESIGN.md
"Observability & tracing").

Quick start::

    MLSL_TRACE=1 python train.py        # arm at launch
    # or programmatically:
    from mlsl_tpu import obs
    obs.enable()
    ... run ...
    path = obs.write_trace()            # load in ui.perfetto.dev

Env knobs: ``MLSL_TRACE`` (arm), ``MLSL_TRACE_DIR`` (output directory,
default CWD), ``MLSL_TRACE_CAPACITY`` (ring size in events, default 65536).

On a watchdog trip (``MLSLTimeoutError``) the flight recorder dumps the
trailing window of spans to ``trace-crash-<ts>.json`` automatically.
"""

from mlsl_tpu.obs.tracer import (  # noqa: F401
    DEFAULT_CAPACITY,
    Tracer,
    disable,
    enable,
    enabled,
    get_tracer,
    span,
    trace_dir,
)
from mlsl_tpu.obs.export import (  # noqa: F401
    flight_record,
    render,
    summarize,
    to_trace_events,
    write_trace,
)

__all__ = [
    "DEFAULT_CAPACITY",
    "Tracer",
    "disable",
    "enable",
    "enabled",
    "get_tracer",
    "span",
    "trace_dir",
    "flight_record",
    "render",
    "summarize",
    "to_trace_events",
    "write_trace",
]
