"""mlsl_tpu.obs — structured comm-timeline tracing (docs/DESIGN.md
"Observability & tracing").

Quick start::

    MLSL_TRACE=1 python train.py        # arm at launch
    # or programmatically:
    from mlsl_tpu import obs
    obs.enable()
    ... run ...
    path = obs.write_trace()            # load in ui.perfetto.dev

Env knobs: ``MLSL_TRACE`` (arm), ``MLSL_TRACE_DIR`` (output directory,
default CWD), ``MLSL_TRACE_CAPACITY`` (ring size in events, default 65536).

On a watchdog trip (``MLSLTimeoutError``) the flight recorder dumps the
trailing window of spans to ``trace-crash-<ts>.json`` automatically (and,
with ``MLSL_PROFILE_ON_TRIP=1``, a jax.profiler device trace next to it).

The telemetry plane (docs/DESIGN.md "Telemetry plane") rides in the same
package: ``obs.metrics`` (typed time-series registry, ``MLSL_METRICS=1``),
``obs.serve`` (``/metrics`` + ``/healthz`` + ``/statusz`` on
``MLSL_METRICS_PORT``), ``obs.straggler`` (cross-replica skew sentinel,
``MLSL_STRAGGLER_SKEW``)::

    MLSL_METRICS=1 MLSL_METRICS_PORT=9090 python train.py
    curl localhost:9090/metrics   # Prometheus text
    curl localhost:9090/healthz   # supervisor.status() as JSON
"""

from mlsl_tpu.obs.tracer import (  # noqa: F401
    DEFAULT_CAPACITY,
    Tracer,
    disable,
    enable,
    enabled,
    get_tracer,
    span,
    trace_dir,
)
from mlsl_tpu.obs.export import (  # noqa: F401
    flight_record,
    render,
    summarize,
    to_trace_events,
    write_trace,
)
from mlsl_tpu.obs import metrics  # noqa: F401
from mlsl_tpu.obs import serve  # noqa: F401
from mlsl_tpu.obs import straggler  # noqa: F401
from mlsl_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    enable as enable_metrics,
    disable as disable_metrics,
    get_registry,
)
from mlsl_tpu.obs.serve import start_server, stop_server  # noqa: F401
from mlsl_tpu.obs.straggler import StragglerSentinel  # noqa: F401

__all__ = [
    "DEFAULT_CAPACITY",
    "Tracer",
    "disable",
    "enable",
    "enabled",
    "get_tracer",
    "span",
    "trace_dir",
    "flight_record",
    "render",
    "summarize",
    "to_trace_events",
    "write_trace",
    "metrics",
    "serve",
    "straggler",
    "MetricsRegistry",
    "StragglerSentinel",
    "enable_metrics",
    "disable_metrics",
    "get_registry",
    "start_server",
    "stop_server",
]
