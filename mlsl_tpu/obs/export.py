"""Trace export: Chrome/Perfetto ``trace_event`` JSON and the flight recorder.

Renders the tracer ring (obs/tracer.py) in the JSON Array-of-objects format
both chrome://tracing and ui.perfetto.dev load directly
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
each event carries ``ph``/``ts``/``pid``/``tid`` (+ ``dur`` for complete
spans), with ``M``-phase metadata naming the tracks.

Track model: one track per OS thread that emitted events (the dispatcher
progress thread, trainer thread, checkpoint workers — thread-scoped spans like
step phases land there), PLUS one synthetic track per logical timeline — a
request or bucket (events recorded with ``track=``). A request's
submit→defer→dispatch→wait lifecycle then reads as one row regardless of
which thread touched it, which is the whole point: the dispatch may run on
``mlsl-dispatch`` while the wait blocks the trainer thread.

The flight recorder is the crash-path consumer: on an ``MLSLTimeoutError``
the watchdog (core/stats.record_watchdog_event) calls :func:`flight_record`,
which dumps the trailing window of the ring to ``trace-crash-<ts>.json`` in
``MLSL_TRACE_DIR`` — a wedged-wait report arrives with the timeline that led
to it, including the stuck request's own track.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from mlsl_tpu.obs import tracer as tracer_mod
from mlsl_tpu.obs.tracer import ARGS, CAT, DUR, NAME, PH, TID, TRACK

#: synthetic track tids start here; real thread tids are remapped to 0..N-1
TRACK_TID_BASE = 1000


def to_trace_events(events: List[tuple],
                    thread_names: Optional[Dict[int, str]] = None,
                    pid: Optional[int] = None) -> List[dict]:
    """Event tuples -> Chrome trace_event dicts (µs timestamps, one ``M``
    metadata row per named track/thread). Timestamps are rebased to the
    earliest event so the viewer opens at t=0."""
    if pid is None:
        pid = os.getpid()
    thread_names = thread_names or {}
    base_ns = min((ev[tracer_mod.TS] for ev in events), default=0)

    tid_of_thread: Dict[int, int] = {}
    tid_of_track: Dict[str, int] = {}
    out: List[dict] = []

    def thread_tid(ident: int) -> int:
        if ident not in tid_of_thread:
            tid = len(tid_of_thread)
            tid_of_thread[ident] = tid
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": thread_names.get(ident, f"thread-{ident}")},
            })
        return tid_of_thread[ident]

    def track_tid(track: str) -> int:
        if track not in tid_of_track:
            tid = TRACK_TID_BASE + len(tid_of_track)
            tid_of_track[track] = tid
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": track},
            })
        return tid_of_track[track]

    out.append({
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": "mlsl_tpu"},
    })
    for ev in events:
        tid = (track_tid(ev[TRACK]) if ev[TRACK] is not None
               else thread_tid(ev[TID]))
        rec = {
            "ph": ev[PH],
            "name": ev[NAME],
            "cat": ev[CAT],
            "ts": (ev[tracer_mod.TS] - base_ns) / 1e3,
            "pid": pid,
            "tid": tid,
        }
        if ev[PH] == "X":
            rec["dur"] = ev[DUR] / 1e3
        elif ev[PH] == "i":
            rec["s"] = "t"  # instant scope: thread
        if ev[ARGS]:
            rec["args"] = dict(ev[ARGS])
        out.append(rec)
    return out


def render(events: List[tuple],
           thread_names: Optional[Dict[int, str]] = None,
           meta: Optional[dict] = None) -> dict:
    """The full JSON-object trace document."""
    doc = {
        "traceEvents": to_trace_events(events, thread_names),
        "displayTimeUnit": "ms",
    }
    if meta:
        doc["otherData"] = meta
    return doc


def _write(doc: dict, path: str) -> Optional[str]:
    """Atomic JSON write, gated by the tracer circuit breaker
    (mlsl_tpu.supervisor): repeated IO failures (full disk, revoked
    credentials on a network mount) trip it and exports become no-ops —
    observability degrades instead of taking the training loop down with it
    — until the half-open probe write succeeds again. Returns None when the
    breaker is open; IO errors below the trip threshold propagate (callers
    on error paths already swallow them — flight_record — and interactive
    callers should see the real failure)."""
    from mlsl_tpu import supervisor

    br = supervisor.breaker("tracer")
    if not br.allow():
        from mlsl_tpu.core import stats as stats_mod

        stats_mod.record_degrade("tracer", "fallback", detail=path)
        return None
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError as e:
        if br.record_failure(e):
            # tripping (or probe-failing) write: served by the fallback —
            # a no-op export — per the rung-3 contract, not raised
            from mlsl_tpu.core import stats as stats_mod

            stats_mod.record_degrade("tracer", "fallback", detail=path)
            return None
        raise
    br.record_success()  # no-op unless HALF_OPEN (the probe write)
    return path


def write_trace(path: Optional[str] = None,
                tracer: Optional[tracer_mod.Tracer] = None) -> Optional[str]:
    """Dump the whole ring to ``path`` (default:
    ``MLSL_TRACE_DIR/trace-<unix_ts>.json``). Returns the written path, or
    None when tracing is disabled."""
    tr = tracer if tracer is not None else tracer_mod._tracer
    if tr is None:
        return None
    if path is None:
        path = os.path.join(tracer_mod.trace_dir(), f"trace-{int(time.time())}.json")
    return _write(
        render(tr.snapshot(), tr.thread_names,
               meta={"kind": "full", "written_at": time.time()}),
        path,
    )


def flight_record(window_s: float, reason: str = "",
                  path: Optional[str] = None) -> Optional[str]:
    """Dump the trailing ``window_s`` seconds of spans to
    ``trace-crash-<unix_ts>.json`` — the watchdog's post-mortem timeline.
    Falls back to the full ring if the window turns out empty (a stall longer
    than the window must still produce evidence). Returns the path, or None
    when tracing is disabled. Never raises: the caller is already on an error
    path and the trip itself must not be masked by a recorder failure."""
    tr = tracer_mod._tracer
    if tr is None:
        return None
    try:
        events = tr.window(window_s)
        if not events:
            events = tr.snapshot()
        if path is None:
            path = os.path.join(
                tracer_mod.trace_dir(), f"trace-crash-{int(time.time())}.json"
            )
        return _write(
            render(events, tr.thread_names,
                   meta={"kind": "flight_record", "reason": reason,
                         "window_s": window_s, "written_at": time.time()}),
            path,
        )
    except Exception:  # pragma: no cover - defensive (error path)
        return None


def summarize(doc: dict, top: int = 10) -> str:
    """Terminal-friendly text summary of a trace document (the engine behind
    scripts/trace_view.py): per-(cat, name) span statistics, the busiest
    tracks, and the slowest individual spans."""
    events = doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[e["tid"]] = e.get("args", {}).get("name", str(e["tid"]))

    lines = [
        f"{len(spans)} spans, {len(instants)} instants, "
        f"{len(names)} tracks"
    ]
    groups: Dict[tuple, List[float]] = {}
    for e in spans:
        groups.setdefault((e.get("cat", "?"), e["name"]), []).append(
            e.get("dur", 0.0) / 1e3  # µs -> ms
        )
    if groups:
        lines.append("")
        lines.append(f"{'cat':<12} {'name':<24} {'n':>6} {'total ms':>10} "
                     f"{'p50 ms':>9} {'p95 ms':>9} {'max ms':>9}")
        for (cat, name), durs in sorted(
            groups.items(), key=lambda kv: -sum(kv[1])
        ):
            durs.sort()
            lines.append(
                f"{cat:<12} {name:<24} {len(durs):>6} {sum(durs):>10.2f} "
                f"{tracer_mod._percentile(durs, 50):>9.3f} "
                f"{tracer_mod._percentile(durs, 95):>9.3f} "
                f"{durs[-1]:>9.3f}"
            )
    # per-algorithm attribution: dispatch/wait spans carry the selected
    # collective algorithm (comm/algos) in their args, so a tuned profile's
    # program switch is visible directly in the trace summary
    by_algo: Dict[str, List[float]] = {}
    for e in spans:
        algo = (e.get("args") or {}).get("algo")
        if algo:
            by_algo.setdefault(str(algo), []).append(e.get("dur", 0.0) / 1e3)
    if by_algo:
        lines.append("")
        lines.append(f"{'algorithm':<14} {'spans':>6} {'total ms':>10} "
                     f"{'p95 ms':>9}")
        for algo, durs in sorted(by_algo.items(), key=lambda kv: -sum(kv[1])):
            durs.sort()
            lines.append(
                f"{algo:<14} {len(durs):>6} {sum(durs):>10.2f} "
                f"{tracer_mod._percentile(durs, 95):>9.3f}"
            )
    busiest: Dict[int, float] = {}
    for e in spans:
        busiest[e["tid"]] = busiest.get(e["tid"], 0.0) + e.get("dur", 0.0)
    if busiest:
        lines.append("")
        lines.append("busiest tracks:")
        for tid, total in sorted(busiest.items(), key=lambda kv: -kv[1])[:top]:
            lines.append(f"  {names.get(tid, tid)}: {total / 1e3:.2f} ms")
    slowest = sorted(spans, key=lambda e: -e.get("dur", 0.0))[:top]
    if slowest:
        lines.append("")
        lines.append("slowest spans:")
        for e in slowest:
            args = e.get("args")
            lines.append(
                f"  {e.get('dur', 0.0) / 1e3:9.3f} ms  {e.get('cat', '?')}:"
                f"{e['name']} @ {names.get(e['tid'], e['tid'])}"
                + (f"  {args}" if args else "")
            )
    if instants:
        lines.append("")
        lines.append("instants:")
        counts: Dict[tuple, int] = {}
        for e in instants:
            key = (e.get("cat", "?"), e["name"])
            counts[key] = counts.get(key, 0) + 1
        for (cat, name), n in sorted(counts.items()):
            lines.append(f"  {cat}:{name} x{n}")
    # static-analysis findings ride the trace as instants (mlsl_tpu.analysis
    # record()); the aggregated count above hides WHICH invariant fired, so
    # list them individually — a rejected plan's codes belong in the same
    # summary an operator reads for the stall it would have caused
    findings = [e for e in instants if e["name"] == "analysis.finding"]
    if findings:
        lines.append("")
        lines.append("analysis findings:")
        for e in findings[:top]:
            a = e.get("args") or {}
            lines.append(
                f"  {a.get('severity', '?'):<5} {a.get('code', '?')} "
                f"@ {a.get('anchor', '?')}"
            )
        if len(findings) > top:
            lines.append(f"  ... {len(findings) - top} more")
    return "\n".join(lines)
