"""Process-wide low-overhead span tracer: the comm timeline's event source.

The aggregate counters in ``core/stats.py`` answer *how much* time went to
communication; this module answers *which request stalled, when a bucket
deferred, and why a watchdog tripped*. Every instrumented layer — request
Start/defer/dispatch/wait (comm/request.py), bucket rounds (core/bucketing.py),
quant ring round-trips (comm/quant_ring.py), checkpoint save/restore
(checkpoint.py), recovery cycles (resilience.py), trainer step phases
(models/train.py), the device feed pipeline (data/: ``h2d.transfer`` +
``feed.decode`` spans, ``feed.cache_hit`` instants), chaos injections
(chaos.py) — appends typed events to one
bounded ring buffer, which ``obs.export`` renders as Chrome/Perfetto
``trace_event`` JSON and the watchdog dumps as a flight record on a trip.

Hot-path contract (mirrors the chaos-site ``if chaos._plans:`` pattern):
instrumented code reads the module global once per operation and guards with
``tr = tracer._tracer`` / ``if tr is not None:`` — when tracing is off that is
ONE attribute load and a None test, with zero allocations (asserted by
tests/test_trace.py). Nothing else in this module runs until tracing is armed
via ``MLSL_TRACE=1`` or :func:`enable`.

Event record (a plain tuple, one allocation per event when enabled)::

    (ph, name, cat, ts_ns, dur_ns, thread_ident, track, args)

``ph`` is the Chrome trace phase ('X' complete span, 'i' instant); ``ts_ns``
is ``time.perf_counter_ns()`` (monotonic — the flight recorder windows on it);
``track`` optionally names a logical timeline (one per request / bucket) that
the exporter renders as its own row, separate from the emitting thread's.

Ring buffer: ``collections.deque(maxlen=capacity)`` — append is GIL-atomic
(no lock on the record path) and wraparound drops the oldest event, so a
long-running trainer keeps the most recent window rather than growing without
bound. Capacity comes from ``MLSL_TRACE_CAPACITY`` (default 65536 events).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

ENV_TRACE = "MLSL_TRACE"
ENV_DIR = "MLSL_TRACE_DIR"
ENV_CAPACITY = "MLSL_TRACE_CAPACITY"
DEFAULT_CAPACITY = 65536

# tuple indices of one event record (kept flat: field access in the exporter
# and the percentile scans without per-event object overhead)
PH, NAME, CAT, TS, DUR, TID, TRACK, ARGS = range(8)


class Tracer:
    """The ring buffer and its append paths. One instance per process
    (module global ``_tracer``); instrumented code never constructs one."""

    __slots__ = ("capacity", "events", "t0_ns", "thread_names")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 16)
        self.events: collections.deque = collections.deque(maxlen=self.capacity)
        self.t0_ns = time.perf_counter_ns()
        # ident -> name, for the exporter's thread_name metadata; written
        # lazily on first event from each thread (dict set is GIL-atomic)
        self.thread_names: Dict[int, str] = {}

    # -- record paths (the only methods on the enabled hot path) -----------

    @staticmethod
    def now() -> int:
        return time.perf_counter_ns()

    def _tid(self) -> int:
        t = threading.current_thread()
        ident = t.ident or 0
        if ident not in self.thread_names:
            self.thread_names[ident] = t.name
        return ident

    def complete(self, name: str, cat: str, t0_ns: int,
                 track: Optional[str] = None, **args) -> None:
        """Record a complete span that began at ``t0_ns`` and ends now."""
        end = time.perf_counter_ns()
        self.events.append(
            ("X", name, cat, t0_ns, end - t0_ns, self._tid(), track,
             args or None)
        )

    def instant(self, name: str, cat: str, track: Optional[str] = None,
                **args) -> None:
        self.events.append(
            ("i", name, cat, time.perf_counter_ns(), 0, self._tid(), track,
             args or None)
        )

    # -- queries ------------------------------------------------------------

    def snapshot(self) -> List[tuple]:
        """Consistent copy of the ring (deque iteration under the GIL)."""
        return list(self.events)

    def clear(self) -> None:
        self.events.clear()

    def window(self, seconds: float) -> List[tuple]:
        """Events whose END falls within the trailing ``seconds`` window —
        the flight recorder's view of 'what just happened'."""
        cutoff = time.perf_counter_ns() - int(seconds * 1e9)
        return [ev for ev in self.snapshot() if ev[TS] + ev[DUR] >= cutoff]

    def wait_stall_durations(self) -> Dict[str, List[int]]:
        """Raw 'wait' span durations (ns) grouped by request name — the
        per-request wait-stall distributions. Statistics.overlap_report
        re-groups these by op ('<op>/' name prefix) for its span-derived
        p50/p95 fields."""
        groups: Dict[str, List[int]] = {}
        for ev in self.snapshot():
            if ev[PH] == "X" and ev[NAME] == "wait" and ev[CAT] == "req":
                key = str((ev[ARGS] or {}).get("req") or ev[TRACK] or "?")
                groups.setdefault(key, []).append(ev[DUR])
        return groups

    def span_durations(self, name: str, cat: Optional[str] = None
                       ) -> List[int]:
        """Raw durations (ns) of every complete span named ``name``
        (optionally filtered by category) still in the ring — e.g.
        ``span_durations("h2d.transfer", "feed")`` for the staging-time
        distribution the input-pipeline bench reports."""
        return [
            ev[DUR]
            for ev in self.snapshot()
            if ev[PH] == "X" and ev[NAME] == name
            and (cat is None or ev[CAT] == cat)
        ]

    def wait_stall_stats(self) -> Dict[str, dict]:
        """Per-request wait-stall summary:
        ``{request_name: {n, p50_ms, p95_ms, max_ms}}``."""
        out = {}
        for key, durs in self.wait_stall_durations().items():
            durs.sort()
            out[key] = {
                "n": len(durs),
                "p50_ms": _percentile(durs, 50) / 1e6,
                "p95_ms": _percentile(durs, 95) / 1e6,
                "max_ms": durs[-1] / 1e6,
            }
        return out


def _percentile(sorted_vals: List[int], pct: float) -> float:
    """Nearest-rank percentile of an already-sorted list (stdlib-only; the
    tracer must not import numpy on the record path)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(pct / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


#: THE hot-path guard: None = disabled. Instrumented code reads this once per
#: operation (``tr = tracer._tracer``) and does nothing when it is None.
_tracer: Optional[Tracer] = None


def enabled() -> bool:
    return _tracer is not None


def get_tracer() -> Optional[Tracer]:
    return _tracer


def enable(capacity: Optional[int] = None) -> Tracer:
    """Arm tracing (idempotent). Capacity defaults to MLSL_TRACE_CAPACITY."""
    global _tracer
    if _tracer is None:
        if capacity is None:
            capacity = int(os.environ.get(ENV_CAPACITY) or DEFAULT_CAPACITY)
        _tracer = Tracer(capacity)
    return _tracer


def disable() -> None:
    """Disarm tracing; the buffer is dropped (export first if needed)."""
    global _tracer
    _tracer = None


def trace_dir() -> str:
    """Where trace-*.json files land (MLSL_TRACE_DIR, default CWD)."""
    return os.environ.get(ENV_DIR) or "."


class span:
    """Context-manager convenience for user code and cold paths::

        with obs.span("load", "data", shard=3):
            ...

    Captures the tracer ONCE at __enter__ (a disable mid-block records
    nothing; an enable mid-block records nothing — consistent either way).
    Instrumented framework hot paths use the explicit ``_tracer`` guard
    instead: this object allocates even when tracing is off.
    """

    __slots__ = ("name", "cat", "track", "args", "_t0", "_tr")

    def __init__(self, name: str, cat: str = "user",
                 track: Optional[str] = None, **args):
        self.name = name
        self.cat = cat
        self.track = track
        self.args = args

    def __enter__(self) -> "span":
        self._tr = _tracer
        self._t0 = self._tr.now() if self._tr is not None else 0
        return self

    def __exit__(self, *exc) -> None:
        if self._tr is not None:
            self._tr.complete(self.name, self.cat, self._t0,
                              track=self.track, **self.args)


def _env_truthy(v: Optional[str]) -> bool:
    return (v or "").strip().lower() not in ("", "0", "false", "no", "off")


# Arm from the environment at import: instrumented modules import this module,
# so MLSL_TRACE=1 on the launch command works with no code changes (the same
# contract as MLSL_CHAOS in mlsl_tpu/chaos.py).
if _env_truthy(os.environ.get(ENV_TRACE)):
    enable()
