"""Pallas-native fused ring collectives: in-kernel int8 codec + RDMA hops.

The ``quant_ring`` lowering composes its compressed ring from ``lax`` ops —
separate quantize / ``ppermute`` / dequantize programs with XLA deciding the
buffering — so every hop round-trips HBM and the codec never overlaps the
DMA. This module is the hand-written alternative (ROADMAP #1, the EQuARX
design from PAPERS.md): ONE Pallas kernel owns the whole ring —

- per-hop inter-chip transfers are explicit ``pltpu.make_async_remote_copy``
  RDMA between VMEM comm slots, double-buffered (``MLSL_PALLAS_RING_SLOTS``
  recv slots per direction, a remote-capacity semaphore handshake guarding
  slot reuse) so hop t+1's wire time can hide behind hop t's codec work;
- the blockwise int8 quantize sits at the VMEM exit (the send slot is
  *written quantized*) and the dequantize is fused into the accumulate at
  the VMEM entry, so the wire stays int8 + per-block f32 scales across all
  G-1 hops and the f32 payload never leaves the chip;
- scales ride the same hop as their payload (a second RDMA per hop on the
  same link) — the THC observation that the compressed representation must
  survive the whole route, not be re-expanded per step;
- an optional bidirectional variant splits the payload's block-rows in two
  and runs opposite-rotation rings concurrently, putting both directions of
  each full-duplex ICI link to work (``MLSL_PALLAS_RING_BIDIR``).

The *entry* quantization (error feedback: ``xq = x + err`` → ``new_err =
xq - deq(q(xq))``) deliberately stays in the wrapper body and reuses
``quant_ring``'s exact helpers: on TPU that is already the fused Pallas
quantize kernel (ops/quant_kernels.py), and sharing the code is what makes
the error-feedback residual bit-exact with the ``quant_ring`` oracle — the
parity contract tests/test_pallas_ring.py pins.

Mesh/addressing: ring neighbors are *world-rank tables* (one row per group
instance, like rhd's member rows) looked up by this member's world rank and
handed to the kernel as scalar-prefetch operands; the RDMA targets them as
LOGICAL device ids (= position in the mesh's flattened device array, which
is grid-major world-rank order for both the 4-axis grid mesh and the flat
'world' mesh). One kernel therefore serves the standalone host-dispatch
program AND the compiled-overlap in-graph emission.

CPU testability: off-TPU the kernels run under the Pallas interpreter
(``interpret=True``), which this jax version executes with true cross-shard
remote-DMA semantics — with two restrictions the module works around:

- the interpreter resolves LOGICAL device ids only under a SINGLE named
  mesh axis, so host-dispatch programs compile over ``topology.flat_mesh``
  (the ``_build_flat`` convention rhd already uses); the in-graph overlap
  form — which must live inside the trainer's 4-axis shard_map — is
  TPU-only (``inline-eligibility`` gates it off the interpreter);
- a *remote* semaphore signal is not implemented, so interpret-mode kernels
  allocate one comm slot per hop (no slot reuse → the capacity handshake is
  statically elided); on TPU the handshake compiles in.

Gate: ``MLSL_PALLAS_INTERPRET`` (``1`` force-interpret, ``0``
force-compiled, unset = compiled on TPU and the interpreter elsewhere).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Callable, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mlsl_tpu.comm.mesh import GRID_AXES, ProcessGroup
from mlsl_tpu.log import mlsl_assert

# jax renamed TPUCompilerParams -> CompilerParams (jax 0.7); accept either
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

#: dense ring chunk alignment (elements): 32 sublane rows x 128 lanes keeps
#: every per-chunk VMEM buffer tile-legal for f32/bf16/i32 alike
DENSE_UNIT = 32 * 128

#: widest group the unrolled hop schedule compiles for (2*(G-1) inline hop
#: bodies; past this the program size stops paying for itself — larger rings
#: belong to the hierarchical lowerings)
MAX_GROUP = 64

#: default comm slots per direction (the double buffer); overridden by
#: MLSL_PALLAS_RING_SLOTS / the builders' ``slots`` argument
DEFAULT_SLOTS = 2

#: kernel-config key -> collective id. Sequential allocation (no modular
#: hash: a hash collision between two ring geometries concurrently in
#: flight would share Mosaic barrier state and deadlock/corrupt on-chip).
#: Deterministic across hosts because SPMD hosts trace identical programs
#: in identical order — the same assumption every shard_map program makes.
_collective_ids: dict = {}


def _compiler_params(key: tuple):
    """collective_id marks the kernel as a cross-device collective for
    Mosaic and must (a) agree across every device running THIS kernel and
    (b) differ between distinct kernels that may be in flight concurrently
    (the overlap engine can interleave several ring units) — allocated
    sequentially per kernel configuration from the registry above.
    has_side_effects (newer jax only — a DMA kernel must not be DCE'd) is
    passed when the dataclass knows the field."""
    cid = _collective_ids.setdefault(key, len(_collective_ids))
    kw = {"collective_id": cid}
    if "has_side_effects" in {f.name for f in dataclasses.fields(_CompilerParams)}:
        kw["has_side_effects"] = True
    return _CompilerParams(**kw)


# ---------------------------------------------------------------------------
# Platform / knob gates
# ---------------------------------------------------------------------------


def _on_tpu() -> bool:
    from mlsl_tpu.sysinfo import on_tpu

    return on_tpu()


def interpret_mode() -> bool:
    """Whether kernel builds run under the Pallas interpreter. Resolution:
    ``MLSL_PALLAS_INTERPRET=1`` forces the interpreter (debugging a TPU
    lowering), ``0`` forces compiled Mosaic, unset = compiled on TPU and the
    interpreter everywhere else (the tier-1 CPU-mesh parity path)."""
    v = os.environ.get("MLSL_PALLAS_INTERPRET", "").strip()
    if v == "1":
        return True
    if v == "0":
        return False
    return not _on_tpu()


def available() -> bool:
    """Can the pallas_ring family serve requests on this backend? On TPU:
    always. Elsewhere only when the operator explicitly armed interpret mode
    (``MLSL_PALLAS_INTERPRET=1``) — the interpreter is a correctness
    vehicle, never a performance win, so plain CPU runs must not select it."""
    return _on_tpu() or os.environ.get("MLSL_PALLAS_INTERPRET", "").strip() == "1"


def env_slots(slots: Optional[int] = None) -> int:
    """Comm-slot count per direction: explicit argument > exported
    MLSL_PALLAS_RING_SLOTS > the Config default."""
    if slots is not None:
        return max(int(slots), 2)
    v = os.environ.get("MLSL_PALLAS_RING_SLOTS")
    return max(int(v), 2) if v not in (None, "") else DEFAULT_SLOTS


def env_bidir(bidir: Optional[bool] = None) -> bool:
    if bidir is not None:
        return bool(bidir)
    v = os.environ.get("MLSL_PALLAS_RING_BIDIR", "").strip().lower()
    return v not in ("", "0", "false", "no", "off")


def ring_axis(group: ProcessGroup) -> Optional[str]:
    """The single live mesh axis a pallas ring can ride, or None when the
    group does not reduce to one physical ring (color groups, true
    multi-axis sub-tori — those keep the lax/rhd/ring2d lowerings)."""
    if group.colors is not None or not group.axes:
        return None
    from mlsl_tpu.comm.collectives import _axis_sizes

    sizes = _axis_sizes(group.topology.mesh)
    live = [a for a in group.axes if sizes[a] > 1]
    if len(live) != 1:
        return None
    return live[0]


def ring_axes2(group: ProcessGroup) -> Optional[Tuple[str, str]]:
    """The (major, minor) live mesh axis pair a 2D-torus snake ring can ride,
    or None when the group is not an axis-aligned 2-axis sub-torus. The snake
    (boustrophedon) Hamiltonian cycle built over this pair alternates minor-
    axis hops within a row with major-axis hops between rows, so the one ring
    keeps BOTH axes' ICI links in flight (the PR 10 bidir split then rides
    each link's two directions on top)."""
    if group.colors is not None or not group.axes:
        return None
    from mlsl_tpu.comm.collectives import _axis_sizes

    sizes = _axis_sizes(group.topology.mesh)
    live = [a for a in group.axes if sizes[a] > 1]
    if len(live) != 2:
        return None
    return live[0], live[1]


def eligible_dense(kind: str, group: ProcessGroup, op=None) -> bool:
    """Engine eligibility for the dense f32/bf16/i32 variant: SUM-reduction
    ring math on a single-live-axis group of tractable size, on a backend
    that can actually run the kernel (TPU, or the explicit interpret gate)."""
    from mlsl_tpu.types import ReductionType

    if kind not in ("allreduce", "reduce_scatter"):
        return False
    if op not in (None, ReductionType.SUM):
        return False
    if not available():
        return False
    ax = ring_axis(group)
    if ax is None:
        return False
    return 1 < int(group.size) <= MAX_GROUP


def eligible_dense2d(kind: str, group: ProcessGroup, op=None) -> bool:
    """Eligibility for the 2D-torus snake-ring variant: the same dense ring
    math, but over an axis-aligned TWO-live-axis sub-torus (where the 1D ring
    is ineligible and ring2d's composed phases were the only topology-aware
    option)."""
    from mlsl_tpu.types import ReductionType

    if kind not in ("allreduce", "reduce_scatter"):
        return False
    if op not in (None, ReductionType.SUM):
        return False
    if not available():
        return False
    if ring_axes2(group) is None:
        return False
    return 1 < int(group.size) <= MAX_GROUP


def eligible_allgather(group: ProcessGroup) -> bool:
    """Eligibility for the all-gather phase kernel (the ZeRO-1 increment
    exchange): same ring shape constraints, no reduction op to restrict."""
    if not available():
        return False
    if ring_axis(group) is None:
        return False
    return 1 < int(group.size) <= MAX_GROUP


def eligible_quant(group: ProcessGroup, block: int) -> bool:
    """Eligibility for the int8-fused variant: dense eligibility plus the
    codec's lane constraint (the quant block rides the VMEM lane dim)."""
    if block % 128 != 0 or not available():
        return False
    ax = ring_axis(group)
    return ax is not None and 1 < int(group.size) <= MAX_GROUP


def inline_ok(group: ProcessGroup) -> bool:
    """Can the kernel be emitted IN-GRAPH (inside the compiled overlap
    engine's 4-axis shard_map)? Compiled-on-TPU only: the interpreter
    resolves remote DMA only under a single named axis, so both off-chip
    AND force-interpret-on-chip (MLSL_PALLAS_INTERPRET=1 debugging) the
    overlap plan falls back to the baseline (loudly, via the engine's
    eligibility gate)."""
    return (_on_tpu() and not interpret_mode()
            and ring_axis(group) is not None)


def inline_ok2d(group: ProcessGroup) -> bool:
    """inline_ok for the 2D snake ring: compiled-on-TPU over a 2-live-axis
    sub-torus (same interpreter restriction as the 1D form)."""
    return (_on_tpu() and not interpret_mode()
            and ring_axes2(group) is not None)


# ---------------------------------------------------------------------------
# Geometry
# ---------------------------------------------------------------------------


def dense_geometry(kind: str, group: ProcessGroup, count: int) -> Tuple[int, int, int]:
    """-> (g, rc, chunk): per-rank logical slice rc and the DENSE_UNIT-aligned
    ring chunk (the same slice-at-chunk-start placement as quant_ring)."""
    g = 1 if group.is_self else int(group.size)
    if kind == "reduce_scatter":
        mlsl_assert(count % g == 0,
                    "reduce_scatter count %d %% group %d != 0", count, g)
        rc = count // g
    elif kind == "all_gather":
        # count is the PER-MEMBER shard (the ZeRO-1 owned slice); the ring
        # circulates one chunk per member and the output is g * count
        rc = count
    else:
        rc = -(-count // g)
    chunk = -(-rc // DENSE_UNIT) * DENSE_UNIT
    return g, rc, chunk


def quant_geometry(
    kind: str, group: ProcessGroup, count: int, block: int
) -> Tuple[int, int, int, int]:
    """-> (g, rc, chunk, err_len) for the fused int8 ring. Mirrors
    quant_ring.ring_geometry with the *pallas* chunk units unconditionally
    (block*ROW_TILE, block*PACK_ROWS past the same threshold) — on TPU this
    IS ring_geometry's answer, and off-TPU using the pallas units keeps the
    interpret-mode kernel's layout identical to what the chip will run."""
    from mlsl_tpu.comm import quant_ring
    from mlsl_tpu.ops import quant_kernels as qk

    g = 1 if group.is_self else int(group.size)
    mlsl_assert(group.colors is None,
                "quantized collectives require axis-aligned groups")
    if kind == "reduce_scatter":
        mlsl_assert(count % g == 0,
                    "reduce_scatter count %d %% group %d != 0", count, g)
        rc = count // g
    else:
        rc = -(-count // g)
    unit = max(quant_ring._chunk_unit(rc, True, block), block * qk.ROW_TILE)
    chunk = -(-rc // unit) * unit
    return g, rc, chunk, g * chunk


def describe_plan(g: int, chunk_elems: int, quantized: bool, block: int,
                  bidir: bool, slots: int, dense_dtype="float32",
                  programs: int = 1) -> str:
    """The ``pallas.hop`` trace/span argument: hops, per-hop slot bytes and
    the codec, so a dispatch span names the wire plan it launched.
    ``dense_dtype`` is the dense wire dtype (f32/bf16/i32 — sizes the
    slot bytes); ``programs`` > 1 marks a large-message request split into
    independent per-chunk ring programs (the plan describes ONE chunk)."""
    dt = jnp.dtype(dense_dtype)
    hops = (g - 1) * (2 if bidir else 1)
    wire = chunk_elems + 4 * (chunk_elems // max(block, 1)) if quantized \
        else chunk_elems * dt.itemsize
    codec = f"int8/b{block}" if quantized else dt.name
    tail = f" programs={programs}" if programs > 1 else ""
    return (f"hops={hops} slot_bytes={wire} codec={codec} "
            f"slots={slots}{' bidir' if bidir else ''}{tail}")


def static_accounting(mode: str, g: int, slots: int, *, bidir: bool = False):
    """-> (events, total_hops, ndirs): the ordered capacity-semaphore event
    trace ONE kernel build emits — ``('wait', dir, hop)`` for slot_wait,
    ``('free', dir, use_hop)`` for slot_free — mirroring the guards in
    ``_ring_kernel_factory`` exactly (slot_wait fires for hops >= slots;
    slot_free only when a later hop reuses the slot, RS slots freed the hop
    they arrive, AG slots one hop later because the forward re-reads them).

    This is the statically-balanced accounting contract the kernel's
    docstrings promise ("sems drain to zero"): the plan verifier
    (mlsl_tpu/analysis/plan.py, MLSL-A130/A131) replays this trace and
    checks that every wait's matching free precedes it in program order and
    that signals == waits per direction at kernel exit. Kept HERE, next to
    the kernel, so the mirror and the emission evolve together — a change
    to slot_wait/slot_free that forgets this function fails the verifier's
    healthy-graph sweep."""
    hops = int(g) - 1
    total_hops = hops * (2 if mode == "allreduce" else 1)
    ndirs = 2 if bidir else 1
    events = []

    def slot_wait(h):
        if h >= slots:
            for d in range(ndirs):
                events.append(("wait", d, h))

    def slot_free(use_h):
        if use_h + slots <= total_hops - 1:
            for d in range(ndirs):
                events.append(("free", d, use_h))

    if mode == "all_gather":       # gather-only: the AG phase stands alone
        for k in range(hops):
            slot_wait(k)
            if k >= 1:
                slot_free(k - 1)   # an AG slot is re-read by the forward
        return events, total_hops, ndirs
    for t in range(hops):          # phase 1: ring reduce-scatter
        slot_wait(t)
        slot_free(t)               # an RS slot is consumed the hop it arrives
    if mode == "allreduce":        # phase 2: ring all-gather
        for k in range(hops):
            h = hops + k
            slot_wait(h)
            if k >= 1:
                slot_free(h - 1)   # an AG slot is re-read by the forward
    return events, total_hops, ndirs


def _ring_tables(group: ProcessGroup):
    """Per-world-rank ring addressing: ``(pos, right, left)`` int32 arrays of
    shape (W,) — this member's group position and its ring neighbors' WORLD
    ranks (= LOGICAL device ids in both mesh forms). One row per group
    instance, so one table set serves every instance of a subgroup ring."""
    from mlsl_tpu.comm import collectives

    rows = collectives._axis_groups_tbl(group)
    w = group.topology.world_size
    pos = np.zeros((w,), dtype=np.int32)
    right = np.zeros((w,), dtype=np.int32)
    left = np.zeros((w,), dtype=np.int32)
    for row in rows:
        g = len(row)
        for i, p in enumerate(row):
            pos[p] = i
            right[p] = row[(i + 1) % g]
            left[p] = row[(i - 1) % g]
    return pos, right, left


def _snake_order(row, a: int, b: int):
    """Reorder one group instance's member row (major-axis-major, length
    a*b) along the boustrophedon Hamiltonian cycle of the (a, b) torus:
    even major rows walk the minor axis ascending, odd rows descending, and
    the final wraparound hop closes the cycle on the major axis. Every
    minor-axis link inside a row and the major-axis links between rows are
    ring edges, so the one ring drives both axes' ICI concurrently."""
    return [row[i * b + (j if i % 2 == 0 else b - 1 - j)]
            for i in range(a) for j in range(b)]


def _ring_tables_2d(group: ProcessGroup):
    """``_ring_tables`` over the snake cycle of a 2-live-axis sub-torus:
    the SAME kernel runs unchanged — only the neighbor addressing differs."""
    from mlsl_tpu.comm import collectives

    axes2 = ring_axes2(group)
    mlsl_assert(axes2 is not None,
                "pallas_ring2d needs a 2-live-axis group (got axes=%s)",
                group.axes)
    sizes = collectives._axis_sizes(group.topology.mesh)
    a, b = int(sizes[axes2[0]]), int(sizes[axes2[1]])
    rows = collectives._axis_groups_tbl(group)
    w = group.topology.world_size
    pos = np.zeros((w,), dtype=np.int32)
    right = np.zeros((w,), dtype=np.int32)
    left = np.zeros((w,), dtype=np.int32)
    for row in rows:
        mlsl_assert(len(row) == a * b,
                    "pallas_ring2d group instance has %d members, torus is "
                    "%dx%d", len(row), a, b)
        cyc = _snake_order(row, a, b)
        g = len(cyc)
        for i, p in enumerate(cyc):
            pos[p] = i
            right[p] = cyc[(i + 1) % g]
            left[p] = cyc[(i - 1) % g]
    return pos, right, left


def _snake_perm(group: ProcessGroup) -> np.ndarray:
    """Ring-slot -> group-position chunk permutation for the snake cycle:
    the kernel scatters/gathers chunks by RING position, so the wrapper
    feeds kernel-chunk i = logical chunk ``perm[i]`` (the group position of
    the member at ring slot i). With that input order, reduce_scatter lands
    each member its OWN group-position chunk (the lax placement convention)
    and allreduce undoes the permutation on the way out."""
    from mlsl_tpu.comm import collectives

    axes2 = ring_axes2(group)
    sizes = collectives._axis_sizes(group.topology.mesh)
    a, b = int(sizes[axes2[0]]), int(sizes[axes2[1]])
    idx = list(range(a * b))
    return np.asarray(_snake_order(idx, a, b), dtype=np.int32)


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------


def _quantize_rows(x):
    """(rows, block) f32 -> (int8 q, (rows, 1) f32 scales): the exact
    blockwise transform of quant_kernels.quantize_blocks_ref, emitted inside
    the kernel so the send slot is written already-compressed."""
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _ring_kernel_factory(
    *,
    mode: str,            # 'allreduce' | 'reduce_scatter' | 'all_gather'
    G: int,
    rows: int,            # block-rows per chunk
    cols: int,            # lanes per row (the quant block, or 128 dense)
    quantized: bool,
    slots: int,
    dirs: Tuple[Tuple[int, int, int], ...],  # (sign, row_lo, row_len)
    handshake: bool,
) -> Callable:
    """Build the kernel body. Hops are unrolled in Python (G <= MAX_GROUP):
    every hop's send slot is quantized on the way out of VMEM, RDMA'd with
    its scales, and dequantize-accumulated on the way in; slot reuse is
    guarded by the remote capacity handshake when compiled for the chip."""
    hops = G - 1
    total_hops = hops * (2 if mode == "allreduce" else 1)
    ndirs = len(dirs)
    mlsl_assert(not (mode == "all_gather" and quantized),
                "the all_gather phase kernel is dense-only (the ZeRO-1 "
                "increment exchange carries f32)")

    def kernel(pos_ref, right_ref, left_ref, x_ref, out_ref, *scr):
        if quantized:
            (acc, loc, qsend, ssend, qbuf, sbuf,
             csem, psend, precv, ssend_sem, srecv_sem) = scr[:11]
            cap = scr[11] if handshake else None
        else:
            acc, loc, fbuf, csem, psend, precv = scr[:6]
            cap = scr[6] if handshake else None

        pos = pos_ref[0]
        right = right_ref[0]
        left = left_ref[0]

        def copy_in(idx, dst, r0, rl, sem):
            c = pltpu.make_async_copy(
                x_ref.at[pl.ds(idx * rows + r0, rl)],
                dst.at[pl.ds(r0, rl)],
                sem,
            )
            c.start()
            return c

        def copy_out(src, r0, rl, idx, sem):
            c = pltpu.make_async_copy(
                src.at[pl.ds(r0, rl)],
                out_ref.at[pl.ds(idx * rows + r0, rl)],
                sem,
            )
            c.start()
            return c

        def rdma(src, dst, send_sem, recv_sem, dst_dev):
            c = pltpu.make_async_remote_copy(
                src_ref=src, dst_ref=dst, send_sem=send_sem,
                recv_sem=recv_sem, device_id=dst_dev,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            return c

        def dmod(v):
            return lax.rem(v + 4 * G, G)

        def slot_wait(h):
            """Before sending into slot h%slots: wait until its previous use
            (hop h-slots) was freed by the consumer on the other end."""
            if handshake and h >= slots:
                for d in range(ndirs):
                    pltpu.semaphore_wait(cap.at[d], 1)

        def slot_free(use_h):
            """The slot used at hop ``use_h`` is fully consumed on this end:
            free it on its producer. Emitted only when some later hop will
            reuse the slot, so every wait has exactly one matching signal
            and the semaphore drains to zero at kernel exit."""
            if handshake and use_h + slots <= total_hops - 1:
                for d, (sign, _r0, _rl) in enumerate(dirs):
                    pltpu.semaphore_signal(
                        cap.at[d], inc=1,
                        device_id=left if sign > 0 else right,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    )

        # ---- init: each direction's travelling partial --------------------
        # (all_gather: x_ref holds only THIS member's shard — chunk index 0)
        pend = []
        for d, (sign, r0, rl) in enumerate(dirs):
            idx = 0 if mode == "all_gather" else dmod(pos - sign)
            pend.append(copy_in(idx, acc, r0, rl, csem.at[d]))
        for c in pend:
            c.wait()

        def hop_send(d, sign, r0, rl, slot, src_q, src_s, src_f):
            """One direction's hop transfer out of VMEM: the already-
            compressed payload plus its scales (or the dense chunk)."""
            dev = right if sign > 0 else left
            if quantized:
                cq = rdma(src_q, qbuf.at[slot, pl.ds(r0, rl)],
                          psend.at[d, slot], precv.at[d, slot], dev)
                cs = rdma(src_s, sbuf.at[slot, pl.ds(r0, rl)],
                          ssend_sem.at[d, slot], srecv_sem.at[d, slot], dev)
                return (cq, cs)
            cf = rdma(src_f, fbuf.at[slot, pl.ds(r0, rl)],
                      psend.at[d, slot], precv.at[d, slot], dev)
            return (cf,)

        # ---- phase 1: ring reduce-scatter (skipped by the gather-only mode)
        for t in ([] if mode == "all_gather" else range(hops)):
            slot = t % slots
            if quantized:
                # quantize on the way out of VMEM: the send buffer holds the
                # compressed form, never the f32 partial
                for d, (sign, r0, rl) in enumerate(dirs):
                    q, s = _quantize_rows(acc[pl.ds(r0, rl)])
                    qsend[pl.ds(r0, rl)] = q
                    ssend[pl.ds(r0, rl)] = s
            slot_wait(t)
            inflight = []
            for d, (sign, r0, rl) in enumerate(dirs):
                # prefetch this hop's local chunk while the wire is busy
                inflight.append(
                    copy_in(dmod(pos - sign * (2 + t)), loc, r0, rl,
                            csem.at[d])
                )
                inflight.extend(hop_send(
                    d, sign, r0, rl, slot,
                    qsend.at[pl.ds(r0, rl)] if quantized else None,
                    ssend.at[pl.ds(r0, rl)] if quantized else None,
                    None if quantized else acc.at[pl.ds(r0, rl)],
                ))
            for c in inflight:
                c.wait()
            for d, (sign, r0, rl) in enumerate(dirs):
                if quantized:
                    # dequantize fused into the accumulate on the way in
                    got = (qbuf[slot, pl.ds(r0, rl)].astype(jnp.float32)
                           * sbuf[slot, pl.ds(r0, rl)])
                else:
                    got = fbuf[slot, pl.ds(r0, rl)]
                acc[pl.ds(r0, rl)] = got + loc[pl.ds(r0, rl)]
            # an RS slot is never re-read: consumed the hop it arrives
            slot_free(t)

        if mode == "reduce_scatter":
            done = []
            for d, (sign, r0, rl) in enumerate(dirs):
                c = pltpu.make_async_copy(
                    acc.at[pl.ds(r0, rl)], out_ref.at[pl.ds(r0, rl)],
                    csem.at[d],
                )
                c.start()
                done.append(c)
            for c in done:
                c.wait()
            return

        # ---- phase 2: ring all-gather -------------------------------------
        # own chunk: (re)quantize once; the SAME compressed payload then
        # circulates all G-1 hops (no per-hop requantization — the wire
        # stays what the owner produced, the quant_ring contract)
        done = []
        for d, (sign, r0, rl) in enumerate(dirs):
            if quantized:
                q, s = _quantize_rows(acc[pl.ds(r0, rl)])
                qsend[pl.ds(r0, rl)] = q
                ssend[pl.ds(r0, rl)] = s
                loc[pl.ds(r0, rl)] = q.astype(jnp.float32) * s
                done.append(copy_out(loc, r0, rl, pos, csem.at[d]))
            else:
                done.append(copy_out(acc, r0, rl, pos, csem.at[d]))
        for c in done:
            c.wait()

        prev_slot = None
        base = 0 if mode == "all_gather" else hops
        for k in range(hops):
            h = base + k
            slot = h % slots
            slot_wait(h)
            inflight = []
            for d, (sign, r0, rl) in enumerate(dirs):
                if k == 0:
                    src_q = qsend.at[pl.ds(r0, rl)] if quantized else None
                    src_s = ssend.at[pl.ds(r0, rl)] if quantized else None
                    src_f = None if quantized else acc.at[pl.ds(r0, rl)]
                elif quantized:
                    src_q = qbuf.at[prev_slot, pl.ds(r0, rl)]
                    src_s = sbuf.at[prev_slot, pl.ds(r0, rl)]
                    src_f = None
                else:
                    src_q = src_s = None
                    src_f = fbuf.at[prev_slot, pl.ds(r0, rl)]
                inflight.extend(
                    hop_send(d, sign, r0, rl, slot, src_q, src_s, src_f)
                )
            for c in inflight:
                c.wait()
            if k >= 1:
                # the forward of prev_slot just completed (send waited):
                # ONLY NOW is an AG slot free for its producer to overwrite —
                # an AG slot is read twice, dequant+copy-out at its own hop
                # and the forward at the next
                slot_free(h - 1)
            done = []
            for d, (sign, r0, rl) in enumerate(dirs):
                idx = dmod(pos - sign * (1 + k))
                if quantized:
                    loc[pl.ds(r0, rl)] = (
                        qbuf[slot, pl.ds(r0, rl)].astype(jnp.float32)
                        * sbuf[slot, pl.ds(r0, rl)]
                    )
                    done.append(copy_out(loc, r0, rl, idx, csem.at[d]))
                else:
                    done.append(copy_out(fbuf.at[slot], r0, rl, idx,
                                         csem.at[d]))
            for c in done:
                c.wait()
            prev_slot = slot

    return kernel


@functools.lru_cache(maxsize=64)
def _ring_call(
    mode: str,
    G: int,
    rows: int,
    cols: int,
    dtype_str: str,
    quantized: bool,
    slots: int,
    bidir: bool,
    interpret: bool,
) -> Callable:
    """The compiled-or-interpreted pallas_call for one ring configuration.
    Cached per configuration (pure geometry — device addressing arrives as
    scalar-prefetch operands, so one call object serves every mesh)."""
    dtype = jnp.dtype(dtype_str)
    total_hops = (G - 1) * (2 if mode == "allreduce" else 1)
    if interpret:
        # no remote semaphore_signal in the interpreter: one slot per hop,
        # statically eliding the capacity handshake (no reuse, no hazard)
        slots_eff = max(total_hops, 1)
        handshake = False
    else:
        slots_eff = min(max(slots, 2), max(total_hops, 1))
        handshake = slots_eff < total_hops

    # bidirectional split: halve the block-rows on a tile boundary; rings
    # whose chunks cannot split cleanly run unidirectional
    row_tile = 32 if quantized else 8
    if bidir and rows >= 2 * row_tile:
        ra = (rows // 2 // row_tile) * row_tile
        dirs = ((1, 0, ra), (-1, ra, rows - ra))
    else:
        dirs = ((1, 0, rows),)
    ndirs = len(dirs)

    kern = _ring_kernel_factory(
        mode=mode, G=G, rows=rows, cols=cols, quantized=quantized,
        slots=slots_eff, dirs=dirs, handshake=handshake,
    )

    out_rows = rows if mode == "reduce_scatter" else G * rows
    out_dtype = jnp.float32 if quantized else dtype
    if quantized:
        scratch = [
            pltpu.VMEM((rows, cols), jnp.float32),           # acc
            pltpu.VMEM((rows, cols), jnp.float32),           # loc / staging
            pltpu.VMEM((rows, cols), jnp.int8),              # qsend
            pltpu.VMEM((rows, 1), jnp.float32),              # ssend
            pltpu.VMEM((slots_eff, rows, cols), jnp.int8),   # qbuf
            pltpu.VMEM((slots_eff, rows, 1), jnp.float32),   # sbuf
            pltpu.SemaphoreType.DMA((ndirs,)),               # local copies
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),     # payload send
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),     # payload recv
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),     # scale send
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),     # scale recv
        ]
    else:
        scratch = [
            pltpu.VMEM((rows, cols), dtype),                 # acc
            pltpu.VMEM((rows, cols), dtype),                 # loc
            pltpu.VMEM((slots_eff, rows, cols), dtype),      # fbuf
            pltpu.SemaphoreType.DMA((ndirs,)),
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),
            pltpu.SemaphoreType.DMA((ndirs, slots_eff)),
        ]
    if handshake:
        scratch.append(pltpu.SemaphoreType.REGULAR((ndirs,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,           # pos, right, left (world ranks)
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((out_rows, cols), out_dtype),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            (mode, G, rows, cols, dtype_str, quantized, slots_eff,
             bidir, ndirs)
        ),
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Wrapper bodies
# ---------------------------------------------------------------------------


def _world_rank_flat():
    return lax.axis_index("world")


def _world_rank_grid(group: ProcessGroup):
    from mlsl_tpu.comm.collectives import _axis_sizes, _group_rank

    sizes = _axis_sizes(group.topology.mesh)
    return lambda: _group_rank(GRID_AXES, sizes)


def _scalars(group: ProcessGroup, world_rank: Callable, snake: bool = False):
    """(pos, right, left) scalar-prefetch operands for this member. ``snake``
    addresses the boustrophedon cycle of a 2-live-axis sub-torus instead of
    the single-axis ring."""
    pos_t, right_t, left_t = (_ring_tables_2d(group) if snake
                              else _ring_tables(group))
    w = world_rank()
    take = lambda t: jnp.take(jnp.asarray(t), w)[None]
    return take(pos_t), take(right_t), take(left_t)


def dense_ring_body(
    kind: str,
    group: ProcessGroup,
    count: int,
    dtype,
    *,
    recv_count: Optional[int] = None,
    slots: Optional[int] = None,
    bidir: Optional[bool] = None,
    world_rank: Optional[Callable] = None,
    snake: bool = False,
) -> Callable:
    """-> local body ``(x) -> out`` for the dense (uncompressed) pallas ring,
    with the standard collectives calling convention: x is the squeezed
    per-member (count,) buffer, out the allreduce result (count,), the
    reduce_scatter slice (recv_count,), or the gathered (G*count,) buffer
    for ``kind='all_gather'`` (where x is this member's shard).
    ``world_rank`` supplies this member's world rank as a traced value —
    ``lax.axis_index('world')`` by default (the flat-mesh host program); the
    overlap engine passes the grid-mesh form. ``snake`` rides the 2D-torus
    boustrophedon cycle (pallas_ring2d) instead of the single-axis ring."""
    from mlsl_tpu.comm.quant_ring import _to_chunks

    if snake:
        mlsl_assert(ring_axes2(group) is not None,
                    "pallas_ring2d needs a 2-live-axis group (got axes=%s)",
                    group.axes)
    else:
        mlsl_assert(ring_axis(group) is not None,
                    "pallas_ring needs a single-live-axis group (got axes=%s)",
                    group.axes)
    g, rc, chunk = dense_geometry(kind, group, count)
    mlsl_assert(g > 1, "pallas_ring needs a group with >1 member")
    if kind == "reduce_scatter" and recv_count is not None:
        mlsl_assert(recv_count == rc,
                    "pallas_ring reduce_scatter recv_count %s != count//G %d",
                    recv_count, rc)
    rows, cols = chunk // 128, 128
    dt = jnp.dtype(dtype)
    call = _ring_call(kind, g, rows, cols, dt.name, False,
                      env_slots(slots), env_bidir(bidir), interpret_mode())
    wr = world_rank or _world_rank_flat

    perm = _snake_perm(group) if snake else None

    def body(x):
        pos, right, left = _scalars(group, wr, snake)
        if kind == "all_gather":
            xc = _to_chunks(x, 1, rc, chunk)        # (1, chunk) own shard
            out2d = call(pos, right, left, xc.reshape(rows, cols))
            outc = out2d.reshape(g, chunk)
            if perm is not None:
                # gathered chunks land by RING position: row i holds member
                # perm[i]'s shard — reorder to group-position (lax) order
                inv = np.argsort(perm).astype(np.int32)
                outc = jnp.take(outc, jnp.asarray(inv), axis=0)
            return outc[:, :rc].reshape(-1)
        xc = _to_chunks(x, g, rc, chunk)            # (g, chunk), dtype kept
        if perm is not None:
            # snake cycle: feed chunks in ring order (see _snake_perm)
            xc = jnp.take(xc, jnp.asarray(perm), axis=0)
        out2d = call(pos, right, left, xc.reshape(g * rows, cols))
        if kind == "reduce_scatter":
            return out2d.reshape(-1)[:rc]
        outc = out2d.reshape(g, chunk)
        if perm is not None:
            # undo the ring-order scatter: logical chunk perm[i] sits at row i
            inv = np.argsort(perm).astype(np.int32)
            outc = jnp.take(outc, jnp.asarray(inv), axis=0)
        return outc[:, :rc].reshape(-1)[:count]

    return body


def quant_ring_body(
    kind: str,
    group: ProcessGroup,
    count: int,
    block: int,
    *,
    slots: Optional[int] = None,
    bidir: Optional[bool] = None,
    world_rank: Optional[Callable] = None,
) -> Tuple[Callable, int]:
    """-> (local body ``(x, err) -> (out, new_err)``, error-feedback length)
    for the fused int8 pallas ring — the drop-in alternative to
    quant_ring._ring_body with identical entry error-feedback math (shared
    helpers, shared geometry units) so the residual is bit-exact with the
    composed ring and the supervisor's degrade flush
    (quant_ring.logical_residual) applies unchanged."""
    from mlsl_tpu.comm import quant_ring

    mlsl_assert(ring_axis(group) is not None,
                "pallas_ring needs a single-live-axis group (got axes=%s)",
                group.axes)
    mlsl_assert(block % 128 == 0,
                "pallas_ring int8 codec needs block %% 128 == 0 (got %d)",
                block)
    g, rc, chunk, err_len = quant_geometry(kind, group, count, block)
    mlsl_assert(g > 1, "pallas_ring needs a group with >1 member")
    rows, cols = chunk // block, block
    use_pallas = quant_ring.use_pallas_for(group, block)
    call = _ring_call(kind, g, rows, cols, "float32", True,
                      env_slots(slots), env_bidir(bidir), interpret_mode())
    wr = world_rank or _world_rank_flat

    def body(x, err):
        # entry quantization + error feedback: quant_ring's exact helpers
        # (the Pallas quantize kernel on TPU), so the residual the request
        # carries is bit-for-bit the composed ring's
        pos, right, left = _scalars(group, wr)
        xq = quant_ring._to_chunks(
            x.astype(jnp.float32), g, rc, chunk
        ).reshape(-1) + err
        q0, s0 = quant_ring._quant(xq.reshape(-1, block), use_pallas)
        xhat = quant_ring._dequant(
            q0.reshape(-1, block), s0, use_pallas
        ).reshape(-1)
        new_err = xq - xhat
        out2d = call(pos, right, left, xhat.reshape(g * rows, cols))
        if kind == "reduce_scatter":
            return out2d.reshape(-1)[:rc], new_err
        return (
            out2d.reshape(g, chunk)[:, :rc].reshape(-1)[:count],
            new_err,
        )

    return body, err_len


def build_flat_program(body: Callable, group: ProcessGroup, kind: str,
                       stateful: bool = False) -> Callable:
    """Compile a pallas-ring body over the flat 'world' mesh, accepting and
    returning standard (R, D, S, M, n) distributed buffers — the
    collectives._build_flat convention with replication checking off (a
    pallas_call output carries no VMA annotation). ``stateful`` wraps the
    ``(x, err) -> (out, new_err)`` error-feedback form."""
    from mlsl_tpu.comm.collectives import smap
    from jax.sharding import PartitionSpec as P

    topo = group.topology
    w = topo.world_size
    grid = topo.grid_shape

    if stateful:
        def local_fn(x, e):
            with jax.named_scope(f"mlsl_{kind}_pallas_ring"):
                out, new_err = body(x.reshape(x.shape[1:]),
                                    e.reshape(e.shape[1:]))
            return out[None], new_err[None]

        sm = smap(local_fn, topo.flat_mesh,
                  in_specs=(P("world", None), P("world", None)),
                  out_specs=(P("world", None), P("world", None)),
                  check=False)

        def fn(buf, err):
            out, new_err = sm(buf.reshape(w, buf.shape[-1]),
                              err.reshape(w, err.shape[-1]))
            return (out.reshape(*grid, out.shape[-1]),
                    new_err.reshape(*grid, new_err.shape[-1]))

        return jax.jit(fn)

    def local_fn(x):
        with jax.named_scope(f"mlsl_{kind}_pallas_ring"):
            out = body(x.reshape(x.shape[1:]))
        return out[None]

    sm = smap(local_fn, topo.flat_mesh,
              in_specs=P("world", None), out_specs=P("world", None),
              check=False)

    def fn(buf):
        out = sm(buf.reshape(w, buf.shape[-1]))
        return out.reshape(*grid, out.shape[-1])

    return jax.jit(fn)


def steps(
    kind: str,
    group: ProcessGroup,
    count: int,
    *,
    op=None,
    recv_count=None,
    slots: Optional[int] = None,
    bidir: Optional[bool] = None,
    snake: bool = False,
) -> Tuple[Callable, List[Callable], Callable]:
    """The compiled-overlap phase form (rhd.steps/ring2d.steps convention):
    ``(prep, phases, finish)`` with ONE phase — the whole fused ring is a
    single kernel launch, which is exactly the point: the overlap scheduler
    interleaves kernels between layers, and Mosaic owns the intra-kernel
    DMA/codec overlap. Bodies run inside the engine's 4-axis grid shard_map,
    so the world rank comes from the grid axes (TPU-only: ``inline_ok``).
    ``kind='all_gather'`` is the ZeRO-1 increment-exchange phase (no
    reduction op); ``snake`` selects the 2D-torus cycle (pallas_ring2d)."""
    from mlsl_tpu.types import ReductionType

    mlsl_assert(op in (None, ReductionType.SUM),
                "pallas_ring supports SUM only (got %s)", op)
    body = dense_ring_body(
        kind, group, count, jnp.float32, recv_count=recv_count,
        slots=slots, bidir=bidir, world_rank=_world_rank_grid(group),
        snake=snake,
    )

    def phase(carry):
        cur, mypos = carry
        return body(cur), mypos

    return (lambda x, mypos: (x, mypos)), [phase], (lambda carry: carry[0])
