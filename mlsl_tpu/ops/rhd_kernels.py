"""Pallas latency-class allreduce: recursive halving/doubling in ONE kernel.

The fused ring (ops/ring_kernels.py) is bandwidth-optimal: 2(G-1) hops, each
carrying 1/G of the payload. Decode-shaped allreduces — the
``msg_priority_threshold`` class — are the opposite regime: the payload is a
few KiB and per-hop LATENCY dominates, so the winning schedule is the one
with the fewest serialized wire rounds. That is recursive halving/doubling
(eplib/allreduce_pr.c, the rhd lowering's pair math): ceil(log2 G) halving
rounds (each exchanging half the current window with a partner and
reducing), mirrored doubling rounds reassembling the full vector, plus one
pre/post fold pair for non-power-of-two groups — 2*log2(G) rounds total
instead of 2(G-1).

This module is that schedule as ONE Pallas kernel: every round is a single
symmetric ``make_async_remote_copy`` exchange between VMEM comm slots
(payloads this small never round-trip HBM between rounds), with the same
double-buffered slot + remote-capacity-handshake machinery as the ring
family and the same ``static_accounting`` mirror for the A130-A132 plan
verifier.

Uniform SPMD round schedule (no in-kernel predication): every member
executes every round. In a fold round, members without a partner RDMA to
THEMSELVES (their own logical id — a local loopback the DMA engine serves
without touching the wire) and the combine masks their contribution with a
``jnp.where`` on the member's traced group position — the same masking idiom
the ring kernel uses for direction splits. For power-of-two groups (every
proof-mesh and most production rings) no fold rounds exist and no self-copy
is ever emitted.

Addressing mirrors the ring: per-member scalar-prefetch operands — the group
position and a per-ROUND partner table of world ranks (= LOGICAL device
ids) — so one cached kernel serves every mesh. CPU testability, interpret
gating (``MLSL_PALLAS_INTERPRET``) and the flat-mesh host program follow
ring_kernels exactly.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.ops import ring_kernels as rk

#: window alignment (elements): 8 sublane rows x 128 lanes — every halving
#: slice stays an f32-tile-legal row block
UNIT = 8 * 128


def _split(g: int) -> Tuple[int, int, int]:
    """-> (c, k, r): the largest power-of-two core c = 2**k <= g and the
    folded remainder r = g - c (rhd.steps' exact decomposition)."""
    c = 1 << (int(g).bit_length() - 1)
    return c, c.bit_length() - 1, int(g) - c


def rounds(g: int) -> int:
    """Total exchange rounds one build emits: pre-fold + k halvings +
    k doublings + post-fold."""
    c, k, r = _split(g)
    return 2 * k + (2 if r else 0)


def geometry(g: int, count: int) -> Tuple[int, int]:
    """-> (m, m_rows): the padded working size. m is ``count`` rounded up so
    every one of the k halvings splits on a UNIT boundary (m a multiple of
    c * UNIT) — the same align-up-then-slice placement the ring's chunks
    use."""
    c, _k, _r = _split(g)
    m = -(-int(count) // (c * UNIT)) * (c * UNIT)
    return m, m // 128


def eligible(kind: str, group: ProcessGroup, op=None) -> bool:
    """Engine eligibility: SUM allreduce on a uniform axis-aligned group of
    tractable size, on a backend that can run the kernel. Unlike the ring
    there is no single-live-axis restriction — partners are addressed by
    world rank, so any axis-aligned sub-grid works (the pairwise schedule
    does not care which physical links it crosses; at these payload sizes
    the wire is not the bottleneck)."""
    from mlsl_tpu.types import ReductionType

    if kind != "allreduce":
        return False
    if op not in (None, ReductionType.SUM):
        return False
    if not rk.available():
        return False
    if group.colors is not None or not group.axes or not group.is_uniform:
        return False
    return 1 < int(group.size) <= rk.MAX_GROUP


def inline_ok(group: ProcessGroup) -> bool:
    """In-graph (compiled overlap) emission: compiled-on-TPU only, the same
    interpreter restriction as the ring family."""
    return (not rk.interpret_mode() and rk._on_tpu()
            and group.colors is None and bool(group.axes))


def env_max_bytes(config=None) -> int:
    """The payload band (bytes) below which the selection table's heuristic
    rung prefers this kernel when ``MLSL_PALLAS_RHD`` armed it: an explicit
    ``MLSL_PALLAS_RHD_MAX_BYTES`` wins, else the existing small-message
    class boundary (msg_priority_threshold elements of f32)."""
    v = int(getattr(config, "pallas_rhd_max_bytes", 0) or 0)
    if v > 0:
        return v
    return 4 * int(getattr(config, "msg_priority_threshold", 10000))


def describe_plan(g: int, m_elems: int, slots: int) -> str:
    """The ``pallas.hop`` span argument (ring_kernels.describe_plan format):
    round count, the widest per-round transfer, codec and slot depth."""
    c, _k, r = _split(g)
    widest = m_elems if r else m_elems // 2
    return (f"hops={rounds(g)} slot_bytes={widest * 4} codec=rhd/f32 "
            f"slots={slots}")


def static_accounting(g: int, slots: int):
    """-> (events, total_hops, ndirs): the capacity-semaphore event trace,
    mirroring ``_rhd_kernel`` exactly — every round's recv slot is consumed
    (added/placed) the round it arrives and never re-read, so the trace is
    the ring's reduce-scatter shape over ``rounds(g)`` symmetric exchanges
    in one direction. The A130/A131 verifier replays this (analysis/plan.py)
    — keep it next to the emission."""
    total = rounds(g)
    events = []
    for t in range(total):
        if t >= slots:
            events.append(("wait", 0, t))
        if t + slots <= total - 1:
            events.append(("free", 0, t))
    return events, total, 1


def _rhd_kernel_factory(
    *, G: int, m_rows: int, slots: int, handshake: bool,
) -> Callable:
    """Build the kernel body: the full pre-fold / halving / doubling /
    post-fold schedule unrolled in Python (G <= MAX_GROUP => at most
    2*log2(64)+2 = 14 rounds). Window offsets are traced (they depend on the
    member's position bits); window LENGTHS are static per round."""
    c, k, r = _split(G)
    R = rounds(G)

    def kernel(pos_ref, peers_ref, x_ref, out_ref, acc, rbuf, csem,
               psend, precv, *rest):
        cap = rest[0] if handshake else None
        pos = pos_ref[0]
        rel = lax.rem(pos, c)
        active = pos < c

        cin = pltpu.make_async_copy(x_ref, acc, csem.at[0])
        cin.start()
        cin.wait()

        def slot_wait(h):
            if handshake and h >= slots:
                pltpu.semaphore_wait(cap.at[0], 1)

        def slot_free(use_h):
            # the slot used at round use_h is consumed: free it on the
            # device that produces its NEXT use — my partner at round
            # use_h + slots (whose slot_wait there blocks on MY signal,
            # the ring handshake's exact routing)
            if handshake and use_h + slots <= R - 1:
                pltpu.semaphore_signal(
                    cap.at[0], inc=1,
                    device_id=peers_ref[use_h + slots],
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

        def exchange(h, src_off, len_rows):
            """One symmetric round: send my [src_off, +len) window to this
            round's partner; its mirrored send lands in my slot h%slots."""
            slot = h % slots
            slot_wait(h)
            cx = pltpu.make_async_remote_copy(
                src_ref=acc.at[pl.ds(src_off, len_rows)],
                dst_ref=rbuf.at[slot, pl.ds(0, len_rows)],
                send_sem=psend.at[slot],
                recv_sem=precv.at[slot],
                device_id=peers_ref[h],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            cx.start()
            cx.wait()
            return slot

        h = 0
        if r:
            # pre-fold: (c+j, j) pairs fold the remainder into the core;
            # only pos < r accumulates (everyone exchanges — unpaired
            # members loop back to themselves and mask)
            slot = exchange(h, 0, m_rows)
            got = rbuf[slot, pl.ds(0, m_rows)]
            acc[...] = acc[...] + jnp.where(pos < r, got, 0.0)
            slot_free(h)
            h += 1

        # halving: shrink the window log2(c) times, reducing as we go
        off = jnp.int32(0)
        for t in range(k):
            half = m_rows >> (t + 1)
            bit0 = ((rel >> (k - 1 - t)) & 1) == 0
            send_off = off + jnp.where(bit0, half, 0)
            new_off = off + jnp.where(bit0, 0, half)
            slot = exchange(h, send_off, half)
            got = rbuf[slot, pl.ds(0, half)]
            acc[pl.ds(new_off, half)] = acc[pl.ds(new_off, half)] + \
                jnp.where(active, got, 0.0)
            slot_free(h)
            off = new_off
            h += 1

        # doubling: mirror the halvings in reverse, reassembling the vector
        for s in range(k):
            cur = m_rows >> (k - s)
            bit0 = ((rel >> s) & 1) == 0
            slot = exchange(h, off, cur)
            recv_off = jnp.where(bit0, off + cur, off - cur)
            got = rbuf[slot, pl.ds(0, cur)]
            acc[pl.ds(recv_off, cur)] = jnp.where(
                active, got, acc[pl.ds(recv_off, cur)])
            slot_free(h)
            off = jnp.where(bit0, off, off - cur)
            h += 1

        if r:
            # post-fold: the core hands the finished vector back to the
            # folded members (pos >= c replaces; everyone else keeps acc)
            slot = exchange(h, 0, m_rows)
            got = rbuf[slot, pl.ds(0, m_rows)]
            acc[...] = jnp.where(pos >= c, got, acc[...])
            slot_free(h)
            h += 1

        cout = pltpu.make_async_copy(acc, out_ref, csem.at[0])
        cout.start()
        cout.wait()

    return kernel


@functools.lru_cache(maxsize=64)
def _rhd_call(G: int, m_rows: int, slots: int, interpret: bool) -> Callable:
    """The compiled-or-interpreted pallas_call for one rhd configuration
    (pure geometry — addressing arrives as scalar-prefetch operands)."""
    R = rounds(G)
    c, _k, r = _split(G)
    if interpret:
        # no remote semaphore_signal in the interpreter: one slot per round
        slots_eff = max(R, 1)
        handshake = False
    else:
        slots_eff = min(max(slots, 2), max(R, 1))
        handshake = slots_eff < R
    buf_rows = m_rows if r else max(m_rows // 2, 8)

    kern = _rhd_kernel_factory(
        G=G, m_rows=m_rows, slots=slots_eff, handshake=handshake,
    )
    scratch = [
        pltpu.VMEM((m_rows, 128), jnp.float32),              # acc
        pltpu.VMEM((slots_eff, buf_rows, 128), jnp.float32),  # recv slots
        pltpu.SemaphoreType.DMA((1,)),                        # local copies
        pltpu.SemaphoreType.DMA((slots_eff,)),                # send
        pltpu.SemaphoreType.DMA((slots_eff,)),                # recv
    ]
    if handshake:
        scratch.append(pltpu.SemaphoreType.REGULAR((1,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,           # pos, per-round partner ranks
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((m_rows, 128), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=rk._compiler_params(
            ("rhd", G, m_rows, slots_eff, handshake)
        ),
        interpret=interpret,
    )


def _rhd_tables(group: ProcessGroup):
    """Per-world-rank addressing: ``pos`` (W,) group positions and
    ``peers`` (W, R) per-round partner WORLD ranks — self where the round's
    pairing leaves the member out (the masked loopback)."""
    from mlsl_tpu.comm import collectives

    g = int(group.size)
    c, k, r = _split(g)
    R = rounds(g)
    rows = collectives._axis_groups_tbl(group)
    w = group.topology.world_size
    pos = np.zeros((w,), dtype=np.int32)
    peers = np.zeros((w, max(R, 1)), dtype=np.int32)
    for row in rows:
        mlsl_assert(len(row) == g,
                    "pallas_rhd needs uniform group instances (got %d vs %d)",
                    len(row), g)
        for i, p in enumerate(row):
            pos[p] = i
            rr = []
            if r:
                rr.append(row[i + c] if i < r else
                          (row[i - c] if i >= c else p))
            for t in range(k):
                rr.append(row[i ^ (c >> (t + 1))] if i < c else p)
            for s in range(k):
                rr.append(row[i ^ (1 << s)] if i < c else p)
            if r:
                rr.append(row[i + c] if i < r else
                          (row[i - c] if i >= c else p))
            peers[p, :R] = rr
    return pos, peers


def _scalars(group: ProcessGroup, world_rank: Callable):
    pos_t, peers_t = _rhd_tables(group)
    wr = world_rank()
    pos = jnp.take(jnp.asarray(pos_t), wr)[None]
    peers = jnp.take(jnp.asarray(peers_t), wr, axis=0)
    return pos, peers


def allreduce_body(
    group: ProcessGroup,
    count: int,
    *,
    slots: Optional[int] = None,
    world_rank: Optional[Callable] = None,
) -> Callable:
    """-> local body ``(x) -> out`` (both (count,) f32) — the standard
    collectives calling convention, like ring_kernels.dense_ring_body."""
    g = int(group.size)
    mlsl_assert(g > 1, "pallas_rhd needs a group with >1 member")
    mlsl_assert(group.colors is None,
                "pallas_rhd needs an axis-aligned group")
    m, m_rows = geometry(g, count)
    call = _rhd_call(g, m_rows, rk.env_slots(slots), rk.interpret_mode())
    wr = world_rank or rk._world_rank_flat

    def body(x):
        pos, peers = _scalars(group, wr)
        xp = jnp.pad(x.astype(jnp.float32).reshape(-1), (0, m - count))
        out = call(pos, peers, xp.reshape(m_rows, 128))
        return out.reshape(-1)[:count]

    return body


def steps(
    kind: str,
    group: ProcessGroup,
    count: int,
    *,
    op=None,
    recv_count=None,
    slots: Optional[int] = None,
) -> Tuple[Callable, list, Callable]:
    """Compiled-overlap phase form: ONE phase (one kernel = one launch),
    the ring_kernels.steps convention. TPU-only in-graph (``inline_ok``)."""
    from mlsl_tpu.types import ReductionType

    mlsl_assert(kind == "allreduce",
                "pallas_rhd lowers allreduce only (got %s)", kind)
    mlsl_assert(op in (None, ReductionType.SUM),
                "pallas_rhd supports SUM only (got %s)", op)
    body = allreduce_body(
        group, count, slots=slots, world_rank=rk._world_rank_grid(group),
    )

    def phase(carry):
        cur, mypos = carry
        return body(cur), mypos

    return (lambda x, mypos: (x, mypos)), [phase], (lambda carry: carry[0])
