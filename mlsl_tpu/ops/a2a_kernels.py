"""Pallas fused all-to-all: the int8 blockwise wire for MoE dispatch/combine.

``models/moe.py``'s expert exchange rides ``algos.inline_alltoall`` — until
this PR a bare ``lax.all_to_all``: f32 on the wire, no engine selection, no
kernel path. This module is the EQuARX/THC wire applied to the exchange
shape (ROADMAP #5): ONE Pallas kernel owns all G-1 transfer steps of the
shifted-permutation all-to-all —

- step t sends the chunk destined for member (pos+t)%G DIRECTLY to that
  device (one hop per chunk — an all-to-all has no reduction, so unlike the
  ring there is nothing to stage) and receives the chunk from (pos-t)%G into
  the double-buffered VMEM slot t%slots, capacity handshake guarding reuse;
- the blockwise int8 quantize sits at the VMEM exit (the send slot is
  written compressed; scales ride the same step) and the dequantize is fused
  at the VMEM entry on the receive side, so the wire carries
  1 byte + 4/block per element instead of 4 — the <= 1/3 wire-bytes contract
  the MoE latency row pins;
- the self chunk never touches the wire but STILL round-trips the codec
  locally, so every chunk of the result carries exactly one quantization
  hop — bit-identical to the composed lax oracle (quantize every chunk ->
  ``lax.all_to_all`` -> dequantize) the parity tests replay;
- entry error feedback stays in the wrapper with ``quant_ring``'s exact
  helpers (the stateful ``(x, err) -> (out, new_err)`` form), so a
  2-round EF-residual lockstep against the oracle is bit-exact — the same
  contract the fused ring pins.

The dense (f32, no codec) variant of the same kernel serves
``MLSL_PALLAS_A2A_QUANT=0`` and non-float payloads. Addressing, interpret
gating (``MLSL_PALLAS_INTERPRET``), scalar-prefetch tables and the
``static_accounting`` verifier mirror follow ops/ring_kernels.py exactly.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mlsl_tpu.comm.mesh import ProcessGroup
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.ops import ring_kernels as rk


def eligible(kind: str, group: ProcessGroup, count: Optional[int] = None,
             op=None) -> bool:
    """Engine eligibility for the fused all-to-all: axis-aligned uniform
    groups of tractable size on a backend that can run the kernel. Chunks
    are addressed by world rank (LOGICAL ids), so multi-axis expert grids
    qualify like single rings do."""
    if kind != "alltoall" or op is not None:
        return False
    if not rk.available():
        return False
    if group.colors is not None or not group.axes or not group.is_uniform:
        return False
    if not (1 < int(group.size) <= rk.MAX_GROUP):
        return False
    if count is not None and count % int(group.size) != 0:
        return False
    return True


def inline_ok(group: ProcessGroup) -> bool:
    """In-graph emission (inside models/moe.py's shard_map): compiled-on-TPU
    only — the interpreter's remote DMA needs the single flat axis, so
    off-chip the inline route falls back to lax LOUDLY (the engine logs)."""
    return (rk._on_tpu() and not rk.interpret_mode()
            and group.colors is None and bool(group.axes))


def quant_enabled(config=None) -> bool:
    """The a2a codec toggle: ``MLSL_PALLAS_A2A_QUANT`` (default ON — the
    compressed wire is the kernel's point; selecting the algo at all is
    already an explicit operator/tuner choice)."""
    if config is not None:
        return bool(getattr(config, "pallas_a2a_quant", True))
    import os

    v = os.environ.get("MLSL_PALLAS_A2A_QUANT", "").strip().lower()
    return v not in ("0", "false", "no", "off")


def geometry(g: int, count: int, block: int,
             quantized: bool) -> Tuple[int, int, int]:
    """-> (rc, chunk, rows): per-destination slice rc = count/G and its
    aligned chunk (slice-at-chunk-start, the quant_ring placement). The
    quantized chunk unit is block * ROW_TILE (int8 tile legality); dense
    chunks align to DENSE_UNIT."""
    mlsl_assert(count % g == 0,
                "alltoall count %d %% group %d != 0", count, g)
    rc = count // g
    if quantized:
        from mlsl_tpu.ops import quant_kernels as qk

        unit = block * qk.ROW_TILE
        chunk = -(-rc // unit) * unit
        return rc, chunk, chunk // block
    chunk = -(-rc // rk.DENSE_UNIT) * rk.DENSE_UNIT
    return rc, chunk, chunk // 128


def wire_bytes(g: int, count: int, block: int, quantized: bool) -> int:
    """Wire bytes ONE member puts on the fabric for one exchange (the G-1
    remote chunks; the self chunk stays local) — the analytic row the MoE
    latency bench reports against the f32 inline baseline."""
    rc, chunk, rows = geometry(g, count, block, quantized)
    per_chunk = chunk + 4 * rows if quantized else chunk * 4
    return (g - 1) * per_chunk


def describe_plan(g: int, count: int, block: int, quantized: bool,
                  slots: int) -> str:
    """The ``pallas.hop`` span argument, ring_kernels.describe_plan format."""
    rc, chunk, rows = geometry(g, count, block, quantized)
    wire = chunk + 4 * rows if quantized else chunk * 4
    codec = f"int8/b{block}" if quantized else "float32"
    return f"hops={g - 1} slot_bytes={wire} codec={codec} slots={slots}"


def static_accounting(g: int, slots: int):
    """-> (events, total_hops, ndirs): every step's recv slot is dequantized
    into the output the step it arrives and never re-read — the ring's
    reduce-scatter trace shape over G-1 steps, one direction. Mirrors
    ``_a2a_kernel_factory``'s slot_wait/slot_free guards for A130/A131."""
    hops = int(g) - 1
    events = []
    for t in range(hops):
        if t >= slots:
            events.append(("wait", 0, t))
        if t + slots <= hops - 1:
            events.append(("free", 0, t))
    return events, hops, 1


def _a2a_kernel_factory(
    *, G: int, rows: int, cols: int, quantized: bool, slots: int,
    handshake: bool,
) -> Callable:
    """Build the kernel body: G-1 shifted-permutation steps unrolled in
    Python. Step t=1..G-1 (hop index h = t-1): quantize chunk (pos+t)%G out
    of VMEM, RDMA payload+scales to device (pos+t)%G's slot h%slots, fuse
    the dequantize into the receive placement at chunk (pos-t)%G."""
    hops = G - 1

    def kernel(pos_ref, to_ref, frm_ref, x_ref, out_ref, *scr):
        if quantized:
            loc, stg, qsend, ssend, qbuf, sbuf, csem, psend, precv, \
                ssend_sem, srecv_sem = scr[:11]
            cap = scr[11] if handshake else None
        else:
            loc, stg, fbuf, csem, psend, precv = scr[:6]
            cap = scr[6] if handshake else None

        pos = pos_ref[0]

        def dmod(v):
            return lax.rem(v + 4 * G, G)

        def copy_in(idx, sem):
            c = pltpu.make_async_copy(
                x_ref.at[pl.ds(idx * rows, rows)], loc, sem)
            c.start()
            return c

        def copy_out(src, idx, sem):
            c = pltpu.make_async_copy(
                src, out_ref.at[pl.ds(idx * rows, rows)], sem)
            c.start()
            return c

        def slot_wait(h):
            if handshake and h >= slots:
                pltpu.semaphore_wait(cap.at[0], 1)

        def slot_free(use_h):
            # my slot used at step use_h is consumed: its next producer is
            # the device sending to me at step use_h + slots
            if handshake and use_h + slots <= hops - 1:
                pltpu.semaphore_signal(
                    cap.at[0], inc=1,
                    device_id=frm_ref[use_h + slots],
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

        # ---- self chunk: no wire, but the same single codec round-trip ----
        cin = copy_in(pos, csem.at[0])
        cin.wait()
        if quantized:
            q, s = rk._quantize_rows(loc[...])
            stg[...] = q.astype(jnp.float32) * s
            cs = copy_out(stg, pos, csem.at[0])
        else:
            cs = copy_out(loc, pos, csem.at[0])
        cs.wait()

        # ---- G-1 shifted-permutation steps --------------------------------
        for t in range(1, G):
            h = t - 1
            slot = h % slots
            cin = copy_in(dmod(pos + t), csem.at[0])
            cin.wait()
            if quantized:
                q, s = rk._quantize_rows(loc[...])
                qsend[...] = q
                ssend[...] = s
            slot_wait(h)
            dev = to_ref[h]
            if quantized:
                cq = pltpu.make_async_remote_copy(
                    src_ref=qsend, dst_ref=qbuf.at[slot],
                    send_sem=psend.at[slot], recv_sem=precv.at[slot],
                    device_id=dev,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                csc = pltpu.make_async_remote_copy(
                    src_ref=ssend, dst_ref=sbuf.at[slot],
                    send_sem=ssend_sem.at[slot], recv_sem=srecv_sem.at[slot],
                    device_id=dev,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                cq.start()
                csc.start()
                cq.wait()
                csc.wait()
                stg[...] = (qbuf[slot].astype(jnp.float32) * sbuf[slot])
                cdone = copy_out(stg, dmod(pos - t), csem.at[0])
            else:
                cf = pltpu.make_async_remote_copy(
                    src_ref=loc, dst_ref=fbuf.at[slot],
                    send_sem=psend.at[slot], recv_sem=precv.at[slot],
                    device_id=dev,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                cf.start()
                cf.wait()
                cdone = copy_out(fbuf.at[slot], dmod(pos - t), csem.at[0])
            cdone.wait()
            slot_free(h)

    return kernel


@functools.lru_cache(maxsize=64)
def _a2a_call(
    G: int, rows: int, cols: int, quantized: bool, slots: int,
    interpret: bool,
) -> Callable:
    """The compiled-or-interpreted pallas_call for one a2a configuration."""
    hops = G - 1
    if interpret:
        slots_eff = max(hops, 1)
        handshake = False
    else:
        slots_eff = min(max(slots, 2), max(hops, 1))
        handshake = slots_eff < hops

    kern = _a2a_kernel_factory(
        G=G, rows=rows, cols=cols, quantized=quantized, slots=slots_eff,
        handshake=handshake,
    )
    if quantized:
        scratch = [
            pltpu.VMEM((rows, cols), jnp.float32),           # loc (f32 in)
            pltpu.VMEM((rows, cols), jnp.float32),           # staging out
            pltpu.VMEM((rows, cols), jnp.int8),              # qsend
            pltpu.VMEM((rows, 1), jnp.float32),              # ssend
            pltpu.VMEM((slots_eff, rows, cols), jnp.int8),   # qbuf
            pltpu.VMEM((slots_eff, rows, 1), jnp.float32),   # sbuf
            pltpu.SemaphoreType.DMA((1,)),                   # local copies
            pltpu.SemaphoreType.DMA((slots_eff,)),           # payload send
            pltpu.SemaphoreType.DMA((slots_eff,)),           # payload recv
            pltpu.SemaphoreType.DMA((slots_eff,)),           # scale send
            pltpu.SemaphoreType.DMA((slots_eff,)),           # scale recv
        ]
    else:
        scratch = [
            pltpu.VMEM((rows, cols), jnp.float32),           # loc
            pltpu.VMEM((rows, cols), jnp.float32),           # staging
            pltpu.VMEM((slots_eff, rows, cols), jnp.float32),  # fbuf
            pltpu.SemaphoreType.DMA((1,)),
            pltpu.SemaphoreType.DMA((slots_eff,)),
            pltpu.SemaphoreType.DMA((slots_eff,)),
        ]
    if handshake:
        scratch.append(pltpu.SemaphoreType.REGULAR((1,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,      # pos, send-target table, recv-from table
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((G * rows, cols), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=rk._compiler_params(
            ("a2a", G, rows, cols, quantized, slots_eff)
        ),
        interpret=interpret,
    )


def _a2a_tables(group: ProcessGroup):
    """Per-world-rank addressing: ``pos`` (W,), ``to`` (W, G-1) the step-t
    send target (pos+t)%G's world rank, ``frm`` (W, G-1) the step-t sender
    (pos-t)%G's world rank (the capacity handshake signals its successor)."""
    from mlsl_tpu.comm import collectives

    g = int(group.size)
    rows = collectives._axis_groups_tbl(group)
    w = group.topology.world_size
    pos = np.zeros((w,), dtype=np.int32)
    to = np.zeros((w, max(g - 1, 1)), dtype=np.int32)
    frm = np.zeros((w, max(g - 1, 1)), dtype=np.int32)
    for row in rows:
        mlsl_assert(len(row) == g,
                    "pallas_a2a needs uniform group instances (got %d vs %d)",
                    len(row), g)
        for i, p in enumerate(row):
            pos[p] = i
            for t in range(1, g):
                to[p, t - 1] = row[(i + t) % g]
                frm[p, t - 1] = row[(i - t) % g]
    return pos, to, frm


def _scalars(group: ProcessGroup, world_rank: Callable):
    pos_t, to_t, frm_t = _a2a_tables(group)
    wr = world_rank()
    take1 = lambda t: jnp.take(jnp.asarray(t), wr)[None]
    take2 = lambda t: jnp.take(jnp.asarray(t), wr, axis=0)
    return take1(pos_t), take2(to_t), take2(frm_t)


def alltoall_body(
    group: ProcessGroup,
    count: int,
    *,
    block: int = 256,
    quantized: bool = True,
    slots: Optional[int] = None,
    world_rank: Optional[Callable] = None,
) -> Callable:
    """-> local body ``(x) -> out`` (both (count,) f32): the stateless form
    (entry error feedback at zero — the inline MoE route, where no residual
    carries across calls). Chunk j of the output is the chunk member j sent
    here — ``lax.all_to_all``'s split_axis=0/concat_axis=0 layout on the
    flattened buffer."""
    body, _ = alltoall_body_ef(
        group, count, block=block, quantized=quantized, slots=slots,
        world_rank=world_rank,
    )

    def stateless(x):
        out, _new_err = body(x, None)
        return out

    return stateless


def alltoall_body_ef(
    group: ProcessGroup,
    count: int,
    *,
    block: int = 256,
    quantized: bool = True,
    slots: Optional[int] = None,
    world_rank: Optional[Callable] = None,
) -> Tuple[Callable, int]:
    """-> (body ``(x, err) -> (out, new_err)``, err_len): the stateful entry
    error-feedback form (quant_ring's exact helpers, so the residual is
    bit-exact with the composed oracle). ``err=None`` runs with a zero
    residual and returns the would-be residual."""
    from mlsl_tpu.comm import quant_ring

    g = int(group.size)
    mlsl_assert(g > 1, "pallas_a2a needs a group with >1 member")
    mlsl_assert(group.colors is None,
                "pallas_a2a needs an axis-aligned group")
    if quantized:
        mlsl_assert(block % 128 == 0,
                    "pallas_a2a int8 codec needs block %% 128 == 0 (got %d)",
                    block)
    rc, chunk, rows = geometry(g, count, block, quantized)
    cols = block if quantized else 128
    err_len = g * chunk if quantized else 0
    use_pallas = quant_ring.use_pallas_for(group, block) if quantized else False
    call = _a2a_call(g, rows, cols, quantized, rk.env_slots(slots),
                     rk.interpret_mode())
    wr = world_rank or rk._world_rank_flat

    def body(x, err):
        pos, to, frm = _scalars(group, wr)
        xc = quant_ring._to_chunks(
            x.astype(jnp.float32), g, rc, chunk
        ).reshape(-1)
        if quantized:
            xq = xc if err is None else xc + err
            q0, s0 = quant_ring._quant(xq.reshape(-1, block), use_pallas)
            xhat = quant_ring._dequant(
                q0.reshape(-1, block), s0, use_pallas
            ).reshape(-1)
            new_err = xq - xhat
            wire_in = xhat
        else:
            new_err = None
            wire_in = xc
        out2d = call(pos, to, frm, wire_in.reshape(g * rows, cols))
        out = out2d.reshape(g, chunk)[:, :rc].reshape(-1)
        return out, new_err

    return body, err_len


def steps(
    kind: str,
    group: ProcessGroup,
    count: int,
    *,
    block: int = 256,
    quantized: bool = True,
    slots: Optional[int] = None,
) -> Tuple[Callable, list, Callable]:
    """Compiled-overlap / inline phase form: ONE phase (one kernel launch),
    the ring_kernels.steps convention. TPU-only in-graph (``inline_ok``)."""
    mlsl_assert(kind == "alltoall",
                "pallas_a2a lowers alltoall only (got %s)", kind)
    body = alltoall_body(
        group, count, block=block, quantized=quantized, slots=slots,
        world_rank=rk._world_rank_grid(group),
    )

    def phase(carry):
        cur, mypos = carry
        return body(cur), mypos

    return (lambda x, mypos: (x, mypos)), [phase], (lambda carry: carry[0])
