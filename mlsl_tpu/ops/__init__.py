"""Device kernels: Pallas TPU implementations of the hot non-matmul ops."""
