"""Flash attention forward kernel (Pallas TPU).

Fused online-softmax attention: scores, exp, and the weighted-value accumulation all
happen in VMEM tile by tile, so the (Sq, Sk) score matrix never touches HBM — the
memory win that matters for the long sequences the sequence-parallel schedules target
(HBM traffic O(S*D) instead of O(S^2)).

Autodiff: a custom VJP with fused Pallas backward kernels — dq accumulates over key
tiles, dk/dv over query tiles, with the tile probabilities recomputed from the saved
per-row log-sum-exp, so the O(S*D) memory property holds in the backward too. On
fully-masked rows the kernel's gradients are exactly zero (consistent with its zero
forward output), unlike a dense softmax which would leak uniform-distribution
gradients.

Grid: (batch*heads, Sq tiles, Sk tiles), Sk innermost and "arbitrary" so the VMEM
scratch (acc, row-max, row-sum) carries across k tiles; outputs are written on the
last k tile (the canonical TPU flash pattern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (jax 0.7); accept either so
# the flash kernels build on both sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

NEG = -1e30


def _pick_tiles(sq: int, sk: int):
    """Largest tiles that divide the shapes (tuned on v5e: big k tiles win —
    fewer scratch-carry round trips per query tile)."""
    tq = next((t for t in (512, 256, 128) if sq % t == 0), None)
    tk = next((t for t in (2048, 1024, 512, 256, 128) if sk % t == 0), None)
    return tq, tk


def _tile_visible(q_off_ref, k_off_ref, qi, ki, tq, tk, causal: bool):
    """Whole-tile causal visibility: skip k tiles entirely in this q tile's future."""
    if not causal:
        return True
    q_pos_max = q_off_ref[0] + (qi + 1) * tq - 1
    k_pos_min = k_off_ref[0] + ki * tk
    return k_pos_min <= q_pos_max


def _kv_idx(causal: bool, tq: int, tk: int, k_tiles: int):
    """k/v BlockSpec index map for a (b, q-tile, k-tile) grid.

    For causal, k tiles past the diagonal CLAMP to the last visible tile:
    pl.when already skips their compute, but the pipeline would still DMA every
    block — repeating the previous index makes Pallas skip the copy, so the
    causal walk does ~half the memory traffic of the full one (this was
    measured slower than the full kernel before the clamp)."""
    if not causal:
        return lambda b, i, j, *_: (b, j, 0)

    def idx(b, i, j, q_off_ref, k_off_ref):
        last = (q_off_ref[0] + (i + 1) * tq - 1 - k_off_ref[0]) // tk
        last = jnp.clip(last, 0, k_tiles - 1)
        return (b, jnp.minimum(j, last), 0)

    return idx


def _q_idx_for_dkv(causal: bool, tq: int, tk: int, q_tiles: int):
    """q-side BlockSpec index map for the (b, k-tile, q-tile) dk/dv grid:
    q tiles BEFORE the diagonal clamp up to the first visible tile (same
    DMA-skip trick as _kv_idx, mirrored)."""
    if not causal:
        return lambda b, i, j, *_: (b, j, 0)

    def idx(b, i, j, q_off_ref, k_off_ref):
        first = -((q_off_ref[0] - k_off_ref[0] - i * tk + tq - 1) // tq)
        first = jnp.clip(first, 0, q_tiles - 1)
        return (b, jnp.maximum(j, first), 0)

    return idx


def _tile_accumulate(q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
                     acc_prev, m_prev, l_prev,
                     qi, ki, tq, tk, scale, causal: bool):
    """The online-softmax tile update (shared by both kernels): fold the (tq, tk)
    score tile into (acc, m, l). Returns the updated triple as values."""
    q = q_ref[0].astype(jnp.float32)              # (tq, D)
    k = k_ref[0].astype(jnp.float32)              # (tk, D)
    v = v_ref[0].astype(jnp.float32)              # (tk, D)
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off_ref[0] + qi * tq + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = k_off_ref[0] + ki * tk + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG)
    s_max = jnp.max(s, axis=1)
    m_new = jnp.maximum(m_prev, s_max)
    p = jnp.exp(s - m_new[:, None])
    p = jnp.where(s <= NEG / 2, 0.0, p)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=1)
    acc_new = acc_prev * corr[:, None] + jnp.dot(
        p, v, preferred_element_type=jnp.float32
    )
    return acc_new, m_new, l_new


def _flash_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, *refs,
                  causal: bool, k_tiles: int, scale: float, tq: int, tk: int,
                  want_lse: bool):
    if want_lse:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
        lse_ref = None
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_tile_visible(q_off_ref, k_off_ref, qi, ki, tq, tk, causal))
    def _accumulate():
        acc, m_new, l_new = _tile_accumulate(
            q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
            acc_ref[:], m_ref[:, 0], l_ref[:, 0],
            qi, ki, tq, tk, scale, causal,
        )
        acc_ref[:] = acc
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == k_tiles - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / denom[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = jnp.broadcast_to(
                (m_ref[:, 0] + jnp.log(denom))[:, None], lse_ref[0].shape
            )


@functools.partial(
    jax.jit, static_argnames=("causal", "interpret", "want_lse")
)
def _flash_fwd(q, k, v, q_offset, k_offset, causal=False, interpret=False,
               want_lse=True):
    """q: (BH, Sq, D), k/v: (BH, Sk, D); shapes must satisfy supports().
    -> (out, lse (BH, Sq, 128) lane-broadcast f32) when want_lse, else (out, None)
    — the inference path skips the lse allocation/write entirely."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    tq, tk = _pick_tiles(sq, sk)
    k_tiles = sk // tk
    scale = 1.0 / (d ** 0.5)
    grid = (bh, sq // tq, k_tiles)
    o_spec = pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0))
    lse_spec = pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0))
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, causal=causal, k_tiles=k_tiles, scale=scale,
            tq=tq, tk=tk, want_lse=want_lse,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tk, d), _kv_idx(causal, tq, tk, k_tiles)),
                pl.BlockSpec((1, tk, d), _kv_idx(causal, tq, tk, k_tiles)),
            ],
            out_specs=[o_spec, lse_spec] if want_lse else [o_spec],
            scratch_shapes=[
                pltpu.VMEM((tq, d), jnp.float32),
                pltpu.VMEM((tq, 128), jnp.float32),
                pltpu.VMEM((tq, 128), jnp.float32),
            ],
        ),
        out_shape=(
            [
                jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
            ]
            if want_lse
            else [jax.ShapeDtypeStruct((bh, sq, d), q.dtype)]
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v)
    if want_lse:
        return out[0], out[1]
    return out[0], None


def _reference_attention(q, k, v, q_offset, k_offset, causal):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        q_pos = q_offset[0] + jnp.arange(q.shape[1])
        k_pos = k_offset[0] + jnp.arange(k.shape[1])
        s = jnp.where((k_pos[None, :] <= q_pos[:, None])[None], s, NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash backward: two fused passes (dq over k tiles; dk/dv over q tiles), the
# score probabilities recomputed per tile from the saved log-sum-exp — the
# (Sq, Sk) matrices never materialize in the backward either.
# ---------------------------------------------------------------------------


def _bwd_p_tile(q_off_ref, k_off_ref, q, kk, lse, qi, ki, tq, tk, scale, causal):
    """Recompute P = exp(s*scale - lse) for one (tq, tk) tile, masked."""
    s = jnp.dot(q, kk.T, preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_off_ref[0] + qi * tq + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = k_off_ref[0] + ki * tk + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG)
    p = jnp.exp(s - lse[:, None])
    return jnp.where(s <= NEG / 2, 0.0, p)


def _bwd_dq_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   dd_ref, dq_ref, dq_acc, *, causal, k_tiles, scale, tq, tk):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_tile_visible(q_off_ref, k_off_ref, qi, ki, tq, tk, causal))
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p = _bwd_p_tile(q_off_ref, k_off_ref, q, kk, lse_ref[0, :, 0],
                        qi, ki, tq, tk, scale, causal)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)   # (tq, tk)
        ds = p * (dp - dd_ref[0, :, 0][:, None])
        dq_acc[:] = dq_acc[:] + scale * jnp.dot(
            ds, kk, preferred_element_type=jnp.float32
        )

    @pl.when(ki == k_tiles - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    dd_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, causal, q_tiles, scale, tq, tk):
    qi = pl.program_id(2)   # q innermost in this pass
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_tile_visible(q_off_ref, k_off_ref, qi, ki, tq, tk, causal))
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)
        kk = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p = _bwd_p_tile(q_off_ref, k_off_ref, q, kk, lse_ref[0, :, 0],
                        qi, ki, tq, tk, scale, causal)
        dv_acc[:] = dv_acc[:] + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dd_ref[0, :, 0][:, None])
        dk_acc[:] = dk_acc[:] + scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(qi == q_tiles - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def _flash_bwd(q, k, v, do, out, lse, q_offset, k_offset, causal, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    tq, tk = _pick_tiles(sq, sk)
    k_tiles, q_tiles = sk // tk, sq // tq
    scale = 1.0 / (d ** 0.5)
    # lse arrives 2-D (residual memory: see _fwd); rebroadcast for the kernels'
    # (tq, 128) tiles, as is D_i = rowsum(dO * O)
    lse = jnp.broadcast_to(lse[..., None], (bh, sq, 128))
    dd = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)[..., None],
        (bh, sq, 128),
    )

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, k_tiles=k_tiles,
                          scale=scale, tq=tq, tk=tk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, q_tiles, k_tiles),
            in_specs=[
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tk, d), _kv_idx(causal, tq, tk, k_tiles)),
                pl.BlockSpec((1, tk, d), _kv_idx(causal, tq, tk, k_tiles)),
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((tq, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v, do, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, q_tiles=q_tiles,
                          scale=scale, tq=tq, tk=tk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, k_tiles, q_tiles),
            in_specs=[
                pl.BlockSpec((1, tq, d), _q_idx_for_dkv(causal, tq, tk, q_tiles)),
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, d), _q_idx_for_dkv(causal, tq, tk, q_tiles)),
                pl.BlockSpec((1, tq, 128), _q_idx_for_dkv(causal, tq, tk, q_tiles)),
                pl.BlockSpec((1, tq, 128), _q_idx_for_dkv(causal, tq, tk, q_tiles)),
            ],
            out_specs=[
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((tk, d), jnp.float32),
                pltpu.VMEM((tk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v, do, lse, dd)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def flash_attention(q, k, v, q_offset, k_offset, causal=False, interpret=False):
    """Fused attention. q: (BH, Sq, D); k, v: (BH, Sk, D); offsets: (1,) int32
    global position bases (for causal masking across sequence shards)."""
    out, _ = _flash_fwd(
        q, k, v, q_offset, k_offset, causal=causal, interpret=interpret,
        want_lse=False,
    )
    return out


def _fwd(q, k, v, q_offset, k_offset, causal, interpret):
    out, lse = _flash_fwd(
        q, k, v, q_offset, k_offset, causal=causal, interpret=interpret
    )
    # keep the lse as a 2-D (BH, Sq) array so Sq packs into the lane dimension —
    # a (BH, Sq, 1) slice would still be lane-padded to 128, keeping the 128x
    # residual bloat this is meant to remove
    return out, (q, k, v, out, lse[:, :, 0], q_offset, k_offset)


def _bwd(causal, interpret, res, g):
    q, k, v, out, lse, q_offset, k_offset = res
    dq, dk, dv = _flash_bwd(
        q, k, v, g, out, lse, q_offset, k_offset, causal, interpret
    )
    return dq, dk, dv, None, None


flash_attention.defvjp(_fwd, _bwd)


def supports(sq: int, sk: int, d: int) -> bool:
    """Whether the kernel's tiling constraints admit these shapes."""
    tq, tk = _pick_tiles(sq, sk)
    return tq is not None and tk is not None and d % 8 == 0 and d >= 8


# ---------------------------------------------------------------------------
# Carried-state block update: the ring-attention inner step.
# One k/v block is folded into a running (acc, m, l) online-softmax state that
# persists across ppermute hops (so it lives in HBM between calls; the kernel
# fuses score/exp/accumulate for the block without materializing scores).
# ---------------------------------------------------------------------------


def _block_kernel(q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
                  acc_in_ref, m_in_ref, l_in_ref,
                  acc_out_ref, m_out_ref, l_out_ref,
                  *, causal: bool, k_tiles: int, scale: float, tq: int, tk: int):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _load_carry():
        acc_out_ref[0] = acc_in_ref[0]
        m_out_ref[0] = m_in_ref[0]
        l_out_ref[0] = l_in_ref[0]

    @pl.when(_tile_visible(q_off_ref, k_off_ref, qi, ki, tq, tk, causal))
    def _accumulate():
        acc, m_new, l_new = _tile_accumulate(
            q_off_ref, k_off_ref, q_ref, k_ref, v_ref,
            acc_out_ref[0], m_out_ref[0, :, 0], l_out_ref[0, :, 0],
            qi, ki, tq, tk, scale, causal,
        )
        acc_out_ref[0] = acc
        m_out_ref[0] = jnp.broadcast_to(m_new[:, None], m_out_ref[0].shape)
        l_out_ref[0] = jnp.broadcast_to(l_new[:, None], l_out_ref[0].shape)


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def _block_update_fwd(q, k, v, acc, m, l, q_offset, k_offset,
                      causal=False, interpret=False):
    """q: (BH, Sq, D); k/v: (BH, Sk, D); acc: (BH, Sq, D) f32;
    m, l: (BH, Sq, 128) f32 (lane-padded) -> (acc', m', l')."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    tq, tk = _pick_tiles(sq, sk)
    k_tiles = sk // tk
    scale = 1.0 / (d ** 0.5)
    grid = (bh, sq // tq, k_tiles)
    return pl.pallas_call(
        functools.partial(
            _block_kernel, causal=causal, k_tiles=k_tiles, scale=scale,
            tq=tq, tk=tk,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, tk, d), lambda b, i, j, *_: (b, j, 0)),
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tq, d), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, tq, 128), lambda b, i, j, *_: (b, i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        # alias the carried state in place: operands (2 scalar-prefetch + q,k,v,
        # acc, m, l) -> acc/m/l reuse their input buffers, saving one HBM copy of
        # the dominant long-sequence state per ring hop
        input_output_aliases={5: 0, 6: 1, 7: 2},
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_offset, k_offset, q, k, v, acc, m, l)


def _block_update_ref(q, k, v, acc, m, l, q_offset, k_offset, causal):
    """jnp twin of the block kernel (used for the VJP and as the oracle)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        q_pos = q_offset[0] + jnp.arange(q.shape[1])
        k_pos = k_offset[0] + jnp.arange(k.shape[1])
        s = jnp.where((k_pos[None, :] <= q_pos[:, None])[None], s, NEG)
    m_prev = m[:, :, 0]
    s_max = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, s_max)
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(s <= NEG / 2, 0.0, p)
    corr = jnp.exp(m_prev - m_new)
    l_new = l[:, :, 0] * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bqk,bkd->bqd", p, v.astype(jnp.float32)
    )
    bcast = lambda x: jnp.broadcast_to(x[..., None], (*x.shape, 128))
    return acc_new, bcast(m_new), bcast(l_new)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9))
def flash_block_update(q, k, v, acc, m, l, q_offset, k_offset,
                       causal=False, interpret=False):
    """Ring-attention inner step: fold one k/v block into (acc, m, l)."""
    return _block_update_fwd(
        q, k, v, acc, m, l, q_offset, k_offset, causal=causal, interpret=interpret
    )


def _bu_fwd(q, k, v, acc, m, l, q_offset, k_offset, causal, interpret):
    out = _block_update_fwd(
        q, k, v, acc, m, l, q_offset, k_offset, causal=causal, interpret=interpret
    )
    return out, (q, k, v, acc, m, l, q_offset, k_offset)


def _bu_bwd(causal, interpret, res, g):
    q, k, v, acc, m, l, q_offset, k_offset = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_, acc_, m_, l_: _block_update_ref(
            q_, k_, v_, acc_, m_, l_, q_offset, k_offset, causal
        ),
        q, k, v, acc, m, l,
    )
    dq, dk, dv, dacc, dm, dl = vjp(g)
    return dq, dk, dv, dacc, dm, dl, None, None


flash_block_update.defvjp(_bu_fwd, _bu_bwd)
