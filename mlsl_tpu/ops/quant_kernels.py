"""Blockwise int8 quantization kernels (Pallas on TPU, jnp reference elsewhere).

TPU-native replacement for the reference's dlopen'd quantization library
(quant/quant.c:153-211): elements are grouped into fixed-size blocks; each block is
scaled by max|x|/127 and rounded to int8; dequantization multiplies back. The
error-feedback ("diff") buffer semantics of the reference — the residual x - deq(q(x))
is carried to the next iteration — are implemented by the caller
(mlsl_tpu.comm.quant_ring) because JAX state is functional.

The Pallas kernel fuses scale computation + clip/round in one VMEM pass (the reference
does the same transform scalar-at-a-time on the endpoint server CPU). Layout: blocks
are rows of a (n_blocks, block) matrix; block must be a multiple of 128 lanes; rows are
tiled in groups of 32 to satisfy the int8 (32, 128) min tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_TILE = 32  # int8 min sublane count
PACK_ROWS = 1024  # rows per grid step on the packed-scale path: the scale
# tile is (rows/128, 128) and Mosaic needs >= 8 sublanes there


def _on_tpu() -> bool:
    from mlsl_tpu.sysinfo import on_tpu

    return on_tpu()


# -- reference (jnp) implementation: the semantic oracle ---------------------


def quantize_blocks_ref(x2d: jax.Array):
    """(n_blocks, block) f32 -> (int8 q, f32 scales (n_blocks,))."""
    amax = jnp.max(jnp.abs(x2d), axis=1)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(x2d / scale[:, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_blocks_ref(q2d: jax.Array, scales: jax.Array) -> jax.Array:
    return q2d.astype(jnp.float32) * scales[:, None]


# -- pallas kernels -----------------------------------------------------------


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:]
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)          # (rows, 1)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    q_ref[:] = q
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, x_ref):
    x_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:]


def _quant_kernel_packed(x_ref, q_ref, s_ref):
    # Blocks are (g, 128, block): rows ride the (leading, sublane) dims and
    # the quant block rides the lanes, so the per-row amax is a lane
    # reduction landing directly in the packed (g, 128) scale shape. A
    # (rows, 1) scale output would be lane-padded 128x in HBM, which turned
    # "n floats" of scale traffic into 128 MiB on a 256 MiB buffer and
    # capped both kernels near half roofline (measured on v5e; an in-kernel
    # (r,1)->(r/128,128) reshape is an unsupported Mosaic shape cast).
    x = x_ref[:]
    amax = jnp.max(jnp.abs(x), axis=2)                         # (g, 128)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    q_ref[:] = jnp.clip(
        jnp.round(x / scale[:, :, None]), -127, 127
    ).astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel_packed(q_ref, s_ref, x_ref):
    x_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:][:, :, None]


def _step_rows(n: int) -> int:
    """Rows per grid step: big steps amortize grid overhead; tiles stay int8-legal
    (multiples of ROW_TILE = 32 sublanes)."""
    for r in (512, 256, 128, 64, ROW_TILE):
        if n % r == 0:
            return r
    return ROW_TILE


@functools.partial(jax.jit, static_argnames=("interpret",))
def _quantize_pallas(x2d, interpret=False):
    n, block = x2d.shape
    if n % PACK_ROWS == 0:
        g = PACK_ROWS // 128
        x3 = x2d.reshape(n // 128, 128, block)
        q, s = pl.pallas_call(
            _quant_kernel_packed,
            grid=(n // PACK_ROWS,),
            in_specs=[pl.BlockSpec((g, 128, block), lambda i: (i, 0, 0))],
            out_specs=[
                pl.BlockSpec((g, 128, block), lambda i: (i, 0, 0)),
                pl.BlockSpec((g, 128), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n // 128, 128, block), jnp.int8),
                jax.ShapeDtypeStruct((n // 128, 128), jnp.float32),
            ],
            interpret=interpret,
        )(x3)
        return q.reshape(n, block), s.reshape(-1)
    # ragged row counts: (n, 1) scales (lane-padded HBM layout — slower, but
    # any row multiple of ROW_TILE is legal)
    r = _step_rows(n)
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=(n // r,),
        in_specs=[pl.BlockSpec((r, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((r, block), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, block), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d)
    return q, s[:, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dequantize_pallas(q2d, scales, interpret=False):
    n, block = q2d.shape
    if n % PACK_ROWS == 0:
        g = PACK_ROWS // 128
        out = pl.pallas_call(
            _dequant_kernel_packed,
            grid=(n // PACK_ROWS,),
            in_specs=[
                pl.BlockSpec((g, 128, block), lambda i: (i, 0, 0)),
                pl.BlockSpec((g, 128), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((g, 128, block), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((n // 128, 128, block), jnp.float32),
            interpret=interpret,
        )(q2d.reshape(n // 128, 128, block), scales.reshape(n // 128, 128))
        return out.reshape(n, block)
    r = _step_rows(n)
    return pl.pallas_call(
        _dequant_kernel,
        grid=(n // r,),
        in_specs=[
            pl.BlockSpec((r, block), lambda i: (i, 0)),
            pl.BlockSpec((r, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((r, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, block), jnp.float32),
        interpret=interpret,
    )(q2d, scales[:, None])


def block_align(n: int, block: int) -> int:
    """Smallest multiple of ``block`` >= n. Coalesced quantized payloads
    (core/bucketing.py) align every member's slot to this so a quant block
    never straddles two members' gradients: each member keeps exactly the
    per-block scale locality it would have on its own individual ring, and the
    inter-member padding quantizes to exact zeros."""
    return -(-n // block) * block


# -- public API: pads to tile geometry, picks backend -------------------------


def quantize(x: jax.Array, block: int = 256, use_pallas: bool | None = None):
    """1-D f32 -> (q int8 (padded n,), scales f32, orig_len).

    Pads to block*ROW_TILE rows, except large pallas-path buffers
    (>= 8*block*PACK_ROWS elements), which pad to block*PACK_ROWS rows so the
    packed-scale kernels engage — scales then pack densely as (rows/128, 128)
    instead of the lane-padded-128x (rows, 1) HBM layout that capped both
    kernels near half roofline (see the kernels). The coarser padding wastes
    <= 12.5% at the threshold, asymptotically ~0; callers must treat the
    returned q length as opaque and slice with orig_len.
    """
    n = x.shape[0]
    if use_pallas is None:
        use_pallas = _on_tpu() and block % 128 == 0
    big = n >= 8 * block * PACK_ROWS
    row_mult = PACK_ROWS if (use_pallas and big) else ROW_TILE
    n_pad = -(-n // (block * row_mult)) * (block * row_mult)
    xp = jnp.pad(x.astype(jnp.float32), (0, n_pad - n))
    x2d = xp.reshape(-1, block)
    if use_pallas:
        q, s = _quantize_pallas(x2d)
    else:
        q, s = quantize_blocks_ref(x2d)
    return q.reshape(-1), s, n


def dequantize(q: jax.Array, scales: jax.Array, block: int = 256, orig_len=None,
               use_pallas: bool | None = None) -> jax.Array:
    q2d = q.reshape(-1, block)
    if use_pallas is None:
        # Pallas by default on TPU. On bare 2-D blocks the two dequant forms
        # are equal (pallas 0.88-1.01x of XLA at 256 MiB streaming), but
        # through THIS 1-D wire-format wrapper the pallas path measured 1.4x
        # FASTER (~1.48 vs ~2.15 ms at 256 MiB, repeated): the reshape chain
        # around the XLA form costs more than the kernel difference. The ring
        # (already 2-D, multiply fused into its accumulate) uses the XLA form
        # — see comm/quant_ring._dequant.
        use_pallas = _on_tpu() and block % 128 == 0
    if use_pallas:
        x = _dequantize_pallas(q2d, scales)
    else:
        x = dequantize_blocks_ref(q2d, scales)
    x = x.reshape(-1)
    if orig_len is None or orig_len == x.shape[0]:
        return x
    return x[:orig_len]
