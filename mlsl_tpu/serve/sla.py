"""SLA governor: the supervisor's degradation ladder at SLO granularity.

The training stack degrades per-subsystem (supervisor circuit breakers:
fast path -> always-correct fallback). Serving needs the same never-die
contract against a different enemy — load, not faults: under sustained queue
growth or a p99 TPOT breach the engine must shed WORK, in a fixed order,
and take it back rung by rung once the pressure clears:

    healthy -> shed_batch -> shed_precision -> shed_admission

- **shed_batch** halves the continuous-batching slot ceiling: fewer
  sequences per decode step, lower per-step latency, the first and cheapest
  lever (quality untouched).
- **shed_precision** drops the decode compute dtype to bf16 (and, with
  MLSL_SERVE_KV_QUANT, the KV at rest is already int8): throughput per slot
  recovers at a bounded numeric cost.
- **shed_admission** closes the front door: ``submit()`` rejects 429-style
  with a retry-after hint while the queue drains. The engine itself never
  dies — rejection IS the availability story at this rung.

Escalation needs ``breach_ticks`` consecutive pressured scheduler ticks
(one transient spike never sheds); recovery needs ``recover_ticks`` clear
ticks per rung (hysteresis — the ladder must not flap). The straggler
sentinel's confirmed candidate counts as pressure: a slow replica inflates
decode-step tails, so tail-latency defense sheds before the p99 breaches.

Every transition is recorded via ``stats.record_serve_shed`` (an immediate
SERVE line in mlsl_stats.log — the degraded-not-down story must be
greppable) and surfaced on /healthz through :func:`status`, which
``supervisor.status()`` aggregates.
"""

from __future__ import annotations

import collections
from typing import List, Optional

from mlsl_tpu.log import MLSLError, log_warning

#: ladder rungs, in shed order; index = rung number
RUNGS = ("healthy", "shed_batch", "shed_precision", "shed_admission")


class ServeOverloadError(MLSLError):
    """429-style admission rejection: the engine is shedding load (full
    queue or an SLA ladder at the admission rung). ``retry_after_s`` is the
    client backoff hint — the request was never admitted, retrying after
    the hint is safe and expected."""

    def __init__(self, msg: str, retry_after_s: float = 0.5):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class SLAGovernor:
    """The ladder state machine. The engine calls :meth:`observe` with queue
    depth / per-step decode latency / straggler signals, then :meth:`tick`
    once per scheduler iteration; :attr:`batch_limit`,
    :attr:`precision_shed` and :attr:`admission_open` are the levers the
    engine reads back. :meth:`force_shed` is the fault path (a classified
    decode failure escalates immediately — no breach accumulation)."""

    def __init__(self, *, max_batch: int, queue_depth: int,
                 tpot_p99_ms: float = 0.0, breach_ticks: int = 3,
                 recover_ticks: int = 16, window: int = 64,
                 queue_frac: float = 0.75, retry_after_s: float = 0.5):
        self.max_batch = int(max_batch)
        self.queue_depth = int(queue_depth)
        #: p99 decode-step budget in ms (0 = no latency SLO, queue-only)
        self.tpot_p99_ms = float(tpot_p99_ms)
        self.breach_ticks = int(breach_ticks)
        self.recover_ticks = int(recover_ticks)
        self.queue_frac = float(queue_frac)
        self.retry_after_s = float(retry_after_s)
        self.rung = 0
        self.sheds = 0
        self.recoveries = 0
        self.last_reason = ""
        self._tpot: collections.deque = collections.deque(maxlen=int(window))
        self._queue = 0
        self._straggler = False
        self._hot = 0
        self._cool = 0

    # -- inputs ------------------------------------------------------------

    def observe(self, *, queue_len: Optional[int] = None,
                tpot_ms: Optional[float] = None,
                straggler: Optional[bool] = None) -> None:
        if queue_len is not None:
            self._queue = int(queue_len)
        if tpot_ms is not None:
            self._tpot.append(float(tpot_ms))
        if straggler is not None:
            self._straggler = bool(straggler)

    def p99_tpot_ms(self) -> Optional[float]:
        """p99 over the recent decode-step window (None below 8 samples —
        an unjudgeable tail must not shed)."""
        if len(self._tpot) < 8:
            return None
        vals: List[float] = sorted(self._tpot)
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    # -- the ladder --------------------------------------------------------

    def _pressure(self) -> Optional[str]:
        if self._queue > self.queue_frac * self.queue_depth:
            return f"queue {self._queue}/{self.queue_depth}"
        if self._straggler:
            return "straggler flagged"
        p99 = self.p99_tpot_ms()
        if self.tpot_p99_ms > 0 and p99 is not None and p99 > self.tpot_p99_ms:
            return f"p99 TPOT {p99:.1f} ms > {self.tpot_p99_ms:.1f} ms"
        return None

    def tick(self) -> int:
        """Evaluate pressure once per scheduler iteration; maybe transition.
        Returns the current rung."""
        reason = self._pressure()
        if reason is not None:
            self._cool = 0
            self._hot += 1
            if self._hot >= self.breach_ticks and self.rung < len(RUNGS) - 1:
                self._shed(reason)
        else:
            self._hot = 0
            self._cool += 1
            if self._cool >= self.recover_ticks and self.rung > 0:
                self._recover()
        return self.rung

    def force_shed(self, reason: str) -> None:
        """Immediate escalation (classified decode fault): the engine skips
        the breach accumulation — a replica loss is not a trend."""
        if self.rung < len(RUNGS) - 1:
            self._shed(reason)

    def _shed(self, reason: str) -> None:
        self.rung += 1
        self._hot = 0
        self._cool = 0
        self.sheds += 1
        self.last_reason = reason
        from mlsl_tpu.core import stats  # lazy: stats imports obs

        stats.record_serve_shed(
            ("batch", "precision", "admission")[self.rung - 1],
            f"-> {RUNGS[self.rung]} ({reason})",
        )
        log_warning("serve SLA shed -> %s (%s)", RUNGS[self.rung], reason)

    def _recover(self) -> None:
        self.rung -= 1
        self._cool = 0
        self.recoveries += 1
        from mlsl_tpu.core import stats

        stats.record_serve_shed("recovery", f"-> {RUNGS[self.rung]}")
        log_warning("serve SLA recovery -> %s", RUNGS[self.rung])

    # -- the levers --------------------------------------------------------

    @property
    def batch_limit(self) -> int:
        """Continuous-batching slot ceiling at the current rung."""
        return self.max_batch if self.rung < 1 else max(1, self.max_batch // 2)

    @property
    def precision_shed(self) -> bool:
        return self.rung >= 2

    @property
    def admission_open(self) -> bool:
        return self.rung < 3

    def status(self) -> dict:
        """JSON-serializable ladder status (rides /healthz via
        supervisor.status)."""
        p99 = self.p99_tpot_ms()
        return {
            "state": RUNGS[self.rung],
            "rung": self.rung,
            "batch_limit": self.batch_limit,
            "queue": self._queue,
            "queue_depth": self.queue_depth,
            "p99_tpot_ms": round(p99, 3) if p99 is not None else None,
            "sheds": self.sheds,
            "recoveries": self.recoveries,
            "reason": self.last_reason,
        }


# -- module registry (supervisor.status() / tests) ----------------------------

_active: Optional[SLAGovernor] = None


def _set_active(g: Optional[SLAGovernor]) -> None:
    global _active
    _active = g


def get_active() -> Optional[SLAGovernor]:
    return _active


def reset() -> None:
    """Drop the active governor (tests)."""
    _set_active(None)


def status() -> dict:
    """Module-level summary for supervisor.status() ({"state": "off"} when
    no engine is live — the straggler/control vocabulary)."""
    if _active is None:
        return {"state": "off"}
    return _active.status()
