"""Paged KV cache: the feed cache's admission model, generalized to pages.

The feed cache (data/cache.py) is admission-capped with no eviction because
epoch replay touches every entry exactly once. Serving breaks that
assumption: sequences arrive and retire continuously, hold wildly different
context lengths, and a single long sequence must not wedge the pool. So the
KV side keeps the same :class:`~mlsl_tpu.data.cache.AdmissionBudget`
admit-or-reject contract underneath, and adds what serving needs on top:

- **fixed-size HBM pages** — the pool is ``(n_blocks, num_pages+1, page,
  heads, head_dim)`` per K and V, owned by the engine as donated device
  arrays; this class is the host-side allocator (free-list + page tables)
  and never touches device memory itself. Page granularity kills the
  fragmentation that per-sequence max-length slabs would cause: a
  16-token-context sequence holds 1 page, not seq_len/page of them.
- **per-sequence page tables** — ``table_padded()`` hands the engine a
  fixed-width int32 gather index (padded with page 0) so the compiled
  decode program has a static shape regardless of how many pages a
  sequence actually holds.
- **page 0 is reserved garbage** — never allocated, never counted against
  the budget. Padded prefill scatter-writes and inactive batch slots land
  there; the decode mask guarantees it is never read into attention.
- **eviction** — ``release(evict=True)`` is the preemption path: the engine
  evicts the youngest active sequence when a decode step cannot extend,
  re-queues it for a resume-prefill, and the freed pages go back on the
  free-list AND the budget.

The int8 variant (``quant=True``, rides ops/quant_kernels semantics via
``models.transformer.kv_block_quant``) stores 1 byte/element plus one f32
scale per (token, head): the page-bytes math below is the single source of
truth for how many pages a given ``MLSL_SERVE_KV_CACHE_MB`` buys.
"""

from __future__ import annotations

from typing import Dict, List

from mlsl_tpu.data.cache import AdmissionBudget
from mlsl_tpu.log import MLSLError, mlsl_assert
from mlsl_tpu.obs import tracer as obs_trace


class PagedKVCache:
    """Host-side page allocator for the serving engine's KV pools.

    ``cfg`` is the model's TransformerConfig (page bytes depend on
    n_blocks/n_heads/head_dim); ``page_elems`` tokens per page
    (MLSL_SERVE_KV_PAGE_ELEMS); ``budget_mb`` the HBM budget
    (MLSL_SERVE_KV_CACHE_MB); ``max_len`` the context ceiling (defaults to
    cfg.seq_len and must stay there for the bit-exactness contract — see
    models/transformer.py decode section)."""

    def __init__(self, cfg, *, page_elems: int, budget_mb: float,
                 max_len: int = 0, quant: bool = False):
        self.page_elems = int(page_elems)
        self.quant = bool(quant)
        self.ctx_len = int(max_len) if max_len else int(cfg.seq_len)
        mlsl_assert(
            self.ctx_len % self.page_elems == 0,
            f"context length {self.ctx_len} must be a multiple of "
            f"MLSL_SERVE_KV_PAGE_ELEMS={self.page_elems} (the compiled "
            "decode program gathers whole pages)",
        )
        self.max_pages_per_seq = self.ctx_len // self.page_elems
        # bytes for ONE page across all layers, K and V: int8 stores
        # 1 byte/elem plus a f32 scale per (token, head); f32 stores 4.
        elem = 1 if self.quant else 4
        scale = 4 if self.quant else 0
        self.page_bytes = (
            cfg.n_blocks * 2 * self.page_elems * cfg.n_heads
            * (cfg.head_dim * elem + scale)
        )
        self.budget = AdmissionBudget(int(budget_mb * (1 << 20)))
        self.num_pages = self.budget.budget_bytes // self.page_bytes
        if self.num_pages < self.max_pages_per_seq:
            raise MLSLError(
                f"MLSL_SERVE_KV_CACHE_MB={budget_mb} buys {self.num_pages} "
                f"pages of {self.page_bytes} B but one full-context sequence "
                f"needs {self.max_pages_per_seq}; raise the budget or lower "
                "seq_len/MLSL_SERVE_KV_PAGE_ELEMS"
            )
        # page ids 1..num_pages; popped from the tail so allocation order is
        # 1, 2, 3, ... (stable ids make the churn tests readable). Page 0 is
        # the reserved garbage page and never appears here.
        self._free: List[int] = list(range(self.num_pages, 0, -1))
        self._tables: Dict[int, List[int]] = {}

    # -- helpers -----------------------------------------------------------

    def pages_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_elems)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def __len__(self) -> int:
        return len(self._tables)

    # -- allocation --------------------------------------------------------

    def admit(self, seq_id: int, n_tokens: int) -> bool:
        """Allocate pages for a sequence entering the batch with
        ``n_tokens`` of context. False = rejected (free-list or budget —
        both count as a kv reject; the engine leaves the request queued)."""
        from mlsl_tpu.core import stats

        mlsl_assert(seq_id not in self._tables,
                    f"seq {seq_id} already admitted")
        need = self.pages_for(n_tokens)
        if need > len(self._free) or not self.budget.admit(
                need * self.page_bytes):
            stats.record_serve("kv_rejects")
            return False
        self._tables[seq_id] = [self._free.pop() for _ in range(need)]
        stats.record_serve("kv_pages_alloc", need)
        return True

    def extend(self, seq_id: int, n_tokens: int) -> bool:
        """Grow a sequence's table to cover ``n_tokens`` total context.
        Decode calls this every step; it is a no-op until the position
        crosses a page boundary. False = pool exhausted (the engine's
        preemption/eviction path fires)."""
        from mlsl_tpu.core import stats

        table = self._tables[seq_id]
        need = self.pages_for(n_tokens) - len(table)
        if need <= 0:
            return True
        if need > len(self._free) or not self.budget.admit(
                need * self.page_bytes):
            stats.record_serve("kv_rejects")
            return False
        table.extend(self._free.pop() for _ in range(need))
        stats.record_serve("kv_pages_alloc", need)
        return True

    def release(self, seq_id: int, evict: bool = False) -> None:
        """Return a sequence's pages to the free-list and the budget.
        ``evict=True`` is the preemption path (counted separately, with a
        ``kv.evict`` instant on the obs timeline — an eviction is the
        engine trading one sequence's progress for the batch's)."""
        from mlsl_tpu.core import stats

        table = self._tables.pop(seq_id)
        self._free.extend(reversed(table))
        self.budget.release(len(table) * self.page_bytes)
        stats.record_serve("kv_pages_freed", len(table))
        if evict:
            stats.record_serve("kv_evictions")
            tr = obs_trace._tracer
            if tr is not None:
                tr.instant("kv.evict", "serve", seq=seq_id,
                           pages=len(table))

    def table_padded(self, seq_id: int) -> List[int]:
        """Fixed-width page table for the compiled decode gather: the live
        pages, padded to ``max_pages_per_seq`` with the garbage page 0."""
        table = self._tables[seq_id]
        return table + [0] * (self.max_pages_per_seq - len(table))

    # -- invariants (tests) ------------------------------------------------

    def check(self) -> None:
        """Assert the allocator's invariants; the churn tests call this
        after every operation."""
        held = [p for t in self._tables.values() for p in t]
        mlsl_assert(len(held) == len(set(held)),
                    "page allocated to two sequences")
        mlsl_assert(0 not in held, "garbage page 0 was allocated")
        mlsl_assert(not (set(held) & set(self._free)),
                    "page simultaneously held and free")
        mlsl_assert(len(held) + len(self._free) == self.num_pages,
                    "pages leaked or duplicated")
        mlsl_assert(
            all(1 <= p <= self.num_pages for p in held + self._free),
            "page id out of range")
        mlsl_assert(self.budget.bytes == len(held) * self.page_bytes,
                    "budget accounting out of sync with the free-list")
