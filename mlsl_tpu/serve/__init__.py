"""Serving engine: continuous batching, paged KV cache, SLA-protected decode.

The serving stack reuses the training stack rather than forking it:

- **engine.py** — :class:`InferenceEngine`: admission queue with
  AsyncLoader-style backpressure accounting, iteration-level (continuous)
  batching where sequences join and retire at decode-step granularity, and
  prefill/decode compiled as donation-enabled smap programs so TP decode
  allreduces route through the comm/algos selection table (pallas_rhd
  eligible in the µs class; circuit-breaker degradation to lax intact).
- **kv_cache.py** — :class:`PagedKVCache`: the feed cache's
  AdmissionBudget generalized to fixed-size HBM pages with a free-list,
  per-sequence page tables, and eviction; optional int8-blockwise pages.
- **sla.py** — :class:`SLAGovernor`: the supervisor degradation ladder
  repurposed for load. Under sustained queue growth or a p99 TPOT breach
  the engine sheds batch size, then precision, then admission (429-style
  :class:`ServeOverloadError` with a retry-after hint) — never dying.

This module stays import-light (no jax at import time): supervisor.status()
and the test teardown call :func:`reset`/:func:`status` in every test, and
the engine/kv symbols are resolved lazily on first touch.
"""

from __future__ import annotations

from mlsl_tpu.serve.sla import (  # noqa: F401  (re-exports)
    RUNGS,
    ServeOverloadError,
    SLAGovernor,
    get_active,
    reset,
    status,
)

__all__ = [
    "RUNGS",
    "ServeOverloadError",
    "SLAGovernor",
    "get_active",
    "reset",
    "status",
    "InferenceEngine",
    "Request",
    "PagedKVCache",
    "oracle_generate",
]

_LAZY = {
    "InferenceEngine": "mlsl_tpu.serve.engine",
    "Request": "mlsl_tpu.serve.engine",
    "oracle_generate": "mlsl_tpu.serve.engine",
    "PagedKVCache": "mlsl_tpu.serve.kv_cache",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
