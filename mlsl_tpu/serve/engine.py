"""Continuous-batching inference engine on the training stack.

One :class:`InferenceEngine` owns a 1 x tp slice of the mesh (dp = sp = 1 —
serving replicates across engines, not inside one), the sharded parameter
tree (the HybridTrainer device_put idiom), the paged KV pools as donated
device arrays, and three compiled smap programs:

- **prefill** — one padded sequence -> next-token logits + per-layer K/V.
  Padded to the full context length so there is exactly one compiled shape.
- **write** — scatter the prefill K/V into the paged pools through the
  sequence's page table (donation-enabled: the pools update in place in
  HBM). The int8 variant quantizes in-graph via ``kv_block_quant``.
- **decode** — one iteration-level step over the whole slot array
  (``models.transformer.decode_local``): every in-flight sequence advances
  one token per call, sequences join and retire between calls. Built per
  compute dtype so the SLA governor's precision shed (bf16) is just a
  different entry in the program cache — KV at rest stays f32/int8 either
  way, which is why recovery is numerically clean.

Scheduling runs entirely on the caller's thread (``step()``/``run()``):
device dispatch from a worker thread is exactly what lint rule A202
exists to prevent, and serving does not need it — ``submit()`` is the only
cross-thread entry point and only touches the queue under a lock.

Fault story (chaos sites ``serve.admit`` / ``serve.decode``): admission
faults fail the one request closed; decode faults go through
``supervisor.classify`` — TRANSIENT retries with jittered backoff, FATAL
propagates, anything else force-sheds the SLA ladder and skips the step.
A chaos ``hang`` is not an exception at all — the step simply takes its
duration, the TPOT window breaches, and the governor sheds: degraded, not
down. KV pool donation stays safe under retry because every failure
injection point precedes the dispatch that consumes the pools.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mlsl_tpu import chaos, supervisor
from mlsl_tpu.analysis import witness
from mlsl_tpu.comm.collectives import smap
from mlsl_tpu.comm.mesh import MODEL_AXIS
from mlsl_tpu.core import stats
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.models import transformer as tfm
from mlsl_tpu.obs import metrics, tracer as obs_trace
from mlsl_tpu.obs import straggler as obs_straggler
from mlsl_tpu.serve import kv_cache as kvc, sla

#: consecutive failed decode steps before the in-flight batch is failed
#: closed (the engine itself survives and keeps admitting)
_DECODE_FAIL_CAP = 8


@dataclass
class Request:
    """One generation request. ``submit()`` returns it immediately;
    ``result()`` blocks until the scheduler retires it."""

    prompt: np.ndarray
    max_new_tokens: int
    id: int = -1
    route: str = "default"
    eos_token: Optional[int] = None
    state: str = "queued"          # queued | active | done | failed
    tokens: List[int] = field(default_factory=list)
    error: Optional[BaseException] = None
    t_submit: float = 0.0
    ttft_ms: Optional[float] = None
    _done: threading.Event = field(default_factory=threading.Event, repr=False)
    _resume: Optional[np.ndarray] = field(default=None, repr=False)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Generated tokens (blocking). Raises the recorded error for a
        failed request."""
        mlsl_assert(self._done.wait(timeout), "request %d still in flight",
                    self.id)
        if self.state == "failed" and self.error is not None:
            raise self.error
        return list(self.tokens)


@dataclass
class _Seq:
    """Scheduler-internal in-flight sequence state."""

    req: Request
    seq_id: int
    slot: int
    position: int       # next KV write index == current context length
    last_token: int
    admitted_at: int    # admission counter: eviction preempts the youngest
    finished: bool = False


class InferenceEngine:
    """Continuous batching + paged KV + SLA ladder over one model slice."""

    def __init__(self, env, cfg, tp: int = 1, params=None, seed: int = 0,
                 devices=None, config=None, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 tpot_p99_ms: float = 0.0):
        self.env = env
        self.cfg = cfg
        self.tp = int(tp)
        self.config = config if config is not None else env.config
        mlsl_assert(cfg.n_heads % self.tp == 0, "heads %d %% tp %d",
                    cfg.n_heads, self.tp)
        self.dist = env.create_distribution(1, self.tp, devices=devices)
        self.mesh = self.dist.topology.mesh
        self.comm = (self.dist.model_group, self.config) \
            if self.tp > 1 else None

        self.specs = tfm.param_specs(cfg)
        if params is None:
            params = tfm.init_params(jax.random.PRNGKey(seed), cfg)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            params, self.specs, is_leaf=lambda x: isinstance(x, P),
        )

        self.quant = bool(self.config.serve_kv_quant)
        self.cache = kvc.PagedKVCache(
            cfg,
            page_elems=self.config.serve_kv_page_elems,
            budget_mb=self.config.serve_kv_cache_mb,
            max_len=cfg.seq_len,
            quant=self.quant,
        )
        # the bit-exactness pin: gathered decode context extent == prefill
        # pad length (kv_cache asserts seq_len % page_elems == 0)
        self.ctx_len = self.cache.ctx_len
        self.max_batch = int(max_batch if max_batch is not None
                             else self.config.serve_max_batch)
        self.governor = sla.SLAGovernor(
            max_batch=self.max_batch,
            queue_depth=int(queue_depth if queue_depth is not None
                            else self.config.serve_queue_depth),
            tpot_p99_ms=tpot_p99_ms,
        )
        sla._set_active(self.governor)

        # KV pools: page 0 is the reserved garbage page (kv_cache.py), so
        # the page axis is num_pages + 1. Heads shard over 'model'.
        npg, page = self.cache.num_pages + 1, self.cache.page_elems
        pool_shape = (cfg.n_blocks, npg, page, cfg.n_heads, cfg.head_dim)
        self._pool_spec = P(None, None, None, MODEL_AXIS, None)
        self._scale_spec = P(None, None, None, MODEL_AXIS)
        kv_dt = jnp.int8 if self.quant else jnp.float32
        self.kpool = jax.device_put(
            jnp.zeros(pool_shape, kv_dt),
            NamedSharding(self.mesh, self._pool_spec))
        self.vpool = jax.device_put(
            jnp.zeros(pool_shape, kv_dt),
            NamedSharding(self.mesh, self._pool_spec))
        if self.quant:
            sshape = pool_shape[:-1]
            self.kscale = jax.device_put(
                jnp.ones(sshape, jnp.float32),
                NamedSharding(self.mesh, self._scale_spec))
            self.vscale = jax.device_put(
                jnp.ones(sshape, jnp.float32),
                NamedSharding(self.mesh, self._scale_spec))

        self._build_programs()

        self._lock = witness.named_lock("serve.engine")
        self._pending: Deque[Request] = collections.deque()
        self._active: Dict[int, _Seq] = {}
        self._next_req_id = 0
        self._next_seq_id = 0
        self._admit_counter = 0
        self._decode_fails = 0
        self._t_start: Optional[float] = None
        self._tokens_total = 0

    # -- compiled programs -------------------------------------------------

    def _build_programs(self) -> None:
        cfg, tp, comm = self.cfg, self.tp, self.comm
        kv_spec = P(None, None, MODEL_AXIS, None)

        def prefill_body(params, tokens, length):
            return tfm.prefill_local(params, tokens, length, cfg, tp,
                                     comm=comm)

        self._prefill = jax.jit(smap(
            prefill_body, self.mesh,
            in_specs=(self.specs, P(), P()),
            out_specs=(P(), kv_spec, kv_spec),
            check=False,
        ))

        page = self.cache.page_elems

        if self.quant:
            def write_body(kpool, vpool, kscale, vscale, k, v, page_ids):
                m = page_ids.shape[0]
                kq, ksc = tfm.kv_block_quant(k)
                vq, vsc = tfm.kv_block_quant(v)
                shp = (cfg.n_blocks, m, page) + kq.shape[-2:]
                kpool = kpool.at[:, page_ids].set(kq.reshape(shp))
                vpool = vpool.at[:, page_ids].set(vq.reshape(shp))
                sshp = shp[:-1]
                kscale = kscale.at[:, page_ids].set(ksc.reshape(sshp))
                vscale = vscale.at[:, page_ids].set(vsc.reshape(sshp))
                return kpool, vpool, kscale, vscale

            self._write = jax.jit(smap(
                write_body, self.mesh,
                in_specs=(self._pool_spec, self._pool_spec,
                          self._scale_spec, self._scale_spec,
                          kv_spec, kv_spec, P()),
                out_specs=(self._pool_spec, self._pool_spec,
                           self._scale_spec, self._scale_spec),
                check=False,
            ), donate_argnums=(0, 1, 2, 3))
        else:
            def write_body(kpool, vpool, k, v, page_ids):
                m = page_ids.shape[0]
                shp = (cfg.n_blocks, m, page) + k.shape[-2:]
                kpool = kpool.at[:, page_ids].set(k.reshape(shp))
                vpool = vpool.at[:, page_ids].set(v.reshape(shp))
                return kpool, vpool

            self._write = jax.jit(smap(
                write_body, self.mesh,
                in_specs=(self._pool_spec, self._pool_spec,
                          kv_spec, kv_spec, P()),
                out_specs=(self._pool_spec, self._pool_spec),
                check=False,
            ), donate_argnums=(0, 1))

        self._decode_cache: Dict[str, object] = {}

    def _decode_prog(self, dtype: str):
        prog = self._decode_cache.get(dtype)
        if prog is not None:
            return prog
        cfg, tp, comm = self.cfg, self.tp, self.comm

        if self.quant:
            def decode_body(params, tokens, positions, pt,
                            kpool, vpool, kscale, vscale):
                return tfm.decode_local(
                    params, tokens, positions, pt, kpool, vpool, cfg, tp,
                    comm=comm, dtype=dtype, kscale=kscale, vscale=vscale)

            in_specs = (self.specs, P(), P(), P(), self._pool_spec,
                        self._pool_spec, self._scale_spec, self._scale_spec)
            out_specs = (P(), self._pool_spec, self._pool_spec,
                         self._scale_spec, self._scale_spec)
            donate = (4, 5, 6, 7)
        else:
            def decode_body(params, tokens, positions, pt, kpool, vpool):
                return tfm.decode_local(
                    params, tokens, positions, pt, kpool, vpool, cfg, tp,
                    comm=comm, dtype=dtype)

            in_specs = (self.specs, P(), P(), P(),
                        self._pool_spec, self._pool_spec)
            out_specs = (P(), self._pool_spec, self._pool_spec)
            donate = (4, 5)

        prog = jax.jit(
            smap(decode_body, self.mesh, in_specs=in_specs,
                 out_specs=out_specs, check=False),
            donate_argnums=donate,
        )
        self._decode_cache[dtype] = prog
        return prog

    # -- admission (any thread) --------------------------------------------

    def submit(self, prompt, max_new_tokens: int, route: str = "default",
               eos_token: Optional[int] = None) -> Request:
        """Queue a request. Raises :class:`~mlsl_tpu.serve.sla.
        ServeOverloadError` (429-style, with ``retry_after_s``) when the
        ladder closed admission or the queue is full — the two rejection
        reasons are distinct on the metrics plane."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        mlsl_assert(prompt.size >= 1, "empty prompt")
        mlsl_assert(max_new_tokens >= 1, "max_new_tokens must be >= 1")
        mlsl_assert(
            prompt.size + max_new_tokens <= self.ctx_len,
            "prompt %d + max_new %d exceeds the context length %d",
            prompt.size, max_new_tokens, self.ctx_len,
        )
        with self._lock:
            reason = None
            if not self.governor.admission_open:
                reason = "shed_admission"
            elif len(self._pending) >= self.governor.queue_depth:
                reason = "queue_full"
            if reason is not None:
                stats.record_serve("rejected")
                m = metrics._registry
                if m is not None:
                    m.inc("mlsl_serve_rejected_total", 1.0,
                          route=route, reason=reason)
                raise sla.ServeOverloadError(
                    f"admission rejected ({reason}); retry after "
                    f"{self.governor.retry_after_s}s",
                    retry_after_s=self.governor.retry_after_s,
                )
            req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                          id=self._next_req_id, route=route,
                          eos_token=eos_token, t_submit=time.monotonic())
            self._next_req_id += 1
            self._pending.append(req)
            stats.record_serve("admitted")
            return req

    # -- scheduler (caller thread only) ------------------------------------

    def step(self) -> int:
        """One scheduler iteration: observe/tick the SLA ladder, admit up
        to the rung's batch limit, advance every in-flight sequence one
        token, retire the finished. Returns the number of in-flight
        sequences after the step."""
        if self._t_start is None:
            self._t_start = time.monotonic()
        sentinel = obs_straggler.get_active()
        straggler = (sentinel is not None
                     and sentinel.shed_candidate() is not None)
        with self._lock:
            qlen = len(self._pending)
        self.governor.observe(queue_len=qlen, straggler=straggler)
        self.governor.tick()

        self._admit()
        if self._active:
            self._decode_step()
        self._retire()
        self._gauges()
        return len(self._active)

    def run(self, deadline_s: Optional[float] = None,
            until_idle: bool = True, max_steps: Optional[int] = None,
            idle_sleep_s: float = 0.001) -> None:
        """Drive ``step()`` until idle (default), a deadline, or a step
        budget — whichever comes first."""
        t0 = time.monotonic()
        steps = 0
        while True:
            n = self.step()
            steps += 1
            with self._lock:
                idle = n == 0 and not self._pending
            if until_idle and idle:
                return
            if deadline_s is not None \
                    and time.monotonic() - t0 >= deadline_s:
                return
            if max_steps is not None and steps >= max_steps:
                return
            if n == 0:
                time.sleep(idle_sleep_s)

    # -- internals ---------------------------------------------------------

    def _admit(self) -> None:
        while len(self._active) < self.governor.batch_limit:
            with self._lock:
                if not self._pending:
                    return
                req = self._pending.popleft()
            seq_id = self._next_seq_id
            self._next_seq_id += 1
            admitted_kv = False
            try:
                chaos.inject("serve.admit", req_id=req.id)
                prefix = req._resume if req._resume is not None \
                    else req.prompt
                if not self.cache.admit(seq_id, prefix.size + 1):
                    # pool backpressure: leave it queued, stop admitting
                    with self._lock:
                        self._pending.appendleft(req)
                    return
                admitted_kv = True
                self._prefill_seq(req, seq_id, prefix)
            except Exception as e:  # fail this one request closed
                if admitted_kv:
                    self.cache.release(seq_id)
                self._active.pop(seq_id, None)
                req.state = "failed"
                req.error = e
                req._done.set()
                stats.record_serve("failed")
                m = metrics._registry
                if m is not None:
                    m.inc("mlsl_serve_requests_total", 1.0,
                          route=req.route, outcome="failed")

    def _prefill_seq(self, req: Request, seq_id: int,
                     prefix: np.ndarray) -> None:
        n = int(prefix.size)
        tokens = np.zeros((self.ctx_len,), np.int32)
        tokens[:n] = prefix
        tr = obs_trace._tracer
        t0 = tr.now() if tr is not None else 0
        logits, k, v = self._prefill(
            self.params, jnp.asarray(tokens), jnp.int32(n))
        page_ids = jnp.asarray(
            np.asarray(self.cache.table_padded(seq_id), np.int32))
        if self.quant:
            self.kpool, self.vpool, self.kscale, self.vscale = self._write(
                self.kpool, self.vpool, self.kscale, self.vscale,
                k, v, page_ids)
        else:
            self.kpool, self.vpool = self._write(
                self.kpool, self.vpool, k, v, page_ids)
        tok = int(np.argmax(np.asarray(logits)))
        if tr is not None:
            tr.complete("serve.prefill", "serve", t0, seq=seq_id, tokens=n)
        stats.record_serve("prefills")
        stats.record_serve("tokens_out")
        self._tokens_total += 1
        resumed = req._resume is not None
        if not resumed:
            req.ttft_ms = (time.monotonic() - req.t_submit) * 1e3
            m = metrics._registry
            if m is not None:
                m.observe("mlsl_serve_ttft_ms", req.ttft_ms,
                          route=req.route)
        req._resume = None
        req.state = "active"
        req.tokens.append(tok)
        seq = _Seq(req=req, seq_id=seq_id, slot=-1, position=n,
                   last_token=tok, admitted_at=self._admit_counter)
        self._admit_counter += 1
        if (req.eos_token is not None and tok == req.eos_token) \
                or len(req.tokens) >= req.max_new_tokens \
                or seq.position >= self.ctx_len:
            seq.finished = True
        self._active[seq_id] = seq

    def _evict_youngest(self) -> None:
        """Preempt the youngest in-flight sequence: free its pages, stash
        prompt + everything generated as the resume prefix, put it back at
        the FRONT of the queue (it has seniority over never-started work)."""
        seq = max(self._active.values(), key=lambda s: s.admitted_at)
        self._active.pop(seq.seq_id)
        self.cache.release(seq.seq_id, evict=True)
        req = seq.req
        req._resume = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        req.state = "queued"
        with self._lock:
            self._pending.appendleft(req)

    def _ensure_capacity(self) -> None:
        """Every live sequence needs pages covering its next KV write; a
        pool that cannot extend evicts the youngest until it can. The
        budget invariant (num_pages >= max_pages_per_seq) guarantees this
        terminates with at least one sequence still running."""
        for seq in sorted(self._active.values(), key=lambda s: s.admitted_at):
            while seq.seq_id in self._active \
                    and not self.cache.extend(seq.seq_id, seq.position + 1):
                self._evict_youngest()

    def _decode_step(self) -> None:
        self._ensure_capacity()
        if not self._active:
            return
        live = sorted(self._active.values(), key=lambda s: s.admitted_at)
        b, mpp = self.max_batch, self.cache.max_pages_per_seq
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        pt = np.zeros((b, mpp), np.int32)     # inactive slots: garbage page
        for i, seq in enumerate(live):
            seq.slot = i
            tokens[i] = seq.last_token
            positions[i] = seq.position
            pt[i] = self.cache.table_padded(seq.seq_id)
        dtype = "bfloat16" if self.governor.precision_shed else None
        prog = self._decode_prog(dtype or self.cfg.dtype)
        attempt = 0
        tr = obs_trace._tracer
        while True:
            t_step = time.monotonic()
            t0 = tr.now() if tr is not None else 0
            try:
                # a chaos 'hang' here is a slow step, not an exception: it
                # lands inside the timed window, breaches the TPOT SLO, and
                # the governor sheds — the degraded-not-down path
                chaos.inject("serve.decode", inflight=len(live))
                out = prog(self.params, jnp.asarray(tokens),
                           jnp.asarray(positions), jnp.asarray(pt),
                           self.kpool, self.vpool,
                           *((self.kscale, self.vscale)
                             if self.quant else ()))
                break
            except Exception as e:
                cls = supervisor.classify(e)
                if cls is supervisor.ErrorClass.TRANSIENT \
                        and attempt < self.config.comm_retries:
                    stats.record_serve("retries")
                    time.sleep(supervisor.jittered_backoff(
                        self.config.comm_retry_backoff_s, attempt))
                    attempt += 1
                    continue
                self._decode_fault(e)
                return
        if self.quant:
            logits, self.kpool, self.vpool, self.kscale, self.vscale = out
        else:
            logits, self.kpool, self.vpool = out
        logits = np.asarray(logits)           # blocks until the step is done
        step_ms = (time.monotonic() - t_step) * 1e3
        if tr is not None:
            tr.complete("serve.decode", "serve", t0, inflight=len(live))
        self._decode_fails = 0
        if attempt > 0:
            stats.record_serve("recoveries")
        self.governor.observe(tpot_ms=step_ms)
        m = metrics._registry
        if m is not None:
            m.observe("mlsl_serve_tpot_ms", step_ms)
        stats.record_serve("decode_steps")
        stats.record_serve("tokens_out", len(live))
        self._tokens_total += len(live)
        for seq in live:
            tok = int(np.argmax(logits[seq.slot]))
            seq.position += 1
            seq.last_token = tok
            seq.req.tokens.append(tok)
            if (seq.req.eos_token is not None
                    and tok == seq.req.eos_token) \
                    or len(seq.req.tokens) >= seq.req.max_new_tokens \
                    or seq.position >= self.ctx_len:
                seq.finished = True

    def _decode_fault(self, e: BaseException) -> None:
        cls = supervisor.classify(e)
        if cls is supervisor.ErrorClass.FATAL:
            raise e
        self._decode_fails += 1
        self.governor.force_shed(f"decode fault: {cls.name}")
        if self._decode_fails < _DECODE_FAIL_CAP:
            return
        # the batch is wedged: fail it closed, keep the engine alive
        for seq in list(self._active.values()):
            self._active.pop(seq.seq_id)
            self.cache.release(seq.seq_id)
            seq.req.state = "failed"
            seq.req.error = e
            seq.req._done.set()
            stats.record_serve("failed")
        self._decode_fails = 0

    def _retire(self) -> None:
        m = metrics._registry
        for seq in [s for s in self._active.values() if s.finished]:
            self._active.pop(seq.seq_id)
            self.cache.release(seq.seq_id)
            seq.req.state = "done"
            seq.req._done.set()
            stats.record_serve("completed")
            if m is not None:
                m.inc("mlsl_serve_requests_total", 1.0,
                      route=seq.req.route, outcome="done")

    def _gauges(self) -> None:
        m = metrics._registry
        if m is None:
            return
        with self._lock:
            qlen = len(self._pending)
        m.set("mlsl_serve_queue_depth", float(qlen))
        m.set("mlsl_serve_inflight", float(len(self._active)))
        m.set("mlsl_serve_kv_free_pages", float(self.cache.free_pages))
        m.set("mlsl_serve_batch_limit", float(self.governor.batch_limit))
        if self._t_start is not None:
            dt = time.monotonic() - self._t_start
            if dt > 0:
                m.set("mlsl_serve_tokens_per_s", self._tokens_total / dt)

    def close(self) -> None:
        """Detach the SLA governor from the module registry (tests and
        multi-engine processes)."""
        if sla.get_active() is self.governor:
            sla._set_active(None)


def oracle_generate(engine: InferenceEngine, prompt, max_new_tokens: int,
                    eos_token: Optional[int] = None) -> List[int]:
    """The UNPAGED oracle: greedy decode by re-running the engine's own
    compiled prefill over the growing full sequence each step — no KV
    cache, no pages. The bit-exactness tests pin the paged engine against
    this (identical program structure, identical reduction extents)."""
    seq = list(np.asarray(prompt, np.int32).reshape(-1))
    out: List[int] = []
    for _ in range(max_new_tokens):
        tokens = np.zeros((engine.ctx_len,), np.int32)
        tokens[:len(seq)] = seq
        logits, _, _ = engine._prefill(
            engine.params, jnp.asarray(tokens), jnp.int32(len(seq)))
        tok = int(np.argmax(np.asarray(logits)))
        out.append(tok)
        seq.append(tok)
        if eos_token is not None and tok == eos_token:
            break
        if len(seq) >= engine.ctx_len:
            break
    return out
