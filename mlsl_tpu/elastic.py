"""Elastic mesh: survive preemption and device loss by rescaling, not restarting.

Every prior rung of the resilience ladder (retry, breaker degrade,
checkpoint restart — mlsl_tpu.supervisor / resilience) answers a fault by
re-running the SAME plan on the SAME world. A preempted host breaks that
premise: the capacity is *gone*, and checkpoint-restart into the original
world size stalls the whole job until identical capacity returns. This
module turns the ladder's last rung from a restart budget into a *capacity
budget* (ROADMAP #4): on a ``DEVICE_LOSS`` fault the coordinator

1. **shrinks** — re-derives the mesh among survivors
   (``comm/mesh.survivor_devices``: flat worlds shed exactly the lost
   devices; tiered worlds drop the whole affected slice, whose ICI domain
   is broken), re-initializes the Environment over the survivor set, and
   carries the training state across LIVE: params/replicated optimizer
   state re-broadcast, ZeRO-1 owned-shard optimizer state re-sharded via
   the engine's all-gather drain collective (``optim.gather_owned_full``)
   and re-partitioned onto the survivor world's ownership chunks
   (``optim.place_owned_vector``) — **no checkpoint restore**. The reshard
   plan is statically verified first (``analysis/plan.verify_reshard``,
   MLSL-A140/A141: every shard element moved exactly once) — a covering bug
   here would silently corrupt the state it exists to carry, so the check
   is unconditional, not gated by ``MLSL_VERIFY``.
2. **continues** at the very step the loss interrupted: the failed step
   never applied its update, so replaying it on the survivor mesh keeps the
   loss trajectory continuous (no replay window, no recovery counted).
3. **grows** when capacity returns (``announce_return()`` or the
   ``MLSL_ELASTIC_GROW_AFTER`` timer): the full world is re-derived, state
   is re-sharded back, and the returning replica is **admitted only after a
   sentinel fingerprint audit** — the PR 7 cross-replica bit-fingerprint
   (``sentinel.Sentinel.audit_now``) is exactly the admission check. A
   failing audit re-syncs the rejoiner from a survivor copy and re-audits
   (``MLSL_ELASTIC_ADMIT_RETRIES``); persistent divergence abandons the
   grow.

Grace-window contract: the shrink drain collective runs on the
*pre-reshard* mesh — survivors plus the departing rank — which is exactly
the TPU-pod preemption model (SIGTERM arrives, the host is reachable for a
drain window; the PR 1 ``PreemptionGuard`` detects it). A truly instant
loss whose shard is unreachable surfaces as a failed drain and falls back
to the restart rung, where verified checkpoints still win (docs/DESIGN.md
"Elastic mesh": when restart still wins).

Scope: ``DataParallelTrainer`` state layouts (replicated params/optax state
+ per-layer ZeRO-1 owned-shard state). Trainers without those attributes
fail the harvest loudly and take the restart rung.

Knobs (docs/TUNING.md §18, validated in Config.validate): ``MLSL_ELASTIC``,
``MLSL_CAPACITY_BUDGET``, ``MLSL_ELASTIC_GROW_AFTER``,
``MLSL_ELASTIC_ADMIT_RETRIES``.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import numpy as np
import jax

from mlsl_tpu.analysis import witness
from mlsl_tpu.log import (
    MLSLDeviceLossError,
    MLSLError,
    log_info,
    log_warning,
    mlsl_assert,
)

# -- process-wide active world -------------------------------------------------
#
# Like the chaos registry and the breakers, the active world survives
# Environment teardown/rebuild cycles BY DESIGN: FaultTolerantLoop's
# make_trainer factories call ``Environment.init()`` with no device list,
# and init consults this registry so a post-shrink rebuild lands on the
# survivor world instead of silently re-adopting the full one.

_active: Optional[Tuple] = None

#: serializes registry *writes* (coordinator thread vs. a main-thread
#: reset/rebuild); reads stay lock-free — a torn read is impossible for a
#: single tuple-or-None rebind, and active_devices() is on the
#: Environment.init path
_registry_lock = witness.named_lock("elastic.registry")

#: last reshard/admission verdict, for supervisor.status()['elastic'] and
#: post-mortems (which world-size transition, which verdict, at which step)
_last_reshard: Optional[dict] = None


def active_devices() -> Optional[Tuple]:
    """The survivor world a rebuilt Environment must adopt, or None (full
    world). Consulted by ``Environment.init`` when no explicit device list
    is passed."""
    return _active


def _set_active(devices: Optional[Sequence]) -> None:
    global _active
    with _registry_lock:
        _active = tuple(devices) if devices is not None else None


def reset() -> None:
    """Clear the active-world registry, verdict record, and capacity-budget
    snapshot (tests) — a stale budget from a dead coordinator would
    otherwise leak into ``status()``."""
    global _last_reshard
    _set_active(None)
    _last_reshard = None
    _budget_info[0] = None
    _budget_info[1] = 0


def armed(config=None) -> bool:
    """Is the elastic coordinator armed (MLSL_ELASTIC / Config.elastic)?"""
    if config is not None:
        return bool(getattr(config, "elastic", False))
    from mlsl_tpu.config import _env_bool

    return _env_bool("MLSL_ELASTIC", False)


def status() -> dict:
    """Elastic-mesh summary for ``supervisor.status()`` dashboards: active
    vs full world size, capacity budget remaining, the event counters, and
    the last reshard verdict. ``state`` mirrors the breaker vocabulary:
    'full' (no capacity shed), 'shrunk' (running on survivors)."""
    from mlsl_tpu.core import stats as stats_mod

    try:
        world = len(jax.devices())
    except Exception:  # pragma: no cover - backend init failure
        world = None
    active = len(_active) if _active is not None else world
    out = {
        "state": "shrunk" if _active is not None else "full",
        "world_size": world,
        "active_size": active,
        **{k: v for k, v in stats_mod.ELASTIC_COUNTERS.items()},
    }
    out["capacity_budget"] = _budget_info[0]
    out["budget_remaining"] = (
        max(0, _budget_info[0] - _budget_info[1])
        if _budget_info[0] is not None else None
    )
    if _last_reshard is not None:
        out["last_reshard"] = dict(_last_reshard)
    return out


#: (budget, shed_total) of the live coordinator — module-level so status()
#: reports it after the loop (and its coordinator handle) are gone
_budget_info: list = [None, 0]


class ElasticCoordinator:
    """Drives shrink -> continue -> grow -> continue for a
    FaultTolerantLoop (which routes DEVICE_LOSS faults here and polls
    :meth:`maybe_grow` between steps).

    Factory contract: ``make_trainer`` must size its Distribution from the
    ACTIVE world (``env.get_process_count()`` after ``Environment.init()``),
    not a constant — the whole point of a reshard is that the world size
    changed underneath it.
    """

    def __init__(self, capacity_budget: Optional[int] = None,
                 grow_after: Optional[int] = None,
                 admit_retries: Optional[int] = None):
        # knobs through Config's parser/defaults (the restart-budget
        # pattern: one definition, the init-time MLSLError contract). An
        # exported env var wins; otherwise the LIVE config — a programmatic
        # Config(capacity_budget=3) must bind exactly like the env knob —
        # and the class default when no Environment is up
        from mlsl_tpu.config import Config, _env_int
        from mlsl_tpu.core.environment import Environment

        cfg = (Environment._instance.config
               if Environment.is_initialized() else None)
        if cfg is None:
            cfg = Config
        try:
            if capacity_budget is None:
                capacity_budget = _env_int(
                    "MLSL_CAPACITY_BUDGET", cfg.capacity_budget
                )
            if grow_after is None:
                grow_after = _env_int(
                    "MLSL_ELASTIC_GROW_AFTER", cfg.elastic_grow_after
                )
            if admit_retries is None:
                admit_retries = _env_int(
                    "MLSL_ELASTIC_ADMIT_RETRIES", cfg.elastic_admit_retries
                )
        except ValueError as e:
            raise MLSLError(f"invalid MLSL_ELASTIC_*/MLSL_CAPACITY_BUDGET "
                            f"value: {e}") from e
        mlsl_assert(
            capacity_budget >= 0 and grow_after >= 0 and admit_retries >= 0,
            "elastic knobs must be >= 0 (budget=%d, grow_after=%d, "
            "admit_retries=%d)", capacity_budget, grow_after, admit_retries,
        )
        self.world: Tuple = tuple(jax.devices())
        # 0 = auto: half the world — losing a majority leaves too little
        # compute for the shrunk job to be worth keeping alive vs restarting
        # on fresh capacity
        self.capacity_budget = capacity_budget or max(1, len(self.world) // 2)
        self.grow_after = grow_after
        self.admit_retries = admit_retries
        self.shed_total = 0
        self._return_due: Optional[int] = None
        self._pending_return = False
        _budget_info[0] = self.capacity_budget
        _budget_info[1] = 0

    # -- capacity-return signalling ---------------------------------------

    def announce_return(self) -> None:
        """Capacity is back (production: the replacement host announced
        itself). The next :meth:`maybe_grow` poll performs the grow."""
        self._pending_return = True

    # -- shrink ------------------------------------------------------------

    def shrink(self, trainer, make_trainer, error=None, step: int = 0):
        """Answer one DEVICE_LOSS fault: drain state off the pre-loss mesh,
        rebuild over survivors, carry the state live. Returns the survivor
        trainer; raises (MLSLError) when the capacity budget refuses the
        loss or the drain/rebuild fails — the caller escalates to the
        restart rung."""
        from mlsl_tpu.comm import mesh as mesh_mod
        from mlsl_tpu.core import stats as stats_mod
        from mlsl_tpu.core.environment import Environment
        from mlsl_tpu.obs import tracer as obs

        active = _active if _active is not None else self.world
        lost = tuple(getattr(error, "devices", ()) or ())
        if not lost:
            # loss observed but not attributed (a failed collective knows a
            # peer vanished, not which): default shed policy — the highest-
            # ranked active device; survivor_devices expands it to the whole
            # tier on a tiered world
            lost = (active[-1],)
        survivors = mesh_mod.survivor_devices(lost, active)
        shed = len(active) - len(survivors)
        detail = (f"step={step} world {len(active)}->{len(survivors)} "
                  f"shed={shed} budget={self.shed_total + shed}"
                  f"/{self.capacity_budget}")
        stats_mod.record_elastic("device_losses", detail)
        if shed == 0:
            # a loss attributing only devices already outside the active
            # world (a stale preemption notice re-surfacing) would make this
            # a no-op reshard — and the loop's reshard branch spends neither
            # budget nor retry attempts, so honoring it spins forever
            stats_mod.record_elastic("restart_fallbacks", detail)
            raise MLSLError(
                f"device loss at step {step} names no active device — "
                "nothing to shed; escalating to the restart rung instead "
                "of spinning no-op reshards"
            )
        if self.shed_total + shed > self.capacity_budget:
            stats_mod.record_elastic("restart_fallbacks", detail)
            raise MLSLError(
                f"capacity budget exhausted: shedding {shed} more device(s) "
                f"would exceed {self.capacity_budget} "
                f"(already shed {self.shed_total}) — escalating to the "
                "restart rung"
            )
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        log_warning(
            "elastic shrink at step %d: %d device(s) lost (%s), "
            "re-deriving the mesh over %d survivors",
            step, shed, type(error).__name__ if error else "announced",
            len(survivors),
        )
        try:
            harvest = self._harvest(trainer)
        except Exception:
            # a failed drain IS an escalation to the restart rung — count
            # it, or the ELASTIC totals line answers "did capacity churn
            # cost a restart" wrongly
            stats_mod.record_elastic("restart_fallbacks", detail)
            raise
        prev_active = _active
        _set_active(survivors)
        try:
            try:
                Environment.get_env().finalize()
            except Exception as e:
                log_warning("environment teardown during shrink failed "
                            "(continuing with rebuild): %s: %s",
                            type(e).__name__, e)
            new_trainer = make_trainer()
            self._check_factory_world(new_trainer, len(survivors))
            self._write_state(new_trainer, harvest, step=step, kind="shrink")
        except Exception:
            # unwind the registry so a restart-rung recovery rebuilds the
            # PRE-shrink world, where the checkpoint shapes still match —
            # and count the escalation (same contract as the drain path)
            _set_active(prev_active)
            stats_mod.record_elastic("restart_fallbacks", detail)
            raise
        self.shed_total += shed
        _budget_info[1] = self.shed_total
        if self.grow_after > 0:
            self._return_due = step + self.grow_after
        global _last_reshard
        _last_reshard = {
            "kind": "shrink", "step": step, "verdict": "pass",
            "d_old": harvest["d_old"], "d_new": new_trainer.data_size,
        }
        stats_mod.record_elastic("shrinks", detail)
        if tr is not None:
            tr.complete("elastic.shrink", "elastic", t0, step=step,
                        world_before=len(active), world_after=len(survivors),
                        shed=shed, budget_remaining=(
                            self.capacity_budget - self.shed_total))
        log_info("elastic shrink complete: continuing at step %d on %d "
                 "devices (capacity budget %d/%d spent)",
                 step, len(survivors), self.shed_total, self.capacity_budget)
        return new_trainer

    # -- straggler shed (obs/straggler.py -> here) --------------------------

    def shed(self, trainer, make_trainer, replica: int, step: int = 0):
        """Hand a confirmed straggler replica to the shrink machinery: the
        telemetry plane's measurement loop closed into action
        (docs/DESIGN.md "Telemetry plane"). ``replica`` carries the
        numbering the straggler sentinel's observations use — the feeding
        process's ``jax.process_index()`` (models/train.py). On a
        multi-process world the shed therefore names ALL of that process's
        active devices (the slow HOST is the straggler unit — shedding one
        of its chips would leave the stall in place); on a single-process
        world every device shares process index 0, so the id falls back to
        active-world device order (the proof-mesh/test numbering, where
        data replica r IS device r). The shed is a synthetic DEVICE_LOSS
        through :meth:`shrink`, so the capacity budget, the A140/A141
        coverage plans, and the grow/re-admission audit all apply untouched
        — a shed straggler that recovers rejoins through the same
        fingerprint audit as a returning preempted host.

        Raises (MLSLError) when the replica does not name active capacity
        or the shrink refuses (budget) — the caller (FaultTolerantLoop)
        logs, counts ``shed_fallbacks``, and keeps training on the full
        world: shedding a straggler is an optimization and must never cost
        availability."""
        from mlsl_tpu.core import stats as stats_mod

        active = _active if _active is not None else self.world
        procs = {getattr(d, "process_index", 0) for d in active}
        if len(procs) > 1:
            devs = tuple(d for d in active
                         if getattr(d, "process_index", 0) == int(replica))
        elif 0 <= int(replica) < len(active):
            devs = (active[int(replica)],)
        else:
            devs = ()
        if not devs:
            stats_mod.record_straggler(
                "shed_fallbacks",
                f"replica={replica} names no active capacity "
                f"(world={len(active)}, processes={len(procs)})",
            )
            raise MLSLError(
                f"straggler shed: replica {replica} does not name active "
                f"capacity (active world {len(active)} devices across "
                f"{len(procs)} process(es))"
            )
        err = MLSLDeviceLossError(
            f"straggler shed: replica {replica} confirmed slow at step "
            f"{step}", devices=devs,
        )
        try:
            new_trainer = self.shrink(trainer, make_trainer, error=err,
                                      step=step)
        except Exception:
            stats_mod.record_straggler(
                "shed_fallbacks",
                f"replica={replica} step={step} shrink refused",
            )
            raise
        stats_mod.record_straggler(
            "sheds",
            f"replica={replica} step={step} "
            f"devices={','.join(str(d) for d in devs)}",
        )
        return new_trainer

    # -- grow --------------------------------------------------------------

    def maybe_grow(self, trainer, make_trainer, step: int):
        """Between-steps poll: grow back to the full world when shrunk and
        capacity has returned (announce_return or the grow_after timer).

        In a pod, grow re-admission is a LEADER decision (mlsl_tpu.control):
        the coordinator's single-controller assumptions — active-world
        registry, capacity budget, admission audit — are epoch-fenced
        behind the elected leader, so a deposed leader polling here cannot
        originate a stale re-admission. Defense in depth with the loop-side
        gate in resilience.py: both must agree this process decides."""
        if _active is None:
            return trainer
        from mlsl_tpu import control as control_mod

        plane = control_mod.get_active()
        if plane is not None and not plane.may_decide():
            return trainer
        due = self._pending_return or (
            self._return_due is not None and step >= self._return_due
        )
        if not due:
            return trainer
        return self.grow(trainer, make_trainer, step)

    def grow(self, trainer, make_trainer, step: int):
        """Re-admit returned capacity: rebuild the full world, re-shard the
        state back, and admit the rejoining replica only after its
        fingerprint audit passes."""
        from mlsl_tpu import chaos
        from mlsl_tpu.core import stats as stats_mod
        from mlsl_tpu.core.environment import Environment
        from mlsl_tpu.obs import tracer as obs

        mlsl_assert(_active is not None, "grow() without a preceding shrink")
        active = _active
        returning = tuple(d for d in self.world if d not in set(active))
        # consult the chaos site BEFORE any teardown: an 'error' plan here
        # models capacity lost again during re-admission (nothing is torn
        # down yet, the shrunk trainer stays live); a 'silent' plan corrupts
        # the rejoining copy below — the admission audit's quarry
        silent_plan = None
        if chaos._plans:
            p = chaos.inject("device.lost", phase="admit", step=step)
            if p is not None and p.kind == "silent":
                silent_plan = p
        tr = obs._tracer
        t0 = tr.now() if tr is not None else 0
        log_info("elastic grow at step %d: re-admitting %d device(s) "
                 "(world %d -> %d)", step, len(returning), len(active),
                 len(self.world))
        harvest = self._harvest(trainer)
        _set_active(None)
        try:
            try:
                Environment.get_env().finalize()
            except Exception as e:
                log_warning("environment teardown during grow failed "
                            "(continuing with rebuild): %s: %s",
                            type(e).__name__, e)
            new_trainer = make_trainer()
            self._check_factory_world(new_trainer, len(self.world))
            self._write_state(new_trainer, harvest, step=step, kind="grow")
            if silent_plan is not None:
                from mlsl_tpu import sentinel as sentinel_mod

                new_trainer.params = sentinel_mod.corrupt_replica(
                    new_trainer.params, returning, silent_plan
                )
            try:
                self._admit(new_trainer, harvest, step)
            except MLSLError as admission_err:
                return self._abandon_grow(
                    make_trainer, harvest, active, step, t0, tr,
                    admission_err,
                )
        except Exception:
            # structural failure (teardown/factory/state-carry): stay
            # shrunk and DISARM the return flags — a still-armed flag would
            # make the next between-steps poll re-attempt the identical
            # grow, and every failure then burns a checkpoint-restart
            # recovery (the spiral the abandon contract forbids)
            _set_active(active)
            self._pending_return = False
            self._return_due = None
            raise
        self._return_due = None
        self._pending_return = False
        global _last_reshard
        _last_reshard = {
            "kind": "grow", "step": step, "verdict": "pass",
            "d_old": harvest["d_old"], "d_new": new_trainer.data_size,
        }
        detail = (f"step={step} world {len(active)}->{len(self.world)} "
                  f"readmitted={len(returning)}")
        stats_mod.record_elastic("grows", detail)
        if tr is not None:
            tr.complete("elastic.grow", "elastic", t0, step=step,
                        world_before=len(active),
                        world_after=len(self.world),
                        readmitted=len(returning))
        log_info("elastic grow complete: step %d continues on the full "
                 "%d-device world", step, len(self.world))
        return new_trainer

    def _abandon_grow(self, make_trainer, harvest, active, step: int,
                      t0, tr, err):
        """Persistent admission divergence: ABANDON the grow (the DESIGN.md
        contract — stay shrunk, zero restores). The full world is torn back
        down, the survivor world rebuilt from the harvest, and the return
        flags disarm: retrying a persistently divergent replica every poll
        would burn a checkpoint-restart recovery per step, so only a fresh
        ``announce_return()`` re-attempts."""
        from mlsl_tpu.core import stats as stats_mod
        from mlsl_tpu.core.environment import Environment

        global _last_reshard
        log_warning(
            "elastic grow ABANDONED at step %d (%s) — staying on the "
            "%d-device survivor world; announce_return() re-attempts",
            step, err, len(active),
        )
        try:
            Environment.get_env().finalize()
        except Exception as e:
            log_warning("full-world teardown during grow abandon failed "
                        "(continuing with rebuild): %s: %s",
                        type(e).__name__, e)
        _set_active(active)
        shrunk = make_trainer()
        self._check_factory_world(shrunk, len(active))
        self._write_state(shrunk, harvest, step=step, kind="abandon")
        self._pending_return = False
        self._return_due = None
        _last_reshard = {
            "kind": "grow", "step": step, "verdict": "abandoned",
            "d_old": harvest["d_old"], "d_new": shrunk.data_size,
        }
        stats_mod.record_elastic(
            "grow_abandons", f"step={step} world stays {len(active)}"
        )
        if tr is not None:
            tr.complete("elastic.grow", "elastic", t0, step=step,
                        world_before=len(active), world_after=len(active),
                        verdict="abandoned")
        return shrunk

    # -- admission audit ----------------------------------------------------

    def _admit(self, trainer, harvest, step: int) -> None:
        """The sentinel fingerprint audit as the admission check: the grown
        trainer's replicated state must fingerprint identically on EVERY
        device — the rejoining copies included — before the replica is
        admitted. A mismatch re-syncs the state from the survivors' copy
        (the harvest) and re-audits; persistent divergence raises."""
        from mlsl_tpu import sentinel as sentinel_mod
        from mlsl_tpu.core import stats as stats_mod
        from mlsl_tpu.obs import tracer as obs

        sent = getattr(trainer, "sentinel", None)
        if sent is None:
            # audit machinery only; none of the gate/cadence knobs arm
            sent = sentinel_mod.Sentinel(trainer.mesh)
        tr = obs._tracer
        for attempt in range(self.admit_retries + 1):
            t0 = tr.now() if tr is not None else 0
            res = sent.audit_now(trainer, step)
            if tr is not None:
                tr.complete("elastic.admit", "elastic", t0, step=step,
                            attempt=attempt, equal=res.equal,
                            digest=res.digest[:16])
            if res.equal:
                stats_mod.record_elastic(
                    "admits",
                    f"step={step} attempt={attempt} "
                    f"digest={res.digest[:16]}",
                )
                return
            stats_mod.record_elastic(
                "admit_rejects",
                f"step={step} attempt={attempt} digest={res.digest[:16]}",
            )
            log_warning(
                "elastic admission audit REJECTED the rejoining replica at "
                "step %d (attempt %d): fingerprints diverge (digest %s)",
                step, attempt, res.digest[:16],
            )
            if attempt < self.admit_retries:
                stats_mod.record_elastic("resyncs", f"step={step}")
                self._resync(trainer, harvest)
        raise MLSLError(
            f"elastic admission failed at step {step}: the rejoining "
            f"replica's fingerprint still diverges after "
            f"{self.admit_retries} resync attempt(s)"
        )

    def _resync(self, trainer, harvest) -> None:
        """Re-broadcast the survivors' verified state over the whole grown
        mesh (the harvest is the survivor copy by construction), replacing
        whatever the rejected replica held."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(trainer.mesh, P())
        trainer.params = jax.device_put(harvest["params"], sharding)
        if harvest.get("opt_state") is not None:
            trainer._opt_state = jax.device_put(
                harvest["opt_state"], sharding
            )
        # ZeRO-1 owned shards are per-rank-unique (never on the rejoiner's
        # replicated axis); they were freshly placed by _write_state and do
        # not participate in the replica comparison, so no re-broadcast

    # -- state harvest / carry ---------------------------------------------

    def _harvest(self, trainer) -> dict:
        """Read the training state off the CURRENT (pre-reshard) mesh: a
        host copy of the replicated trees, and the full flat vector of every
        ZeRO-1 owned-shard leaf via the all-gather drain collective on the
        pre-reshard mesh (the grace-window read)."""
        from mlsl_tpu import optim

        for attr in ("params", "layers", "layer_counts", "padded_counts",
                     "data_size", "dist"):
            if not hasattr(trainer, attr):
                raise MLSLError(
                    f"elastic reshard supports DataParallelTrainer-shaped "
                    f"state; {type(trainer).__name__} lacks {attr!r} — "
                    "falling back to the restart rung"
                )
        out = {
            "params": jax.device_get(trainer.params),
            "opt_state": None,
            "du": None,
            "d_old": trainer.data_size,
            "layer_counts": dict(trainer.layer_counts),
            "padded_counts": dict(trainer.padded_counts),
        }
        if getattr(trainer, "_opt_state", None) is not None:
            out["opt_state"] = jax.device_get(trainer._opt_state)
        du = getattr(trainer, "_du_opt_state", None)
        if du:
            # quiesce the dispatcher first: the loss interrupted a step, and
            # gathering concurrently with its abandoned in-flight programs
            # is the XLA:CPU rendezvous hazard (KNOWN_FAILURES.md / A102)
            try:
                trainer.env.dispatcher.shutdown()
            except Exception as e:
                log_warning("dispatcher quiesce before reshard drain "
                            "failed: %s: %s", type(e).__name__, e)
            topo = trainer.dist.topology
            gathered = {}
            for name in sorted(du):
                gathered[name] = jax.tree.map(
                    lambda leaf: optim.gather_owned_full(topo, leaf), du[name]
                )
            out["du"] = gathered
        return out

    def _write_state(self, trainer, harvest, step: int, kind: str) -> None:
        """Place the harvested state onto the rebuilt trainer's mesh:
        replicated trees re-broadcast, ZeRO-1 state re-partitioned under a
        verified reshard plan."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(trainer.mesh, P())
        trainer.params = jax.device_put(harvest["params"], sharding)
        if harvest["opt_state"] is not None:
            mlsl_assert(
                getattr(trainer, "_opt_state", None) is not None,
                "reshard factory mismatch: the %s trainer carries no "
                "replicated optimizer state to receive the harvest", kind,
            )
            trainer._opt_state = jax.device_put(
                harvest["opt_state"], sharding
            )
        if harvest["du"]:
            self._reshard_du(trainer, harvest, step, kind)

    def _reshard_du(self, trainer, harvest, step: int, kind: str) -> None:
        """Re-partition the gathered ZeRO-1 state onto the new world's
        ownership chunks, under an A140/A141-verified plan. Leaves whose
        per-rank payload spans the owned shard reshard; leaves replicated by
        construction (scalar counts, adafactor's factored vectors) carry one
        copy; anything else is unreshardable and raises."""
        from mlsl_tpu import optim
        from mlsl_tpu.analysis import diagnostics
        from mlsl_tpu.analysis import plan as plan_mod
        from mlsl_tpu.core import stats as stats_mod

        d_old = harvest["d_old"]
        d_new = trainer.data_size
        plan = build_reshard_plan(
            harvest["layer_counts"], harvest["padded_counts"],
            trainer.padded_counts, d_old, d_new,
        )
        t0 = time.perf_counter()
        rep = plan_mod.verify_reshard(plan)
        diagnostics.record(rep, time.perf_counter() - t0)
        if rep.errors:
            # unconditional (not MLSL_VERIFY_SEVERITY-gated): executing an
            # uncovered plan silently corrupts optimizer state
            raise MLSLError(
                f"elastic {kind} reshard plan rejected: "
                + "; ".join(d.format() for d in rep.errors)
            )
        topo = trainer.dist.topology
        moved = 0
        for name in sorted(harvest["du"]):
            mlsl_assert(
                name in trainer._du_opt_state,
                "reshard factory mismatch: layer %r has harvested ZeRO-1 "
                "state but the rebuilt trainer does not register it", name,
            )
            count = harvest["layer_counts"][name]
            padded_old = harvest["padded_counts"][name]
            padded_new = trainer.padded_counts[name]
            old_leaves, old_def = jax.tree.flatten(harvest["du"][name])
            new_leaves, new_def = jax.tree.flatten(trainer._du_opt_state[name])
            mlsl_assert(
                old_def == new_def,
                "reshard factory mismatch: layer %r optimizer state trees "
                "differ between worlds (%s vs %s)", name, old_def, new_def,
            )
            roles = _du_leaf_roles(trainer, harvest["du"][name])
            if roles is not None and len(roles) != len(old_leaves):
                roles = None
            placed = []
            for i, (old_vec, new_leaf) in enumerate(
                    zip(old_leaves, new_leaves)):
                full = np.asarray(old_vec).reshape(-1)
                k_old = full.shape[0] // d_old
                k_new = int(np.prod(
                    new_leaf.shape[len(topo.grid_shape):]
                ))
                owned_fit = (k_old * d_old == padded_old
                             and k_new * d_new == padded_new)
                repl_fit = k_old == k_new
                if owned_fit and repl_fit and roles is not None:
                    # shapes alone cannot tell a replicated scalar from a
                    # k==1 owned shard (a layer with count <= world ranks on
                    # both sides); the state STRUCTURE can — see
                    # _du_leaf_roles
                    owned_fit, repl_fit = roles[i], not roles[i]
                if owned_fit:
                    placed.append(optim.place_owned_vector(
                        topo, full, count, padded_new, d_new
                    ))
                elif repl_fit:
                    # replicated-by-construction leaf (scalar step count,
                    # adafactor v_row/v_col): every old rank held the same
                    # value — carry rank 0's copy to every new rank
                    rep0 = full[:k_old]
                    grid = topo.grid_shape
                    placed.append(topo.shard_buffer(np.ascontiguousarray(
                        np.broadcast_to(rep0, grid + rep0.shape)
                    )))
                else:
                    raise MLSLError(
                        f"unreshardable optimizer leaf in layer {name!r}: "
                        f"per-rank payload {k_old} is neither the owned "
                        f"shard ({padded_old // d_old}) nor "
                        f"world-invariant ({k_new} expected) — falling "
                        "back to the restart rung"
                    )
                moved += 1
            trainer._du_opt_state[name] = jax.tree.unflatten(new_def, placed)
        stats_mod.record_elastic("reshard_buffers", n=moved)

    # -- shared checks ------------------------------------------------------

    @staticmethod
    def _check_factory_world(trainer, expected: int) -> None:
        size = int(trainer.dist.topology.world_size)
        mlsl_assert(
            size == expected,
            "make_trainer built a %d-device Distribution but the active "
            "world is %d: elastic factories must size from "
            "env.get_process_count(), not a constant", size, expected,
        )


def _du_leaf_roles(trainer, state) -> Optional[list]:
    """Per flattened leaf of one layer's ZeRO-1 state: True when the leaf's
    size scales with the owned-shard count (elementwise moments — reshard),
    False when it is world-invariant (scalar step counts, adafactor's
    factored v_row/v_col — carry one copy). Classified by STRUCTURE, never
    by shape arithmetic: a (1,)-payload scalar is indistinguishable by
    shape from a k==1 owned shard when a layer holds fewer elements than
    the world has ranks, and misrouting the scalar through the owned path
    mixes rank copies with zero padding.

    The adafactor dict schema (``optim.init_adafactor_state``) is
    classified by key; a generic optax state is probed by initializing the
    transform at two different counts and seeing which leaf sizes move.
    Returns None when neither applies (the caller falls back to shape
    arithmetic, which resolves every unambiguous layer)."""
    if isinstance(state, dict) and {"count", "v_row", "v_col"} <= set(state):
        # jax flattens dicts in sorted-key order; 'v'/'m' ride the owned
        # shard, the rest are replicated by construction
        return [k in ("v", "m") for k in sorted(state)]
    init = getattr(getattr(trainer, "optimizer", None), "init", None)
    if init is None:
        return None
    try:
        a = jax.tree.leaves(init(np.zeros((2,), np.float32)))
        b = jax.tree.leaves(init(np.zeros((3,), np.float32)))
    except Exception:
        return None
    if len(a) != len(b):
        return None
    return [np.size(x) != np.size(y) for x, y in zip(a, b)]


def build_reshard_plan(layer_counts: dict, padded_old: dict,
                       padded_new: dict, d_old: int, d_new: int) -> dict:
    """The statically verifiable description of one ZeRO-1 reshard: per
    layer, the old ownership-chunk intervals that tile the real elements
    ``[0, count)`` (sources) and the new ownership chunks (targets).
    ``analysis/plan.verify_reshard`` proves coverage before execution."""
    layers = []
    for name in sorted(layer_counts):
        count = int(layer_counts[name])
        po, pn = int(padded_old[name]), int(padded_new[name])
        k_old, k_new = po // max(d_old, 1), pn // max(d_new, 1)
        sources = []
        for r in range(d_old):
            lo, hi = r * k_old, min((r + 1) * k_old, count)
            if hi > lo:
                sources.append((r, lo, hi))
        targets = [(r, r * k_new, (r + 1) * k_new) for r in range(d_new)]
        layers.append({
            "name": name, "count": count,
            "padded_old": po, "k_old": k_old,
            "padded_new": pn, "k_new": k_new,
            "sources": sources, "targets": targets,
        })
    return {"d_old": int(d_old), "d_new": int(d_new), "layers": layers}
