"""Multi-process pod simulation worker: ``python -m mlsl_tpu.control.sim``.

One OS process = one pod member. N of these (spawned by tests/test_pod.py
or scripts/run_pod_sim.sh) form a real cross-process control plane over
localhost TCP — real sockets, real SIGKILL, real miss-budget detection —
while the "training" is a deterministic host loop. That split is
deliberate: jax.distributed/gloo cannot survive member death (the whole
collective world aborts when a rank dies), so the CPU pod sim runs WITHOUT
a cross-process device world — the control plane is the only cross-process
fabric, which is exactly the layer under test. The full training-loop
integration (FaultTolerantLoop + elastic shrink on a real device mesh)
is exercised in-process by tests/test_control.py; what only a real pod
can add is resharding a device world that truly spans hosts
(DESIGN.md "Pod control plane": what still needs a real pod).

Configuration comes from the standard env knobs (MLSL_CONTROL_PORT/
MLSL_CONTROL_WORLD/MLSL_CONTROL_RANK, MLSL_HEARTBEAT_*,
MLSL_PREEMPTION_FILE, MLSL_ELASTIC) through the normal
``Environment.init()`` arming path. Machine-readable stdout protocol::

    READY rank=0 world=3 pid=1234 http=40123
    STEP rank=0 step=7 loss=0.740741
    EVENT rank=0 kind=commit epoch=1 dead=2 survivors=0,1 leader=0
    DRAIN rank=0 mode=shrink target=1 epoch=2
    DRAINED rank=1 mode=shrink step=12
    EXIT rank=0 step=40 epoch=2 alive=0,1
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _fmt_event(rank: int, ev: dict) -> str:
    dead = ",".join(map(str, ev.get("dead", []))) or "-"
    surv = ",".join(map(str, ev.get("survivors", [])))
    return (
        f"EVENT rank={rank} kind={ev['kind']} epoch={ev['epoch']} "
        f"dead={dead} survivors={surv} leader={ev.get('leader')}"
        + (f" mode={ev['mode']} target={ev['rank']}"
           if ev["kind"] == "drain" else "")
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--steps", type=int, default=200,
                    help="host training steps to run")
    ap.add_argument("--step-s", type=float, default=0.02,
                    help="wall time per simulated step")
    ap.add_argument("--dir", default="",
                    help="rendezvous dir: rank<r>.{pid,port,state} files")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import mlsl_tpu as mlsl
    from mlsl_tpu import control, supervisor
    from mlsl_tpu.obs import serve
    from mlsl_tpu.resilience import PreemptionGuard

    env = mlsl.Environment.get_env().init()
    plane = control.get_active()
    if plane is None:
        print("ERROR control plane not armed (set MLSL_CONTROL_PORT/"
              "MLSL_CONTROL_WORLD/MLSL_CONTROL_RANK)", flush=True)
        return 2
    rank = plane.rank
    # scrape surface on an ephemeral port (collision-free N-per-host); the
    # bound port lands in the rendezvous dir for the harness to read back
    srv = serve.get_server() or serve.start_server(port=0)
    if args.dir:
        os.makedirs(args.dir, exist_ok=True)
        with open(os.path.join(args.dir, f"rank{rank}.pid"), "w") as f:
            f.write(str(os.getpid()))
        with open(os.path.join(args.dir, f"rank{rank}.port"), "w") as f:
            f.write(str(srv.port if srv is not None else 0))
    print(f"READY rank={rank} world={plane.world} pid={os.getpid()} "
          f"http={srv.port if srv is not None else 0}", flush=True)

    loss = 1.0
    step = 0
    events_seen = 0
    rc = 0
    with PreemptionGuard() as guard:
        while step < args.steps:
            time.sleep(args.step_s)  # the "training step" (host-only)
            loss = 1.0 / (1.0 + 0.05 * step)
            plane.push_status(supervisor.status(), step=step,
                              step_ms=args.step_s * 1e3)
            print(f"STEP rank={rank} step={step} loss={loss:.6f}",
                  flush=True)
            # committed membership losses: label-only device map here, so
            # take_loss records the pod transition without a local error
            fault = plane.take_loss()
            if fault is not None:  # pragma: no cover - label-only maps
                print(f"FAULT rank={rank} {fault}", flush=True)
            evs = list(plane.events)
            for ev in evs[events_seen:]:
                print(_fmt_event(rank, ev), flush=True)
            events_seen = len(evs)

            drain = plane.take_drain()
            if guard.triggered and drain is None:
                # the coordinated path: SIGTERM becomes a structured notice;
                # the pod answers with ONE decision (or we time out and
                # drain locally — a partitioned leader must not hang us)
                drain = plane.coordinate_preemption("sigterm")
            if drain is not None:
                print(f"DRAIN rank={rank} mode={drain['mode']} "
                      f"target={drain['rank']} epoch={drain['epoch']}",
                      flush=True)
                if drain["mode"] == "save" or drain["rank"] == rank:
                    # our part of the pod drain: a verified save of the
                    # host state (the sim's checkpoint analog)
                    if args.dir:
                        with open(os.path.join(
                                args.dir, f"rank{rank}.state"), "w") as f:
                            f.write(f"step={step} loss={loss:.6f}\n")
                    plane.record_drain_executed(step, drain["mode"])
                    print(f"DRAINED rank={rank} mode={drain['mode']} "
                          f"step={step}", flush=True)
                    break
                # a shrink aimed at another rank: the survivors' business —
                # keep stepping on the shrunken pod
            elif guard.triggered:
                print(f"DRAINED rank={rank} mode=local step={step}",
                      flush=True)
                break
            step += 1
    st = plane.status()
    print(f"EXIT rank={rank} step={step} epoch={st['epoch']} "
          f"alive={','.join(map(str, st['alive']))}", flush=True)
    plane.stop()
    env.finalize()
    return rc


if __name__ == "__main__":
    sys.exit(main())
