"""Pod-scale control plane: membership, heartbeat, election, drain.

Module registry (the chaos._plans / elastic._active pattern): the active
:class:`~mlsl_tpu.control.plane.ControlPlane` is process-wide state that
survives Environment rebuilds BY DESIGN — pod membership outlives any one
mesh generation, exactly like breaker history and the elastic world. Tests
reset it via the conftest autouse fixture.

Arming: `Environment.init()` calls :func:`ensure_started` after the obs
plane comes up; it is a no-op unless the config names a control world
(``MLSL_CONTROL_ADDRS`` or ``MLSL_CONTROL_PORT`` + ``MLSL_CONTROL_WORLD``
with ``MLSL_CONTROL_RANK``). Single-process runs — every existing test and
bench — therefore never start a socket.
"""

from __future__ import annotations

from typing import Optional

from mlsl_tpu.control.plane import ControlPlane  # noqa: F401 (public)
from mlsl_tpu.log import log_warning

_active: Optional[ControlPlane] = None


def get_active() -> Optional[ControlPlane]:
    """The process's control plane, or None when not armed."""
    return _active


def set_active(plane: Optional[ControlPlane]) -> Optional[ControlPlane]:
    """Install a plane built by the caller (tests, the pod sim). Stops any
    previous one: a process is exactly one pod member."""
    global _active
    if _active is not None and _active is not plane:
        _active.stop()
    _active = plane
    return plane


def reset() -> None:
    """Stop and forget the active plane (test isolation)."""
    set_active(None)


def armed(config=None) -> bool:
    """Whether this process participates in a pod control plane."""
    return _active is not None


def status() -> dict:
    """JSON-serializable summary for supervisor.status() / healthz."""
    if _active is None:
        return {"state": "off"}
    return _active.status()


def replica_id(default: int) -> int:
    """The replica identity for straggler reports and per-host attribution:
    the pod rank when the control plane is armed (pod-wide peer medians need
    pod-unique ids), else the caller's default (jax.process_index())."""
    return _active.rank if _active is not None else int(default)


def _addr_table(config):
    """rank -> (host, port) from config. ``control_addrs`` is the explicit
    form ("h0:p0,h1:p1,..."); ``control_port`` + ``control_world`` is the
    localhost shorthand the CPU pod sim uses (consecutive ports from the
    base)."""
    if config.control_addrs:
        addrs = []
        for ent in config.control_addrs.split(","):
            host, _, port = ent.strip().rpartition(":")
            addrs.append((host or "127.0.0.1", int(port)))
        return addrs
    if config.control_port and config.control_world:
        return [("127.0.0.1", config.control_port + r)
                for r in range(config.control_world)]
    return []


def ensure_started(config) -> Optional[ControlPlane]:
    """Arm the control plane from config if it names a pod; idempotent.
    The device map here is label-only (``rank<r>``): a committed loss from
    this path records the pod transition without synthesizing a local
    device error — Environment.init() cannot know which jax devices a
    REMOTE rank owned. Embedders/tests that do know pass a real device_map
    to :class:`ControlPlane` directly and install it via
    :func:`set_active`."""
    global _active
    if _active is not None:
        return _active
    addrs = _addr_table(config)
    if not addrs:
        return None
    rank = config.control_rank
    if rank < 0 or rank >= len(addrs):
        log_warning(
            "control plane not armed: MLSL_CONTROL_RANK=%d outside the "
            "%d-member address table", rank, len(addrs),
        )
        return None
    plane = ControlPlane(
        rank=rank,
        addrs=addrs,
        device_map={r: (f"rank{r}",) for r in range(len(addrs))},
        interval_s=config.heartbeat_interval_s,
        misses=config.heartbeat_misses,
        grace_s=config.heartbeat_grace_s,
        notice_file=config.preemption_file or None,
    )
    plane.start()
    _active = plane
    return plane
