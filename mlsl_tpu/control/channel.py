"""Control-channel transport: stdlib TCP, one JSON frame per connection.

The control plane deliberately rides its OWN socket fabric, not the JAX
collective fabric: a wedged ICI collective (the A202/XLA:CPU rendezvous
hazard, a hung gloo pair, a preempted neighbor) must never take down
liveness detection, because liveness detection is precisely what recovers
from it. The reference draws the same line — its endpoint servers own a
dedicated progress channel beside the data path (SURVEY §3).

Wire format: one newline-terminated JSON object per connection, sender
closes after writing. No acks — TCP either delivers the frame or raises on
the sender, and the membership layer (plane.py) is built on misses being
survivable. Frames carry HOST-READ SCALARS ONLY (rank, epoch, step counts,
status dicts already rendered to JSON-serializable values): the sending
thread never touches device state, so the A202 no-dispatch-off-thread rule
holds by construction, not by audit.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Callable, Optional, Tuple

from mlsl_tpu.log import log_debug, log_warning

#: hard cap on one frame's wire size: a status frame is a few KB; anything
#: bigger is a protocol bug, not a bigger buffer's job
MAX_FRAME_BYTES = 1 << 20

#: per-connection socket timeout: the channel is LAN/localhost control
#: traffic — a peer that cannot complete a tiny frame in this window is
#: indistinguishable from a dead one, and the heartbeat layer owns that call
CONNECT_TIMEOUT_S = 2.0


def send_frame(
    addr: Tuple[str, int],
    frame: dict,
    retries: int = 0,
    backoff_s: float = 0.2,
    timeout_s: float = CONNECT_TIMEOUT_S,
) -> None:
    """Deliver one frame to ``addr``; raises OSError when every attempt
    fails. ``retries`` follows the MLSL_DIST_INIT_RETRIES contract
    (attempts beyond the first, exponential backoff): heartbeats send with
    retries=0 — a miss is the signal — while membership commits and drain
    orders retry, because losing one is an availability event."""
    data = json.dumps(frame).encode("utf-8") + b"\n"
    if len(data) > MAX_FRAME_BYTES:
        raise ValueError(
            f"control frame exceeds {MAX_FRAME_BYTES} bytes "
            f"({len(data)}; type={frame.get('t')!r})"
        )
    last: Optional[OSError] = None
    for attempt in range(max(0, int(retries)) + 1):
        if attempt:
            time.sleep(backoff_s * (2 ** (attempt - 1)))
        try:
            with socket.create_connection(addr, timeout=timeout_s) as s:
                s.sendall(data)
            return
        except OSError as e:
            last = e
    assert last is not None
    raise last


class Listener:
    """One accept-loop daemon thread delivering parsed frames to a handler.

    The handler runs ON the listener thread and must therefore stay
    host-only (plane.py's handlers update membership dicts and feed the
    straggler sentinel's host-side windows — no device dispatch, the same
    contract as the /metrics scrape handler). A malformed or oversized
    frame is dropped with a debug log: the channel survives garbage, the
    membership layer survives silence."""

    def __init__(self, addr: Tuple[str, int],
                 handler: Callable[[dict], None]):
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(16)
        self._sock.settimeout(0.2)  # bounded accept wait -> prompt stop()
        self.addr = self._sock.getsockname()[:2]
        self.port = int(self.addr[1])
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name=f"mlsl-control-listen:{self.port}",
            daemon=True,
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us during stop()
            try:
                with conn:
                    conn.settimeout(CONNECT_TIMEOUT_S)
                    frame = self._read_frame(conn)
                if frame is not None:
                    self._handler(frame)
            except Exception as e:
                # one bad peer/frame must not kill liveness for everyone
                log_debug("control listener dropped a frame: %s: %s",
                          type(e).__name__, e)

    @staticmethod
    def _read_frame(conn: socket.socket) -> Optional[dict]:
        chunks = []
        size = 0
        while True:
            buf = conn.recv(65536)
            if not buf:
                break
            chunks.append(buf)
            size += len(buf)
            if size > MAX_FRAME_BYTES:
                log_warning("control frame over %d bytes dropped",
                            MAX_FRAME_BYTES)
                return None
            if buf.endswith(b"\n"):
                break
        if not size:
            return None
        doc = json.loads(b"".join(chunks).decode("utf-8"))
        return doc if isinstance(doc, dict) else None

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover - defensive
            pass
        self._thread.join(timeout=5)
        if self._thread.is_alive():  # pragma: no cover - defensive
            log_warning("control listener thread did not stop within 5s")
