"""Pod membership, heartbeat failure detection, election, and drain.

One :class:`ControlPlane` per process turns N single-process fault-tolerance
stacks into one pod-wide contract (ROADMAP #2):

- **Membership + heartbeat.** Every member heartbeats a tiny liveness/status
  frame to every live peer at ``MLSL_HEARTBEAT_INTERVAL_S``;
  ``MLSL_HEARTBEAT_MISSES`` consecutive missed intervals declare a peer
  locally dead. Local suspicion is NOT a reshard: survivors converge on one
  plan through a loss-epoch barrier (below), then every survivor synthesizes
  ``MLSLDeviceLossError(devices=<dead host's devices>)`` into its training
  loop, feeding the elastic shrink path (PR 14) with cross-process agreement
  on the survivor set.

- **Election + epoch fencing.** The lowest surviving rank leads. Membership
  changes are *committed* only by the member that believes it leads, after a
  barrier-with-timeout (one miss budget) that unions every survivor's
  observed losses into ONE survivor set — two hosts observing different
  losses converge on one reshard plan instead of split-brain meshes. Every
  commit carries ``(epoch, leader)``; a receiver rejects any order whose
  epoch is not strictly newer or whose leader is not its current minimum
  live rank, so a deposed leader's stale reshard order dies at the fence.
  Leader death needs no special machinery: it is one more membership event,
  and the next-lowest survivor commits it.

- **Coordinated preemption drain.** A SIGTERM (resilience.PreemptionGuard)
  or the appearance of ``MLSL_PREEMPTION_FILE`` submits a structured notice
  to the leader; the leader makes exactly ONE pod-wide drain decision —
  ``shrink`` (survivors absorb the draining host's shards, elastic armed) or
  ``save`` (pod-wide verified checkpoint) — and broadcasts it epoch-fenced,
  instead of N racing local SIGTERM handlers.

- **Pod observability.** Heartbeat frames carry each member's pushed
  supervisor-status snapshot and its recent per-step times; the leader's
  merged ``/healthz`` (obs/serve.py) reports per-host status + heartbeat
  ages, and remote step times are fed into the local straggler sentinel so
  cross-host stragglers are judged against true pod-wide peer medians.

Threading contract (the A202 rule, by construction): the heartbeat and
listener threads touch host state only — membership dicts, JSON documents
pushed from the training thread, socket IO, stats appends. Device dispatch
stays on the consumer thread; losses surface there via :meth:`take_loss`.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from mlsl_tpu import chaos
from mlsl_tpu.analysis import witness
from mlsl_tpu.control import channel
from mlsl_tpu.log import MLSLDeviceLossError, log_info, log_warning

ENV_INTERVAL = "MLSL_HEARTBEAT_INTERVAL_S"
ENV_MISSES = "MLSL_HEARTBEAT_MISSES"
ENV_GRACE = "MLSL_HEARTBEAT_GRACE_S"
ENV_NOTICE_FILE = "MLSL_PREEMPTION_FILE"

DEFAULT_INTERVAL_S = 2.0
DEFAULT_MISSES = 3
DEFAULT_GRACE_S = 30.0

#: commit/drain frames retry (losing one is an availability event);
#: heartbeats never do (a miss IS the signal)
COMMIT_SEND_RETRIES = 2


def _tracer_instant(name: str, **fields) -> None:
    from mlsl_tpu.obs import tracer as obs

    if obs._tracer is not None:
        obs._tracer.instant(name, "control", **fields)


class ControlPlane:
    """One process's endpoint in the pod control plane.

    ``rank``: this process's pod rank (0-based, dense).
    ``addrs``: rank -> (host, port) for every member, identical on all
        members (the membership bootstrap — on a real pod this comes from
        the scheduler's hostfile; the CPU sim derives it from
        ``MLSL_CONTROL_PORT`` + world size).
    ``device_map``: rank -> devices that rank contributes to the pod world.
        jax.Device entries make a committed loss locally actionable
        (:meth:`take_loss` raises the device-loss error the elastic
        coordinator reshards around); plain-string labels record the pod
        transition only (the multi-process CPU sim, where a survivor's
        local mesh never contained the dead host's devices).
    """

    def __init__(
        self,
        rank: int,
        addrs: Sequence[Tuple[str, int]],
        device_map: Optional[Dict[int, tuple]] = None,
        interval_s: Optional[float] = None,
        misses: Optional[int] = None,
        grace_s: Optional[float] = None,
        notice_file: Optional[str] = None,
    ):
        from mlsl_tpu.config import _env_float, _env_int

        if interval_s is None:
            interval_s = _env_float(ENV_INTERVAL, DEFAULT_INTERVAL_S)
        if misses is None:
            misses = _env_int(ENV_MISSES, DEFAULT_MISSES)
        if grace_s is None:
            grace_s = _env_float(ENV_GRACE, DEFAULT_GRACE_S)
        if notice_file is None:
            notice_file = os.environ.get(ENV_NOTICE_FILE, "")
        self.rank = int(rank)
        self.addrs = [tuple(a) for a in addrs]
        if not 0 <= self.rank < len(self.addrs):
            raise ValueError(
                f"control rank {rank} outside the address table "
                f"(world {len(self.addrs)})"
            )
        self.world = len(self.addrs)
        self.device_map = dict(device_map or {})
        self.interval_s = max(0.01, float(interval_s))
        self.misses = max(1, int(misses))
        self.grace_s = max(0.0, float(grace_s))
        self.notice_file = notice_file or ""

        self._lock = witness.named_lock("control.plane")
        self.epoch = 0
        self.alive = set(range(self.world))
        self._last_seen: Dict[int, float] = {}
        self._peer_status: Dict[int, dict] = {}
        self._peer_step: Dict[int, int] = {}
        self._observed_dead: set = set()
        self._suspected_at: Dict[int, float] = {}
        self._proposals: Dict[int, set] = {}
        self._barrier_deadline: Optional[float] = None
        self._barrier_extensions = 0
        self._drained: set = set()
        self._evicted = False
        self._leader_last = 0  # rank 0 leads epoch 0 by construction
        self._pending_losses: deque = deque()
        self._pending_drain: Optional[dict] = None
        self._notice_out: Optional[dict] = None
        self._decided_notices: set = set()
        self._pushed_status: Optional[dict] = None
        self._local_step: Optional[int] = None
        self._step_samples: List[float] = []
        #: committed membership/drain events, newest last (sim + tests read
        #: these; bounded so a long soak cannot grow without bound)
        self.events: deque = deque(maxlen=64)

        self._stop = threading.Event()
        self._listener: Optional[channel.Listener] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ControlPlane":
        """Bind the listener and start heartbeating. Connect-side failures
        during bootstrap are absorbed by the grace window (peers may still
        be importing jax)."""
        if self._listener is not None:
            return self
        self._listener = channel.Listener(
            self.addrs[self.rank], self._on_frame
        )
        self._started_at = time.monotonic()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name=f"mlsl-control-hb:{self.rank}",
            daemon=True,
        )
        self._hb_thread.start()
        log_info(
            "control plane up: rank %d/%d on %s:%d (interval %.3gs, "
            "miss budget %d)", self.rank, self.world,
            self.addrs[self.rank][0] or "0.0.0.0", self.listen_port,
            self.interval_s, self.misses,
        )
        return self

    @property
    def listen_port(self) -> int:
        return self._listener.port if self._listener is not None else 0

    def stop(self) -> None:
        """Graceful stop: peers keep their own miss accounting; a stopped
        member that was not drained first will be detected as dead (that is
        the correct reading of an unannounced exit)."""
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            if self._hb_thread.is_alive():  # pragma: no cover - defensive
                log_warning("control heartbeat thread did not stop within 5s")
            self._hb_thread = None
        if self._listener is not None:
            self._listener.stop()
            self._listener = None

    def kill(self) -> None:
        """Abrupt stop (tests): identical to stop() on purpose — from the
        peers' side there is no difference between a SIGKILLed process and
        one that silently stopped heartbeating."""
        self.stop()

    # -- feed from the training thread ------------------------------------

    def push_status(self, status: Optional[dict] = None,
                    step: Optional[int] = None,
                    step_ms: Optional[float] = None) -> None:
        """Publish this member's health snapshot for the next heartbeat
        frame. Called from the training thread (the loop pushes
        ``supervisor.status()`` + the step clock); the heartbeat thread only
        serializes what was pushed — host-read scalars, the A202 contract."""
        with self._lock:
            if status is not None:
                self._pushed_status = status
            if step is not None:
                self._local_step = int(step)
            if step_ms is not None:
                self._step_samples.append(float(step_ms))
                del self._step_samples[:-32]

    # -- consumed by the training thread ----------------------------------

    def take_loss(self) -> Optional[MLSLDeviceLossError]:
        """The next committed membership loss that is LOCALLY actionable,
        as the device-loss error the elastic coordinator reshards around
        (FaultTolerantLoop raises it inside its recovery try). Commits whose
        devices are not in this process's world (the multi-process sim, a
        remote host's slice) are consumed as bookkeeping — the pod epoch
        advanced, the local mesh did not change."""
        while True:
            with self._lock:
                if not self._pending_losses:
                    return None
                ev = self._pending_losses.popleft()
            devices: list = []
            for r in ev["dead"]:
                devices.extend(self.device_map.get(r, ()))
            local = tuple(d for d in devices if not isinstance(d, str))
            if local:
                return MLSLDeviceLossError(
                    f"pod control plane: rank(s) {ev['dead']} lost at epoch "
                    f"{ev['epoch']} ({ev['reason']})", devices=local,
                )

    def take_drain(self) -> Optional[dict]:
        """The pending pod drain decision (once), or None."""
        with self._lock:
            d, self._pending_drain = self._pending_drain, None
            return d

    def submit_notice(self, reason: str) -> None:
        """A preemption notice for THIS rank (SIGTERM guard, notice file,
        or the embedder). Delivery to the leader happens on the heartbeat
        thread and is retried every tick until a drain decision covers this
        rank, so a dropped/delayed notice (the ``control.notice`` chaos
        site) degrades to latency, not to a lost drain."""
        with self._lock:
            if self._notice_out is None and self.rank not in self._drained:
                # "ts" is display-only forensics (who noticed first, in
                # human time, across hosts). Liveness NEVER reads it: all
                # miss/grace accounting compares the receiver's OWN
                # time.monotonic() stamps (_on_heartbeat/_detect_misses), so
                # an NTP step on either host cannot fabricate or mask a
                # death (tests/test_pod.py::test_ntp_step_does_not_kill)
                self._notice_out = {
                    "t": "notice", "rank": self.rank, "reason": str(reason),
                    "ts": time.time(),
                }
                self._record("notices",
                             f"rank={self.rank} reason={reason}")
                _tracer_instant("control.notice", rank=self.rank,
                                reason=str(reason))

    def coordinate_preemption(self, reason: str,
                              timeout_s: Optional[float] = None
                              ) -> Optional[dict]:
        """Submit a notice and wait (bounded) for the pod's drain decision.
        Returns the decision dict, or None on timeout — the caller falls
        back to a local drain, because a partitioned leader must not turn a
        grace window into a hang."""
        if timeout_s is None:
            timeout_s = 2.0 * self.interval_s * self.misses + 1.0
        self.submit_notice(reason)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            d = self.take_drain()
            if d is not None:
                return d
            time.sleep(min(0.05, self.interval_s / 4))
        return None

    def record_drain_executed(self, step: int, mode: str) -> None:
        """The local loop finished its part of the pod drain (final save
        written / shrink handed to the survivors)."""
        self._record("drains_executed",
                     f"rank={self.rank} mode={mode} step={step}")
        _tracer_instant("control.drain_executed", rank=self.rank,
                        mode=mode, step=step)

    # -- leadership --------------------------------------------------------

    def leader(self) -> int:
        with self._lock:
            return min(self.alive) if self.alive else self.rank

    def is_leader(self) -> bool:
        return self.leader() == self.rank

    def may_decide(self) -> bool:
        """May this process make pod-level elastic decisions (grow
        re-admission, straggler shed)? The elastic coordinator's
        single-controller assumptions are re-homed behind the elected
        leader; followers apply committed epochs instead of originating
        them."""
        return not self._evicted and self.is_leader()

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """JSON-serializable local summary (supervisor.status()['control'],
        the /healthz contract)."""
        now = time.monotonic()
        with self._lock:
            ages = {
                str(r): round(now - t, 3)
                for r, t in self._last_seen.items() if r in self.alive
            }
            return {
                "state": "leader" if (
                    self.alive and min(self.alive) == self.rank
                ) else "member",
                "rank": self.rank,
                "world": self.world,
                "epoch": self.epoch,
                "leader": min(self.alive) if self.alive else None,
                "alive": sorted(self.alive),
                "dead": sorted(set(range(self.world)) - self.alive),
                "drained": sorted(self._drained),
                "evicted": self._evicted,
                "interval_s": self.interval_s,
                "misses": self.misses,
                "hb_age_s": ages,
            }

    def pod_status(self) -> dict:
        """The leader's merged view: every member's last pushed
        supervisor-status snapshot + heartbeat age (obs/serve.py merges
        this into /healthz on the leader)."""
        now = time.monotonic()
        with self._lock:
            members = {}
            for r in range(self.world):
                if r == self.rank:
                    members[str(r)] = {
                        "alive": r in self.alive, "hb_age_s": 0.0,
                        "step": self._local_step,
                        "status": self._pushed_status,
                    }
                else:
                    seen = self._last_seen.get(r)
                    members[str(r)] = {
                        "alive": r in self.alive,
                        "hb_age_s": round(now - seen, 3)
                        if seen is not None else None,
                        "step": self._peer_step.get(r),
                        "status": self._peer_status.get(r),
                    }
            return {
                "epoch": self.epoch,
                "leader": min(self.alive) if self.alive else None,
                "survivors": sorted(self.alive),
                "members": members,
            }

    # -- heartbeat thread --------------------------------------------------

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception as e:  # pragma: no cover - defensive
                log_warning("control tick failed: %s: %s",
                            type(e).__name__, e)

    def _tick(self) -> None:
        now = time.monotonic()
        self._poll_notice_file()
        self._flush_notice()
        self._send_heartbeats()
        self._detect_misses(now)
        self._maybe_commit(now)

    def _poll_notice_file(self) -> None:
        """The cluster-scheduler hook (ROADMAP #2a): a scheduler that cannot
        signal writes ``MLSL_PREEMPTION_FILE``; its appearance is a
        preemption notice for this host."""
        if self.notice_file and os.path.exists(self.notice_file):
            self.submit_notice(f"notice-file:{self.notice_file}")

    def _flush_notice(self) -> None:
        with self._lock:
            notice = self._notice_out
            if notice is None or self.rank in self._drained:
                return
            target = min(self.alive - self._observed_dead, default=self.rank)
        try:
            # injectable: a lost/delayed notice or a partitioned leader
            # (error/delay/hang at this site) degrades to retry-next-tick
            chaos.inject("control.notice", kinds=("error", "delay", "hang"))
            if target == self.rank:
                self._decide_drain(notice)
            else:
                channel.send_frame(self.addrs[target], notice, retries=1)
        except Exception as e:
            self._record("send_failures", line=False)
            log_warning("preemption notice delivery failed (retrying next "
                        "tick): %s: %s", type(e).__name__, e)

    def _send_heartbeats(self) -> None:
        with self._lock:
            # step samples are DRAINED, not re-sent: the remote sentinel's
            # windows must see each observation once, or duplicates would
            # skew the very medians the pod feed exists to widen
            samples, self._step_samples = self._step_samples, []
            # "ts" is display-only (log correlation across hosts); the
            # receiver stamps its own monotonic arrival time and liveness
            # compares monotonic-vs-monotonic only — sender wall-clock is
            # untrusted by contract (NTP steps, skewed hosts)
            frame = {
                "t": "hb", "rank": self.rank, "epoch": self.epoch,
                "step": self._local_step, "status": self._pushed_status,
                "steps_ms": samples[-16:], "ts": time.time(),
            }
            peers = sorted(
                (self.alive - self._observed_dead) - {self.rank}
            )
        for p in peers:
            try:
                # injectable: error = frame lost, delay/hang = late frame ->
                # the PEER's miss accounting sees it, which is the point
                chaos.inject("control.heartbeat",
                             kinds=("error", "delay", "hang"))
                channel.send_frame(self.addrs[p], frame, retries=0)
                self._record("heartbeats_sent", line=False)
            except Exception:
                self._record("send_failures", line=False)

    def _detect_misses(self, now: float) -> None:
        budget = self.interval_s * self.misses
        with self._lock:
            peers = sorted(self.alive - {self.rank} - self._observed_dead)
            newly_dead = []
            for p in peers:
                seen = self._last_seen.get(p)
                if seen is None:
                    # never heard from: the boot grace window applies (a
                    # peer may still be importing jax); after it, silence
                    # is death like anywhere else
                    deadline = (self._started_at or now) + max(
                        self.grace_s, budget
                    )
                else:
                    deadline = seen + budget
                if now >= deadline:
                    self._observed_dead.add(p)
                    self._suspected_at[p] = seen if seen is not None else now
                    newly_dead.append((p, now - (seen if seen is not None
                                                 else now)))
            if newly_dead:
                candidate = min(self.alive - self._observed_dead,
                                default=self.rank)
        for p, age in newly_dead:
            self._record(
                "deaths_detected",
                f"rank={p} last_hb_age={age:.3f}s budget={budget:.3f}s "
                f"observer={self.rank}",
            )
            _tracer_instant("control.death_detected", rank=p,
                            observer=self.rank, age_s=round(age, 3))
        if not newly_dead:
            return
        if candidate == self.rank:
            with self._lock:
                self._proposals.setdefault(self.rank, set()).update(
                    self._observed_dead
                )
                if self._barrier_deadline is None:
                    self._barrier_deadline = now + budget
                    self._barrier_extensions = 0
        else:
            self._propose_to(candidate)

    def _propose_to(self, candidate: int) -> None:
        with self._lock:
            dead = sorted(self._observed_dead)
        if not dead:
            return
        try:
            channel.send_frame(
                self.addrs[candidate],
                {"t": "propose", "rank": self.rank, "dead": dead,
                 "epoch": self.epoch},
                retries=1,
            )
        except OSError:
            # the candidate may be freshly dead too; the next tick's miss
            # accounting will move the candidacy down the rank order
            self._record("send_failures", line=False)

    def _maybe_commit(self, now: float) -> None:
        """Close the loss-epoch barrier: the member that believes it leads
        waits one miss budget for peers' proposals, then commits the union
        it can itself corroborate — one reshard plan, not N."""
        with self._lock:
            if self._barrier_deadline is None or now < self._barrier_deadline:
                return
            union = set()
            for s in self._proposals.values():
                union |= s
            union &= self.alive
            # corroboration: commit only losses this member observed too (a
            # peer's false alarm about a rank we still hear from must not
            # shed live capacity); give uncorroborated proposals one more
            # barrier window to become observable before dropping them
            dead = union & self._observed_dead
            if not dead:
                if union and self._barrier_extensions < 1:
                    self._barrier_extensions += 1
                    self._barrier_deadline = (
                        now + self.interval_s * self.misses
                    )
                else:
                    self._barrier_deadline = None
                    self._proposals.clear()
                return
            if min(self.alive - dead, default=self.rank) != self.rank:
                # someone lower still lives: not ours to commit
                self._barrier_deadline = None
                return
            survivors = sorted(self.alive - dead)
            epoch = self.epoch + 1
            detect_s = max(
                (now - self._suspected_at.get(p, now) for p in dead),
                default=0.0,
            )
            commit = {
                "t": "commit", "epoch": epoch, "leader": self.rank,
                "survivors": survivors, "dead": sorted(dead),
                "reason": "heartbeat-miss",
                "detect_s": round(detect_s, 3),
            }
            self._barrier_deadline = None
            self._proposals.clear()
        if self._apply_commit(commit):
            # include the removed ranks: to a truly dead host this is a
            # refused connect and a warning, but a STALLED one (GC pause,
            # partition healed late) must hear it was evicted or it would
            # keep making pod decisions on a stale membership
            self._broadcast(commit, to=set(survivors) | dead,
                            best_effort=dead)

    def _broadcast(self, frame: dict,
                   to: Optional[Sequence[int]] = None,
                   best_effort: Sequence[int] = ()) -> None:
        """Fan ``frame`` out to ``to`` (default: current live peers). Drain
        orders pass an explicit recipient list: a shrink-mode apply removes
        the draining rank from ``alive`` BEFORE the broadcast, and that rank
        is precisely the one that must hear the verdict. ``best_effort``
        recipients (the ranks a commit itself removed — probably corpses)
        get ONE unretried attempt: retry backoff to a dead host would stall
        this thread past the miss budget and get the SENDER declared dead."""
        with self._lock:
            peers = sorted(
                (set(to) if to is not None else self.alive) - {self.rank}
            )
        for p in peers:
            try:
                channel.send_frame(
                    self.addrs[p], frame,
                    retries=0 if p in best_effort else COMMIT_SEND_RETRIES,
                )
            except OSError as e:
                self._record("send_failures", line=False)
                if p not in best_effort:
                    log_warning(
                        "control broadcast to rank %d failed: %s: %s",
                        p, type(e).__name__, e,
                    )

    # -- listener thread ---------------------------------------------------

    def _on_frame(self, frame: dict) -> None:
        t = frame.get("t")
        if t == "hb":
            self._on_heartbeat(frame)
        elif t == "propose":
            self._on_propose(frame)
        elif t == "commit":
            self._apply_commit(frame)
        elif t == "notice":
            self._on_notice(frame)
        elif t == "drain":
            self._apply_drain(frame)

    def _on_heartbeat(self, frame: dict) -> None:
        r = int(frame["rank"])
        now = time.monotonic()
        feed: List[float] = []
        with self._lock:
            if r not in self.alive:
                return  # removed by a committed epoch; re-admission is grow
            self._last_seen[r] = now
            if frame.get("status") is not None:
                self._peer_status[r] = frame["status"]
            if frame.get("step") is not None:
                self._peer_step[r] = int(frame["step"])
            if r in self._observed_dead:
                # heard from again before any commit removed it: a false
                # alarm (GC pause, loaded link) recovers without resharding
                self._observed_dead.discard(r)
                self._suspected_at.pop(r, None)
                log_info("control: rank %d resumed heartbeats before "
                         "commit; suspicion cleared", r)
            samples = frame.get("steps_ms") or ()
            if r != self.rank:
                feed = [float(x) for x in samples][-16:]
        self._record("heartbeats_recv", line=False)
        if feed:
            # pod-wide straggler judgment (ROADMAP #2b): remote replicas'
            # step times enter the LOCAL sentinel's windows, so the peer
            # median a replica is judged against spans the whole pod.
            # Host-side list appends only — safe on this thread.
            from mlsl_tpu.obs import straggler as straggler_mod

            sent = straggler_mod.get_active()
            if sent is not None:
                sent.observe_remote(r, feed)

    def _on_propose(self, frame: dict) -> None:
        r = int(frame["rank"])
        dead = set(int(d) for d in frame.get("dead", ()))
        now = time.monotonic()
        with self._lock:
            if r not in self.alive or not dead:
                return
            # accept into the barrier only while this member is the lowest
            # rank OUTSIDE the proposed dead set (i.e. the candidate the
            # proposer elected); otherwise the proposal is for someone else
            if min(self.alive - dead, default=self.rank) != self.rank:
                return
            self._proposals[r] = dead & self.alive
            if self._barrier_deadline is None:
                self._barrier_deadline = (
                    now + self.interval_s * self.misses
                )
                self._barrier_extensions = 0

    def _fence(self, frame: dict, kind: str) -> bool:
        """Epoch + leadership fence (caller holds no lock). True = accept.

        The leadership check is evaluated NET OF the ranks the order itself
        removes: a leader-death commit is signed by the next-lowest
        survivor, who only becomes the minimum once the dead leader is out
        — judging it against the pre-commit membership would reject the
        very order that removes the dead leader. A deposed leader's stale
        order still dies here: it was already removed from the receiver's
        membership by the newer epoch, so it is never the minimum of any
        view, removed-set or not."""
        with self._lock:
            epoch = int(frame.get("epoch", -1))
            leader = frame.get("leader")
            removed = (
                set(int(d) for d in frame.get("dead", ()))
                if kind == "commit" else set()
            )
            expected = min(self.alive - removed, default=None)
            if epoch <= self.epoch or leader != expected:
                stale = (
                    f"{kind} epoch={epoch} leader={leader} rejected at "
                    f"rank={self.rank} (local epoch={self.epoch} "
                    f"expected leader={expected})"
                )
            else:
                return True
        self._record("stale_rejected", stale)
        _tracer_instant("control.stale_rejected", kind=kind,
                        epoch=epoch, rank=self.rank)
        return False

    def _apply_commit(self, frame: dict) -> bool:
        if not self._fence(frame, "commit"):
            return False
        with self._lock:
            epoch = int(frame["epoch"])
            survivors = set(int(s) for s in frame["survivors"])
            dead = sorted(int(d) for d in frame.get("dead", ()))
            prev_leader = min(self.alive) if self.alive else None
            self.epoch = epoch
            self.alive = survivors
            for d in dead:
                self._observed_dead.discard(d)
                self._suspected_at.pop(d, None)
                self._proposals.pop(d, None)
            for prop in self._proposals.values():
                prop.difference_update(dead)
            self._proposals = {r: s for r, s in self._proposals.items()
                               if s and r in survivors}
            if not self._proposals:
                self._barrier_deadline = None
            new_leader = min(survivors) if survivors else None
            elected = new_leader != prev_leader
            if self.rank not in survivors:
                self._evicted = True
            ev = {
                "kind": "commit", "epoch": epoch, "dead": dead,
                "survivors": sorted(survivors), "leader": new_leader,
                "reason": frame.get("reason", "heartbeat-miss"),
                "detect_s": frame.get("detect_s"),
            }
            self.events.append(ev)
            self._pending_losses.append({
                "epoch": epoch, "dead": dead,
                "reason": ev["reason"],
            })
        self._record(
            "epochs_committed",
            f"epoch={epoch} dead={','.join(map(str, dead))} "
            f"survivors={','.join(map(str, sorted(survivors)))} "
            f"leader={new_leader} reason={ev['reason']} "
            f"detect_s={ev.get('detect_s')}",
        )
        _tracer_instant("control.epoch", epoch=epoch,
                        dead=",".join(map(str, dead)),
                        leader=new_leader)
        if elected:
            self._record(
                "elections",
                f"epoch={epoch} leader={new_leader} deposed={prev_leader}",
            )
        if self._evicted:
            self._record("evicted", f"rank={self.rank} epoch={epoch}")
            log_warning(
                "control: rank %d was declared dead by the pod at epoch %d "
                "(partition?) — this process no longer makes pod decisions",
                self.rank, epoch,
            )
        return True

    # -- drain -------------------------------------------------------------

    def _on_notice(self, frame: dict) -> None:
        with self._lock:
            am_leader = bool(self.alive) and min(self.alive) == self.rank
        if am_leader:
            self._decide_drain(frame)
        # else: the sender's leader view is stale; its next tick re-targets

    def _decide_drain(self, notice: dict) -> None:
        """Leader only: exactly ONE pod-wide drain decision per noticed
        rank — shrink onto the survivors when the elastic coordinator is
        armed and survivors remain, else a pod-wide verified save."""
        r = int(notice["rank"])
        from mlsl_tpu import elastic as elastic_mod

        with self._lock:
            if r in self._decided_notices or r not in self.alive:
                return  # duplicate notice: the decision already stands
            self._decided_notices.add(r)
            shrinkable = elastic_mod.armed() and len(self.alive) > 1
            mode = "shrink" if shrinkable else "save"
            epoch = self.epoch + 1
            survivors = sorted(self.alive - {r}) if mode == "shrink" \
                else sorted(self.alive)
            drain = {
                "t": "drain", "epoch": epoch, "leader": self.rank,
                "mode": mode, "rank": r, "survivors": survivors,
                "reason": notice.get("reason", "preemption"),
            }
        self._record(
            "drain_decisions",
            f"epoch={epoch} rank={r} mode={mode} leader={self.rank} "
            f"reason={drain['reason']}",
        )
        _tracer_instant("control.drain", epoch=epoch, rank=r, mode=mode)
        try:
            # the decision broadcast is notice-path traffic too: a delayed
            # or dropped order is the injectable failure mode here
            chaos.inject("control.notice", kinds=("error", "delay", "hang"))
        except Exception as e:
            log_warning("drain broadcast perturbed by chaos (%s); "
                        "proceeding: %s", type(e).__name__, e)
        if self._apply_drain(drain):
            self._broadcast(drain, to=set(survivors) | {r})

    def _apply_drain(self, frame: dict) -> bool:
        if not self._fence(frame, "drain"):
            return False
        r = int(frame["rank"])
        mode = frame["mode"]
        with self._lock:
            epoch = int(frame["epoch"])
            self.epoch = epoch
            self._drained.add(r)
            self._decided_notices.add(r)
            if r == self.rank:
                self._notice_out = None
            if mode == "shrink":
                self.alive.discard(r)
                self._observed_dead.discard(r)
                if r != self.rank:
                    # survivors reshard around the drained rank; the rank
                    # itself is exiting, not suffering a device loss
                    self._pending_losses.append({
                        "epoch": epoch, "dead": [r], "reason": "drain",
                    })
            ev = {
                "kind": "drain", "epoch": epoch, "rank": r, "mode": mode,
                "survivors": sorted(self.alive),
                "leader": frame.get("leader"),
                "reason": frame.get("reason"),
            }
            self.events.append(ev)
            self._pending_drain = dict(frame)
        self._record(
            "epochs_committed",
            f"epoch={epoch} drain rank={r} mode={mode} "
            f"survivors={','.join(map(str, ev['survivors']))} "
            f"leader={frame.get('leader')}",
        )
        return True

    # -- stats -------------------------------------------------------------

    @staticmethod
    def _record(event: str, detail: str = "", line: bool = True,
                count: bool = True) -> None:
        from mlsl_tpu.core import stats as stats_mod

        stats_mod.record_control(event, detail, line=line, count=count)
