"""Typed configuration with MLSL_* environment-variable overrides.

The reference scatters ~25 env knobs across three tiers (src/env.cpp:26-40,
src/comm_ep.cpp:43-92,1543-1699, eplib/env.c). Here a single dataclass holds the typed
config; every field can be overridden by the same ``MLSL_*`` names the reference honors
(where a knob still makes sense on TPU). Knobs tied to MPI endpoint servers are accepted
and mapped to their TPU analog or recorded as no-ops, so existing launch scripts keep
working.
"""

from __future__ import annotations

import dataclasses
import os

from mlsl_tpu.obs.tracer import DEFAULT_CAPACITY as _TRACE_DEFAULT_CAPACITY


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return v.strip().lower() not in ("0", "false", "no", "off")


# Registry codec names (mlsl_tpu.codecs), mirrored statically: validate()
# must stay importable without jax, and the registry re-asserts membership
# at every get() so the mirror cannot drift silently past dispatch.
_CODEC_NAMES = ("f32", "int8", "prune", "topk", "vq")

# env var -> Config field, for the explicit-override bookkeeping in from_env
# (auto_config must never clobber a knob the user exported)
_ENV_FIELDS = {
    "MLSL_LARGE_MSG_SIZE_MB": "large_msg_size_mb",
    "MLSL_LARGE_MSG_CHUNKS": "large_msg_chunks",
    "MLSL_MSG_PRIORITY_THRESHOLD": "msg_priority_threshold",
    "MLSL_MSG_PRIORITY_FLUSH_MS": "msg_priority_flush_ms",
    "MLSL_GATHER_DEVICE_LIMIT_MB": "gather_device_limit_mb",
    "MLSL_GRAD_BUCKET_MB": "grad_bucket_mb",
    "MLSL_NUM_SERVERS": "num_servers",
    "MLSL_QUANT_BLOCK_ELEMS": "quant_block_elems",
    "MLSL_HIER_DCN_CODEC": "hier_dcn_codec",
    "MLSL_CODEC": "codec",
    "MLSL_CODEC_NSR_BUDGET": "codec_nsr_budget",
    "MLSL_CODEC_GUARD_BREACHES": "codec_guard_breaches",
    "MLSL_VQ_DIM": "vq_dim",
    "MLSL_VQ_CODEBOOK": "vq_codebook",
    "MLSL_PRUNE_RATIO": "prune_ratio",
    "MLSL_PALLAS_RING_SLOTS": "pallas_ring_slots",
    "MLSL_PALLAS_RHD_MAX_BYTES": "pallas_rhd_max_bytes",
    "MLSL_OVERLAP_STAGES": "overlap_stages",
    "MLSL_FEED_DEPTH": "feed_depth",
    "MLSL_FEED_CACHE_MB": "feed_cache_mb",
    "MLSL_FEED_WIRE_DTYPE": "feed_wire_dtype",
    "MLSL_SENTINEL_EVERY": "sentinel_every",
    "MLSL_METRICS_EVERY": "metrics_every",
    "MLSL_STRAGGLER_EVERY": "straggler_every",
    "MLSL_HEARTBEAT_MISSES": "heartbeat_misses",
    "MLSL_SERVE_MAX_BATCH": "serve_max_batch",
    "MLSL_SERVE_KV_PAGE_ELEMS": "serve_kv_page_elems",
    "MLSL_SERVE_KV_CACHE_MB": "serve_kv_cache_mb",
    "MLSL_SERVE_QUEUE_DEPTH": "serve_queue_depth",
}


@dataclasses.dataclass
class Config:
    # --- core tier (reference src/env.cpp:26-40) ---
    log_level: int = 0              # MLSL_LOG_LEVEL
    dup_group: bool = False         # MLSL_DUP_GROUP: force a dedicated data group even
                                    # when dataParts == world size
    enable_stats: bool = False      # MLSL_STATS
    auto_config_type: int = 0       # MLSL_AUTO_CONFIG_TYPE

    # --- dispatch/backend tier (reference src/comm_ep.cpp:43-92) ---
    # Number of parallel dispatch lanes. TPU analog of MLSL_NUM_SERVERS (endpoint
    # count): how many independent collective launches may be in flight.
    num_servers: int = 4            # MLSL_NUM_SERVERS
    # Chunking for very large messages (reference splits >128 MiB into chunks,
    # src/comm_ep.cpp:95-97). XLA handles ICI channelization; the knob survives as the
    # size at which a collective is split into independently dispatched chunks so Wait
    # can complete (and overlap) incrementally.
    large_msg_size_mb: int = 128    # MLSL_LARGE_MSG_SIZE_MB
    large_msg_chunks: int = 4       # MLSL_LARGE_MSG_CHUNKS
    max_short_msg_size: int = 0     # MLSL_MAX_SHORT_MSG_SIZE
    # Gradient bucketing (core/bucketing.py): coalesce per-layer gradient
    # allreduces below this bucket size into one concatenated allreduce
    # (fewer host dispatches, bandwidth-sized wire messages). 0 = off.
    grad_bucket_mb: int = 0         # MLSL_GRAD_BUCKET_MB
    # Per-device output cap (MiB) for the device-side rooted gather, whose
    # rank-uniform SPMD result replicates the concatenation on every member
    # (docs/DESIGN.md 'Rooted gather'); larger gathers must use
    # gather_to_host (host delivery, no device footprint). 0 = unlimited.
    gather_device_limit_mb: int = 1024  # MLSL_GATHER_DEVICE_LIMIT_MB

    # --- priority scheduling (reference eplib/env.c:135-165, allreduce_pr.c) ---
    msg_priority: bool = False        # MLSL_MSG_PRIORITY: newest-first dispatch
    msg_priority_threshold: int = 10000  # MLSL_MSG_PRIORITY_THRESHOLD (bytes)
    msg_priority_mode: bool = True    # MLSL_MSG_PRIORITY_MODE: 1 = LIFO
    # Coalescing window before the progress thread launches deferred requests on
    # its own (reference: endpoint servers progress without app polls,
    # eplib/allreduce_pr.c:69-278). Requests deferred within the window are
    # launched together, newest first.
    msg_priority_flush_ms: float = 2.0  # MLSL_MSG_PRIORITY_FLUSH_MS

    # --- collective algorithm engine (comm/algos) + autotuner (tuner/) ---
    # Forced algorithm selection: '' = auto (tuned profile, else the 'lax'
    # baseline). Either one registry name ('rhd') applied to every engine
    # kind, or a comma list of kind=name entries
    # ('allreduce=rhd,reduce_scatter=ring2d'). Validated against the
    # registry at init (validate()) — an unknown name is an MLSLError there,
    # not a failure deep in dispatch.
    collective_algo: str = ""       # MLSL_ALGO
    # Run the topology autotuner at Environment.init: sweep candidate
    # algorithms x chunk/bucket/priority knobs on the live mesh and persist
    # the winning table to ``tune_profile`` (tuner/).
    tune: bool = False              # MLSL_TUNE
    # Profile path: read at init when set (MLSL_TUNE=0), written when the
    # sweep runs (MLSL_TUNE=1). '' = the default mlsl_tune_profile.json in
    # MLSL_STATS_DIR (or CWD). A profile whose topology fingerprint does not
    # match the probed hardware is rejected with a warning; a missing or
    # corrupt file is an MLSLError at init.
    tune_profile: str = ""          # MLSL_TUNE_PROFILE
    # Loaded tuner.TunedProfile (or None): consulted by comm/algos.select
    # for every engine collective. Set by Environment.init, never from env.
    tuned_profile: object = None

    # --- hierarchical (two-tier) collectives (comm/algos/hier.py;
    # docs/TUNING.md §17) ---
    # Synthetic tier override 'TxL' (T DCN slices x L devices/slice): how
    # the CPU proof mesh and tier-1 exercise a two-tier world. On real TPU
    # multislice the tier map comes from device.slice_index and this stays
    # ''. Recorded for discoverability like pallas_interpret: the mesh/hier
    # modules read the SAME env var per build, so a monkeypatched env is
    # honored without a Config handle. Validated at init.
    mesh_tiers: str = ""            # MLSL_MESH_TIERS
    # DCN-tier codec for the 'hier' compressed wire: 'int8' (blockwise
    # shared-scale integer sum — the THC shape, default), 'topk', or 'f32'
    # (no compression on the slow hop). The ICI tier is always f32.
    # Tunable via a tuner profile (tuner.KNOB_CHOICES); exported env wins.
    hier_dcn_codec: str = "int8"    # MLSL_HIER_DCN_CODEC

    # --- pallas ring kernels (ops/ring_kernels.py; docs/TUNING.md §15) ---
    # Comm slots per ring direction for the 'pallas_ring' lowering: how many
    # in-flight recv slots the double-buffered RDMA cycles through (>= 2; a
    # remote-capacity semaphore handshake guards reuse). More slots = more
    # hop-pipelining headroom at (slots x chunk) VMEM cost. Tunable via a
    # tuner profile (tuner.KNOB_RANGES); an exported env var always wins.
    pallas_ring_slots: int = 2      # MLSL_PALLAS_RING_SLOTS
    # Bidirectional variant: split the payload's block-rows in half and run
    # opposite-rotation rings concurrently (both directions of each full-
    # duplex ICI link). Changes quantization grouping order, so the
    # quantized EF-parity oracle covers the unidirectional form only.
    pallas_ring_bidir: bool = False  # MLSL_PALLAS_RING_BIDIR
    # Arm the latency-class fused allreduce heuristic rung: with this set,
    # dense SUM allreduces whose payload fits the small-message band lower
    # to 'pallas_rhd' (ops/rhd_kernels.py — log2(G) halving/doubling rounds
    # in one kernel) WITHOUT a tuned profile or MLSL_ALGO. Off by default:
    # untuned selection stays bit-for-bit the baseline. A forced or tuned
    # 'pallas_rhd' works regardless of this knob, like any algorithm.
    pallas_rhd: bool = False         # MLSL_PALLAS_RHD
    # Upper edge (bytes) of the heuristic band above. 0 = derive from the
    # reference's small-message boundary: 4 x msg_priority_threshold
    # elements' worth of f32 payload (rhd_kernels.env_max_bytes). Tunable
    # via a tuner profile (tuner.KNOB_RANGES); an exported env always wins.
    pallas_rhd_max_bytes: int = 0    # MLSL_PALLAS_RHD_MAX_BYTES
    # Fuse the int8 blockwise codec into the 'pallas_a2a' alltoall wire
    # (quantize on send-slot write, dequantize on receive — wire bytes
    # <= 1/3 of f32). Off = the same kernel exchanges dense f32. The codec
    # block size rides MLSL_QUANT_BLOCK_ELEMS like every quantized wire.
    pallas_a2a_quant: bool = True    # MLSL_PALLAS_A2A_QUANT
    # Interpreter gate, recorded for discoverability like chaos_spec: the
    # kernels read the SAME env var per build ('1' force-interpret, '0'
    # force-compiled, '' = compiled on TPU / interpreter elsewhere — but
    # selection only admits pallas_ring off-TPU when explicitly '1').
    pallas_interpret: str = ""       # MLSL_PALLAS_INTERPRET

    # --- compiled overlap engine (comm/overlap.py; docs/TUNING.md §14) ---
    # Arm the single-dispatch compiled step: the backward pass decomposed
    # per layer with every gradient collective emitted IN-GRAPH,
    # newest-first, so XLA's latency-hiding scheduler overlaps ICI DMA with
    # compute instead of the host per-layer poll loop. The host path stays
    # the default and the parity oracle.
    overlap_compiled: bool = False   # MLSL_OVERLAP_COMPILED
    # Staging depth: a layer's reduce phases are spread over the next this-
    # many unit starts (stage boundaries pinned with optimization_barrier).
    # Tunable via a tuner profile (tuner.KNOB_RANGES); exported env wins.
    overlap_stages: int = 2          # MLSL_OVERLAP_STAGES

    # --- device feed pipeline (mlsl_tpu.data; docs/TUNING.md §12) ---
    # Wire dtype for host->device batch transfer: '' = full width (off),
    # 'uint8' (images: 4x vs f32), 'bf16' (2x), 'int8' (block codec shared
    # with the quantized collectives). Per-leaf overrides ride in the same
    # string ('uint8,y=none'); parsed/validated by data.wire.parse_wire_spec
    # at validate(). The data package reads the SAME env var per feed, so
    # standalone DeviceFeed construction honors it without a Config handle.
    feed_wire_dtype: str = ""       # MLSL_FEED_WIRE_DTYPE
    # HBM budget (MiB) for the feed cache: wire batches pin on device after
    # first touch and epoch replays skip h2d entirely. 0 = off.
    feed_cache_mb: int = 0          # MLSL_FEED_CACHE_MB
    # Prefetch depth: batches in flight device-side (2 = double buffering).
    # Tunable via a tuner profile (tuner.KNOB_RANGES) — an exported env var
    # always wins (the Config._explicit contract).
    feed_depth: int = 2             # MLSL_FEED_DEPTH
    # TRANSIENT source-read retries per batch (supervisor taxonomy, rung 2).
    feed_retries: int = 2           # MLSL_FEED_RETRIES

    # --- serving engine (mlsl_tpu.serve; docs/TUNING.md §21) ---
    # Decode-slot ceiling for the in-flight continuous batch. New sequences
    # join at decode-step granularity up to this many slots; the SLA ladder
    # sheds below it under pressure. Tunable via a tuner profile — an
    # exported env var always wins (the Config._explicit contract).
    serve_max_batch: int = 8        # MLSL_SERVE_MAX_BATCH
    # Tokens per KV page: the paged-cache allocation granularity. Small
    # pages waste less HBM on short tails but grow the page tables; sized
    # by the tuner, an exported env always wins.
    serve_kv_page_elems: int = 16   # MLSL_SERVE_KV_PAGE_ELEMS
    # HBM budget (MiB) for the paged KV cache (global logical bytes, the
    # FeedCache accounting contract). Caps total pages; admissions that
    # cannot get pages are refused or trigger eviction of finished tails.
    serve_kv_cache_mb: int = 64     # MLSL_SERVE_KV_CACHE_MB
    # Admission queue depth: requests waiting beyond the in-flight batch.
    # Over it, submit() rejects 429-style with a retry-after hint instead
    # of queueing unboundedly (the AsyncLoader backpressure contract).
    serve_queue_depth: int = 32     # MLSL_SERVE_QUEUE_DEPTH
    # Store KV pages int8-blockwise (ops/quant_kernels codec) instead of
    # full width: ~4x more tokens per HBM byte at a bounded dequantize
    # error; also what SLA ladder rung 2 switches on under pressure.
    serve_kv_quant: bool = False    # MLSL_SERVE_KV_QUANT

    # --- compression ---
    quant_block_elems: int = 256
    topk_ratio: float = 0.01       # MLSL_TOPK_RATIO: fraction of elements kept
    # user-pluggable codec (comm/codec.py CustomCodec), registered through
    # Environment.set_quantization_params; None = built-in Pallas int8 kernels
    custom_codec: object = None

    # --- codec lab (mlsl_tpu.codecs; docs/TUNING.md §22) ---
    # Registry codec for every QUANTIZATION-compressed gradient wire:
    # '' = the seed int8 path; any mlsl_tpu.codecs name ('vq', 'prune',
    # 'topk', 'f32') routes through the registry transport. An EXPORTED
    # MLSL_CODEC beats a calibrated per-set assignment (the _explicit
    # contract); a programmatic value is the default the calibration
    # overrides per set.
    codec: str = ""                 # MLSL_CODEC
    # Run the codec calibration pass at Session.commit (tuner/calibrate.py):
    # measure per-set norm spectra + quantization noise-to-signal, solve
    # codec x block per ParameterSet against codec_nsr_budget, persist the
    # assignment into the topology-keyed tuned profile, and re-route the
    # live gradient requests to the solved codecs.
    tune_codec: bool = False        # MLSL_TUNE_CODEC
    # Per-set codec assignment (request name -> calibration cell dict):
    # written by the calibration pass or loaded from a tuned profile at
    # init. Never set from env.
    codec_assignment: dict = dataclasses.field(default_factory=dict)
    # Calibration convergence budget: max per-set quantization-noise-to-
    # signal power ratio a solved codec may incur; sets where no cheaper
    # codec fits the budget stay int8.
    codec_nsr_budget: float = 0.02  # MLSL_CODEC_NSR_BUDGET
    # Consecutive sentinel loss z-score breaches (while a calibrated codec
    # is live) before the guardrail demotes every calibrated set to int8.
    codec_guard_breaches: int = 3   # MLSL_CODEC_GUARD_BREACHES
    # VQ codec shape: elements per vector and codebook rows (<= 256: one
    # index byte per vector on the wire). Tunable via a tuner profile.
    vq_dim: int = 4                 # MLSL_VQ_DIM
    vq_codebook: int = 16           # MLSL_VQ_CODEBOOK
    # Pruning codec keep ratio (importance-weighted masks); the calibrated
    # per-set ratio overrides this uniform default.
    prune_ratio: float = 0.05       # MLSL_PRUNE_RATIO

    # --- robustness tier (chaos layer + watchdog + checkpoint retry) ---
    # Request watchdog: wait() on an async request raises MLSLTimeoutError
    # (recoverable) once the request has been in flight longer than this,
    # instead of blocking forever on a hung collective. 0 = off.
    watchdog_timeout_s: float = 0.0   # MLSL_WATCHDOG_TIMEOUT (seconds)
    # Checkpoint save retry on transient IO errors (OSError): attempts beyond
    # the first, with exponential backoff starting at the base below. Recorded
    # here for discoverability/printing only (like chaos_spec): CheckpointManager
    # has no Config handle and reads the SAME env vars at construction —
    # override programmatically via its save_retries/retry_backoff_s ctor args,
    # not by mutating these fields.
    ckpt_save_retries: int = 3          # MLSL_CKPT_SAVE_RETRIES
    ckpt_retry_backoff_s: float = 0.05  # MLSL_CKPT_RETRY_BACKOFF_S
    # Recovery ladder (mlsl_tpu.supervisor). Rung 2: transient collective
    # dispatch/wait failures retry in place with exponential backoff +
    # jitter before anything escalates. 0 = no retries (fail straight to
    # the breaker/restart rungs).
    comm_retries: int = 2               # MLSL_COMM_RETRIES
    comm_retry_backoff_s: float = 0.05  # MLSL_COMM_RETRY_BACKOFF_S
    # Rung 3: per-subsystem circuit breakers (quant codec, grad buckets,
    # algo engine, tracer). After `threshold` classified failures inside the
    # sliding window the subsystem degrades to its always-correct fallback;
    # after the cooldown a half-open probe re-engages the fast path.
    # Breakers are process-wide (state survives Environment rebuilds —
    # deliberately, so recovery cycles can escalate); these knobs are
    # (re)applied to them at Environment.init via supervisor.configure.
    breaker_threshold: int = 3          # MLSL_BREAKER_THRESHOLD
    breaker_window_s: float = 30.0      # MLSL_BREAKER_WINDOW_S
    breaker_cooldown_s: float = 10.0    # MLSL_BREAKER_COOLDOWN_S
    # Rung 4: total checkpoint recoveries FaultTolerantLoop performs across
    # a run before aborting with a flight record. Read by the loop itself
    # (like the checkpoint retry knobs: recorded here for discoverability —
    # override via the FaultTolerantLoop ctor, not by mutating this field).
    restart_budget: int = 20            # MLSL_RESTART_BUDGET
    # --- elastic mesh (mlsl_tpu.elastic; docs/TUNING.md §18) ---
    # Arm the elastic coordinator: a DEVICE_LOSS fault (preemption, the
    # chaos device.lost site) is answered by re-deriving the mesh among
    # survivors and re-sharding ZeRO-1 optimizer state live — no checkpoint
    # restore — instead of the restart rung. Off, every loss restarts
    # (pre-elastic behavior, bit-for-bit unchanged).
    elastic: bool = False               # MLSL_ELASTIC
    # Capacity budget: total devices the run may shed across its lifetime
    # before a further loss escalates to the restart rung (the elastic
    # analog of MLSL_RESTART_BUDGET — bounded capacity churn, not bounded
    # restarts). 0 = auto: half the world, resolved at coordinator
    # construction where the world size is known.
    capacity_budget: int = 0            # MLSL_CAPACITY_BUDGET
    # Simulated/announced capacity return: steps after a shrink at which the
    # lost devices rejoin (through the admission audit). 0 = only on an
    # explicit ElasticCoordinator.announce_return() (production: the
    # replacement host announcing itself).
    elastic_grow_after: int = 0         # MLSL_ELASTIC_GROW_AFTER
    # Admission-audit retries: a rejoining replica whose fingerprint audit
    # fails is re-synced from a survivor copy and re-audited up to this many
    # times before the grow is abandoned.
    elastic_admit_retries: int = 1      # MLSL_ELASTIC_ADMIT_RETRIES
    # --- integrity sentinel (mlsl_tpu.sentinel; docs/TUNING.md §13) ---
    # Step quality gate response: '' = gate off; 'warn' logs and continues,
    # 'skip_step' discards the poisoned update (EF residuals and data order
    # stay consistent — the step behaves as if it never ran), 'rollback'
    # raises MLSLIntegrityError so FaultTolerantLoop restores the newest
    # VERIFIED checkpoint. An armed gate disables the no-comm fused step
    # shortcut (the gate needs the gradient boundary).
    sentinel_gate: str = ""             # MLSL_SENTINEL_GATE
    # Cross-replica consistency audit interval in steps (0 = off): a
    # blockwise int32 fingerprint of params + optimizer state is compared
    # across replicas via on-device pmin/pmax equality (no host gather).
    # Tunable via a tuner profile (tuner.KNOB_RANGES); exported env wins.
    sentinel_every: int = 0             # MLSL_SENTINEL_EVERY
    # Grad-norm spike screen: fire when the global gradient norm exceeds
    # this factor times its EMA (armed after sentinel_warmup healthy steps).
    sentinel_spike: float = 10.0        # MLSL_SENTINEL_SPIKE
    # Loss z-score screen: fire when |loss - EMA mean| exceeds this many
    # EMA standard deviations (armed after warmup).
    sentinel_zmax: float = 8.0          # MLSL_SENTINEL_ZMAX
    # Healthy steps observed before the spike/z-score screens arm (the
    # nonfinite screen is always armed — it needs no history).
    sentinel_warmup: int = 5            # MLSL_SENTINEL_WARMUP
    # Fingerprint block size in elements: one int32 checksum per block.
    # Smaller blocks localize a corruption better but grow the on-device
    # fingerprint vector (total_elems / block int32s).
    sentinel_block: int = 4096          # MLSL_SENTINEL_BLOCK
    # --- static analysis (mlsl_tpu.analysis; docs/TUNING.md §16) ---
    # Commit-time collective-plan verifier: MLSL_VERIFY=1 walks the
    # committed graph at Session.commit and statically checks issue-order
    # consistency, in-flight budgets, quantization geometry, EF
    # snapshot/rewind pairing, and Pallas-ring semaphore accounting
    # (analysis/plan.py; findings use the stable MLSL-Axxx codes).
    verify: bool = False                # MLSL_VERIFY
    # What an error-severity finding does at commit: 'error' (default)
    # raises MLSLError naming every code; 'warn' logs the findings and
    # commits anyway (both record the verdict in supervisor.status()['analysis']
    # and the ANALYSIS stats line).
    verify_severity: str = "error"      # MLSL_VERIFY_SEVERITY
    # Runtime lock witness (analysis/witness.py; docs/TUNING.md §23): kept
    # here for discoverability/printing only, like chaos_spec — the witness
    # reads the env at lock *creation* time (subsystems build their locks at
    # import/__init__, before any Config exists), so arming mid-run has no
    # effect. MLSL_LOCK_WITNESS=1 routes the named locks of the threaded
    # subsystems through an instrumented wrapper that records acquisition-
    # order edges, cycles, and over-budget holds.
    lock_witness: bool = False          # MLSL_LOCK_WITNESS
    # Hold-time budget: a release after more than this many ms is reported
    # as an over-budget hold (the runtime shadow of static rule A211).
    lock_witness_budget_ms: float = 250.0   # MLSL_LOCK_WITNESS_BUDGET_MS
    # Fault-injection spec; parsed by mlsl_tpu.chaos
    # (site:kind[=v][@after][xN][%p], comma-separated). Kept here for
    # discoverability/printing only.
    chaos_spec: str = ""            # MLSL_CHAOS

    # --- telemetry plane (mlsl_tpu.obs.metrics/serve/straggler;
    # docs/TUNING.md §19) ---
    # Arm the typed time-series registry: counter/gauge/histogram series
    # over every stats counter family plus per-step scalars (loss,
    # grad-norm, step_ms, input_stall_ms, dispatch->wait latency, per-algo
    # achieved algbw). Disabled = one module-attr check per site, zero
    # allocations (the tracer contract). Armed implicitly by
    # MLSL_METRICS_PORT.
    metrics: bool = False           # MLSL_METRICS
    # Sampler cadence in steps: loss readback, counter-family snapshot,
    # ring sample, and the JSONL append happen every this-many steps.
    # Tunable via a tuner profile (tuner.KNOB_RANGES); exported env wins.
    metrics_every: int = 20         # MLSL_METRICS_EVERY
    # Scrape surface: serve /metrics (Prometheus text), /healthz
    # (supervisor.status() as JSON) and /statusz (human summary) from a
    # stdlib HTTP daemon thread on this port. 0 = off.
    metrics_port: int = 0           # MLSL_METRICS_PORT
    # Timestamped samples retained per series (ring, deque(maxlen)).
    metrics_retention: int = 512    # MLSL_METRICS_RETENTION
    # Straggler sentinel (obs/straggler.py): fire when one replica's
    # windowed median step time exceeds this multiple of its peers'
    # median, sustained over straggler_sustain consecutive audits.
    # 0 = off; armed values must be > 1.
    straggler_skew: float = 0.0     # MLSL_STRAGGLER_SKEW
    # Observed steps per cross-replica audit window. Tunable via a tuner
    # profile (tuner.KNOB_RANGES); exported env wins.
    straggler_every: int = 20       # MLSL_STRAGGLER_EVERY
    # Consecutive suspect audits before a replica is CONFIRMED (one GC
    # pause / load spike must not flag, let alone shed).
    straggler_sustain: int = 2      # MLSL_STRAGGLER_SUSTAIN
    # Hand a confirmed straggler to the elastic coordinator as a shed
    # candidate (synthetic DEVICE_LOSS through ElasticCoordinator.shed;
    # needs MLSL_ELASTIC armed to act). Off = observe/flag only.
    straggler_shed: bool = False    # MLSL_STRAGGLER_SHED
    # Watchdog-trip device profile: on MLSLTimeoutError also capture a
    # short jax.profiler trace next to the flight record, so a wedged wait
    # arrives with host timeline AND device profile. Read per trip by
    # core/stats (recorded here for discoverability, like chaos_spec).
    profile_on_trip: bool = False   # MLSL_PROFILE_ON_TRIP

    # --- pod control plane (mlsl_tpu.control; docs/TUNING.md §20) ---
    # Heartbeat cadence on the control channel (stdlib TCP, separate from
    # the JAX collective fabric). Detection latency is
    # interval * misses; LAN/localhost pods can run well under a second.
    heartbeat_interval_s: float = 2.0   # MLSL_HEARTBEAT_INTERVAL_S
    # Consecutive missed intervals before a peer is declared locally dead
    # and proposed for a loss-epoch commit. Tunable via a tuner profile
    # (tuner.KNOB_RANGES: false-positive resharding vs detection latency);
    # exported env wins.
    heartbeat_misses: int = 3           # MLSL_HEARTBEAT_MISSES
    # Boot grace: silence from a never-heard peer is tolerated this long
    # (it may still be importing jax / compiling) before miss accounting
    # treats it like any other death.
    heartbeat_grace_s: float = 30.0     # MLSL_HEARTBEAT_GRACE_S
    # Cluster-scheduler hook (ROADMAP #2a): a scheduler that cannot
    # deliver SIGTERM writes this file; its appearance is a preemption
    # notice for this host, coordinated pod-wide like the signal.
    preemption_file: str = ""           # MLSL_PREEMPTION_FILE
    # Control-world bootstrap. Explicit form: "host:port,host:port,..."
    # (rank-ordered). Localhost shorthand for the CPU pod sim:
    # control_port (base) + control_world (N members, consecutive ports).
    # Both empty/0 = this process is not a pod member (the default — no
    # socket is ever opened).
    control_addrs: str = ""             # MLSL_CONTROL_ADDRS
    control_port: int = 0               # MLSL_CONTROL_PORT
    control_world: int = 0              # MLSL_CONTROL_WORLD
    control_rank: int = -1              # MLSL_CONTROL_RANK
    # jax.distributed.initialize retry budget (the gloo TCP preamble race,
    # KNOWN_FAILURES.md): attempts beyond the first, exponential backoff
    # from dist_init_backoff_s. Control-channel commit sends reuse the
    # same retry idiom.
    dist_init_retries: int = 3          # MLSL_DIST_INIT_RETRIES
    dist_init_backoff_s: float = 0.5    # MLSL_DIST_INIT_BACKOFF_S

    # --- observability tier (mlsl_tpu.obs span tracer) ---
    # Kept for discoverability/printing only, like chaos_spec: the tracer is
    # process-wide (armed at import from MLSL_TRACE, or obs.enable()) and the
    # output dir / ring capacity are read from the SAME env vars per call —
    # override via the obs API, not by mutating these fields.
    trace: bool = False             # MLSL_TRACE: arm the comm timeline tracer
    trace_dir: str = ""             # MLSL_TRACE_DIR: trace-*.json output dir
    # MLSL_TRACE_CAPACITY: ring size (events); single source of truth is the
    # tracer's own default
    trace_capacity: int = _TRACE_DEFAULT_CAPACITY

    # --- accepted-for-parity no-ops (MPI/shm specific) ---
    server_affinity: str = ""       # MLSL_SERVER_AFFINITY
    heap_size_gb: int = 0           # MLSL_HEAP_SIZE_GB
    alltoall_split: int = 1         # MLSL_ALLTOALL_SPLIT
    thp_threshold_mb: int = 0       # MLSL_THP_THRESHOLD_MB

    # Commit-time AOT precompilation (comm: Session.precompile_collectives):
    # warm-execute every collective program the committed graph can dispatch —
    # plain, bucketed, and quant-ring — on zero buffers at Commit, so step 0
    # of the training loop contains no collective compilation. Composes with
    # compile_cache_dir below (the warm run itself reloads from disk).
    precompile: bool = False        # MLSL_PRECOMPILE

    # Persistent XLA compilation cache (TPU-native: Session::Commit pre-lowers
    # every per-edge collective, and on real chips each first compile costs
    # tens of seconds — a warm cache makes restarts near-instant; the
    # reference has no analog because MPI has no compile step). Empty = off.
    compile_cache_dir: str = ""     # MLSL_COMPILE_CACHE_DIR

    def validate(self) -> None:
        """Reject contradictory or unserviceable settings with a clear
        MLSLError at init time instead of failing deep in dispatch. Parses
        ``collective_algo`` into the ``_forced_algos`` dict comm/algos.select
        consults (raising on names not in the registry); basic range sanity
        on the numeric knobs the engine and tuner rely on. Profile-file
        errors (missing/corrupt MLSL_TUNE_PROFILE) are raised by
        mlsl_tpu.tuner.init_profile, which Environment.init calls right after
        this."""
        from mlsl_tpu.comm import algos
        from mlsl_tpu.log import mlsl_assert

        self._forced_algos = algos.parse_forced(self.collective_algo)
        mlsl_assert(
            self.large_msg_size_mb >= 0,
            "MLSL_LARGE_MSG_SIZE_MB must be >= 0 (got %d)",
            self.large_msg_size_mb,
        )
        mlsl_assert(
            self.large_msg_chunks >= 1,
            "MLSL_LARGE_MSG_CHUNKS must be >= 1 (got %d)",
            self.large_msg_chunks,
        )
        mlsl_assert(
            self.quant_block_elems > 0,
            "MLSL_QUANT_BLOCK_ELEMS must be > 0 (got %d)",
            self.quant_block_elems,
        )
        mlsl_assert(
            0.0 < self.topk_ratio <= 1.0,
            "MLSL_TOPK_RATIO must be in (0, 1] (got %r)", self.topk_ratio,
        )
        mlsl_assert(
            self.grad_bucket_mb >= 0,
            "MLSL_GRAD_BUCKET_MB must be >= 0 (got %d)", self.grad_bucket_mb,
        )
        mlsl_assert(
            self.overlap_stages >= 1,
            "MLSL_OVERLAP_STAGES must be >= 1 (got %d)", self.overlap_stages,
        )
        mlsl_assert(
            self.pallas_ring_slots >= 2,
            "MLSL_PALLAS_RING_SLOTS must be >= 2 (the ring needs a double "
            "buffer; got %d)", self.pallas_ring_slots,
        )
        mlsl_assert(
            self.pallas_rhd_max_bytes >= 0,
            "MLSL_PALLAS_RHD_MAX_BYTES must be >= 0 (0 = derive from "
            "MLSL_MSG_PRIORITY_THRESHOLD; got %d)", self.pallas_rhd_max_bytes,
        )
        # MLSL_MESH_TIERS grammar, checked locally (comm.mesh's
        # parse_mesh_tiers applies the same rules but imports jax; validate()
        # must stay importable without it). World-coverage is checked where
        # the world is known (mesh.world_tier_ids).
        spec = (self.mesh_tiers or "").strip().lower()
        if spec:
            parts = spec.split("x")
            mlsl_assert(
                len(parts) == 2
                and all(p.strip().isdigit() and int(p) >= 1 for p in parts),
                "MLSL_MESH_TIERS must be 'TxL' with positive ints (got %r)",
                self.mesh_tiers,
            )
        mlsl_assert(
            self.hier_dcn_codec in _CODEC_NAMES,
            "MLSL_HIER_DCN_CODEC must be one of %s (got %r)",
            "/".join(_CODEC_NAMES), self.hier_dcn_codec,
        )
        mlsl_assert(
            self.codec in ("",) + _CODEC_NAMES,
            "MLSL_CODEC must be '' or one of %s (got %r)",
            "/".join(_CODEC_NAMES), self.codec,
        )
        mlsl_assert(
            isinstance(self.codec_assignment, dict),
            "codec_assignment must be a dict of request name -> calibration "
            "cell (got %r)", type(self.codec_assignment).__name__,
        )
        mlsl_assert(
            self.codec_nsr_budget > 0.0,
            "MLSL_CODEC_NSR_BUDGET must be > 0 (got %r)", self.codec_nsr_budget,
        )
        mlsl_assert(
            self.codec_guard_breaches >= 1,
            "MLSL_CODEC_GUARD_BREACHES must be >= 1 (got %d)",
            self.codec_guard_breaches,
        )
        mlsl_assert(
            1 <= self.vq_dim <= 64,
            "MLSL_VQ_DIM must be in [1, 64] (got %d)", self.vq_dim,
        )
        mlsl_assert(
            2 <= self.vq_codebook <= 256,
            "MLSL_VQ_CODEBOOK must be in [2, 256] (one index byte per "
            "vector; got %d)", self.vq_codebook,
        )
        mlsl_assert(
            0.0 < self.prune_ratio <= 1.0,
            "MLSL_PRUNE_RATIO must be in (0, 1] (got %r)", self.prune_ratio,
        )
        mlsl_assert(
            self.pallas_interpret in ("", "0", "1"),
            "MLSL_PALLAS_INTERPRET must be '', '0' or '1' (got %r)",
            self.pallas_interpret,
        )
        mlsl_assert(
            self.watchdog_timeout_s >= 0,
            "MLSL_WATCHDOG_TIMEOUT must be >= 0 (got %r)",
            self.watchdog_timeout_s,
        )
        mlsl_assert(
            self.comm_retries >= 0,
            "MLSL_COMM_RETRIES must be >= 0 (got %d)", self.comm_retries,
        )
        mlsl_assert(
            self.comm_retry_backoff_s >= 0,
            "MLSL_COMM_RETRY_BACKOFF_S must be >= 0 (got %r)",
            self.comm_retry_backoff_s,
        )
        mlsl_assert(
            self.breaker_threshold >= 1,
            "MLSL_BREAKER_THRESHOLD must be >= 1 (got %d)",
            self.breaker_threshold,
        )
        mlsl_assert(
            self.breaker_window_s >= 0 and self.breaker_cooldown_s >= 0,
            "MLSL_BREAKER_WINDOW_S / MLSL_BREAKER_COOLDOWN_S must be >= 0 "
            "(got %r / %r)", self.breaker_window_s, self.breaker_cooldown_s,
        )
        mlsl_assert(
            self.restart_budget >= 0,
            "MLSL_RESTART_BUDGET must be >= 0 (got %d)", self.restart_budget,
        )
        mlsl_assert(
            self.capacity_budget >= 0,
            "MLSL_CAPACITY_BUDGET must be >= 0 (0 = half the world; got %d)",
            self.capacity_budget,
        )
        mlsl_assert(
            self.elastic_grow_after >= 0,
            "MLSL_ELASTIC_GROW_AFTER must be >= 0 (0 = manual announce; "
            "got %d)", self.elastic_grow_after,
        )
        mlsl_assert(
            self.elastic_admit_retries >= 0,
            "MLSL_ELASTIC_ADMIT_RETRIES must be >= 0 (got %d)",
            self.elastic_admit_retries,
        )
        mlsl_assert(
            self.sentinel_gate in ("", "warn", "skip_step", "rollback"),
            "MLSL_SENTINEL_GATE must be one of '', 'warn', 'skip_step', "
            "'rollback' (got %r)", self.sentinel_gate,
        )
        mlsl_assert(
            self.sentinel_every >= 0,
            "MLSL_SENTINEL_EVERY must be >= 0 (got %d)", self.sentinel_every,
        )
        mlsl_assert(
            self.sentinel_spike > 1.0,
            "MLSL_SENTINEL_SPIKE must be > 1 (got %r)", self.sentinel_spike,
        )
        mlsl_assert(
            self.sentinel_zmax > 0,
            "MLSL_SENTINEL_ZMAX must be > 0 (got %r)", self.sentinel_zmax,
        )
        mlsl_assert(
            self.sentinel_warmup >= 0,
            "MLSL_SENTINEL_WARMUP must be >= 0 (got %d)",
            self.sentinel_warmup,
        )
        mlsl_assert(
            self.sentinel_block > 0,
            "MLSL_SENTINEL_BLOCK must be > 0 (got %d)", self.sentinel_block,
        )
        try:
            # common, not wire: the grammar parser is dependency-free, so
            # validate() does not drag in jax/numpy/the Pallas kernels
            from mlsl_tpu.data.common import parse_wire_spec

            parse_wire_spec(self.feed_wire_dtype)
        except ValueError as e:
            from mlsl_tpu.log import MLSLError

            raise MLSLError(f"MLSL_FEED_WIRE_DTYPE: {e}") from e
        mlsl_assert(
            self.feed_depth >= 1,
            "MLSL_FEED_DEPTH must be >= 1 (got %d)", self.feed_depth,
        )
        mlsl_assert(
            self.feed_cache_mb >= 0,
            "MLSL_FEED_CACHE_MB must be >= 0 (got %d)", self.feed_cache_mb,
        )
        mlsl_assert(
            self.feed_retries >= 0,
            "MLSL_FEED_RETRIES must be >= 0 (got %d)", self.feed_retries,
        )
        mlsl_assert(
            self.serve_max_batch >= 1,
            "MLSL_SERVE_MAX_BATCH must be >= 1 (got %d)",
            self.serve_max_batch,
        )
        mlsl_assert(
            self.serve_kv_page_elems >= 1,
            "MLSL_SERVE_KV_PAGE_ELEMS must be >= 1 (got %d)",
            self.serve_kv_page_elems,
        )
        mlsl_assert(
            self.serve_kv_cache_mb >= 1,
            "MLSL_SERVE_KV_CACHE_MB must be >= 1 — a zero-page cache "
            "cannot admit any sequence (got %d)", self.serve_kv_cache_mb,
        )
        mlsl_assert(
            self.serve_queue_depth >= 1,
            "MLSL_SERVE_QUEUE_DEPTH must be >= 1 (got %d)",
            self.serve_queue_depth,
        )
        mlsl_assert(
            self.verify_severity in ("error", "warn"),
            "MLSL_VERIFY_SEVERITY must be 'error' or 'warn' (got %r)",
            self.verify_severity,
        )
        mlsl_assert(
            self.lock_witness_budget_ms > 0,
            "MLSL_LOCK_WITNESS_BUDGET_MS must be > 0 (got %s)",
            self.lock_witness_budget_ms,
        )
        mlsl_assert(
            self.metrics_every >= 1,
            "MLSL_METRICS_EVERY must be >= 1 (got %d)", self.metrics_every,
        )
        mlsl_assert(
            0 <= self.metrics_port <= 65535,
            "MLSL_METRICS_PORT must be in [0, 65535] (0 = off; got %d)",
            self.metrics_port,
        )
        mlsl_assert(
            self.metrics_retention >= 2,
            "MLSL_METRICS_RETENTION must be >= 2 (got %d)",
            self.metrics_retention,
        )
        mlsl_assert(
            self.straggler_skew == 0 or self.straggler_skew > 1.0,
            "MLSL_STRAGGLER_SKEW must be 0 (off) or > 1 — a skew ratio at "
            "or below 1 would flag healthy replicas (got %r)",
            self.straggler_skew,
        )
        mlsl_assert(
            self.straggler_every >= 3,
            "MLSL_STRAGGLER_EVERY must be >= 3 (a replica needs 3 window "
            "samples to be judged — a smaller window closes before anyone "
            "is judgeable and silently disables detection; got %d)",
            self.straggler_every,
        )
        mlsl_assert(
            self.straggler_sustain >= 1,
            "MLSL_STRAGGLER_SUSTAIN must be >= 1 (got %d)",
            self.straggler_sustain,
        )
        mlsl_assert(
            self.heartbeat_interval_s > 0,
            "MLSL_HEARTBEAT_INTERVAL_S must be > 0 (got %r)",
            self.heartbeat_interval_s,
        )
        mlsl_assert(
            self.heartbeat_misses >= 1,
            "MLSL_HEARTBEAT_MISSES must be >= 1 (a zero miss budget would "
            "declare every peer dead on the first tick; got %d)",
            self.heartbeat_misses,
        )
        mlsl_assert(
            self.heartbeat_grace_s >= 0,
            "MLSL_HEARTBEAT_GRACE_S must be >= 0 (got %r)",
            self.heartbeat_grace_s,
        )
        mlsl_assert(
            0 <= self.control_port <= 65535,
            "MLSL_CONTROL_PORT must be in [0, 65535] (0 = off; got %d)",
            self.control_port,
        )
        mlsl_assert(
            self.control_world >= 0,
            "MLSL_CONTROL_WORLD must be >= 0 (got %d)", self.control_world,
        )
        mlsl_assert(
            not (self.control_addrs and self.control_world),
            "MLSL_CONTROL_ADDRS and MLSL_CONTROL_PORT/WORLD are mutually "
            "exclusive bootstrap forms — set one",
        )
        if self.control_addrs or self.control_world:
            world = (
                len(self.control_addrs.split(","))
                if self.control_addrs else self.control_world
            )
            mlsl_assert(
                0 <= self.control_rank < world,
                "MLSL_CONTROL_RANK must name this process's slot in the "
                "%d-member control world (got %d)", world, self.control_rank,
            )
        mlsl_assert(
            self.dist_init_retries >= 0,
            "MLSL_DIST_INIT_RETRIES must be >= 0 (got %d)",
            self.dist_init_retries,
        )
        mlsl_assert(
            self.dist_init_backoff_s >= 0,
            "MLSL_DIST_INIT_BACKOFF_S must be >= 0 (got %r)",
            self.dist_init_backoff_s,
        )

    @staticmethod
    def from_env() -> "Config":
        c = Config()
        # Record which knobs the user set EXPLICITLY via MLSL_* env vars:
        # sysinfo.auto_config tunes only the others (explicit always wins,
        # mirroring the reference where MLSL_AUTO_CONFIG never overrides a
        # user-exported variable, src/mlsl.cpp:649-682).
        c._explicit = {
            field for env, field in _ENV_FIELDS.items() if os.environ.get(env)
        }
        c.log_level = _env_int("MLSL_LOG_LEVEL", c.log_level)
        c.dup_group = _env_bool("MLSL_DUP_GROUP", c.dup_group)
        c.enable_stats = _env_bool("MLSL_STATS", c.enable_stats)
        c.auto_config_type = _env_int("MLSL_AUTO_CONFIG_TYPE", c.auto_config_type)
        c.num_servers = _env_int("MLSL_NUM_SERVERS", c.num_servers)
        c.large_msg_size_mb = _env_int("MLSL_LARGE_MSG_SIZE_MB", c.large_msg_size_mb)
        c.large_msg_chunks = _env_int("MLSL_LARGE_MSG_CHUNKS", c.large_msg_chunks)
        c.max_short_msg_size = _env_int("MLSL_MAX_SHORT_MSG_SIZE", c.max_short_msg_size)
        c.gather_device_limit_mb = _env_int(
            "MLSL_GATHER_DEVICE_LIMIT_MB", c.gather_device_limit_mb
        )
        c.grad_bucket_mb = _env_int("MLSL_GRAD_BUCKET_MB", c.grad_bucket_mb)
        c.msg_priority = _env_bool("MLSL_MSG_PRIORITY", c.msg_priority)
        c.msg_priority_threshold = _env_int(
            "MLSL_MSG_PRIORITY_THRESHOLD", c.msg_priority_threshold
        )
        c.msg_priority_mode = _env_bool("MLSL_MSG_PRIORITY_MODE", c.msg_priority_mode)
        c.msg_priority_flush_ms = _env_float(
            "MLSL_MSG_PRIORITY_FLUSH_MS", c.msg_priority_flush_ms
        )
        c.collective_algo = os.environ.get("MLSL_ALGO", c.collective_algo)
        c.tune = _env_bool("MLSL_TUNE", c.tune)
        c.tune_profile = os.environ.get("MLSL_TUNE_PROFILE", c.tune_profile)
        c.feed_wire_dtype = os.environ.get(
            "MLSL_FEED_WIRE_DTYPE", c.feed_wire_dtype
        )
        c.feed_cache_mb = _env_int("MLSL_FEED_CACHE_MB", c.feed_cache_mb)
        c.feed_depth = _env_int("MLSL_FEED_DEPTH", c.feed_depth)
        c.feed_retries = _env_int("MLSL_FEED_RETRIES", c.feed_retries)
        c.serve_max_batch = _env_int("MLSL_SERVE_MAX_BATCH", c.serve_max_batch)
        c.serve_kv_page_elems = _env_int("MLSL_SERVE_KV_PAGE_ELEMS",
                                         c.serve_kv_page_elems)
        c.serve_kv_cache_mb = _env_int("MLSL_SERVE_KV_CACHE_MB",
                                       c.serve_kv_cache_mb)
        c.serve_queue_depth = _env_int("MLSL_SERVE_QUEUE_DEPTH",
                                       c.serve_queue_depth)
        c.serve_kv_quant = _env_bool("MLSL_SERVE_KV_QUANT", c.serve_kv_quant)
        c.overlap_compiled = _env_bool("MLSL_OVERLAP_COMPILED", c.overlap_compiled)
        c.overlap_stages = _env_int("MLSL_OVERLAP_STAGES", c.overlap_stages)
        c.quant_block_elems = _env_int("MLSL_QUANT_BLOCK_ELEMS", c.quant_block_elems)
        c.mesh_tiers = os.environ.get("MLSL_MESH_TIERS", c.mesh_tiers).strip()
        c.hier_dcn_codec = (
            os.environ.get("MLSL_HIER_DCN_CODEC", "").strip().lower()
            or c.hier_dcn_codec
        )
        c.pallas_ring_slots = _env_int("MLSL_PALLAS_RING_SLOTS",
                                       c.pallas_ring_slots)
        c.pallas_ring_bidir = _env_bool("MLSL_PALLAS_RING_BIDIR",
                                        c.pallas_ring_bidir)
        c.pallas_rhd = _env_bool("MLSL_PALLAS_RHD", c.pallas_rhd)
        c.pallas_rhd_max_bytes = _env_int("MLSL_PALLAS_RHD_MAX_BYTES",
                                          c.pallas_rhd_max_bytes)
        c.pallas_a2a_quant = _env_bool("MLSL_PALLAS_A2A_QUANT",
                                       c.pallas_a2a_quant)
        c.pallas_interpret = os.environ.get("MLSL_PALLAS_INTERPRET",
                                            c.pallas_interpret).strip()
        c.topk_ratio = _env_float("MLSL_TOPK_RATIO", c.topk_ratio)
        c.codec = os.environ.get("MLSL_CODEC", c.codec).strip().lower()
        c.tune_codec = _env_bool("MLSL_TUNE_CODEC", c.tune_codec)
        c.codec_nsr_budget = _env_float(
            "MLSL_CODEC_NSR_BUDGET", c.codec_nsr_budget
        )
        c.codec_guard_breaches = _env_int(
            "MLSL_CODEC_GUARD_BREACHES", c.codec_guard_breaches
        )
        c.vq_dim = _env_int("MLSL_VQ_DIM", c.vq_dim)
        c.vq_codebook = _env_int("MLSL_VQ_CODEBOOK", c.vq_codebook)
        c.prune_ratio = _env_float("MLSL_PRUNE_RATIO", c.prune_ratio)
        c.watchdog_timeout_s = _env_float("MLSL_WATCHDOG_TIMEOUT", c.watchdog_timeout_s)
        c.comm_retries = _env_int("MLSL_COMM_RETRIES", c.comm_retries)
        c.comm_retry_backoff_s = _env_float(
            "MLSL_COMM_RETRY_BACKOFF_S", c.comm_retry_backoff_s
        )
        c.breaker_threshold = _env_int("MLSL_BREAKER_THRESHOLD", c.breaker_threshold)
        c.breaker_window_s = _env_float("MLSL_BREAKER_WINDOW_S", c.breaker_window_s)
        c.breaker_cooldown_s = _env_float(
            "MLSL_BREAKER_COOLDOWN_S", c.breaker_cooldown_s
        )
        c.restart_budget = _env_int("MLSL_RESTART_BUDGET", c.restart_budget)
        c.elastic = _env_bool("MLSL_ELASTIC", c.elastic)
        c.capacity_budget = _env_int("MLSL_CAPACITY_BUDGET", c.capacity_budget)
        c.elastic_grow_after = _env_int(
            "MLSL_ELASTIC_GROW_AFTER", c.elastic_grow_after
        )
        c.elastic_admit_retries = _env_int(
            "MLSL_ELASTIC_ADMIT_RETRIES", c.elastic_admit_retries
        )
        c.sentinel_gate = os.environ.get("MLSL_SENTINEL_GATE", c.sentinel_gate)
        c.sentinel_every = _env_int("MLSL_SENTINEL_EVERY", c.sentinel_every)
        c.sentinel_spike = _env_float("MLSL_SENTINEL_SPIKE", c.sentinel_spike)
        c.sentinel_zmax = _env_float("MLSL_SENTINEL_ZMAX", c.sentinel_zmax)
        c.sentinel_warmup = _env_int("MLSL_SENTINEL_WARMUP", c.sentinel_warmup)
        c.sentinel_block = _env_int("MLSL_SENTINEL_BLOCK", c.sentinel_block)
        c.ckpt_save_retries = _env_int("MLSL_CKPT_SAVE_RETRIES", c.ckpt_save_retries)
        c.ckpt_retry_backoff_s = _env_float(
            "MLSL_CKPT_RETRY_BACKOFF_S", c.ckpt_retry_backoff_s
        )
        c.metrics = _env_bool("MLSL_METRICS", c.metrics)
        c.metrics_every = _env_int("MLSL_METRICS_EVERY", c.metrics_every)
        c.metrics_port = _env_int("MLSL_METRICS_PORT", c.metrics_port)
        c.metrics_retention = _env_int(
            "MLSL_METRICS_RETENTION", c.metrics_retention
        )
        c.straggler_skew = _env_float("MLSL_STRAGGLER_SKEW", c.straggler_skew)
        c.straggler_every = _env_int(
            "MLSL_STRAGGLER_EVERY", c.straggler_every
        )
        c.straggler_sustain = _env_int(
            "MLSL_STRAGGLER_SUSTAIN", c.straggler_sustain
        )
        c.straggler_shed = _env_bool("MLSL_STRAGGLER_SHED", c.straggler_shed)
        c.profile_on_trip = _env_bool(
            "MLSL_PROFILE_ON_TRIP", c.profile_on_trip
        )
        c.heartbeat_interval_s = _env_float(
            "MLSL_HEARTBEAT_INTERVAL_S", c.heartbeat_interval_s
        )
        c.heartbeat_misses = _env_int(
            "MLSL_HEARTBEAT_MISSES", c.heartbeat_misses
        )
        c.heartbeat_grace_s = _env_float(
            "MLSL_HEARTBEAT_GRACE_S", c.heartbeat_grace_s
        )
        c.preemption_file = os.environ.get(
            "MLSL_PREEMPTION_FILE", c.preemption_file
        )
        c.control_addrs = os.environ.get(
            "MLSL_CONTROL_ADDRS", c.control_addrs
        )
        c.control_port = _env_int("MLSL_CONTROL_PORT", c.control_port)
        c.control_world = _env_int("MLSL_CONTROL_WORLD", c.control_world)
        c.control_rank = _env_int("MLSL_CONTROL_RANK", c.control_rank)
        c.dist_init_retries = _env_int(
            "MLSL_DIST_INIT_RETRIES", c.dist_init_retries
        )
        c.dist_init_backoff_s = _env_float(
            "MLSL_DIST_INIT_BACKOFF_S", c.dist_init_backoff_s
        )
        c.verify = _env_bool("MLSL_VERIFY", c.verify)
        c.verify_severity = os.environ.get(
            "MLSL_VERIFY_SEVERITY", c.verify_severity
        ).strip().lower() or c.verify_severity
        c.lock_witness = _env_bool("MLSL_LOCK_WITNESS", c.lock_witness)
        c.lock_witness_budget_ms = _env_float(
            "MLSL_LOCK_WITNESS_BUDGET_MS", c.lock_witness_budget_ms
        )
        c.chaos_spec = os.environ.get("MLSL_CHAOS", c.chaos_spec)
        c.trace = _env_bool("MLSL_TRACE", c.trace)
        c.trace_dir = os.environ.get("MLSL_TRACE_DIR", c.trace_dir)
        c.trace_capacity = _env_int("MLSL_TRACE_CAPACITY", c.trace_capacity)
        c.precompile = _env_bool("MLSL_PRECOMPILE", c.precompile)
        c.server_affinity = os.environ.get("MLSL_SERVER_AFFINITY", c.server_affinity)
        c.heap_size_gb = _env_int("MLSL_HEAP_SIZE_GB", c.heap_size_gb)
        c.alltoall_split = _env_int("MLSL_ALLTOALL_SPLIT", c.alltoall_split)
        c.thp_threshold_mb = _env_int("MLSL_THP_THRESHOLD_MB", c.thp_threshold_mb)
        c.compile_cache_dir = os.environ.get(
            "MLSL_COMPILE_CACHE_DIR", c.compile_cache_dir
        )
        return c
