"""Wire codecs + sharded zero-staging placement + jitted on-device decode.

The trainer is input-bound whenever the host->device link is slow relative to
the step (through the tunneled bench h2d runs at tens of MB/s while the
ResNet-50 step takes ~100 ms): shipping full-width f32 batches wastes the one
resource that matters. The same principle the gradient path already exploits
(quantize before the wire, decode where FLOPs are cheap — comm/quant_ring,
THC in PAPERS.md) applies to the feed: batches cross the link in a compact
*wire dtype* and a jitted on-device decode restores the training dtype.

Wire kinds per leaf (``MLSL_FEED_WIRE_DTYPE``, parsed by
:func:`parse_wire_spec`):

- ``none``/``f32`` — ship unchanged (the baseline path).
- ``bf16``        — host cast, device cast back: 2x for f32 leaves.
- ``uint8``       — images. A uint8 source leaf ships raw (4x vs f32); a f32
  leaf ships affine-quantized with a per-shard (offset, scale) pair riding
  alongside (decode contract ``(q + off) * scale`` — FMA-proof, see
  ``_encode_uint8``). Decode = cast + affine + optional (mean, std)
  normalize, bit-exact against the same host-side f32 math.
- ``int8``        — generic tensors via the SAME blockwise int8 codec the
  quantized collectives use (ops/quant_kernels: max|x|/127 per block,
  per-block f32 scales; the device decode IS quant_kernels.dequantize, so
  feeds share the quant kernels and their block/scale conventions).

Placement is *sharded zero-staging*: every (replica, data) shard slice of the
host batch is encoded independently and goes up via
``jax.make_array_from_single_device_arrays`` — no (R, D, S, M, ...)
full-replica staging array is ever materialized on the host, and the decode
program DONATES the wire buffers so the compact staging HBM is reclaimed the
moment the f32 batch exists. Per-shard encoding also keeps the int8 block
geometry local: a quant block never straddles two devices' examples.

Non-float leaves (labels) always ride unchanged: a wire kind that cannot
represent a leaf losslessly-or-by-contract falls back to ``none`` for that
leaf rather than corrupting it.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mlsl_tpu.comm.mesh import GRID_AXES, NUM_GRID_AXES
from mlsl_tpu.log import MLSLError, mlsl_assert
from mlsl_tpu.obs import tracer as obs_trace
from mlsl_tpu.ops import quant_kernels

# the wire-spec grammar lives in data/common.py (dependency-free, so
# Config.validate can parse it without importing the kernel stack)
from mlsl_tpu.data.common import WIRE_KINDS, parse_wire_spec  # noqa: F401


def _path_key(path) -> str:
    """Flattened-tree path -> stable leaf name ('0', '1', 'img.raw', ...)."""
    parts = []
    for e in path:
        if hasattr(e, "idx"):
            parts.append(str(e.idx))
        elif hasattr(e, "key"):
            parts.append(str(e.key))
        elif hasattr(e, "name"):
            parts.append(str(e.name))
        else:  # pragma: no cover - future jax key types
            parts.append(str(e))
    return ".".join(parts)


def _effective_kind(kind: str, arr: np.ndarray) -> str:
    """Clamp a requested kind to what the leaf can carry. Integer labels and
    other non-float leaves always ride unchanged; uint8 additionally accepts
    native uint8 leaves (raw image bytes)."""
    if kind == "none":
        return "none"
    if kind == "uint8":
        if arr.dtype == np.uint8 or np.issubdtype(arr.dtype, np.floating):
            return "uint8"
        return "none"
    if np.issubdtype(arr.dtype, np.floating):
        return kind
    return "none"


# -- host-side encoders (numpy; run on the loader's worker thread) -----------


#: |off| bound for the affine uint8 codec: above this, float32 ulp(off)
#: exceeds 0.25 quant units and ``q + off`` starts eating the 8 payload
#: bits — the leaf would decode toward a constant, silently. Loud > wrong.
_UINT8_OFF_LIMIT = float(2 ** 22)


def _encode_uint8(sl: np.ndarray, key: str = "?"):
    """Affine uint8: decode contract is ``(q + off) * scale`` — an add
    FEEDING a multiply, deliberately: a ``q * scale + lo`` form is an FMA
    pattern that XLA fuses (through optimization_barrier, on CPU at least)
    into a single-rounding fma, breaking bit-exact parity with the two-
    rounding host reference. Add-then-multiply has no fused form, so every
    backend rounds each op exactly once.

    The formulation carries the DC offset in quant units (off = lo/scale),
    which float32 can only do faithfully while |off| stays small; a leaf
    whose offset dwarfs its spread (|lo| >> hi - lo) fails LOUDLY here
    instead of silently collapsing to a constant on decode — route such
    leaves to ``bf16``/``none`` via a per-leaf override."""
    if sl.dtype == np.uint8:
        return np.ascontiguousarray(sl), None
    f = sl.astype(np.float32)
    lo = np.float32(f.min()) if f.size else np.float32(0.0)
    hi = np.float32(f.max()) if f.size else np.float32(0.0)
    scale = np.float32((hi - lo) / np.float32(255.0))
    if scale == 0.0:
        scale = np.float32(1.0)
    off = np.float32(lo / scale)
    if abs(float(off)) > _UINT8_OFF_LIMIT:
        raise MLSLError(
            f"feed leaf {key!r}: uint8 affine wire cannot carry this data — "
            f"DC offset / spread ratio too large (lo={float(lo):g}, "
            f"scale={float(scale):g}, off=lo/scale={float(off):g} exceeds "
            f"{_UINT8_OFF_LIMIT:g}); float32 would drop quantization bits "
            f"and decode toward a constant. Use a per-leaf override "
            f"(MLSL_FEED_WIRE_DTYPE='...,{key}=bf16' or '...,{key}=none') "
            f"for this leaf."
        )
    q = np.clip(np.rint(f / scale - off), 0, 255).astype(np.uint8)
    return q, np.array([off, scale], np.float32)


def _encode_int8(sl: np.ndarray, block: int):
    """Blockwise int8: the numpy mirror of quant_kernels.quantize_blocks_ref
    (same max|x|/127 scale, same round-half-even), padded to the kernels'
    block*ROW_TILE unit so the Pallas dequant path is always tile-legal."""
    f = sl.reshape(-1).astype(np.float32)
    n = f.size
    unit = block * quant_kernels.ROW_TILE
    npad = -(-max(n, 1) // unit) * unit
    buf = np.zeros(npad, np.float32)
    buf[:n] = f
    x2d = buf.reshape(-1, block)
    amax = np.abs(x2d).max(axis=1)
    scale = np.where(amax == 0.0, 1.0, amax / 127.0).astype(np.float32)
    q = np.clip(np.rint(x2d / scale[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scale


def _encode_slice(kind: str, sl: np.ndarray, block: int, key: str = "?"):
    """-> (payload np array, meta np array or None) for one shard slice."""
    if kind == "none":
        return np.ascontiguousarray(sl), None
    if kind == "bf16":
        import ml_dtypes

        return np.ascontiguousarray(sl.astype(ml_dtypes.bfloat16)), None
    if kind == "uint8":
        return _encode_uint8(sl, key)
    return _encode_int8(sl, block)


@dataclasses.dataclass(frozen=True)
class _Leaf:
    """Static per-leaf layout, fixed after the first staged batch."""

    key: str
    kind: str
    local_shape: Tuple[int, ...]  # decoded per-shard shape (localB, *payload)
    dtype: np.dtype               # source dtype (decode target for 'none')
    n: int                        # flattened elements per shard (int8)
    has_meta: bool
    payload_ndim: int             # wire payload rank (sans grid dims)


class FeedCodec:
    """Wire encode + zero-staging placement + jitted decode for one batch
    structure (shapes fixed across batches, like the rest of the Session
    graph). ``normalize=(mean, std)`` is applied to uint8-decoded leaves
    (image pipelines); ``augment`` is an optional traced transform applied to
    the decoded batch inside the decode program."""

    def __init__(self, topology, wire: Optional[str] = None, *,
                 normalize: Optional[Tuple] = None,
                 train_dtype=jnp.float32,
                 augment: Optional[Callable] = None,
                 quant_block: int = 256):
        self.topo = topology
        self.default, self.overrides = parse_wire_spec(wire)
        self.normalize = None
        if normalize is not None:
            # mean + HOST-computed reciprocal of std: the device applies
            # (x - mean) * inv_std. A device-side division would let XLA
            # rewrite it as multiply-by-reciprocal with its own rounding —
            # the decode-parity contract (bit-exact vs the same host f32
            # math) requires one canonical formulation on both sides.
            self.normalize = (
                np.asarray(normalize[0], np.float32),
                np.float32(1.0) / np.asarray(normalize[1], np.float32),
            )
        self.train_dtype = train_dtype
        self.augment = augment
        self.block = int(quant_block)
        self._layout: Optional[List[_Leaf]] = None
        self._treedef = None
        self._decode_jit: Dict[bool, Callable] = {}
        self._batches = 0

    # -- encode + placement -------------------------------------------------

    def leaf_kind(self, key: str, arr: np.ndarray) -> str:
        kind = self.overrides.get(key)
        if kind is None and key in ("0", "1"):
            # x/y alias the canonical batch tuple's positional leaves; an
            # exact key match (e.g. a dict leaf literally named 'x') wins
            alias = "x" if key == "0" else "y"
            kind = self.overrides.get(alias)
        if kind is None:
            kind = self.default
        return _effective_kind(kind, arr)

    def stage(self, host_batch, corrupt: bool = False):
        """Host batch -> wire-format device batch.

        Each (replica, data) shard slice is encoded independently and placed
        via ``jax.make_array_from_single_device_arrays`` — zero-staging: no
        full-replica host array, one compact h2d transfer per device.
        Returns ``(wire_batch, wire_bytes, full_bytes)`` where ``full_bytes``
        is what the uncompressed f32 path would have shipped. ``corrupt``
        flips bytes in the first payload block (the chaos ``bitrot`` kind —
        a bad host read must flow through decode/cache, not crash them)."""
        t0 = time.perf_counter_ns() if obs_trace._tracer is not None else 0
        leaves, treedef = jax.tree_util.tree_flatten_with_path(host_batch)
        if self._layout is None:
            self._treedef = treedef
            self._layout = self._build_layout(leaves)
        else:
            mlsl_assert(
                treedef == self._treedef,
                "feed batch structure changed mid-stream (got %s, staged %s)",
                treedef, self._treedef,
            )
        topo = self.topo
        r_, d_, s_, m_ = topo.grid_shape
        mesh_devs = topo.mesh.devices
        wire_leaves = []
        wire_bytes = full_bytes = 0
        for leaf, (_, arr) in zip(self._layout, leaves):
            arr = np.asarray(arr)
            b = arr.shape[0]
            local_b = b // (r_ * d_)
            mlsl_assert(
                local_b * r_ * d_ == b,
                "batch size %d must divide over %d data ranks", b, r_ * d_,
            )
            mlsl_assert(
                (local_b, *arr.shape[1:]) == leaf.local_shape,
                "feed leaf %s shape changed mid-stream (got %s, staged %s)",
                leaf.key, (local_b, *arr.shape[1:]), leaf.local_shape,
            )
            f32_nbytes = (
                arr[: local_b].size * 4
                if np.issubdtype(arr.dtype, np.floating)
                else arr[: local_b].nbytes
            )
            q_parts, s_parts = [], []
            for r in range(r_):
                for d in range(d_):
                    i = r * d_ + d
                    sl = arr[i * local_b : (i + 1) * local_b]
                    q, meta = _encode_slice(leaf.kind, sl, self.block,
                                            leaf.key)
                    if corrupt:
                        q = q.copy()
                        flat = q.view(np.uint8).reshape(-1)
                        flat[: min(64, flat.size)] ^= 0xFF
                        corrupt = False  # one rotted block per batch
                    q_parts.append(q)
                    s_parts.append(meta)
            wire_leaf = {
                "q": self._place(q_parts, mesh_devs),
            }
            per_dev = s_ * m_
            wire_bytes += sum(q.nbytes for q in q_parts) * per_dev
            full_bytes += f32_nbytes * r_ * d_ * per_dev
            if leaf.has_meta:
                wire_leaf["s"] = self._place(s_parts, mesh_devs)
                wire_bytes += sum(s.nbytes for s in s_parts) * per_dev
            wire_leaves.append(wire_leaf)
        self._batches += 1
        from mlsl_tpu.core import stats

        stats.record_feed_stage(wire_bytes, full_bytes)
        tr = obs_trace._tracer
        if tr is not None:
            tr.complete("h2d.transfer", "feed", t0, batch=self._batches,
                        wire_bytes=wire_bytes, saved=full_bytes - wire_bytes)
        return tuple(wire_leaves), wire_bytes, full_bytes

    def _place(self, blocks, mesh_devs) -> jax.Array:
        """Per-(r, d) host blocks -> one sharded array, one compact transfer
        per device (broadcast over the seq/model axes like shard_batch)."""
        r_, d_, s_, m_ = self.topo.grid_shape
        payload = blocks[0].shape
        grid1 = (1,) * NUM_GRID_AXES
        global_shape = (r_, d_, s_, m_, *payload)
        sharding = self.topo.buffer_sharding(len(payload))
        arrays = []
        for r in range(r_):
            for d in range(d_):
                block = blocks[r * d_ + d].reshape(grid1 + payload)
                for s in range(s_):
                    for m in range(m_):
                        arrays.append(
                            jax.device_put(block, mesh_devs[r, d, s, m])
                        )
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, arrays
        )

    def _build_layout(self, leaves) -> List[_Leaf]:
        r_, d_ = self.topo.grid_shape[:2]
        layout = []
        for path, arr in leaves:
            arr = np.asarray(arr)
            key = _path_key(path)
            kind = self.leaf_kind(key, arr)
            local_b = arr.shape[0] // (r_ * d_)
            local_shape = (local_b, *arr.shape[1:])
            n = int(np.prod(local_shape))
            if kind == "int8":
                payload_ndim, has_meta = 1, True
            elif kind == "uint8":
                payload_ndim = len(local_shape)
                has_meta = arr.dtype != np.uint8
            else:
                payload_ndim, has_meta = len(local_shape), False
            layout.append(_Leaf(key, kind, local_shape, arr.dtype, n,
                                has_meta, payload_ndim))
        return layout

    # -- on-device decode ---------------------------------------------------

    def decode(self, wire_batch, donate: bool = False):
        """Wire batch -> decoded distributed-buffer batch (the same layout
        ``DataParallelTrainer.shard_batch`` produces). ``donate=True`` hands
        the wire buffers to XLA (fresh-staged batches: the compact staging
        HBM is reclaimed immediately); cached batches must decode with
        ``donate=False`` so the cache entry survives."""
        fn = self._decode_jit.get(donate)
        if fn is None:
            fn = self._build_decode(donate)
            self._decode_jit[donate] = fn
        tr = obs_trace._tracer
        t0 = tr.now() if tr is not None else 0
        out = fn(wire_batch)
        if tr is not None:
            tr.complete("feed.decode", "feed", t0, donated=donate)
        return out

    def _build_decode(self, donate: bool):
        from mlsl_tpu.comm.collectives import smap

        layout, treedef = self._layout, self._treedef
        mlsl_assert(layout is not None, "decode before any staged batch")
        mesh = self.topo.mesh
        block, train_dtype = self.block, self.train_dtype
        normalize, augment = self.normalize, self.augment
        grid1 = (None,) * NUM_GRID_AXES

        in_specs = tuple(
            {
                "q": P(*GRID_AXES, *([None] * leaf.payload_ndim)),
                **({"s": P(*GRID_AXES, None)} if leaf.has_meta else {}),
            }
            for leaf in layout
        )
        out_specs = tuple(
            P(*GRID_AXES, *([None] * len(leaf.local_shape)))
            for leaf in layout
        )

        def body(wire):
            out = []
            for leaf, w in zip(layout, wire):
                q = w["q"]
                q = q.reshape(q.shape[NUM_GRID_AXES:])
                if leaf.kind == "none":
                    x = q
                elif leaf.kind == "bf16":
                    x = q.astype(train_dtype)
                elif leaf.kind == "uint8":
                    x = q.astype(jnp.float32)
                    if leaf.has_meta:
                        # (q + off) * scale — NOT q*scale + lo: see
                        # _encode_uint8 (FMA-proof decode formulation)
                        s = w["s"].reshape(-1)
                        x = (x + s[0]) * s[1]
                    if normalize is not None:
                        x = (x - normalize[0]) * normalize[1]
                    x = x.astype(train_dtype)
                else:  # int8 block codec: the gradient path's dequant kernel
                    s = w["s"].reshape(-1)
                    flat = quant_kernels.dequantize(
                        q.reshape(-1), s, block=block, orig_len=leaf.n
                    )
                    x = flat.reshape(leaf.local_shape).astype(train_dtype)
                out.append(x[grid1])
            return tuple(out)

        sm = smap(body, mesh, in_specs=(in_specs,), out_specs=out_specs,
                  check=False)

        def fn(wire):
            decoded = sm(wire)
            batch = jax.tree_util.tree_unflatten(treedef, list(decoded))
            if augment is not None:
                batch = augment(batch)
            return batch

        return jax.jit(fn, donate_argnums=(0,) if donate else ())
