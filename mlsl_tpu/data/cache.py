"""HBM-resident dataset cache: epoch replays skip the host->device link.

Small/synthetic datasets (bench loops, eval sets, distillation corpora) are
re-shipped over the slow h2d link every epoch even though they fit in device
HBM many times over. This cache pins WIRE-format batches (compact: a uint8
image batch costs 4x less HBM than its decoded f32 form) on first touch,
under an ``MLSL_FEED_CACHE_MB`` budget; a replayed epoch decodes straight
from HBM — zero wire bytes.

Eviction policy: admission-capped, no eviction. Epoch replay touches every
entry exactly once per epoch, so evicting entry A to admit entry B converts
A's future hits into misses one-for-one — LRU would just rotate the misses.
A batch that does not fit is simply not cached (counted as a reject) and
keeps streaming over the wire.

Budget accounting uses global logical bytes (`.nbytes` over the sharded wire
arrays); per-device HBM is that divided by the data-parallel degree for
batch-sharded leaves.

:class:`AdmissionBudget` is the accounting core, factored out so the paged
KV cache (serve/kv_cache.py) rides the same admit-or-reject contract —
serving breaks the replay-touches-everything-once assumption above, so the
KV side adds a free-list and eviction ON TOP of this budget rather than
changing the feed cache's admission-capped policy.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from mlsl_tpu.obs import tracer as obs_trace


class AdmissionBudget:
    """Byte-budget admission accounting: admit-or-reject against a fixed
    budget, with release for allocators that free. The feed cache never
    releases (admission-capped by design); the paged KV cache does, on
    sequence retirement and eviction."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self.bytes = 0
        self.rejects = 0

    def admit(self, nbytes: int) -> bool:
        """Reserve ``nbytes`` if the budget allows; False = rejected (and
        counted — a rejected admission is news, a granted one is not)."""
        if self.bytes + nbytes > self.budget_bytes:
            self.rejects += 1
            return False
        self.bytes += nbytes
        return True

    def release(self, nbytes: int) -> None:
        """Return ``nbytes`` to the budget (KV retirement/eviction path)."""
        self.bytes = max(0, self.bytes - nbytes)


class FeedCache(AdmissionBudget):
    """Wire-batch cache keyed by position-in-epoch."""

    def __init__(self, budget_mb: float):
        super().__init__(int(budget_mb * (1 << 20)))
        self._slots: Dict[int, object] = {}
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._slots)

    def get(self, key: int):
        """Cached wire batch or None. Counts hits/misses into FEED_COUNTERS
        and drops a ``feed.cache_hit`` instant on the obs timeline."""
        from mlsl_tpu.core import stats

        item = self._slots.get(key)
        if item is None:
            self.misses += 1
            stats.record_feed_cache("miss")
            return None
        self.hits += 1
        stats.record_feed_cache("hit")
        tr = obs_trace._tracer
        if tr is not None:
            tr.instant("feed.cache_hit", "feed", batch=key)
        return item

    def put(self, key: int, wire_batch) -> bool:
        """Admit a staged wire batch if the budget allows; False = rejected
        (the caller may then donate the buffers to decode)."""
        from mlsl_tpu.core import stats

        if key in self._slots:
            return True
        nbytes = sum(
            leaf.nbytes for leaf in jax.tree.leaves(wire_batch)
        )
        if not self.admit(nbytes):
            stats.record_feed_cache("reject")
            return False
        self._slots[key] = wire_batch
        return True

    def complete(self, n: Optional[int]) -> bool:
        """True when every one of the dataset's ``n`` batches is pinned."""
        return n is not None and len(self._slots) == n

    def clear(self) -> None:
        self._slots.clear()
        self.bytes = 0
