"""Shared helpers for the feed pipeline: the wire-spec grammar, env
defaults, and the rung-2 retry gate — ONE implementation serving both
AsyncLoader (worker reads) and DeviceFeed (source reads), so the two layers
of the same recovery-ladder rung cannot drift apart. Deliberately free of
jax/numpy imports: Config.validate() parses the wire grammar through this
module without dragging in the kernel stack."""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional, Tuple

from mlsl_tpu.log import log_warning

#: canonical wire kinds; spec strings may use the aliases below
WIRE_KINDS = ("none", "bf16", "uint8", "int8")

_KIND_ALIASES = {
    "": "none", "none": "none", "f32": "none", "float32": "none", "off": "none",
    "bf16": "bf16", "bfloat16": "bf16",
    "uint8": "uint8", "u8": "uint8",
    "int8": "int8", "i8": "int8",
}


def parse_wire_spec(spec: Optional[str]) -> Tuple[str, Dict[str, str]]:
    """``MLSL_FEED_WIRE_DTYPE`` grammar -> (default kind, per-leaf overrides).

    ``"uint8"`` applies uint8 to every eligible leaf; ``"uint8,y=none"`` or
    ``"x=uint8"`` override single leaves. Leaf names are flattened tree paths
    (``"0"``, ``"1"``, dict keys joined with ``.``); ``x``/``y`` additionally
    alias the first/second leaf of the canonical (x, y) batch TUPLE — the
    alias is resolved at lookup against positional keys only, so a dict
    batch whose key is literally ``"x"`` matches its own name, never the
    alias. Unknown kinds or malformed entries raise ValueError
    (Config.validate turns that into an MLSLError at init)."""
    default = "none"
    overrides: Dict[str, str] = {}
    for entry in filter(None, (e.strip() for e in (spec or "").split(","))):
        name, sep, kind = entry.partition("=")
        if not sep:
            name, kind = None, entry
        k = _KIND_ALIASES.get(kind.strip().lower())
        if k is None:
            raise ValueError(
                f"unknown feed wire dtype {kind!r} in {spec!r}; "
                f"known: {sorted(set(_KIND_ALIASES))}"
            )
        if name is None:
            default = k
        else:
            overrides[name.strip()] = k
    return default, overrides


def env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def env_default(name: str, fallback):
    """Env override typed like ``fallback`` (str fallbacks pass through)."""
    v = os.environ.get(name)
    if v in (None, ""):
        return fallback
    return type(fallback)(v) if not isinstance(fallback, str) else v


def retry_or_raise(e: BaseException, attempt: int, retries: int,
                   backoff_s: float,
                   stopping: Optional[Callable[[], bool]] = None) -> int:
    """Rung-2 gate (supervisor taxonomy): sleep with exponential backoff and
    return ``attempt + 1`` for a retryable TRANSIENT failure; re-raise ``e``
    for anything else (PERSISTENT/CORRUPTION/FATAL, retries exhausted, or
    the owner shutting down)."""
    from mlsl_tpu import supervisor
    from mlsl_tpu.core import stats

    if (
        supervisor.classify(e) is not supervisor.ErrorClass.TRANSIENT
        or attempt >= retries
        or (stopping is not None and stopping())
    ):
        raise e
    attempt += 1
    delay = backoff_s * (2 ** (attempt - 1))
    stats.record_feed_retry()
    log_warning(
        "feed: transient source error (%r); retry %d/%d in %.3fs",
        e, attempt, retries, delay,
    )
    time.sleep(delay)
    return attempt
