"""Asynchronous prefetch onto the device mesh: the feed pipeline's engine.

TPU-native equivalent of the reference's endpoint-server file-IO offload
(ENABLE_FILEIO, eplib/eplib.h:51-58 fopen/fread_nb/fwait: a second command
ring lets the server stream files into shared memory while the trainer
computes). Here the "server" is a background thread and the "shared memory"
is device HBM: batches are read/encoded, sharded onto the mesh, and
transferred ahead of use so the training loop never blocks on input.

Depth-N device-side buffering: the queue holds up to ``depth`` batches whose
transfers/decodes are already dispatched — the worker blocks (backpressure)
once that many are in flight, so HBM use is bounded at depth x batch bytes.
Both sides of the queue are accounted: time the CONSUMER blocks on an empty
queue is input stall (the number the feed pipeline exists to drive to zero,
surfaced as ``input_stall_ms`` on the bench row), time the WORKER blocks on
a full queue is healthy backpressure. Both land in ``FEED_COUNTERS``.

Failure contract: a worker that dies mid-epoch surfaces its ORIGINAL
exception on the consumer's next ``__next__`` (never a hang on an empty
queue). Failures are classified through ``supervisor.classify`` first:
TRANSIENT source errors (flaky NFS reads, connection resets) retry in place
with exponential backoff under ``MLSL_FEED_RETRIES`` before anything
surfaces — the rung-2 contract of the recovery ladder, applied to the feed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, Optional

from mlsl_tpu import chaos
from mlsl_tpu.data.common import env_int as _env_int, retry_or_raise
from mlsl_tpu.log import log_warning, mlsl_assert


class AsyncLoader:
    """Wraps a host batch source with prefetch-to-device.

    source: iterator/callable yielding host batches (any pytree of np
    arrays), or a :class:`mlsl_tpu.data.DeviceFeed` (already-device batches);
    place: fn(host_batch) -> device batch (e.g. trainer.shard_batch);
    None = identity (the source already places);
    depth: batches kept in flight (default ``MLSL_FEED_DEPTH``, 2 = classic
    double buffering);
    retries: TRANSIENT source-read retries per batch (default
    ``MLSL_FEED_RETRIES``).
    """

    def __init__(self, source, place: Optional[Callable] = None,
                 depth: Optional[int] = None,
                 retries: Optional[int] = None,
                 retry_backoff_s: float = 0.05):
        # A DeviceFeed splits its work across the queue: the worker runs the
        # host encode + h2d staging (_prefetch_iter), and the DECODE program
        # is dispatched by the CONSUMER (_consumer_decode) — a background
        # thread must never launch device programs concurrently with the
        # training loop's own dispatches (on the CPU proof mesh that
        # cross-thread interleaving starves the collective rendezvous and
        # wedges the per-layer trainer).
        self._finalize = getattr(source, "_consumer_decode", None)
        # A DeviceFeed source also runs its own data.prefetch injection AND
        # its own TRANSIENT-retry loop per read (see below) — capture the
        # hint before the source is swapped for its wire stream.
        self._inject = getattr(source, "_chaos_site", None) != "data.prefetch"
        if self._finalize is not None and hasattr(source, "_prefetch_iter"):
            mlsl_assert(
                place is None,
                "AsyncLoader: place must be None for a DeviceFeed source — "
                "the feed already places and decodes its batches (got %r)",
                place,
            )
            source = source._prefetch_iter()
        self._source = iter(source) if not callable(source) else None
        self._source_fn = source if callable(source) else None
        self._place = place
        self._depth = max(1, depth if depth is not None
                          else _env_int("MLSL_FEED_DEPTH", 2))
        # Firing the chaos site here too would double every armed plan's hit
        # count, and re-retrying an error the feed already retried would
        # call next() on a generator that just raised — which yields
        # StopIteration and silently truncates the stream instead of
        # surfacing the failure.
        self._retries = (
            (retries if retries is not None
             else _env_int("MLSL_FEED_RETRIES", 2))
            if self._inject else 0
        )
        self._retry_backoff_s = retry_backoff_s
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._done = False
        self._exc: Optional[BaseException] = None
        self._batches = 0  # descriptor for the join-timeout warning in close()
        self._stall_s = 0.0          # consumer blocked on empty queue
        self._producer_wait_s = 0.0  # worker blocked on full queue (healthy)
        self._consumed = 0
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name=f"mlsl-prefetch-{id(self):x}"
        )
        self._thread.start()

    def _next_host_batch(self):
        if self._source_fn is not None:
            return self._source_fn()
        return next(self._source)

    def _retry_or_raise(self, e: BaseException, attempt: int) -> int:
        return retry_or_raise(e, attempt, self._retries,
                              self._retry_backoff_s, self._stop.is_set)

    def _read_with_retries(self):
        """One batch read, with the chaos site and the rung-2 retry loop.

        Only re-attemptable reads retry: a CALLABLE source can simply be
        called again, and a chaos-site fault fires before the source is
        touched, so both are safe. A generator/iterator source whose frame
        raised is DEAD — next() on it returns StopIteration, so a "retry"
        would silently truncate the stream instead of surfacing the error;
        its failures propagate immediately with the original exception."""
        attempt = 0
        while True:
            if self._inject and chaos._plans:
                try:
                    chaos.inject("data.prefetch", batch=self._batches)
                except BaseException as e:
                    attempt = self._retry_or_raise(e, attempt)
                    continue
            try:
                return self._next_host_batch()
            except StopIteration:
                raise
            except BaseException as e:
                if self._source_fn is None:
                    raise  # iterator source: not re-attemptable (see above)
                attempt = self._retry_or_raise(e, attempt)

    def _worker(self):
        try:
            while not self._stop.is_set():
                try:
                    host = self._read_with_retries()
                except StopIteration:
                    self._q.put(_SENTINEL)
                    return
                self._batches += 1
                # placement dispatches the transfer asynchronously; holding
                # the resulting arrays in the queue keeps `depth` transfers
                # in flight (device-side buffering, bounded HBM)
                if self._place is None:
                    dev = host
                else:
                    dev = (self._place(*host) if isinstance(host, tuple)
                           else self._place(host))
                t0 = time.perf_counter()
                self._q.put(dev)
                waited = time.perf_counter() - t0
                self._producer_wait_s += waited
                if waited > 1e-4:  # actual backpressure, not queue overhead
                    from mlsl_tpu.core import stats

                    stats.record_feed_wait(waited * 1e3)
        except BaseException as e:  # surface worker failures to the consumer
            self._exc = e
            self._q.put(_SENTINEL)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            # stay exhausted instead of blocking on an empty queue forever
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            # input stall: the training loop is about to wait on its feed
            t0 = time.perf_counter()
            item = self._q.get()
            stall = time.perf_counter() - t0
            self._stall_s += stall
            from mlsl_tpu.core import stats

            stats.record_feed_stall(stall * 1e3)
        if item is _SENTINEL:
            self._done = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        self._consumed += 1
        if self._finalize is not None:
            # consumer-thread decode (DeviceFeed): the device program is
            # dispatched here, in deterministic order with the training
            # loop's own dispatches
            item = self._finalize(item)
        return item

    def stats(self) -> dict:
        """Backpressure accounting for this loader: batches produced/consumed,
        consumer input-stall and producer backpressure-wait totals (ms)."""
        return {
            "depth": self._depth,
            "produced": self._batches,
            "consumed": self._consumed,
            "in_flight": self._q.qsize(),
            "stall_ms": self._stall_s * 1e3,
            "producer_wait_ms": self._producer_wait_s * 1e3,
        }

    def close(self) -> None:
        self._stop.set()
        # drain so the worker is not blocked on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # The worker is wedged in the source or the device transfer —
            # abandoning it silently would hide the leak until HBM or file
            # handles run out.
            log_warning(
                "prefetch thread %s still alive after 5s join "
                "(was serving batch %d); abandoning it",
                self._thread.name,
                self._batches,
            )


_SENTINEL = object()
