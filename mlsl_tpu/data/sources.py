"""Host batch sources: the producers the feed pipeline pulls from.

The analog of the reference's endpoint-server file reads (EPLIB_fopen/
fread_nb, eplib/eplib.h:51-58): a source yields host batches; the loader's
worker thread performs the disk read AND the host->device transfer while the
trainer computes, so the training loop never blocks on IO.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def file_source(paths, epochs: Optional[int] = 1):
    """Stream (x, y) batches from ``.npz`` files (keys 'x' and 'y').
    ``epochs=None`` cycles forever."""
    paths = list(paths)  # a one-shot iterable must survive multiple epochs
    e = 0
    while epochs is None or e < epochs:
        for p in paths:
            with np.load(p) as z:
                yield z["x"], z["y"]
        e += 1


def synthetic_source(batch: int, shape, num_classes: int, seed: int = 0,
                     steps: Optional[int] = None, dtype=np.float32):
    """Deterministic synthetic (x, y) batches (the reference tests likewise use
    generated algebraic data rather than real datasets). Pass
    dtype=ml_dtypes.bfloat16 to cast on the host — or, better, feed through
    :class:`mlsl_tpu.data.DeviceFeed` with a wire dtype, which also moves the
    cast/normalize work onto the device (docs/DESIGN.md 'Device feed
    pipeline')."""
    rng = np.random.default_rng(seed)
    produced = 0
    while steps is None or produced < steps:
        x = rng.normal(size=(batch, *shape)).astype(dtype)
        y = rng.integers(0, num_classes, size=(batch,)).astype(np.int32)
        produced += 1
        yield x, y
