"""DeviceFeed: the wire-compressed, HBM-cached, epoch-aware device feed.

Composes the three feed-pipeline pieces over one dataset:

1. :class:`mlsl_tpu.data.wire.FeedCodec` — host batches cross the h2d link
   in the configured wire dtype and a jitted on-device decode restores the
   training dtype;
2. :class:`mlsl_tpu.data.cache.FeedCache` — wire batches pin in HBM under
   ``MLSL_FEED_CACHE_MB``; epoch replays decode straight from HBM (zero wire
   bytes);
3. epoch bookkeeping — per-epoch shuffle from a fixed seed, identical with
   the cache on or off (parity pinned by tests/test_feed.py), so enabling
   the cache is a pure transport optimization, never a data change.

Iteration yields DECODED distributed-buffer batches — the same layout
``DataParallelTrainer.shard_batch`` produces — so ``trainer.step`` consumes
them unchanged. Wrap in :class:`mlsl_tpu.data.AsyncLoader` (or use
``DataParallelTrainer.feed``) for background prefetch.

Source forms:

- a **sequence** of host batches (list/tuple): random access — per-epoch
  shuffle works with or without the cache;
- a **callable** returning a fresh iterator per epoch (e.g.
  ``lambda: synthetic_source(...)``): sequential replay — once the cache
  holds the full epoch the source is never consulted again;
- a **one-shot iterator**: epoch 0 streams it; later epochs replay from the
  cache and raise MLSLError if the cache does not hold the full dataset.

``shuffle_seed`` requires a sequence source: shuffle is a property of the
FEED, so it must produce the same order whether batches come over the wire
or out of the cache — a streaming source cannot be replayed out of order.

The ``data.prefetch`` chaos site fires per batch read (error/delay/hang act
in place; ``bitrot`` corrupts the encoded wire payload so a bad host read
flows through the codec + cache paths instead of crashing them).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence, Tuple

import jax.numpy as jnp

from mlsl_tpu import chaos
from mlsl_tpu.data.cache import FeedCache
from mlsl_tpu.data.common import env_default as _env_default, retry_or_raise
from mlsl_tpu.data.wire import FeedCodec
from mlsl_tpu.log import MLSLError, mlsl_assert


class DeviceFeed:
    """One dataset's wire-compressed device feed (see module docstring).

    epochs: passes over the source (None = cycle forever);
    shuffle_seed: per-epoch deterministic batch-order shuffle (None = in
    order; sequence sources only); wire/cache_mb/retries default from
    ``MLSL_FEED_WIRE_DTYPE`` / ``MLSL_FEED_CACHE_MB`` (0 = no cache) /
    ``MLSL_FEED_RETRIES``; normalize/train_dtype/augment/quant_block pass
    through to :class:`FeedCodec`.
    """

    #: AsyncLoader reads this to avoid double-firing the chaos site
    _chaos_site = "data.prefetch"

    def __init__(self, source, topology, *,
                 wire: Optional[str] = None,
                 cache_mb: Optional[float] = None,
                 epochs: Optional[int] = 1,
                 shuffle_seed: Optional[int] = None,
                 normalize: Optional[Tuple] = None,
                 train_dtype=jnp.float32,
                 augment: Optional[Callable] = None,
                 quant_block: Optional[int] = None,
                 retries: Optional[int] = None):
        if wire is None:
            wire = os.environ.get("MLSL_FEED_WIRE_DTYPE", "")
        if cache_mb is None:
            cache_mb = float(_env_default("MLSL_FEED_CACHE_MB", 0.0))
        self.codec = FeedCodec(
            topology, wire, normalize=normalize, train_dtype=train_dtype,
            augment=augment, quant_block=int(quant_block or 256),
        )
        self.cache = FeedCache(cache_mb) if cache_mb > 0 else None
        self.epochs = epochs
        self.shuffle_seed = shuffle_seed
        self.retries = (retries if retries is not None
                        else int(_env_default("MLSL_FEED_RETRIES", 2)))
        self._seq: Optional[Sequence] = (
            source if isinstance(source, (list, tuple)) else None
        )
        self._factory = source if callable(source) else None
        self._iter = (iter(source)
                      if self._seq is None and self._factory is None else None)
        self._n: Optional[int] = (
            len(self._seq) if self._seq is not None else None
        )
        mlsl_assert(
            shuffle_seed is None or self._seq is not None,
            "DeviceFeed: shuffle_seed requires a sequence source (random "
            "access) — a streaming source cannot replay out of order",
        )
        self._gen = self._drive(self._serve)

    # -- epoch machinery ----------------------------------------------------

    def _order(self, epoch: int):
        """Batch visit order for one epoch. The SAME order with the cache on
        or off: shuffling is a property of the feed, the cache only changes
        where the bytes come from."""
        if self.shuffle_seed is None:
            return range(self._n)
        import numpy as np

        rng = np.random.default_rng((self.shuffle_seed, epoch))
        return rng.permutation(self._n)

    def _retry_or_raise(self, e: BaseException, attempt: int) -> int:
        return retry_or_raise(e, attempt, self.retries, 0.05)

    def _read_host(self, index: Optional[int], it):
        """One host batch (sequence index, or iterator step), with the chaos
        site and the TRANSIENT-retry loop (rung 2 of the recovery ladder,
        applied to the feed). Returns (host_batch, bitrot_fired).

        Only re-attemptable reads retry: a sequence index can be fetched
        again, and a chaos-site fault fires before the source is touched. An
        ITERATOR whose frame raised is dead — next() on it would yield
        StopIteration, which ``_drive`` reads as a (truncated!) end of epoch
        and would pin ``self._n`` to the short length forever — so iterator
        failures propagate immediately with the original exception."""
        attempt = 0
        while True:
            fired = None
            if chaos._plans:
                try:
                    fired = chaos.inject("data.prefetch", batch=index)
                except BaseException as e:
                    attempt = self._retry_or_raise(e, attempt)
                    continue
            try:
                host = self._seq[index] if it is None else next(it)
            except StopIteration:
                raise
            except BaseException as e:
                if it is not None:
                    raise  # dead iterator: not re-attemptable (see above)
                attempt = self._retry_or_raise(e, attempt)
                continue
            return host, (fired is not None and fired.kind == "bitrot")

    def _serve(self, key: int, it):
        """One decoded batch: a cache hit decodes from HBM; a miss reads the
        source, stages over the wire, and pins the wire batch if the budget
        allows. Fresh-staged batches that did NOT get cached donate their
        wire buffers to decode (the staging HBM is reclaimed immediately).

        A STREAMING epoch (``it`` not None) always advances the iterator
        first — a partially-cached epoch must stay aligned with the source —
        and the cache then only short-circuits the h2d transfer; random
        access (``it`` None) skips the host read entirely on a hit."""
        wire_batch, donate = self._serve_wire(key, it)
        return self._checked_decode(wire_batch, donate)

    def _checked_decode(self, wire_batch, donate):
        """Decode + the CHKP boundary: under MLSL_CHKP=2 every float leaf of
        the decoded batch is finiteness-verified (one batched device sync —
        mlsl_tpu.checker) so a wire-codec or cache fault that produced
        garbage surfaces at the decode boundary, not three layers later as a
        poisoned gradient."""
        batch = self.codec.decode(wire_batch, donate=donate)
        from mlsl_tpu import checker

        lvl = checker.level()
        if lvl >= checker.CHKP_VALUES:
            checker.check_feed_batch(batch, lvl)
        return batch

    @property
    def cache_complete(self) -> bool:
        return (self.cache is not None and self._n is not None
                and self.cache.complete(self._n))

    def _stream_iter(self, epoch: int):
        if self._factory is not None:
            return iter(self._factory())
        if epoch == 0:
            return self._iter
        raise MLSLError(
            "DeviceFeed: source is a one-shot iterator and the feed cache "
            "does not hold the full dataset (%d of %s batches cached) — "
            "epoch %d cannot replay. Pass a sequence / factory source or "
            "raise MLSL_FEED_CACHE_MB." % (
                0 if self.cache is None else len(self.cache), self._n, epoch,
            )
        )

    def _serve_wire(self, key: int, it):
        """The wire half of :meth:`_serve`: -> (wire_batch, donate). Runs on
        whatever thread drives the stream (the AsyncLoader worker under
        prefetch); the DECODE program is dispatched separately so a
        background thread never launches device programs concurrently with
        the training loop's own dispatches — on the CPU proof mesh that
        cross-thread interleaving starves the collective rendezvous
        (observed wedging the 8-dev per-layer trainer)."""
        if it is not None:
            host, rot = self._read_host(None, it)
            # a fired bitrot must corrupt what is SERVED: skip the cache
            # shortcut so the rotted read flows through stage+decode (the
            # pinned clean copy is kept — transient rot, not a poisoned pin)
            if self.cache is not None and not rot:
                cached = self.cache.get(key)
                if cached is not None:
                    return cached, False
        else:
            if self.cache is not None:
                cached = self.cache.get(key)
                if cached is not None:
                    return cached, False
            host, rot = self._read_host(key, None)
        wire_batch, _, _ = self.codec.stage(host, corrupt=rot)
        kept = self.cache is not None and self.cache.put(key, wire_batch)
        return wire_batch, not kept

    def _consumer_decode(self, item):
        """Decode hook the AsyncLoader applies on the CONSUMER thread (see
        _serve_wire): (wire_batch, donate) -> decoded batch."""
        wire_batch, donate = item
        return self._checked_decode(wire_batch, donate)

    def _prefetch_iter(self):
        """Wire-batch stream for AsyncLoader prefetch: the worker runs the
        host encode + h2d staging ahead of use, the consumer dispatches
        decode."""
        return self._drive(self._serve_wire)

    def _drive(self, emit):
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            if self._seq is not None:
                for i in self._order(epoch):
                    yield emit(int(i), None)
            elif self.cache_complete:
                # full epoch pinned in HBM: the source is never touched again
                for i in range(self._n):
                    yield emit(i, None)
            else:
                it = self._stream_iter(epoch)
                i = 0
                while True:
                    try:
                        item = emit(i, it)
                    except StopIteration:
                        break
                    yield item
                    i += 1
                if self._n is None:
                    self._n = i
                else:
                    mlsl_assert(
                        self._n == i,
                        "source epoch length changed (%d, then %d)",
                        self._n, i,
                    )
            epoch += 1

    def __iter__(self):
        return self._gen

    def __next__(self):
        return next(self._gen)
