"""Device feed pipeline: wire-compressed h2d transfer, on-device decode,
prefetch, and an HBM-resident dataset cache.

Grown from the original single-module ``mlsl_tpu.data`` (background prefetch
only — the TPU analog of the reference's endpoint-server file-IO offload,
ENABLE_FILEIO / eplib fread_nb) into a package that also minimizes BYTES on
the host->device link and hides what remains under compute:

- :mod:`mlsl_tpu.data.wire`    — wire codecs (uint8 / bf16 / int8 block
  codec shared with the quantized collectives), sharded zero-staging
  placement, jitted on-device decode (``FeedCodec``);
- :mod:`mlsl_tpu.data.cache`   — HBM-resident dataset cache
  (``MLSL_FEED_CACHE_MB``): epoch replays skip h2d entirely;
- :mod:`mlsl_tpu.data.feed`    — ``DeviceFeed``, composing codec + cache +
  epoch/shuffle bookkeeping;
- :mod:`mlsl_tpu.data.loader`  — ``AsyncLoader``, depth-N device-side
  buffering with backpressure accounting and supervised retry
  (``MLSL_FEED_DEPTH`` / ``MLSL_FEED_RETRIES``);
- :mod:`mlsl_tpu.data.sources` — host batch sources (``file_source``,
  ``synthetic_source``).

See docs/DESIGN.md "Device feed pipeline" and docs/TUNING.md §12.
"""

# Lazy exports (PEP 562): importing the package — or its dependency-free
# submodules (data.common, which Config.validate uses for the wire-spec
# grammar) — must not drag in the jax/numpy/Pallas kernel stack behind
# wire.py. Submodules load on first attribute access.
_EXPORTS = {
    "AsyncLoader": "mlsl_tpu.data.loader",
    "DeviceFeed": "mlsl_tpu.data.feed",
    "FeedCache": "mlsl_tpu.data.cache",
    "FeedCodec": "mlsl_tpu.data.wire",
    "WIRE_KINDS": "mlsl_tpu.data.common",
    "parse_wire_spec": "mlsl_tpu.data.common",
    "file_source": "mlsl_tpu.data.sources",
    "synthetic_source": "mlsl_tpu.data.sources",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)


def __dir__():
    return sorted(set(globals()) | set(__all__))
