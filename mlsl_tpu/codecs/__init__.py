"""Codec lab: the pluggable gradient-compression registry (ROADMAP #4).

One declared contract subsumes every compressed wire the comm layer speaks:

  - ``encode(x) -> wire``: f32 ``(n,)`` chunk -> self-contained uint8 wire
    image (indices, masks, scales, codebooks — everything decode needs).
  - ``decode(wire, n) -> x_hat``: inverse; always f32 ``(n,)``.
  - ``wire_dtype`` / ``wire_len(n)``: the on-wire element type and count, the
    honest byte accounting behind per-codec wire stats and the tuner's
    bandwidth model.
  - ``geometry(n)``: a static dict the analysis verifier pins (A115/A116
    siblings of the quant-geometry codes) — codebook/index alignment for VQ,
    mask-length == chunk for pruning.
  - ``aggregate(a, b)`` (optional): THC-class compressed-domain sum — two
    wire images in, one wire image out, no dequantize on the hop (the ring
    folds partials through it; arXiv:2302.08545).
  - ``hier_aggregate(xq, ...)`` (optional override): the two-tier DCN hop.
    The base implementation is generic (encode, gather wires, fold through
    ``aggregate`` when present else decode-and-sum), which makes EVERY
    registered codec DCN-eligible; int8/topk override it with the seed's
    bit-exact shared-scale / shared-mask forms.

Error feedback is owned by the transport (comm/codec.py entry EF), not the
codec: a codec is a pure ``encode``/``decode`` pair and the residual
``x - decode(encode(x))`` carries to the next round with the same
snapshot/rewind and degrade-flush contracts as the seed int8 path.

The registry also hosts the convergence guardrail for calibrated
assignments (tuner/calibrate.py): requests running a calibrated non-int8
codec register here; the sentinel's loss z-score screen feeds
``guard_note`` and a sustained breach demotes every registered set to int8
— one DEGRADE-ladder rung with an exactly-once EF flush, pinned bit-exact
like every other fallback.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.log import mlsl_assert

__all__ = [
    "Codec", "register", "get", "names", "configure", "assigned",
    "guard_register", "guard_unregister", "guard_note", "guard_reset",
    "guard_status", "status",
]


def _bytes_of_f32(x: jax.Array) -> jax.Array:
    """f32 (...,) -> uint8 (...*4,) little-endian byte image."""
    return lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint8).reshape(-1)


def _f32_of_bytes(w: jax.Array, n: int) -> jax.Array:
    """uint8 (4n,) byte image -> f32 (n,)."""
    return lax.bitcast_convert_type(w.reshape(n, 4), jnp.float32)


class Codec:
    """Base contract. Subclasses set ``name`` and implement encode/decode;
    everything else has a generic default. Instances are immutable after
    construction (they are cached and shared across requests)."""

    name: str = "?"
    wire_dtype: str = "uint8"
    #: True when decode(encode(x)) == x bitwise for every finite f32 input
    #: (the registry's exact-sum parity class; f32 and ratio-1 prune)
    lossless: bool = False

    #: optional compressed-domain pairwise sum (THC hook); None = the
    #: transport decodes-and-adds each hop and EF absorbs the difference
    aggregate: Optional[Callable] = None

    def __init__(self) -> None:
        self._custom = None

    # -- identity ----------------------------------------------------------

    def knob_key(self) -> Tuple:
        """Hashable identity of this configured instance (cache key)."""
        return (self.name,)

    # -- wire --------------------------------------------------------------

    def encode(self, x: jax.Array) -> jax.Array:
        raise NotImplementedError

    def decode(self, wire: jax.Array, n: int) -> jax.Array:
        raise NotImplementedError

    def wire_len(self, n: int) -> int:
        """Wire elements (uint8 bytes) for an n-element f32 chunk."""
        raise NotImplementedError

    def wire_bytes(self, n: int) -> int:
        return self.wire_len(n)  # uint8 wire: elements == bytes

    def geometry(self, n: int) -> dict:
        """Static geometry the verifier pins (analysis/plan.py A115/A116)."""
        return {"codec": self.name, "chunk": int(n),
                "wire_len": int(self.wire_len(n))}

    # -- hier DCN hop ------------------------------------------------------

    def hier_aggregate(self, xq: jax.Array, *, axis, inter, t: int):
        """One inter-slice hop of the two-tier lowering: compress the local
        (slen,) shard, exchange wires across the t slice-peers, return the
        reduced shard and the entry EF residual. Generic form; codecs with
        a cheaper shared-statistics exchange override it."""
        n = xq.shape[0]
        w = self.encode(xq)
        xhat = self.decode(w, n)
        new_err = xq - xhat
        if t == 1:
            return xhat, new_err
        # mlsl-lint: disable=A201 -- the DCN-hop wire exchange runs INSIDE
        # the hier collective program (comm/algos/hier.py dcn_hop); the
        # engine routed here, there is no outer collective to defer to
        gathered = lax.all_gather(w, axis, axis_index_groups=inter)
        if self.aggregate is not None:
            acc = gathered[0]
            for i in range(1, t):  # t is static: unrolled compressed fold
                acc = self.aggregate(acc, gathered[i])
            red = self.decode(acc, n)
        else:
            red = self.decode(gathered[0], n)
            for i in range(1, t):
                red = red + self.decode(gathered[i], n)
        return red, new_err

    # -- transport adapter -------------------------------------------------

    def as_custom(self):
        """Wrap as a comm.codec.CustomCodec so build_custom_collective
        supplies the full ring/EF/degrade/chaos machinery. Cached per
        instance: the CustomCodec program cache must persist."""
        if self._custom is None:
            from mlsl_tpu.comm.codec import CustomCodec

            self._custom = CustomCodec(
                compress=self.encode,
                decompress=self.decode,
                reduce=self.aggregate,
                name=f"registry:{self.name}",
            )
        return self._custom


# -- registry ----------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}
_INSTANCES: Dict[Tuple, Codec] = {}
_ILOCK = threading.Lock()


def register(cls):
    """Class decorator: add a Codec subclass to the registry by its name."""
    mlsl_assert(
        isinstance(cls.name, str) and cls.name not in ("", "?"),
        "codec class %s must set a name", cls,
    )
    _REGISTRY[cls.name] = cls
    return cls


def names() -> Tuple[str, ...]:
    _ensure_builtin()
    return tuple(sorted(_REGISTRY))


def get(name: str, **knobs) -> Codec:
    """Cached codec instance for (name, knobs); default knobs when omitted."""
    _ensure_builtin()
    mlsl_assert(
        name in _REGISTRY,
        "unknown codec %r (registry: %s)", name, ", ".join(sorted(_REGISTRY)),
    )
    probe = _REGISTRY[name](**knobs)
    key = probe.knob_key()
    with _ILOCK:
        inst = _INSTANCES.get(key)
        if inst is None:
            inst = _INSTANCES[key] = probe
    return inst


def configure(name: str, config=None, cell: Optional[dict] = None) -> Codec:
    """Codec instance with knobs resolved from a calibration cell (first)
    then the session Config (MLSL_* knobs), then codec defaults."""
    cell = cell or {}
    params = cell.get("params", {}) or {}

    def pick(key, cfg_attr, default):
        if key in params:
            return params[key]
        if config is not None and cfg_attr:
            return getattr(config, cfg_attr, default)
        return default

    if name == "int8":
        block = cell.get("block") or pick("block", "quant_block_elems", 256)
        return get("int8", block=int(block))
    if name == "vq":
        import numpy as np

        cb = params.get("codebook")
        return get(
            "vq",
            dim=int(pick("vq_dim", "vq_dim", 4)),
            k=int(pick("vq_codebook", "vq_codebook", 16)),
            codebook=np.asarray(cb, dtype=np.float32) if cb is not None else None,
        )
    if name == "prune":
        return get("prune", ratio=float(pick("ratio", "prune_ratio", 0.05)))
    if name == "topk":
        return get("topk", ratio=float(pick("ratio", "topk_ratio", 0.01)))
    return get(name)


def _ensure_builtin() -> None:
    # import-cycle-free lazy registration of the shipped members; Python's
    # module cache makes repeat calls free
    from mlsl_tpu.codecs import prune, vq  # noqa: F401  (register on import)


# -- built-in members: the seed trio behind the contract ---------------------


@register
class Int8Codec(Codec):
    """Blockwise int8 (the seed default): per-block max-abs scale, RNE round
    (ops/quant_kernels.py reference semantics). Wire = int8 payload bytes ++
    f32 scale bytes. The hier hop overrides with the seed's shared-scale
    integer-sum exchange — the THC special case the registry generalizes."""

    name = "int8"

    def __init__(self, block: int = 256) -> None:
        super().__init__()
        mlsl_assert(block >= 1, "int8 codec block must be >= 1 (got %r)", block)
        self.block = int(block)

    def knob_key(self):
        return ("int8", self.block)

    def _nb(self, n: int) -> int:
        return -(-n // self.block)

    def wire_len(self, n: int) -> int:
        return self._nb(n) * self.block + 4 * self._nb(n)

    def geometry(self, n: int) -> dict:
        g = super().geometry(n)
        g.update(block=self.block, n_blocks=self._nb(n))
        return g

    def encode(self, x: jax.Array) -> jax.Array:
        from mlsl_tpu.ops.quant_kernels import quantize_blocks_ref

        n = x.shape[0]
        nb = self._nb(n)
        x2 = jnp.pad(x.astype(jnp.float32), (0, nb * self.block - n))
        q, s = quantize_blocks_ref(x2.reshape(nb, self.block))
        return jnp.concatenate(
            [lax.bitcast_convert_type(q, jnp.uint8).reshape(-1), _bytes_of_f32(s)]
        )

    def decode(self, wire: jax.Array, n: int) -> jax.Array:
        from mlsl_tpu.ops.quant_kernels import dequantize_blocks_ref

        nb = self._nb(n)
        body = nb * self.block
        q = lax.bitcast_convert_type(wire[:body], jnp.int8)
        s = _f32_of_bytes(wire[body:body + 4 * nb], nb)
        return dequantize_blocks_ref(q.reshape(nb, self.block), s).reshape(-1)[:n]

    def hier_aggregate(self, xq, *, axis, inter, t):
        from mlsl_tpu.comm.algos import hier

        return hier._block_quant_shared(xq, self.block, axis, inter, t)


@register
class F32Codec(Codec):
    """Identity byte-image codec: the dense wire expressed in registry terms.
    Lossless, and its ``aggregate`` is an exact compressed-domain f32 add —
    the simplest THC member, and the contract the dlopen ``reduce_sum_fn``
    of a user CustomCodec plugs into."""

    name = "f32"
    lossless = True

    def knob_key(self):
        return ("f32",)

    def wire_len(self, n: int) -> int:
        return 4 * n

    def encode(self, x: jax.Array) -> jax.Array:
        return _bytes_of_f32(x)

    def decode(self, wire: jax.Array, n: int) -> jax.Array:
        return _f32_of_bytes(wire, n)

    def aggregate(self, a: jax.Array, b: jax.Array) -> jax.Array:
        n = a.shape[0] // 4
        return _bytes_of_f32(_f32_of_bytes(a, n) + _f32_of_bytes(b, n))

    def hier_aggregate(self, xq, *, axis, inter, t):
        from mlsl_tpu.comm.algos import hier

        red = hier._inter_sum(xq, axis, inter) if t > 1 else xq
        return red, jnp.zeros_like(xq)  # dense hop: residual fully drained


# -- assignment resolution ---------------------------------------------------


def assigned(config, req_name: str) -> Tuple[str, Optional[dict], str]:
    """Resolve the codec for a QUANTIZATION-compressed request.

    Precedence (docs/TUNING.md §22): explicit ``MLSL_CODEC`` env >
    calibrated per-set assignment (``config.codec_assignment``, written by
    tuner/calibrate.py under ``MLSL_TUNE_CODEC=1``) > programmatic
    ``config.codec`` > the seed default int8. Returns
    ``(name, cell_or_None, source)`` where source is one of
    env/calibrated/config/default."""
    if config is None:
        return "int8", None, "default"
    forced = getattr(config, "codec", "") or ""
    explicit = getattr(config, "_explicit", ()) or ()
    if forced and "codec" in explicit:
        return forced, None, "env"
    asn = getattr(config, "codec_assignment", None) or {}
    cell = asn.get(req_name)
    if isinstance(cell, dict) and cell.get("codec"):
        return str(cell["codec"]), cell, "calibrated"
    if forced:
        return forced, None, "config"
    return "int8", None, "default"


# -- convergence guardrail (sentinel loss z-score -> int8 demotion) ----------

_GLOCK = threading.Lock()
_GUARDED: Dict[int, "weakref.ReferenceType"] = {}
_BREACH_STREAK = 0


def guard_register(req) -> None:
    """Register a live request running a CALIBRATED non-int8 codec; the
    sentinel's loss screen can demote it (weakref: a dropped request
    unregisters itself)."""
    with _GLOCK:
        _GUARDED[id(req)] = weakref.ref(req)


def guard_unregister(req) -> None:
    with _GLOCK:
        _GUARDED.pop(id(req), None)


def guard_active() -> bool:
    with _GLOCK:
        return any(w() is not None for w in _GUARDED.values())


def guard_note(loss_outlier: bool, *, window: int = 3, step: int = -1) -> bool:
    """One screened step's verdict from the sentinel gate. A healthy step
    resets the streak; ``window`` consecutive loss z-score breaches while a
    calibrated codec is live demote every guarded set to int8. Returns True
    when a demotion fired this call."""
    global _BREACH_STREAK
    with _GLOCK:
        live = [r for r in (w() for w in _GUARDED.values()) if r is not None]
        if not live:
            _GUARDED.clear()
            _BREACH_STREAK = 0
            return False
        if not loss_outlier:
            _BREACH_STREAK = 0
            return False
        _BREACH_STREAK += 1
        from mlsl_tpu.core import stats as stats_mod

        stats_mod.record_codec("guard_breaches")
        if _BREACH_STREAK < max(1, int(window)):
            return False
        _GUARDED.clear()
        _BREACH_STREAK = 0
    reason = f"sentinel loss z-score breach x{window} (step {step})"
    for req in live:
        req.demote_codec(reason)
    return True


def guard_reset() -> None:
    """Test/lifecycle hook: forget guarded requests and the breach streak."""
    global _BREACH_STREAK
    with _GLOCK:
        _GUARDED.clear()
        _BREACH_STREAK = 0


def guard_status() -> dict:
    with _GLOCK:
        live = [r for r in (w() for w in _GUARDED.values()) if r is not None]
        return {
            "guarded": sorted(getattr(r, "name", "?") for r in live),
            "breach_streak": _BREACH_STREAK,
        }


def status() -> dict:
    """JSON-serializable section for supervisor.status() / /healthz."""
    from mlsl_tpu.core import stats as stats_mod

    out = {"registered": list(names())}
    out.update(guard_status())
    out["counters"] = dict(stats_mod.CODEC_COUNTERS)
    out["wire_bytes"] = dict(stats_mod.CODEC_WIRE_BYTES)
    out["demotions"] = list(stats_mod.CODEC_DEMOTIONS)
    return out
