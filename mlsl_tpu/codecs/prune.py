"""Importance-weighted pruning codec: magnitude masks + EF residual carry.

Generalizes comm/sparse.py's top-k sparsification into the registry
contract: the wire is a bit-packed keep mask over the WHOLE chunk (so the
verifier can pin mask-length == chunk, the A116 geometry) followed by the
kept values in index order. Within one tensor, importance is magnitude;
the LAYER-sensitivity half of the importance product enters through the
calibrated per-set keep ratio — tuner/calibrate.py spends wire bytes where
the measured norm spectrum says the set is sensitive, and prunes hard where
it is flat. Dropped mass is carried by the transport's entry error feedback
exactly like every other lossy member.

``ratio=1.0`` keeps every element and round-trips bitwise (lossless), which
is how the exact-sum parity matrix pins this codec; ``topk`` is the same
wire at the seed sparsifier's default ratio, with the hier hop overridden
to the seed's shared-mask exchange.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from mlsl_tpu.codecs import Codec, _bytes_of_f32, _f32_of_bytes, register
from mlsl_tpu.log import mlsl_assert

_BIT_WEIGHTS = tuple(1 << b for b in range(8))


@register
class PruneCodec(Codec):
    """Bit-packed magnitude mask ++ kept f32 values."""

    name = "prune"

    def __init__(self, ratio: float = 0.05) -> None:
        super().__init__()
        mlsl_assert(0.0 < ratio <= 1.0,
                    "prune ratio must be in (0, 1] (got %r)", ratio)
        self.ratio = float(ratio)

    def knob_key(self):
        return (self.name, self.ratio)

    # -- geometry ----------------------------------------------------------

    def kept(self, n: int) -> int:
        return min(n, max(1, int(round(n * self.ratio))))

    def _mask_bytes(self, n: int) -> int:
        return -(-n // 8)

    def wire_len(self, n: int) -> int:
        return self._mask_bytes(n) + 4 * self.kept(n)

    def geometry(self, n: int) -> dict:
        g = super().geometry(n)
        g.update(mask_len=int(n), k=self.kept(n))
        return g

    # -- wire --------------------------------------------------------------

    def encode(self, x: jax.Array) -> jax.Array:
        n = x.shape[0]
        k = self.kept(n)
        nb8 = self._mask_bytes(n)
        xf = x.astype(jnp.float32)
        _, idx = lax.top_k(jnp.abs(xf), k)
        idx = jnp.sort(idx)  # decode reads values in ascending-index order
        mask = jnp.zeros((nb8 * 8,), jnp.uint32).at[idx].set(1)
        weights = jnp.asarray(_BIT_WEIGHTS, jnp.uint32)
        bits = (mask.reshape(nb8, 8) * weights).sum(axis=1).astype(jnp.uint8)
        return jnp.concatenate([bits, _bytes_of_f32(xf[idx])])

    def decode(self, wire: jax.Array, n: int) -> jax.Array:
        k = self.kept(n)
        nb8 = self._mask_bytes(n)
        bits = lax.convert_element_type(wire[:nb8], jnp.uint32)
        shifts = jnp.arange(8, dtype=jnp.uint32)
        mask = ((bits[:, None] >> shifts) & 1).reshape(-1)[:n]
        vals = _f32_of_bytes(wire[nb8:nb8 + 4 * k], k)
        rank = jnp.cumsum(mask) - 1
        return jnp.where(mask > 0, vals[jnp.clip(rank, 0, k - 1)], 0.0)

    @property
    def lossless(self) -> bool:  # type: ignore[override]
        return self.ratio >= 1.0


@register
class TopKCodec(PruneCodec):
    """The seed top-k sparsifier as a registry member: same mask+values wire
    as prune at the seed's default ratio, with the two-tier DCN hop pinned
    to the seed's shared-mask form (comm/algos/hier.py _topk_shared)."""

    name = "topk"

    def __init__(self, ratio: float = 0.01) -> None:
        super().__init__(ratio=ratio)

    def hier_aggregate(self, xq, *, axis, inter, t):
        from mlsl_tpu.comm.algos import hier

        return hier._topk_shared(xq, self.ratio, axis, inter, t)
