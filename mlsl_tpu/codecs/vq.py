"""GradiVeQ-style vector-quantization codec (arXiv:1811.03617).

Gradients are sliced into ``dim``-element vectors; each vector is assigned
to its nearest row of a ``k``-row codebook; the wire carries one index byte
per vector plus the codebook and a global scale, so decode is fully
self-contained (no side-channel state, and a hop peer needs nothing but the
wire image). Compression for dim=4, k<=256 is ~16x vs the f32 wire at
``n + 16*k + 4`` bytes per n-element chunk.

The codebook is learned OFFLINE by tuner/calibrate.py from a short gradient
sample (deterministic Lloyd iterations over max-abs-normalized vectors) and
rides in the calibration cell; an uncalibrated instance uses a fixed
deterministic default so the codec is usable standalone. Lossy in general —
entry error feedback (comm/codec.py) carries the residual — but exact
whenever the normalized input vectors are codebook rows and the scale is a
power of two, which is how the parity tests pin it.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mlsl_tpu.codecs import Codec, _bytes_of_f32, _f32_of_bytes, register
from mlsl_tpu.log import mlsl_assert


def default_codebook(k: int, dim: int) -> np.ndarray:
    """Deterministic starter codebook: a fixed-seed Gaussian cloud scaled to
    unit max-abs (inputs are normalized to max|x| == 1 before assignment),
    with row 0 pinned to the zero vector so sparse gradients round-trip
    their zero blocks exactly."""
    rng = np.random.default_rng(0)
    cb = rng.standard_normal((k, dim)).astype(np.float32)
    cb /= max(1e-12, np.max(np.abs(cb)))
    cb[0] = 0.0
    return cb


@register
class VQCodec(Codec):
    """Per-block VQ indices + codebook on the wire."""

    name = "vq"

    def __init__(self, dim: int = 4, k: int = 16,
                 codebook: Optional[np.ndarray] = None) -> None:
        super().__init__()
        mlsl_assert(1 <= dim <= 64, "vq dim must be in [1, 64] (got %r)", dim)
        mlsl_assert(2 <= k <= 256, "vq codebook size must be in [2, 256] "
                    "(one index byte per vector; got %r)", k)
        self.dim = int(dim)
        self.k = int(k)
        cb = default_codebook(self.k, self.dim) if codebook is None else (
            np.asarray(codebook, dtype=np.float32))
        mlsl_assert(cb.shape == (self.k, self.dim),
                    "vq codebook shape %r != (k=%d, dim=%d)",
                    cb.shape, self.k, self.dim)
        self.codebook = cb
        self._cb_digest = hash(cb.tobytes())

    def knob_key(self):
        return ("vq", self.dim, self.k, self._cb_digest)

    # -- geometry ----------------------------------------------------------

    def _nvec(self, n: int) -> int:
        return -(-n // self.dim)

    def wire_len(self, n: int) -> int:
        # index byte per vector ++ f32 codebook image ++ f32 scale
        return self._nvec(n) + 4 * self.k * self.dim + 4

    def geometry(self, n: int) -> dict:
        g = super().geometry(n)
        g.update(dim=self.dim, k=self.k, idx_elems=self._nvec(n),
                 codebook_elems=self.k * self.dim)
        return g

    # -- wire --------------------------------------------------------------

    def encode(self, x: jax.Array) -> jax.Array:
        n = x.shape[0]
        nv = self._nvec(n)
        xf = jnp.pad(x.astype(jnp.float32), (0, nv * self.dim - n))
        amax = jnp.max(jnp.abs(xf))
        scale = jnp.where(amax == 0, 1.0, amax).astype(jnp.float32)
        vecs = (xf / scale).reshape(nv, self.dim)
        cb = jnp.asarray(self.codebook)
        # nearest codebook row by squared distance; argmin ties break low,
        # matching the numpy oracle in the tests
        d2 = jnp.sum((vecs[:, None, :] - cb[None, :, :]) ** 2, axis=-1)
        idx = jnp.argmin(d2, axis=1).astype(jnp.uint8)
        return jnp.concatenate([
            idx,
            _bytes_of_f32(cb.reshape(-1)),
            _bytes_of_f32(scale.reshape(1)),
        ])

    def decode(self, wire: jax.Array, n: int) -> jax.Array:
        nv = self._nvec(n)
        cb_elems = self.k * self.dim
        idx = lax.convert_element_type(wire[:nv], jnp.int32)
        cb = _f32_of_bytes(wire[nv:nv + 4 * cb_elems], cb_elems)
        cb = cb.reshape(self.k, self.dim)
        scale = _f32_of_bytes(wire[nv + 4 * cb_elems:nv + 4 * cb_elems + 4], 1)[0]
        return (cb[idx] * scale).reshape(-1)[:n]


def learn_codebook(sample: np.ndarray, k: int, dim: int,
                   iters: int = 8) -> np.ndarray:
    """Deterministic Lloyd iterations over max-abs-normalized sample vectors
    (the calibration-time codebook fit; pure numpy, no RNG beyond the fixed
    default_codebook init). ``sample`` is any f32 array; it is flattened,
    padded to the vector grid, and normalized per the encode contract."""
    flat = np.asarray(sample, dtype=np.float32).reshape(-1)
    nv = -(-flat.size // dim)
    flat = np.pad(flat, (0, nv * dim - flat.size))
    amax = float(np.max(np.abs(flat))) if flat.size else 0.0
    vecs = (flat / (amax if amax > 0 else 1.0)).reshape(nv, dim)
    cb = default_codebook(k, dim).copy()
    for _ in range(max(1, int(iters))):
        d2 = ((vecs[:, None, :] - cb[None, :, :]) ** 2).sum(axis=-1)
        idx = np.argmin(d2, axis=1)
        for j in range(k):
            hit = vecs[idx == j]
            if hit.size:
                cb[j] = hit.mean(axis=0)
    cb[0] = 0.0  # keep the zero row: sparse blocks stay exact
    return cb.astype(np.float32)
