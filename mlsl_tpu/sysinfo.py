"""Platform probing and auto-configuration.

TPU counterpart of the reference's sysinfo (src/sysinfo.hpp:27-48: Xeon-vs-Phi CPU and
ETH/MLX/HFI NIC probing feeding AutoConfig, src/mlsl.cpp:649-682). Here the probed
"hardware" is the JAX device set: platform kind, chip generation, per-chip memory, and
the host topology — used to pick dispatch defaults (chunk sizes, lanes).
"""

from __future__ import annotations

import dataclasses
import functools

import jax


@dataclasses.dataclass(frozen=True)
class SysInfo:
    platform: str            # 'tpu' | 'cpu' | 'gpu'
    device_kind: str         # e.g. 'TPU v5 lite'
    num_devices: int
    num_hosts: int
    memory_per_device: int   # bytes, 0 if unknown


def apply_platform_override() -> None:
    """Honor MLSL_TPU_PLATFORM (e.g. 'cpu' for the virtual multi-device mesh).

    The env var must be applied via jax.config AFTER importing jax — site hooks
    (the axon plugin) pin JAX_PLATFORMS, so the env var alone is not enough. Every
    entry point (bench, examples, C shim, curve harness) funnels through here.
    """
    import os

    platform = os.environ.get("MLSL_TPU_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)


def on_tpu() -> bool:
    """True when the active JAX backend is a TPU — the one platform probe
    model/kernel code should key fast-path defaults on."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _num_hosts(devices) -> int:
    """DISTINCT hosts, not max(process_index)+1: a survivor subset may
    exclude every device of a low-indexed host, and a profile measured on
    a genuine N-host spread must not transfer to it."""
    return len({d.process_index for d in devices})


@functools.lru_cache(maxsize=1)
def probe() -> SysInfo:
    devices = jax.devices()
    d0 = devices[0]
    mem = 0
    try:
        stats = d0.memory_stats()
        if stats:
            mem = int(stats.get("bytes_limit", 0))
    except Exception:
        mem = 0
    return SysInfo(
        platform=d0.platform,
        device_kind=getattr(d0, "device_kind", d0.platform),
        num_devices=len(devices),
        num_hosts=_num_hosts(devices),
        memory_per_device=mem,
    )


def topology_fingerprint(devices=None) -> dict:
    """The identity a tuner profile (mlsl_tpu.tuner) is keyed by: measured
    algorithm selections transfer exactly to the hardware they were measured
    on — same platform, same chip generation, same world size and host
    spread. A profile whose fingerprint disagrees with the probe is stale
    (different machine / different slice shape) and must be re-measured, the
    same contract as the reference's AutoConfig re-probing per launch.

    ``devices``: the ACTIVE world (default the full jax world). An elastic
    reshard (mlsl_tpu.elastic) re-initializes the Environment over a
    survivor subset, and a profile measured at the full world size must go
    stale there — world size and tier shape are computed from the active
    set, not the physical machine."""
    si = probe()
    from mlsl_tpu.comm.mesh import world_tiers

    devices = tuple(jax.devices() if devices is None else devices)
    num_hosts = _num_hosts(devices)
    tiers = world_tiers(devices)
    return {
        "platform": si.platform,
        "device_kind": si.device_kind,
        "num_devices": len(devices),
        "num_hosts": num_hosts,
        # two-tier shape (T slices x L devices/slice) or None for a flat
        # world: a profile tuned on a two-tier mesh — where 'hier' cells
        # and the DCN codec knob were measured — must not transfer to a
        # flat one, and vice versa (comm/algos/hier.py)
        "tiers": list(tiers) if tiers is not None else None,
    }


def device_class(si: SysInfo) -> str:
    """Coarse tuning class from the probed device kind (the analog of the
    reference's Xeon-vs-Phi x ETH-vs-MLX-vs-HFI matrix, src/sysinfo.hpp:27-48):

    - 'tpu-performance': v4/v5p-class (3D-torus ICI, wide links) — dispatch
      overhead dominates; defer only genuinely large messages, few chunks.
    - 'tpu-efficiency': v5e/v6e-class ('lite' kinds; 2D-torus, narrower links)
      — collectives are slower relative to compute; defer earlier and chunk
      more so Waits can complete (and overlap) incrementally.
    - 'host-sim': CPU/GPU simulation meshes — keep chunking off so tests stay
      cheap and deterministic.
    """
    if si.platform != "tpu":
        return "host-sim"
    k = si.device_kind.lower()
    if "lite" in k or "v5e" in k or "v6e" in k:
        return "tpu-efficiency"
    return "tpu-performance"


# Per-class knob defaults applied by auto_config (each may be further keyed on
# probed HBM below). Values are design-rule settings pending on-chip tuning —
# the table exists so the tuning has one place to land, and so v5e-class and
# host-sim probes demonstrably pick different dispatch policies.
_CLASS_DEFAULTS = {
    "tpu-performance": dict(
        msg_priority_threshold=1 << 20,   # defer only >1 MiB
        msg_priority_flush_ms=1.0,        # fast dispatch: short coalescing
        large_msg_size_mb=128,
        large_msg_chunks=4,
        grad_bucket_mb=4,                 # coalesce launch-bound small grads
    ),
    "tpu-efficiency": dict(
        msg_priority_threshold=1 << 18,   # defer >256 KiB: narrower ICI
        msg_priority_flush_ms=2.0,
        large_msg_size_mb=64,             # chunk earlier
        large_msg_chunks=4,
        grad_bucket_mb=4,
    ),
    "host-sim": dict(
        msg_priority_threshold=10000,
        msg_priority_flush_ms=2.0,
        large_msg_size_mb=128,
        large_msg_chunks=1,               # chunking only costs on a sim mesh
        grad_bucket_mb=0,                 # keep sim tests launch-for-launch
    ),
}


def auto_config(config) -> None:
    """Adjust config defaults from probed hardware (reference AutoConfig,
    src/mlsl.cpp:649-682): pick the device-class row from _CLASS_DEFAULTS,
    then key memory-sensitive knobs on probed per-device HBM. Knobs the user
    exported explicitly (Config._explicit, tracked by from_env) are NEVER
    overridden — same contract as the reference, where AutoConfig fills only
    unset variables. Gated on MLSL_AUTO_CONFIG_TYPE != 0."""
    si = probe()
    if config.auto_config_type == 0:
        return
    tuned = dict(_CLASS_DEFAULTS[device_class(si)])
    if si.memory_per_device:
        # one deferred chunk should stay under ~1.5% of per-device HBM so a
        # chunked large allreduce never spikes transient memory
        cap_mb = max(8, si.memory_per_device // (64 * 1024 * 1024))
        tuned["large_msg_size_mb"] = min(tuned["large_msg_size_mb"], cap_mb)
        # the device-gather cap scales with the actual HBM: a quarter of the
        # chip, rather than a fixed 1 GiB, keeps the contract meaningful on
        # both 16 GiB v5e and 95 GiB v5p
        tuned["gather_device_limit_mb"] = max(
            256, si.memory_per_device // (4 * 1024 * 1024)
        )
    explicit = getattr(config, "_explicit", set())
    for k, v in tuned.items():
        if k not in explicit:
            setattr(config, k, v)
