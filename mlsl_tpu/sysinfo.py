"""Platform probing and auto-configuration.

TPU counterpart of the reference's sysinfo (src/sysinfo.hpp:27-48: Xeon-vs-Phi CPU and
ETH/MLX/HFI NIC probing feeding AutoConfig, src/mlsl.cpp:649-682). Here the probed
"hardware" is the JAX device set: platform kind, chip generation, per-chip memory, and
the host topology — used to pick dispatch defaults (chunk sizes, lanes).
"""

from __future__ import annotations

import dataclasses
import functools

import jax


@dataclasses.dataclass(frozen=True)
class SysInfo:
    platform: str            # 'tpu' | 'cpu' | 'gpu'
    device_kind: str         # e.g. 'TPU v5 lite'
    num_devices: int
    num_hosts: int
    memory_per_device: int   # bytes, 0 if unknown


def apply_platform_override() -> None:
    """Honor MLSL_TPU_PLATFORM (e.g. 'cpu' for the virtual multi-device mesh).

    The env var must be applied via jax.config AFTER importing jax — site hooks
    (the axon plugin) pin JAX_PLATFORMS, so the env var alone is not enough. Every
    entry point (bench, examples, C shim, curve harness) funnels through here.
    """
    import os

    platform = os.environ.get("MLSL_TPU_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)


@functools.lru_cache(maxsize=1)
def probe() -> SysInfo:
    devices = jax.devices()
    d0 = devices[0]
    mem = 0
    try:
        stats = d0.memory_stats()
        if stats:
            mem = int(stats.get("bytes_limit", 0))
    except Exception:
        mem = 0
    num_hosts = max(d.process_index for d in devices) + 1
    return SysInfo(
        platform=d0.platform,
        device_kind=getattr(d0, "device_kind", d0.platform),
        num_devices=len(devices),
        num_hosts=num_hosts,
        memory_per_device=mem,
    )


def auto_config(config) -> None:
    """Adjust config defaults from probed hardware (reference src/mlsl.cpp:649-682).

    The reference bumps MLSL_LARGE_MSG_CHUNKS on Ethernet; the TPU analog keys on
    platform: on real TPU keep few large chunks (ICI is fast, dispatch overhead
    dominates); on CPU simulation keep chunking minimal so tests stay cheap.
    """
    si = probe()
    if config.auto_config_type == 0:
        return
    if si.platform == "tpu":
        config.large_msg_chunks = max(config.large_msg_chunks, 4)
    else:
        config.large_msg_chunks = 1
