"""mlsl_tpu — a TPU-native ML scaling framework with the capabilities of Intel MLSL.

A brand-new design, idiomatic to JAX/XLA/Pallas, providing the semantic model of the
reference (``/root/reference``, intel/MLSL): ``Environment`` / ``Session`` + ``Operation``
graph / ``Distribution`` (data x model process grid) / ``Activation`` + ``ParameterSet``
handles with asynchronous Start/Wait/Test collectives, distributed-update gradient sync,
activation redistribution, int8 gradient-quantized allreduce, priority scheduling and
built-in statistics — implemented over a ``jax.sharding.Mesh`` with XLA collectives over
ICI/DCN instead of MPI communicators (reference API surface: include/mlsl.hpp:85-915).
"""

from mlsl_tpu.types import (
    DataType,
    PhaseType,
    GroupType,
    ReductionType,
    OpType,
    CompressionType,
    QuantParams,
)
from mlsl_tpu.log import (
    MLSLCorruptionError,
    MLSLDeviceLossError,
    MLSLError,
    MLSLIntegrityError,
    MLSLTimeoutError,
)
from mlsl_tpu.core.environment import Environment
from mlsl_tpu.core.distribution import Distribution
from mlsl_tpu.core.session import Session, Operation, OperationRegInfo
from mlsl_tpu.core.activation import Activation, CommBlockInfo
from mlsl_tpu.core.parameter_set import ParameterSet
from mlsl_tpu.core.stats import Statistics

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "PhaseType",
    "GroupType",
    "ReductionType",
    "OpType",
    "CompressionType",
    "QuantParams",
    "Environment",
    "Distribution",
    "Session",
    "Operation",
    "OperationRegInfo",
    "Activation",
    "CommBlockInfo",
    "ParameterSet",
    "Statistics",
    "MLSLError",
    "MLSLTimeoutError",
    "MLSLCorruptionError",
    "MLSLDeviceLossError",
    "MLSLIntegrityError",
]
