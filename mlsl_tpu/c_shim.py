"""Flat-function shim backing the embedded-Python C API (native/c_api.cpp).

The reference exposes its C++ core to C via opaque handles (src/c_bind.cpp) and to
Python via ctypes over that C layer (include/mlsl/mlsl.py). This framework inverts the
stack — the core is Python/JAX — so the C API embeds the interpreter and calls these
flat functions. Handles are integers into a registry; buffers cross the boundary as
raw pointer addresses wrapped with ctypes (single-controller: a C caller provides the
whole world's buffer, shape (world, count), and receives results the same way).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from mlsl_tpu.core.environment import Environment
from mlsl_tpu.types import CompressionType, DataType, GroupType, OpType, ReductionType, jnp_dtype

_registry: dict = {}
_next_id = 1
_lock = threading.Lock()


def _put(obj) -> int:
    global _next_id
    with _lock:
        hid = _next_id
        _next_id += 1
        _registry[hid] = obj
    return hid


def _get(hid: int):
    return _registry[int(hid)]


def _release(hid: int) -> int:
    _registry.pop(int(hid), None)
    return 0


# ---- environment ----

def env_init() -> int:
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    Environment.get_env().init()
    return 0


def env_finalize() -> int:
    Environment.get_env().finalize()
    return 0


def env_process_count() -> int:
    return Environment.get_env().get_process_count()


def env_create_distribution(data_parts: int, model_parts: int, seq_parts: int) -> int:
    env = Environment.get_env()
    return _put(env.create_distribution(data_parts, model_parts, seq_parts=seq_parts))


def env_create_session() -> int:
    return _put(Environment.get_env().create_session())


# ---- buffers: address <-> numpy ----

def _read_world_buffer(dist, addr: int, count: int, data_type: int):
    """C buffer at `addr`, logical shape (world, count), -> distributed buffer."""
    dt = jnp_dtype(DataType(data_type))
    world = dist.get_process_count_global()
    flat = np.ctypeslib.as_array(
        ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_char)),
        shape=(world * count * np.dtype(dt).itemsize,),
    ).view(dt).reshape(world, count)
    return dist.make_buffer(lambda p: flat[p], count, DataType(data_type))


def _write_world_buffer(dist, result, addr: int, count: int, data_type: int) -> int:
    dt = np.dtype(jnp_dtype(DataType(data_type)))
    world = dist.get_process_count_global()
    out = np.ctypeslib.as_array(
        ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_char)),
        shape=(world * count * dt.itemsize,),
    ).view(dt).reshape(world, count)
    host = np.asarray(result).reshape(world, -1)
    out[:, : host.shape[1]] = host[:, :count]
    return 0


# ---- distribution collectives (sync + async) ----

def dist_collective_start(
    dist_h: int, kind: str, addr: int, count: int, data_type: int,
    op: int, root: int, group: int,
) -> int:
    dist = _get(dist_h)
    buf = _read_world_buffer(dist, addr, count, data_type)
    gt = GroupType(group)
    if kind == "allreduce":
        req = dist.all_reduce(buf, count, data_type, ReductionType(op), gt)
    elif kind == "bcast":
        req = dist.bcast(buf, count, data_type, root, gt)
    elif kind == "reduce":
        req = dist.reduce(buf, count, data_type, ReductionType(op), root, gt)
    elif kind == "allgather":
        req = dist.all_gather(buf, count, data_type, gt)
    elif kind == "gather":
        req = dist.gather(buf, count, data_type, root, gt)
    elif kind in ("scatter", "reduce_scatter", "alltoall"):
        from mlsl_tpu.log import mlsl_assert

        g = dist._group(gt)
        gsize = 1 if g.is_self else g.size
        mlsl_assert(
            count % gsize == 0,
            "%s send count %d must be divisible by group size %d",
            kind, count, gsize,
        )
        per = count // gsize
        if kind == "scatter":
            req = dist.scatter(buf, per, data_type, root, gt)
        elif kind == "reduce_scatter":
            req = dist.reduce_scatter(buf, per, data_type, ReductionType(op), gt)
        else:
            req = dist.all_to_all(buf, per, data_type, gt)
    else:
        raise ValueError(f"unknown collective {kind}")
    return _put((dist, req))


def request_wait(req_h: int, out_addr: int, out_count: int, data_type: int) -> int:
    dist, req = _get(req_h)
    result = Environment.get_env().wait(req)
    _write_world_buffer(dist, result, out_addr, out_count, data_type)
    _release(req_h)
    return 0


def request_test(req_h: int) -> int:
    """1 if complete, 0 otherwise. Non-consuming: a later request_wait still
    delivers the result (the request caches it on test completion)."""
    dist, req = _get(req_h)
    done, _ = req.test()
    return 1 if done else 0


def dist_send_recv_list(
    dist_h: int, addr: int, count: int, data_type: int,
    pairs_addr: int, n_pairs: int, group: int,
) -> int:
    """pairs_addr: int64 array [src0, dst0, src1, dst1, ...] of length 2*n_pairs."""
    dist = _get(dist_h)
    flat = np.ctypeslib.as_array(
        ctypes.cast(int(pairs_addr), ctypes.POINTER(ctypes.c_int64)),
        shape=(2 * n_pairs,),
    )
    pairs = [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n_pairs)]
    buf = _read_world_buffer(dist, addr, count, data_type)
    req = dist.send_recv_list(buf, count, data_type, pairs, GroupType(group))
    return _put((dist, req))


def dist_barrier(dist_h: int, group: int) -> int:
    _get(dist_h).barrier(GroupType(group))
    return 0


def dist_process_count(dist_h: int, group: int) -> int:
    return _get(dist_h).get_process_count(GroupType(group))


# ---- session graph ----

def session_set_minibatch(sess_h: int, size: int) -> int:
    _get(sess_h).set_global_minibatch_size(size)
    return 0


def session_create_reginfo(sess_h: int, op_type: int) -> int:
    return _put(_get(sess_h).create_operation_reg_info(OpType(op_type)))


def reginfo_add_input(reg_h: int, count: int, size: int, data_type: int) -> int:
    return _get(reg_h).add_input(count, size, DataType(data_type))


def reginfo_add_output(reg_h: int, count: int, size: int, data_type: int) -> int:
    return _get(reg_h).add_output(count, size, DataType(data_type))


def reginfo_add_parameter_set(
    reg_h: int, count: int, size: int, data_type: int, dist_update: int, compression: int
) -> int:
    return _get(reg_h).add_parameter_set(
        count, size, DataType(data_type),
        distributed_update=bool(dist_update),
        compression_type=CompressionType(compression),
    )


def session_add_operation(sess_h: int, reg_h: int, dist_h: int) -> int:
    sess = _get(sess_h)
    idx = sess.add_operation(_get(reg_h), _get(dist_h))
    return _put(sess.get_operation(idx))


def session_commit(sess_h: int) -> int:
    _get(sess_h).commit()
    return 0


def operation_set_next(op_h: int, next_h: int, out_idx: int, in_idx: int) -> int:
    _get(op_h).set_next(_get(next_h), out_idx, in_idx)
    return 0


def operation_local_minibatch(op_h: int) -> int:
    return _get(op_h).get_local_minibatch_size()


def operation_param_local_count(op_h: int, ps_idx: int) -> int:
    ps = _get(op_h).get_parameter_set(ps_idx)
    return ps.get_local_kernel_count() * ps.get_kernel_size()


def operation_param_owned_count(op_h: int, ps_idx: int) -> int:
    ps = _get(op_h).get_parameter_set(ps_idx)
    return ps.get_owned_kernel_count() * ps.get_kernel_size()


def param_start_gradient_comm(op_h: int, ps_idx: int, addr: int, data_type: int) -> int:
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    count = ps.get_local_kernel_count() * ps.get_kernel_size()
    buf = _read_world_buffer(op.distribution, addr, count, data_type)
    ps.start_gradient_comm(buf)
    return 0


def param_wait_gradient_comm(op_h: int, ps_idx: int, out_addr: int, data_type: int) -> int:
    """Returns the per-rank element count written (0 if no comm was needed)."""
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    out = ps.wait_gradient_comm()
    if out is None:
        return 0
    n = int(np.asarray(out).shape[-1])
    _write_world_buffer(op.distribution, out, out_addr, n, data_type)
    return n


def handle_release(hid: int) -> int:
    return _release(hid)
