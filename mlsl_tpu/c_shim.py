"""Flat-function shim backing the embedded-Python C API (native/c_api.cpp).

The reference exposes its C++ core to C via opaque handles (src/c_bind.cpp) and to
Python via ctypes over that C layer (include/mlsl/mlsl.py). This framework inverts the
stack — the core is Python/JAX — so the C API embeds the interpreter and calls these
flat functions. Handles are integers into a registry; buffers cross the boundary as
raw pointer addresses wrapped with ctypes (single-controller: a C caller provides the
whole world's buffer, shape (world, count), and receives results the same way).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from mlsl_tpu.core.environment import Environment
from mlsl_tpu.types import CompressionType, DataType, GroupType, OpType, ReductionType, jnp_dtype

_registry: dict = {}
_next_id = 1
_lock = threading.Lock()


def _put(obj) -> int:
    global _next_id
    with _lock:
        hid = _next_id
        _next_id += 1
        _registry[hid] = obj
    return hid


def _get(hid: int):
    return _registry[int(hid)]


def _release(hid: int) -> int:
    _registry.pop(int(hid), None)
    return 0


# ---- environment ----

def env_init() -> int:
    from mlsl_tpu.sysinfo import apply_platform_override

    apply_platform_override()
    Environment.get_env().init()
    return 0


def env_finalize() -> int:
    Environment.get_env().finalize()
    return 0


def env_process_count() -> int:
    return Environment.get_env().get_process_count()


def env_create_distribution(data_parts: int, model_parts: int, seq_parts: int) -> int:
    env = Environment.get_env()
    return _put(env.create_distribution(data_parts, model_parts, seq_parts=seq_parts))


def env_create_distribution_with_colors(
    data_addr: int, model_addr: int, n: int
) -> int:
    """Color-defined process groups (reference CreateDistributionWithColors,
    include/mlsl.hpp:864): int64[n] per-rank color vectors at the given
    addresses; ranks sharing a data/model color form that group (unequal
    partitions ride the padded ragged-group contract)."""
    data = tuple(int(c) for c in _read_i64_array(data_addr, int(n)))
    model = tuple(int(c) for c in _read_i64_array(model_addr, int(n)))
    env = Environment.get_env()
    return _put(env.create_distribution_with_colors(data, model))


def env_create_session() -> int:
    return _put(Environment.get_env().create_session())


def env_set_quantization_params(
    lib_path, quant_name, dequant_name, reduce_name,
    block_size: int, elem_in_block: int,
) -> int:
    """Register codec parameters (reference src/mlsl.cpp:798). A lib_path is
    honored via the dlopen/ctypes trampoline (comm/codec.py); load failures
    raise and surface as MLSL_TPU_FAILURE with the message in
    mlsl_get_last_error()."""
    from mlsl_tpu.types import QuantParams

    Environment.get_env().set_quantization_params(QuantParams(
        block_size=int(block_size) if block_size else 256,
        elem_in_block=int(elem_in_block) if elem_in_block else 256,
        lib_path=lib_path or None,
        quant_buffer_func_name=quant_name or None,
        dequant_buffer_func_name=dequant_name or None,
        reduce_sum_func_name=reduce_name or None,
    ))
    return 0


# ---- buffers: address <-> numpy ----

def _read_world_buffer(dist, addr: int, count: int, data_type: int):
    """C buffer at `addr`, logical shape (world, count), -> distributed buffer."""
    dt = jnp_dtype(DataType(data_type))
    world = dist.get_process_count_global()
    flat = np.ctypeslib.as_array(
        ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_char)),
        shape=(world * count * np.dtype(dt).itemsize,),
    ).view(dt).reshape(world, count)
    return dist.make_buffer(lambda p: flat[p], count, DataType(data_type))


def _write_world_buffer(dist, result, addr: int, count: int, data_type: int) -> int:
    dt = np.dtype(jnp_dtype(DataType(data_type)))
    world = dist.get_process_count_global()
    out = np.ctypeslib.as_array(
        ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_char)),
        shape=(world * count * dt.itemsize,),
    ).view(dt).reshape(world, count)
    host = np.asarray(result).reshape(world, -1)
    out[:, : host.shape[1]] = host[:, :count]
    return 0


# ---- distribution collectives (sync + async) ----

def dist_collective_start(
    dist_h: int, kind: str, addr: int, count: int, data_type: int,
    op: int, root: int, group: int,
) -> int:
    dist = _get(dist_h)
    buf = _read_world_buffer(dist, addr, count, data_type)
    gt = GroupType(group)
    if kind == "allreduce":
        req = dist.all_reduce(buf, count, data_type, ReductionType(op), gt)
    elif kind == "bcast":
        req = dist.bcast(buf, count, data_type, root, gt)
    elif kind == "reduce":
        req = dist.reduce(buf, count, data_type, ReductionType(op), root, gt)
    elif kind == "allgather":
        req = dist.all_gather(buf, count, data_type, gt)
    elif kind == "gather":
        req = dist.gather(buf, count, data_type, root, gt)
    elif kind in ("scatter", "reduce_scatter", "alltoall"):
        from mlsl_tpu.log import mlsl_assert

        g = dist._group(gt)
        gsize = 1 if g.is_self else g.size
        mlsl_assert(
            count % gsize == 0,
            "%s send count %d must be divisible by group size %d",
            kind, count, gsize,
        )
        per = count // gsize
        if kind == "scatter":
            req = dist.scatter(buf, per, data_type, root, gt)
        elif kind == "reduce_scatter":
            req = dist.reduce_scatter(buf, per, data_type, ReductionType(op), gt)
        else:
            req = dist.all_to_all(buf, per, data_type, gt)
    else:
        raise ValueError(f"unknown collective {kind}")
    return _put((dist, req))


def request_wait(req_h: int, out_addr: int, out_count: int, data_type: int) -> int:
    dist, req = _get(req_h)
    result = Environment.get_env().wait(req)
    _write_world_buffer(dist, result, out_addr, out_count, data_type)
    _release(req_h)
    return 0


def request_test(req_h: int) -> int:
    """1 if complete, 0 otherwise. Non-consuming: a later request_wait still
    delivers the result (the request caches it on test completion)."""
    dist, req = _get(req_h)
    done, _ = req.test()
    return 1 if done else 0


def dist_send_recv_list(
    dist_h: int, addr: int, count: int, data_type: int,
    pairs_addr: int, n_pairs: int, group: int,
) -> int:
    """pairs_addr: int64 array [src0, dst0, src1, dst1, ...] of length 2*n_pairs."""
    dist = _get(dist_h)
    flat = np.ctypeslib.as_array(
        ctypes.cast(int(pairs_addr), ctypes.POINTER(ctypes.c_int64)),
        shape=(2 * n_pairs,),
    )
    pairs = [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n_pairs)]
    buf = _read_world_buffer(dist, addr, count, data_type)
    req = dist.send_recv_list(buf, count, data_type, pairs, GroupType(group))
    return _put((dist, req))


def dist_barrier(dist_h: int, group: int) -> int:
    _get(dist_h).barrier(GroupType(group))
    return 0


def dist_process_count(dist_h: int, group: int) -> int:
    return _get(dist_h).get_process_count(GroupType(group))


def dist_process_idx(dist_h: int, group: int, global_idx: int) -> int:
    """Member index of world rank `global_idx` within the group — the per-rank
    GetProcessIdx (reference include/mlsl.hpp:361) with the rank explicit."""
    return _get(dist_h).get_process_idx(GroupType(group), global_idx)


# ---- session graph ----

def session_set_minibatch(sess_h: int, size: int) -> int:
    _get(sess_h).set_global_minibatch_size(size)
    return 0


def session_create_reginfo(sess_h: int, op_type: int) -> int:
    return _put(_get(sess_h).create_operation_reg_info(OpType(op_type)))


def reginfo_add_input(reg_h: int, count: int, size: int, data_type: int) -> int:
    return _get(reg_h).add_input(count, size, DataType(data_type))


def reginfo_add_output(reg_h: int, count: int, size: int, data_type: int) -> int:
    return _get(reg_h).add_output(count, size, DataType(data_type))


def reginfo_add_parameter_set(
    reg_h: int, count: int, size: int, data_type: int, dist_update: int, compression: int
) -> int:
    return _get(reg_h).add_parameter_set(
        count, size, DataType(data_type),
        distributed_update=bool(dist_update),
        compression_type=CompressionType(compression),
    )


def session_add_operation(sess_h: int, reg_h: int, dist_h: int) -> int:
    sess = _get(sess_h)
    idx = sess.add_operation(_get(reg_h), _get(dist_h))
    return _put(sess.get_operation(idx))


def session_commit(sess_h: int) -> int:
    _get(sess_h).commit()
    return 0


def operation_set_next(op_h: int, next_h: int, out_idx: int, in_idx: int) -> int:
    _get(op_h).set_next(_get(next_h), out_idx, in_idx)
    return 0


def operation_set_prev(op_h: int, prev_h: int, in_idx: int, prev_out_idx: int) -> int:
    _get(op_h).set_prev(_get(prev_h), in_idx, prev_out_idx)
    return 0


def operation_local_minibatch(op_h: int) -> int:
    return _get(op_h).get_local_minibatch_size()


def operation_global_minibatch(op_h: int) -> int:
    return _get(op_h).get_global_minibatch_size()


def operation_param_local_count(op_h: int, ps_idx: int) -> int:
    ps = _get(op_h).get_parameter_set(ps_idx)
    return ps.get_local_kernel_count() * ps.get_kernel_size()


def operation_param_owned_count(op_h: int, ps_idx: int) -> int:
    ps = _get(op_h).get_parameter_set(ps_idx)
    return ps.get_owned_kernel_count() * ps.get_kernel_size()


# ---- activations (reference c_bind.cpp activation wrappers over
# include/mlsl.hpp:210-268) ----

def operation_get_input(op_h: int, idx: int) -> int:
    return _put(_get(op_h).get_input(idx))


def operation_get_output(op_h: int, idx: int) -> int:
    return _put(_get(op_h).get_output(idx))


def operation_input_count(op_h: int) -> int:
    return _get(op_h).get_input_count()


def operation_output_count(op_h: int) -> int:
    return _get(op_h).get_output_count()


def activation_query(act_h: int, what: int) -> int:
    """what: 0=global_fm_count 1=local_fm_count 2=fm_size 3=pack_block_count
    4=unpack_block_count 5=comm_buf_size 6=need_comm 7=send_count
    8=recv_count."""
    act = _get(act_h)
    if what == 0:
        return act.get_global_fm_count()
    if what == 1:
        return act.get_local_fm_count()
    if what == 2:
        return act.get_fm_size()
    if what == 3:
        return act.get_pack_block_count()
    if what == 4:
        return act.get_unpack_block_count()
    if what == 5:
        return act.get_comm_buf_size()
    if what == 6:
        return int(act.need_comm)
    if what == 7:
        return _act_wire_count(act)
    if what == 8:
        return _act_recv_count(act)
    raise ValueError(f"unknown activation query {what}")


def _act_wire_count(act) -> int:
    """Per-rank wire-buffer element count for this activation's request (an
    AlltoAll request's desc.count is the per-member block; the buffer holds one
    block per group member)."""
    req = act.comm_req
    if req is None:
        return 0
    if req.desc.kind == "alltoall":
        g = req.desc.group
        return req.desc.count * (1 if g.is_self else g.size)
    return req.desc.count


def _act_recv_count(act) -> int:
    """Per-rank element count of this activation's request RESULT (what a
    peer's wait_comm delivers) — sizes the C caller's recv buffer."""
    req = act.comm_req
    if req is None:
        return 0
    g = req.desc.group
    gsize = 1 if g.is_self else g.size
    kind = req.desc.kind
    if kind in ("allgather", "alltoall"):
        return req.desc.count * gsize
    if kind == "reduce_scatter":
        return req.desc.recv_count
    return req.desc.count  # allreduce


def activation_fm_offset(act_h: int, model_idx: int) -> int:
    """Per-rank GetGlobalFmOffset (reference include/mlsl.hpp:219) with the
    rank's model-group index explicit."""
    return _get(act_h).get_global_fm_offset(model_idx)


def activation_block_query(act_h: int, is_unpack: int, idx: int, field: int) -> int:
    """field: 0=mb_offset 1=mb_count 2=fm_offset 3=fm_count 4=fm_size
    5=buf_offset (reference CommBlockInfo include/mlsl.hpp:177-204)."""
    act = _get(act_h)
    b = (act.unpack_blocks if is_unpack else act.pack_blocks)[idx]
    return (b.mb_offset, b.mb_count, b.fm_offset, b.fm_count,
            b.fm_size, b.buf_offset)[field]


def activation_start_comm(act_h: int, addr: int, data_type: int) -> int:
    act = _get(act_h)
    n = _act_wire_count(act)
    if n == 0:
        return 0  # no comm on this edge (reference: no-op start)
    buf = _read_world_buffer(act.dist, addr, n, data_type)
    act.start_comm(buf)
    return 0


def activation_wait_comm(act_h: int, out_addr: int, data_type: int) -> int:
    """Waits the PEER's transfer (reference invariant) and writes (world, n);
    returns per-rank n (0 = no comm on this edge)."""
    act = _get(act_h)
    out = act.wait_comm()
    if out is None:
        return 0
    n = int(np.asarray(out).shape[-1])
    peer = act.peer_act
    dist = peer.dist if peer is not None else act.dist
    _write_world_buffer(dist, out, out_addr, n, data_type)
    return n


# ---- v-collectives (reference mlsl.hpp:418-471 AllGatherv/AlltoAllv) ----

def _read_i64_array(addr: int, n: int):
    return np.ctypeslib.as_array(
        ctypes.cast(int(addr), ctypes.POINTER(ctypes.c_int64)), shape=(n,)
    ).copy()


def dist_all_gatherv(dist_h: int, addr: int, send_count: int,
                     recv_counts_addr: int, data_type: int, group: int) -> int:
    """recv_counts: int64[group_size], identical on every rank (MPI semantics);
    the send buffer is (world, max(recv_counts)) with rank p's first
    recv_counts[member_idx(p)] elements valid."""
    dist = _get(dist_h)
    gt = GroupType(group)
    g = dist._group(gt)
    gsize = 1 if g.is_self else g.size
    counts = tuple(int(c) for c in _read_i64_array(recv_counts_addr, gsize))
    buf = _read_world_buffer(dist, addr, send_count, data_type)
    req = dist.all_gatherv(buf, send_count, counts, data_type, gt)
    return _put((dist, req))


def dist_all_to_allv(dist_h: int, addr: int, send_len: int,
                     send_counts_addr: int, send_offsets_addr: int,
                     recv_offsets_addr: int, data_type: int, group: int) -> int:
    """MPI AlltoAllv with rank-uniform int64[group_size] count/displacement
    arrays (the 1-D 'same on every rank' mode; see comm.request._normalize_alltoallv).
    Pass 0 for an offsets addr to use the packed default."""
    dist = _get(dist_h)
    gt = GroupType(group)
    g = dist._group(gt)
    gsize = 1 if g.is_self else g.size
    counts = _read_i64_array(send_counts_addr, gsize)
    soff = _read_i64_array(send_offsets_addr, gsize) if send_offsets_addr else None
    roff = _read_i64_array(recv_offsets_addr, gsize) if recv_offsets_addr else None
    buf = _read_world_buffer(dist, addr, send_len, data_type)
    req = dist.all_to_allv(buf, counts, soff, None, roff, data_type, gt)
    return _put((dist, req))


def dist_all_to_allv_full(dist_h: int, addr: int, send_len: int,
                          send_counts_addr: int, send_offsets_addr: int,
                          recv_counts_addr: int, recv_offsets_addr: int,
                          data_type: int, group: int) -> int:
    """General per-rank AlltoAllv: int64[world * group] row-major tables, row w
    = world rank w's own count/displacement vectors (full MPI generality; see
    comm.request._normalize_alltoallv_per_rank). 0 addr = packed default
    offsets / derived recv counts."""
    dist = _get(dist_h)
    gt = GroupType(group)
    g = dist._group(gt)
    gsize = 1 if g.is_self else g.size
    w = dist.topology.world_size
    rd = lambda a: _read_i64_array(a, w * gsize).reshape(w, gsize) if a else None
    buf = _read_world_buffer(dist, addr, send_len, data_type)
    req = dist.all_to_allv(
        buf, rd(send_counts_addr), rd(send_offsets_addr),
        rd(recv_counts_addr), rd(recv_offsets_addr), data_type, gt,
    )
    return _put((dist, req))


# ---- statistics (reference mlsl.hpp:651-726, c_bind stats wrappers) ----

def session_get_stats(sess_h: int) -> int:
    return _put(_get(sess_h).get_stats())


def stats_control(stats_h: int, what: int) -> int:
    """what: 0=start 1=stop 2=reset 3=is_enabled 4=is_started."""
    st = _get(stats_h)
    if what == 0:
        st.start()
    elif what == 1:
        st.stop()
    elif what == 2:
        st.reset()
    elif what == 3:
        return int(st.is_enabled())
    elif what == 4:
        return int(st.is_started())
    else:
        raise ValueError(f"unknown stats control {what}")
    return 0


def stats_query(stats_h: int, what: int, op_idx: int) -> int:
    """what: 0=comm_size 1=comm_cycles 2=compute_cycles 3=isolation_comm_cycles
    4=overlap_permille (hidden/isolation x 1000; -1 until isolation stats and
    accounted steps exist). Per-op with op_idx >= 0, totals with op_idx < 0.
    Cycles are nanoseconds (the TPU analog of the reference's rdtsc cycles)."""
    st = _get(stats_h)
    if what == 4:
        # index-keyed (robust to duplicate op names); out-of-range op_idx has
        # no slots and yields the no-data sentinel like the sibling queries
        f = st.get_overlap_fraction(None if op_idx < 0 else int(op_idx))
        return -1 if f is None else int(round(f * 1000))
    if op_idx < 0:
        return (st.get_total_comm_size(), st.get_total_comm_cycles(),
                st.get_total_compute_cycles(),
                st.get_total_isolation_comm_cycles())[what]
    return (st.get_comm_size(op_idx), st.get_comm_cycles(op_idx),
            st.get_compute_cycles(op_idx),
            st.get_isolation_comm_cycles(op_idx))[what]


def stats_print(stats_h: int) -> int:
    _get(stats_h).print_()
    return 0


# ---- parameter sets (cont.) ----

def param_query(op_h: int, ps_idx: int, what: int) -> int:
    """what: 0=global_kernel_count 1=local_kernel_count 2=owned_kernel_count
    3=kernel_size 4=is_distributed_update."""
    ps = _get(op_h).get_parameter_set(ps_idx)
    return (ps.get_global_kernel_count(), ps.get_local_kernel_count(),
            ps.get_owned_kernel_count(), ps.get_kernel_size(),
            int(ps.is_distributed_update()))[what]


def param_owned_offset(op_h: int, ps_idx: int, data_idx: int) -> int:
    """Per-rank GetOwnedKernelOffset (reference include/mlsl.hpp:298) with the
    rank's data-group index explicit."""
    return _get(op_h).get_parameter_set(ps_idx).get_owned_kernel_offset(data_idx)


def param_test_gradient_comm(op_h: int, ps_idx: int) -> int:
    done, _ = _get(op_h).get_parameter_set(ps_idx).test_gradient_comm()
    return 1 if done else 0


def param_start_increment_comm(op_h: int, ps_idx: int, addr: int, data_type: int) -> int:
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    count = ps.get_owned_kernel_count() * ps.get_kernel_size()
    buf = _read_world_buffer(op.distribution, addr, count, data_type)
    ps.start_increment_comm(buf)
    return 0


def param_wait_increment_comm(op_h: int, ps_idx: int, out_addr: int, data_type: int) -> int:
    """Returns the per-rank element count written (0 if no comm was needed)."""
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    out = ps.wait_increment_comm()
    if out is None:
        return 0
    n = int(np.asarray(out).shape[-1])
    _write_world_buffer(op.distribution, out, out_addr, n, data_type)
    return n


def param_start_gradient_comm(op_h: int, ps_idx: int, addr: int, data_type: int) -> int:
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    count = ps.get_local_kernel_count() * ps.get_kernel_size()
    buf = _read_world_buffer(op.distribution, addr, count, data_type)
    ps.start_gradient_comm(buf)
    return 0


def param_wait_gradient_comm(op_h: int, ps_idx: int, out_addr: int, data_type: int) -> int:
    """Returns the per-rank element count written (0 if no comm was needed)."""
    op = _get(op_h)
    ps = op.get_parameter_set(ps_idx)
    out = ps.wait_gradient_comm()
    if out is None:
        return 0
    n = int(np.asarray(out).shape[-1])
    _write_world_buffer(op.distribution, out, out_addr, n, data_type)
    return n


def handle_release(hid: int) -> int:
    return _release(hid)
