"""ParameterSet: gradient synchronization with optional distributed update.

Mirrors the reference ParameterSetImpl (src/mlsl_impl.cpp:388-444 and
include/mlsl.hpp:276-341):

- kernels are partitioned over the model group: localKernelCount =
  globalKernelCount/modelParts at offset localKernelCount*modelIdx;
- plain path: gradients AllReduce'd over the data group;
- distributedUpdate (ZeRO-1 ancestor): ownedKernelCount = ceil(local/dataParts),
  localKernelCount padded up to owned*dataParts; gradients ReduceScatter'd so each data
  rank owns a shard, the optimizer updates only the owned shard, and the parameter
  increments AllGather back (reference :401-435);
- int8 quantized gradients when compression is enabled (reference swaps the MPI op for
  MPI_QUANT_OP, src/comm_ep.cpp:946-950; here the request uses the Pallas quantized
  ring allreduce).
"""

from __future__ import annotations

from typing import Optional

from mlsl_tpu.comm.request import CommDesc, CommRequest, ComputeType
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import CompressionType, DataType, ReductionType


class ParameterSet:
    def __init__(self, op, reg, index: int):
        self.op = op
        self.param_index = index
        self.dist = op.distribution
        self.distributed_update = bool(reg.distributed_update)
        self.compression = CompressionType(reg.compression)
        self.data_type = DataType(reg.data_type)
        self.kernel_size = reg.size
        self.global_kernel_count = reg.count

        model_size = self.dist.get_process_count_model()
        # Gradient reduction spans data AND sequence shards (sequence parallelism is
        # data parallelism from the parameters' point of view).
        grad_group = self.dist.grad_group
        data_size = 1 if grad_group.is_self else grad_group.size
        mlsl_assert(
            self.global_kernel_count % model_size == 0,
            "kernel count %d not divisible by model parts %d",
            self.global_kernel_count,
            model_size,
        )
        self.local_kernel_count = self.global_kernel_count // model_size
        self._local_kernel_offset_per_model_idx = self.local_kernel_count

        self.need_comm = data_size > 1
        if self.distributed_update:
            self.owned_kernel_count = -(-self.local_kernel_count // data_size)  # ceil
            # The local count is padded up so each data rank owns an equal shard
            # (reference :403-405).
            self.local_kernel_count = self.owned_kernel_count * data_size
        else:
            self.owned_kernel_count = self.local_kernel_count

        self.grad_req: Optional[CommRequest] = None
        self.inc_req: Optional[CommRequest] = None
        # gradient bucketing (core/bucketing.py, assigned at Session.commit):
        # the buckets opportunistically coalesce this set's grad collective
        # (allreduce or ZeRO-1 reduce_scatter, uncompressed or int8-quantized
        # — a quantized set joins a compressed-ring bucket whose single
        # error-feedback residual carries this member's slice) and its
        # increment all_gather with its neighbors'; the *_round flags track
        # whether the CURRENT round is bucket-owned or individual (fallback —
        # which for a quantized member runs its own compressed request with
        # its own residual, so correctness never depends on co-arrival)
        self.bucket = None
        self._bucket_round = False
        self.inc_bucket = None
        self._inc_bucket_round = False
        env = op.session.env
        if self.need_comm:
            n_owned = self.owned_kernel_count * self.kernel_size
            # op-attributed request names: the trace timeline (mlsl_tpu.obs)
            # and the watchdog descriptor name the owning operation, and the
            # span-derived per-op wait-stall fields of
            # Statistics.overlap_report key on the '<op>/' prefix
            # (op.name is never empty: Operation defaults it to op<idx>)
            req_name = f"{op.name}/"
            if self.distributed_update:
                self.grad_req = CommRequest(
                    CommDesc(
                        "reduce_scatter",
                        self.dist.grad_group,
                        n_owned * data_size,
                        self.data_type,
                        compute_type=ComputeType.PARAM_GRAD,
                        op=ReductionType.SUM,
                        recv_count=n_owned,
                        compression=self.compression,
                    ),
                    env.dispatcher,
                    name=f"{req_name}grad{index}",
                )
                self.inc_req = CommRequest(
                    CommDesc(
                        "allgather",
                        self.dist.grad_group,
                        n_owned,
                        self.data_type,
                        compute_type=ComputeType.PARAM_INC,
                    ),
                    env.dispatcher,
                    name=f"{req_name}inc{index}",
                )
                self.inc_req.setup()
            else:
                self.grad_req = CommRequest(
                    CommDesc(
                        "allreduce",
                        self.dist.grad_group,
                        n_owned,
                        self.data_type,
                        compute_type=ComputeType.PARAM_GRAD,
                        op=ReductionType.SUM,
                        compression=self.compression,
                    ),
                    env.dispatcher,
                    name=f"{req_name}grad{index}",
                )
            self.grad_req.setup()

    # -- introspection (reference include/mlsl.hpp:284-341) ----------------

    def get_global_kernel_count(self) -> int:
        return self.global_kernel_count

    def get_global_kernel_offset(self, model_idx: int = 0) -> int:
        return self._local_kernel_offset_per_model_idx * model_idx

    def get_local_kernel_count(self) -> int:
        return self.local_kernel_count

    def get_owned_kernel_count(self) -> int:
        return self.owned_kernel_count

    def get_owned_kernel_offset(self, data_idx: int = 0) -> int:
        if self.distributed_update:
            return self.owned_kernel_count * data_idx
        return 0

    def get_kernel_size(self) -> int:
        return self.kernel_size

    def get_data_type(self) -> DataType:
        return self.data_type

    def is_distributed_update(self) -> bool:
        return self.distributed_update

    @property
    def codec_name(self) -> str:
        """The grad collective's resolved registry codec (mlsl_tpu.codecs):
        'int8' for the seed wire, 'vq'/'prune'/... when calibration or
        MLSL_CODEC assigned one, '' when this set needs no comm. Bucketing
        partitions on it — mixed-codec buckets stay split (each codec owns
        its residual layout and wire geometry)."""
        return self.grad_req.codec_name if self.grad_req is not None else ""

    # -- gradient sync (reference src/mlsl_impl.cpp:446-539) ---------------

    def start_gradient_comm(self, grad_buf) -> None:
        """Dispatch the gradient collective. grad_buf: distributed buffer of shape
        (R, D, S, M, localKernelCount*kernelSize)."""
        self.op.session._stat_event(self, "start", is_param=True)
        if self.need_comm:
            if self.bucket is not None and self.bucket.start(self, grad_buf):
                self._bucket_round = True
            else:
                self._bucket_round = False
                self.grad_req.start(grad_buf)
        self.op.session._stat_event(self, "start_done", is_param=True)

    def wait_gradient_comm(self):
        self.op.session._stat_event(self, "wait", is_param=True)
        out = None
        if self.need_comm and self._bucket_round:
            handled, out = self.bucket.wait(self)
            if not handled:
                # the bucket's fallback just started our individual request
                self._bucket_round = False
                out = self.grad_req.wait()
        # A request completed via test() has is_started False but a cached
        # result; wait() must still deliver it (MPI: MPI_Wait on a completed
        # request). Only a never-started request yields None.
        elif self.need_comm and (
            self.grad_req.is_started or self.grad_req._result is not None
        ):
            out = self.grad_req.wait()
        self.op.session._stat_event(self, "wait_done", is_param=True)
        return out

    def test_gradient_comm(self):
        """-> (is_completed, result_or_None)."""
        self.op.session._stat_event(self, "test", is_param=True)
        if not self.need_comm:
            done, out = True, None
        elif self._bucket_round:
            handled, done, out = self.bucket.test(self)
            if not handled:
                self._bucket_round = False
                done, out = self.grad_req.test()
        else:
            done, out = self.grad_req.test()
        self.op.session._stat_event(self, "test_done", is_param=True)
        return done, out

    def start_increment_comm(self, inc_buf) -> None:
        """AllGather the locally updated owned shard (distributedUpdate only)."""
        self.op.session._stat_event(self, "start", is_param=True, is_increment=True)
        if self.need_comm and self.distributed_update:
            if self.inc_bucket is not None and self.inc_bucket.start(self, inc_buf):
                self._inc_bucket_round = True
            else:
                self._inc_bucket_round = False
                self.inc_req.start(inc_buf)
        self.op.session._stat_event(
            self, "start_done", is_param=True, is_increment=True
        )

    def wait_increment_comm(self):
        self.op.session._stat_event(self, "wait", is_param=True, is_increment=True)
        out = None
        if self.need_comm and self.distributed_update and self._inc_bucket_round:
            handled, out = self.inc_bucket.wait(self)
            if not handled:
                self._inc_bucket_round = False
                out = self.inc_req.wait()
        elif self.need_comm and self.distributed_update and self.inc_req.is_started:
            out = self.inc_req.wait()
        self.op.session._stat_event(self, "wait_done", is_param=True, is_increment=True)
        return out

    # PascalCase parity aliases
    GetGlobalKernelCount = get_global_kernel_count
    GetGlobalKernelOffset = get_global_kernel_offset
    GetLocalKernelCount = get_local_kernel_count
    GetOwnedKernelCount = get_owned_kernel_count
    GetOwnedKernelOffset = get_owned_kernel_offset
    GetKernelSize = get_kernel_size
    GetDataType = get_data_type
    IsDistributedUpdate = is_distributed_update
    StartGradientComm = start_gradient_comm
    WaitGradientComm = wait_gradient_comm
    TestGradientComm = test_gradient_comm
    StartIncrementComm = start_increment_comm
    WaitIncrementComm = wait_increment_comm
