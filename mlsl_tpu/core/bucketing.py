"""Gradient bucketing: coalesce small per-layer gradient allreduces.

A beyond-reference capability (the reference syncs one collective per
ParameterSet, src/mlsl_impl.cpp:446-539, with LIFO *scheduling* but no
*coalescing*): a deep model's backward pass issues one small allreduce per
parameter tensor, each paying a full host dispatch and wire latency — on the
dispatch-floor numbers (README 'Host dispatch floor') a ResNet-50's ~160
small tensors are launch-bound, not bandwidth-bound.

Buckets pack eligible ParameterSets — same gradient group, same dtype, same
compression — into ``MLSL_GRAD_BUCKET_MB``-sized groups in REVERSE creation
order (the backward-pass start order), at Session.commit. The last member to
Start triggers ONE concatenated collective for the whole bucket; each
member's Wait/Test slices its own segment from the bucket result. One
dispatch + one wire latency amortized over the bucket, and the wire sees a
bandwidth-sized message.

The compressed path coalesces too (EQuARX/THC both show quantized
collectives only reach peak algbw at coalesced message sizes, where the
per-block scale overhead amortizes): QUANTIZATION members pack into one int8
ring reduce-scatter + all-gather over the whole bucket, with the per-member
error-feedback residuals carried as slices of the bucket request's single
residual buffer. Member slots align to the quant block (a block never
straddles two members, so per-member scale locality matches the individual
ring) and the total aligns to the ring chunk unit (every hop takes the
dense-scale kernel path; quant_ring.ring_aligned_rc). TOPK stays individual
— the sparse wire format has no coalesced form.

Opportunistic by design: correctness never depends on co-arrival. Any
pattern the bucket cannot serve exactly — a Wait/Test before the bucket
fills, a member restarted while the bucket is in flight — falls back to the
member's individual cached request (the always-correct path the bucket
merely optimizes), and the bucket re-arms for the next round.
"""

from __future__ import annotations

import threading
from typing import List

import jax
import jax.numpy as jnp

from mlsl_tpu import checker, supervisor
from mlsl_tpu.comm.request import CommDesc, CommRequest, ComputeType
from mlsl_tpu.core import stats as stats_mod
from mlsl_tpu.obs import tracer as obs
from mlsl_tpu.log import log_debug, mlsl_assert
from mlsl_tpu.types import CompressionType, ReductionType


class GradBucket:
    """One coalesced collective shared by several ParameterSets.

    ``kind`` selects the phase being coalesced:
      - "allreduce":      plain gradient sync (each member contributes its
                          local gradient vector; receives the group sum slice)
      - "reduce_scatter": ZeRO-1 gradient phase (member buffers are G chunks
                          of owned elements; the pack interleaves chunks so
                          one reduce_scatter delivers every member's owned
                          shard inside this rank's chunk)
      - "allgather":      ZeRO-1 increment phase (owned shards concatenate;
                          one all_gather; the unpack reassembles each
                          member's group-rank-major shard concatenation)

    Round lifecycle (all transitions under _lock):
      collecting --(all members registered)--> dispatched
      collecting --(any Wait/Test early)-----> fallback: registered members'
                                               individual requests start, the
                                               round re-arms immediately
      dispatched --(every member consumed)---> re-armed for the next round
    A member restarting while dispatched abandons its bucket slot for that
    round (counts as consumed) and runs individually.
    """

    def __init__(self, members: List, env, kind: str = "allreduce",
                 compression: CompressionType = CompressionType.NONE,
                 codec: str = ""):
        from mlsl_tpu.types import dtype_size

        # members in START order (reverse creation = backward pass order)
        self.members = members
        self.kind = kind
        self.compression = CompressionType(compression)
        # registry codec the members resolved to (mlsl_tpu.codecs) — pinned
        # into the coalesced desc so the bucket rides the members' wire; a
        # user custom codec routes via config, not the desc pin
        self.codec = codec if codec not in ("", "custom") else ""
        quant = self.compression == CompressionType.QUANTIZATION
        # which ParameterSet round flag / fallback request this bucket drives
        self.round_attr = (
            "_inc_bucket_round" if kind == "allgather" else "_bucket_round"
        )
        self.req_attr = "inc_req" if kind == "allgather" else "grad_req"
        self._idx = {id(ps): i for i, ps in enumerate(members)}
        # owned elements per member (== local for the plain allreduce path)
        self.counts = [ps.owned_kernel_count * ps.kernel_size for ps in members]
        ps0 = members[0]
        group = ps0.dist.grad_group
        g = 1 if group.is_self else group.size
        if quant:
            mlsl_assert(
                kind in ("allreduce", "reduce_scatter"),
                "quantized buckets coalesce allreduce/reduce_scatter only "
                "(got %s)", kind,
            )
            from mlsl_tpu.comm.quant_ring import ring_aligned_rc
            from mlsl_tpu.ops.quant_kernels import block_align

            block = env.config.quant_block_elems
            # member slots align to the quant block (scale locality parity
            # with the individual ring; padding quantizes to exact zeros) and
            # the total aligns to the ring chunk unit so every hop takes the
            # dense-scale kernel path with zero ring-internal padding
            self.slots = [block_align(c, block) for c in self.counts]
            total_slots = sum(self.slots)
            if kind == "reduce_scatter":
                total = ring_aligned_rc(group, total_slots, block)
            else:
                total = g * ring_aligned_rc(group, -(-total_slots // g), block)
        else:
            self.slots = list(self.counts)
            total = sum(self.counts)
        self.offsets = [0]
        for s in self.slots[:-1]:
            self.offsets.append(self.offsets[-1] + s)
        # ring-alignment tail beyond the last member's slot (quant only)
        tail = total - (self.offsets[-1] + self.slots[-1])
        offsets, counts, slots = self.offsets, self.counts, self.slots
        # stats: coalesced member payload bytes per dispatched round, and the
        # wire bytes a quantized round saves vs the f32 wire (int8 payload +
        # one f32 scale per block instead of f32 data; an estimate — the real
        # wire repeats per hop, but the ratio is the tracked signal)
        esize = dtype_size(ps0.data_type)
        mult = g if kind == "reduce_scatter" else 1
        self._coalesced_bytes = sum(self.counts) * esize * mult
        n_wire = total * mult
        self._wire_saved_bytes = (
            max(0, n_wire * esize - (n_wire + (n_wire // env.config.quant_block_elems) * 4))
            if quant else 0
        )
        # jitted pack/unpack: EAGER concatenate/slice on sharded arrays pays
        # one full dispatch per op (~2 ms each on the CPU mesh); one compiled
        # program for the whole pack and one for the whole unpack keeps the
        # bucket's overhead below a single member's dispatch cost
        sl = lambda x, a, b: jax.lax.slice_in_dim(x, a, b, axis=x.ndim - 1)

        def padded(x, c, s):
            if s == c:
                return x
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, s - c)])

        def tail_zeros(x):
            return jnp.zeros((*x.shape[:-1], tail), x.dtype)

        # slot-padded concat pack / slot-offset-slice unpack are the defaults
        # (identical to plain concat/slice when slots == counts and tail == 0,
        # the uncompressed case); each kind overrides only its genuinely
        # different side
        def pack(*xs):
            parts = [padded(x, c, s) for x, c, s in zip(xs, counts, slots)]
            if tail:
                parts.append(tail_zeros(xs[0]))
            return jnp.concatenate(parts, axis=-1)

        self._concat = jax.jit(pack)
        self._split = jax.jit(lambda x: tuple(
            sl(x, o, o + c) for o, c in zip(offsets, counts)
        ))
        if kind == "allreduce":
            desc = CommDesc(
                "allreduce", group, total, ps0.data_type,
                compute_type=ComputeType.PARAM_GRAD, op=ReductionType.SUM,
                compression=self.compression, codec=self.codec,
            )
        elif kind == "reduce_scatter":
            # member m's buffer is G chunks of counts[m]; chunk r of the
            # PACKED buffer must hold every member's chunk r so the scatter
            # hands rank r one contiguous (total,) block
            desc = CommDesc(
                "reduce_scatter", group, total * g, ps0.data_type,
                compute_type=ComputeType.PARAM_GRAD, op=ReductionType.SUM,
                recv_count=total,
                compression=self.compression, codec=self.codec,
            )

            def rs_pack(*xs):
                parts = []
                for r in range(g):
                    parts.extend(
                        padded(sl(x, r * c, (r + 1) * c), c, s)
                        for x, c, s in zip(xs, counts, slots)
                    )
                    if tail:
                        parts.append(tail_zeros(xs[0]))
                return jnp.concatenate(parts, axis=-1)

            self._concat = jax.jit(rs_pack)
        elif kind == "allgather":
            # result is G blocks of (total,); member m's shard concatenation
            # = its offsets[m] slice of every block, in group-rank order
            desc = CommDesc(
                "allgather", group, total, ps0.data_type,
                compute_type=ComputeType.PARAM_INC,
            )
            self._split = jax.jit(lambda x: tuple(
                jnp.concatenate(
                    [sl(x, r * total + o, r * total + o + c) for r in range(g)],
                    axis=-1,
                )
                for o, c in zip(offsets, counts)
            ))
        else:  # pragma: no cover - kinds are closed
            raise ValueError(kind)
        self.req = CommRequest(
            desc, env.dispatcher,
            name=f"bucket-{kind}[{len(members)}x{total}]",
        )
        self.req.setup()
        self._lock = threading.Lock()
        self._warmed = False         # precompile() ran (per-instance jits hot)
        self._bufs: dict = {}        # member index -> buffer (this round)
        self._dispatched = False
        self._parts = None           # split bucket result (this round)
        self._consumed: set = set()
        self._last: dict = {}        # member index -> last delivered result
        self._round = 0              # bumped at every re-arm: detects a round
                                     # completing under an out-of-lock wait
        self._degraded_round = -1    # _round value the last degrade fired on
        # a failed bucket dispatch must raise at EVERY member's wait/test —
        # like the per-layer path, where each request raises its own error —
        # not only at the first waiter (CommRequest consumes its error once)
        self._error = None
        self._error_left: set = set()
        # recovery ladder (mlsl_tpu.supervisor): classified failures of the
        # coalesced request count against the process-wide bucket breaker;
        # once OPEN, rounds degrade to the members' individual requests (the
        # always-correct path coalescing merely optimizes) until the
        # half-open probe round succeeds
        self._breaker = supervisor.breaker("bucket")

    # -- round state machine (all under _lock) -----------------------------

    def start(self, ps, buf) -> bool:
        """Register a member's gradient buffer. True = the bucket owns this
        round for ps; False = run this start on ps's individual request."""
        i = self._idx[id(ps)]
        with self._lock:
            if self._error is not None:
                # THIS member's restart supersedes its undelivered error (the
                # CommRequest.start contract); other members still collect it
                self._error_left.discard(i)
                if not self._error_left:
                    self._error = None
            if self._dispatched:
                # restart while the bucket is in flight: abandon the slot for
                # this round and run individually (well-defined supersede
                # semantics live on the individual request)
                stats_mod.record_bucket_round("abandon", self.kind)
                self._consume_locked(i)
                return False
            if not self._bufs and not self._breaker.allow():
                # bucket breaker OPEN (supervisor rung 3): deny the fresh
                # round at its boundary — every member runs its individual
                # request until the cooldown admits a half-open probe round.
                # Mid-round members keep registering so an admitted round
                # always completes or fails as a unit.
                return False
            chkp = checker.level()
            if chkp:
                # CHKP through the pack: validate the member buffer against
                # ITS OWN request descriptor before it joins the coalesced
                # round — the contract its individual Start would enforce,
                # so a bad buffer is named per member instead of blending
                # into the packed concatenation. Checked only on the
                # REGISTERING paths: a declined round (abandon / open
                # breaker, above) runs the individual request, whose own
                # Start performs this exact check — doing it here too would
                # double-count every buffer in the CHKP stats.
                checker.check_buffer(buf, getattr(ps, self.req_attr).desc,
                                     chkp)
            self._bufs[i] = buf  # a pre-dispatch restart supersedes
            if len(self._bufs) == len(self.members):
                # _error is necessarily None here: every member passed the
                # per-member supersede block above on its way into this round
                ordered = [self._bufs[j] for j in range(len(self.members))]
                tr = obs._tracer
                t0 = tr.now() if tr is not None else 0
                try:
                    self.req.start(self._concat(*ordered))
                except Exception as e:
                    # a DIRECT dispatch (msg_priority off) fails at Start,
                    # not at the members' waits: run the same ladder here.
                    # Degrade pops OUR buffer first — the caller starts our
                    # individual request on the False return, while
                    # _fallback_locked starts everyone else's. Below the
                    # breaker threshold the error propagates to THIS caller
                    # only: the round never dispatched, so the other
                    # members' buffers are intact and their waits take the
                    # existing pre-dispatch fallback (individual requests) —
                    # correctness never depends on co-arrival.
                    del self._bufs[i]
                    if self._degrade_locked(e):
                        return False
                    raise
                if tr is not None:
                    # pack + coalesced Start on the bucket request's track
                    # (its submit/dispatch/wait spans land there too)
                    tr.complete("bucket.pack", "bucket", t0,
                                track=self.req._trace_name, kind=self.kind,
                                members=len(self.members),
                                bytes=self._coalesced_bytes,
                                algo=self.req.algo)
                self._dispatched = True
                stats_mod.record_bucket_round(
                    "dispatched", self.kind, members=len(self.members),
                    coalesced=self._coalesced_bytes,
                    wire_saved=self._wire_saved_bytes,
                )
            return True

    def _fallback_locked(self) -> None:
        """A member was waited/tested before the bucket filled: dispatch every
        registered member's individual request and re-arm. Those members'
        current round becomes individual (their round flag cleared)."""
        log_debug(
            "%s bucket fallback: %d/%d members started",
            self.kind, len(self._bufs), len(self.members),
        )
        stats_mod.record_bucket_round(
            "fallback", self.kind, members=len(self._bufs)
        )
        for j, buf in self._bufs.items():
            ps = self.members[j]
            getattr(ps, self.req_attr).start(buf)
            setattr(ps, self.round_attr, False)
        self._bufs.clear()
        self._consumed.clear()
        self._round += 1

    def _consume_locked(self, i: int) -> None:
        self._consumed.add(i)
        if self._dispatched and len(self._consumed) == len(self.members):
            self._bufs.clear()
            self._consumed.clear()
            self._dispatched = False
            self._parts = None
            self._round += 1

    def _part_locked(self, out, i: int):
        if self._parts is None:
            self._parts = self._split(out)  # one compiled unpack per round
        res = self._parts[i]
        self._last[i] = res
        self._consume_locked(i)
        return res

    def _record_error_locked(self, e: BaseException) -> None:
        self._error = e
        self._error_left = set(range(len(self.members)))
        self._bufs.clear()
        self._consumed.clear()
        self._dispatched = False
        self._parts = None
        self._round += 1

    def _degrade_locked(self, e: BaseException) -> bool:
        """Rung 3 for a failed coalesced round (caller holds _lock): count
        the classified failure against the bucket breaker; once it is OPEN
        (this failure tripped it, or a probe round failed) the round degrades
        — every registered member's INDIVIDUAL request starts with its
        registered buffer (the always-correct path, delivering this round's
        gradients without a recovery cycle) and the bucket re-arms. Returns
        True when degraded; False leaves the error for _record_error_locked
        (below threshold: the failure escalates to supervised restart)."""
        if supervisor.classify(e) is supervisor.ErrorClass.FATAL:
            return False
        if not self._breaker.record_failure(e):
            return False
        stats_mod.record_degrade(
            "bucket", "fallback",
            detail=f"{self.kind}[{len(self.members)}]: "
                   f"{type(e).__name__}: {e}",
        )
        self._degraded_round = self._round
        self._dispatched = False
        self._parts = None
        self._fallback_locked()
        return True

    def _raise_error_locked(self, i: int) -> None:
        err = self._error
        self._error_left.discard(i)
        if not self._error_left:  # every member has seen it: clear for reuse
            self._error = None
        raise err

    def wait(self, ps):
        """-> (handled, result). handled=False: the fallback just started
        ps's individual request; the caller must wait it."""
        i = self._idx[id(ps)]
        with self._lock:
            if self._error is not None and i in self._error_left:
                # deliver the failed round's error ONCE per member; a member
                # that already consumed it proceeds normally (a fresh partial
                # registration falls back below)
                self._raise_error_locked(i)
            if not self._dispatched:
                if i not in self._bufs:
                    # nothing pending this round: MPI no-op, last result again
                    return True, self._last.get(i)
                self._fallback_locked()
                return False, None
            if i in self._consumed:
                # duplicate wait on an already-consumed member: MPI no-op —
                # MUST not touch req.wait again (the round may re-arm under a
                # second out-of-lock wait and stale parts would be installed)
                return True, self._last.get(i)
            r0 = self._round
        # Blocking wait OUTSIDE the lock: a concurrent Test on another member
        # must stay a non-blocking poll. Safe on success: the round cannot
        # re-arm (or the request restart) until THIS member consumes, and
        # CommRequest.wait is idempotent for concurrent waiters of a completed
        # round. On FAILURE CommRequest consumes its error once, so a second
        # concurrent waiter raises a secondary artifact — first error wins
        # below, and everyone re-raises the stored real error.
        try:
            out = self.req.wait()
        except Exception as e:
            with self._lock:
                if self._round == r0:
                    # first waiter to see the failure decides the round's
                    # fate: degrade (breaker OPEN — individual requests are
                    # now running, ours included) or record for every member
                    if self._degrade_locked(e):
                        return False, None
                    if self._error is None:
                        self._record_error_locked(e)
                    self._raise_error_locked(i)
                if self._degraded_round == r0:
                    # a concurrent waiter degraded this round under us; our
                    # individual request was started by its fallback
                    return False, None
                if self._error is not None and i in self._error_left:
                    self._raise_error_locked(i)
                # round completed under us despite our local failure (e.g. a
                # watchdog trip racing a successful concurrent wait): keep
                # the first-error-wins contract
                if self._error is None:
                    self._record_error_locked(e)
                self._raise_error_locked(i)
        self._breaker.record_success()  # no-op unless HALF_OPEN (the probe)
        with self._lock:
            if self._round != r0:
                # the round completed (or failed over) under us — a concurrent
                # duplicate wait consumed this member; its delivered result is
                # cached, and splitting the stale `out` would poison the NEW
                # round's _parts
                if self._error is not None and i in self._error_left:
                    self._raise_error_locked(i)
                return True, self._last.get(i)
            return True, self._part_locked(out, i)

    def test(self, ps):
        """-> (handled, done, result_or_None); handled=False as in wait()."""
        i = self._idx[id(ps)]
        with self._lock:
            if self._error is not None and i in self._error_left:
                self._raise_error_locked(i)
            if not self._dispatched:
                if i not in self._bufs:
                    return True, True, self._last.get(i)
                self._fallback_locked()
                return False, False, None
            if i in self._consumed:  # duplicate poll: MPI no-op
                return True, True, self._last.get(i)
            try:
                done, out = self.req.test()
            except Exception as e:
                if self._degrade_locked(e):
                    # degraded: the member's individual request is running —
                    # handled=False sends the caller to poll it
                    return False, False, None
                if self._error is None:
                    self._record_error_locked(e)
                self._raise_error_locked(i)
            if not done:
                return True, False, None
            self._breaker.record_success()  # no-op unless HALF_OPEN
            return True, True, self._part_locked(out, i)

    # -- AOT precompilation (Session.precompile_collectives) ---------------

    def precompile(self) -> int:
        """Warm this bucket's pack/unpack programs and its coalesced request
        on zero buffers (jit-cache warm — see CommRequest.precompile for why a
        call, not AOT lower().compile(), is what eliminates the step-0 stall).
        Round state is untouched. Returns the number of programs run.

        Idempotent per INSTANCE, not per shape: _concat/_split are fresh
        jax.jit closures on every GradBucket, so a same-shaped sibling (or a
        second session's bucket) holds cold caches of its own — a shared
        shape-keyed plan entry would skip them and leak the pack/unpack
        compiles back into step 0."""
        import numpy as np

        from mlsl_tpu.types import jnp_dtype

        if self._warmed:
            return 0
        self._warmed = True
        d = self.req.desc
        topo = d.group.topology
        grid = topo.grid_shape
        g = 1 if d.group.is_self else d.group.size
        in_mult = g if self.kind == "reduce_scatter" else 1
        in_dt = jnp_dtype(d.data_type)
        bufs = [
            topo.shard_buffer(np.zeros((*grid, c * in_mult), dtype=in_dt))
            for c in self.counts
        ]
        jax.block_until_ready(self._concat(*bufs))
        if self.kind == "reduce_scatter":
            out_len = d.recv_count
        elif self.kind == "allgather":
            out_len = d.count * g
        else:
            out_len = d.count
        # the quantized ring delivers float32 regardless of the entry dtype
        out_dt = (
            jnp.float32
            if self.compression == CompressionType.QUANTIZATION else in_dt
        )
        out = topo.shard_buffer(np.zeros((*grid, out_len), dtype=out_dt))
        jax.block_until_ready(self._split(out))
        return 2 + self.req.precompile()


def pack_by_size(pss: List, limit: int, size_of) -> List[List]:
    """Greedy packing in reverse creation (= backward start) order; singleton
    groups are dropped (a 1-member bucket is pure overhead). ``size_of(ps)``
    is the member's WIRE contribution — full local gradient bytes, so an
    already-bandwidth-sized layer is excluded regardless of how its buffer is
    chunked. Public: the compiled overlap engine (comm/overlap.py) reuses
    this exact policy to coalesce its in-graph bucket units."""
    cur: List = []
    cur_bytes = 0
    groups: List[List] = []
    for ps in reversed(pss):
        nbytes = size_of(ps)
        if nbytes >= limit:
            # bandwidth-sized already: bucketing adds only copy traffic
            if len(cur) > 1:
                groups.append(cur)
            cur, cur_bytes = [], 0
            continue
        if cur_bytes + nbytes > limit and cur:
            if len(cur) > 1:
                groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(ps)
        cur_bytes += nbytes
    if len(cur) > 1:
        groups.append(cur)
    return groups


#: compressions whose gradient collective coalesces (TOPK stays individual:
#: the sparse wire format has no coalesced form)
_BUCKETABLE = (CompressionType.NONE, CompressionType.QUANTIZATION)


def build_buckets(session, bucket_mb: int) -> int:
    """Pack eligible ParameterSets into GradBuckets (called at Commit):
    plain sets coalesce their gradient allreduce (uncompressed, or the int8
    quantized ring — quantized sets bucket with their own kind, never mixed
    with uncompressed neighbors); distributed-update (ZeRO-1) sets coalesce
    BOTH phases — the gradient reduce_scatter (uncompressed or quantized) and
    the increment all_gather (always uncompressed: there is no compressed
    allgather). Returns the number of buckets formed."""
    from mlsl_tpu.comm.collectives import _group_key
    from mlsl_tpu.types import dtype_size

    # (group key, dtype, compression, codec) -> [ps] creation order: the
    # codec component keeps mixed-codec buckets split — each registry codec
    # owns its residual layout and wire geometry, so a vq set never shares a
    # coalesced ring with an int8 neighbor
    plain: dict = {}
    du: dict = {}
    du_inc: dict = {}  # (group key, dtype) -> [ps]: the increment all_gather
    # is ALWAYS uncompressed, so it coalesces across compression types AND
    # codecs — only the gradient phase partitions by them
    for op in session.operations:
        for ps in op.parameter_sets:
            if not ps.need_comm:
                continue
            key = (_group_key(ps.dist.grad_group), ps.data_type,
                   ps.compression, ps.codec_name)
            if (
                not ps.distributed_update
                and ps.compression in _BUCKETABLE
                and ps.bucket is None
            ):
                plain.setdefault(key, []).append(ps)
            elif ps.distributed_update:
                du.setdefault(key, []).append(ps)
                du_inc.setdefault(key[:2], []).append(ps)

    limit = bucket_mb * 1024 * 1024
    n_buckets = 0

    cfg = session.env.config

    def form(pss, kind, attr, compression=CompressionType.NONE, codec=""):
        nonlocal n_buckets
        if not pss:
            return
        limit_eff = limit
        if (
            compression == CompressionType.QUANTIZATION
            and kind == "allreduce"
            and cfg.large_msg_size_mb > 0
            and cfg.large_msg_chunks > 1
        ):
            # a quantized allreduce above MLSL_LARGE_MSG_SIZE_MB would be
            # linspace-chunked by CommRequest.setup at arbitrary offsets,
            # voiding the slot/ring alignment this bucket just computed and
            # splitting the single bucket residual per chunk — cap the bucket
            # under the chunk threshold instead (7/8: alignment padding can
            # grow the payload by up to 12.5%, the quantize() waste bound)
            limit_eff = min(limit, cfg.large_msg_size_mb * 1024 * 1024 * 7 // 8)
        esize = dtype_size(pss[0].data_type)
        grp = pss[0].dist.grad_group
        g = 1 if grp.is_self else grp.size
        # member's wire contribution: full LOCAL gradient bytes — for the
        # ZeRO-1 reduce_scatter that is owned * g (the whole chunked buffer),
        # so bandwidth-sized layers are excluded consistently across kinds
        # (quantized members are sized at their f32 bytes too: the bucket knob
        # bounds the coalesced payload, not the compressed wire image)
        mult = g if kind == "reduce_scatter" else 1
        size_of = lambda ps: ps.owned_kernel_count * ps.kernel_size * esize * mult
        for members in pack_by_size(pss, limit_eff, size_of):
            bucket = GradBucket(
                members, session.env, kind=kind, compression=compression,
                codec=codec,
            )
            for ps in members:
                setattr(ps, attr, bucket)
            n_buckets += 1

    for (_, _, comp, cname), pss in plain.items():
        form(pss, "allreduce", "bucket", compression=comp, codec=cname)
    for (_, _, comp, cname), pss in du.items():
        if comp in _BUCKETABLE:
            form([ps for ps in pss if ps.bucket is None],
                 "reduce_scatter", "bucket", compression=comp, codec=cname)
    for pss in du_inc.values():
        form([ps for ps in pss if ps.inc_bucket is None],
             "allgather", "inc_bucket")
    if n_buckets:
        log_debug("grad bucketing: %d bucket(s) formed", n_buckets)
    return n_buckets
