"""The Environment singleton — framework bootstrap and global services.

Mirrors the reference Environment (include/mlsl.hpp:799-915, src/mlsl.cpp:684-812):
Init/Finalize, Distribution and Session factories, Alloc/Free, Wait/Test on generic
requests, quantization-params registration, and color-based global-group configuration.
The TPU-native difference: Init builds no MPI world — it captures the JAX device set;
"process count" is the device count and "process idx" is only meaningful per-device
(SPMD), so the single-controller API exposes rank math as pure functions instead.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import numpy as np
import jax

from mlsl_tpu import sysinfo
from mlsl_tpu.config import Config
from mlsl_tpu.comm.request import CommRequest, Dispatcher, RequestStorage
from mlsl_tpu.log import mlsl_assert, set_log_level
from mlsl_tpu.types import DataType, QuantParams, jnp_dtype


class Environment:
    """Process-wide singleton (reference include/mlsl.hpp:799)."""

    _instance: Optional["Environment"] = None
    _lock = threading.Lock()
    _jax_distributed_up = False  # process-wide: jax.distributed inits at most once

    def __init__(self):
        self._initialized = False
        self._init_pid: Optional[int] = None
        self.config: Optional[Config] = None
        self.dispatcher: Optional[Dispatcher] = None
        self.request_storage = RequestStorage()
        self.devices: Sequence[jax.Device] = ()
        self.quant_params: Optional[QuantParams] = None
        self._distributions: list = []
        self._sessions: list = []
        self._global_colors: Optional[tuple] = None

    # -- singleton --------------------------------------------------------

    @classmethod
    def get_env(cls) -> "Environment":
        with cls._lock:
            if cls._instance is None:
                cls._instance = Environment()
            return cls._instance

    @classmethod
    def is_initialized(cls) -> bool:
        return cls._instance is not None and cls._instance._initialized

    # -- lifecycle (reference src/mlsl.cpp:684-746) -----------------------

    def init(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ) -> "Environment":
        """Bootstrap. For multi-host slices/pods pass the jax.distributed
        coordination parameters (the DCN analog of the reference's multi-node MPI
        launch); single-host/single-controller needs none."""
        if self._initialized:
            return self
        if coordinator_address is not None and not Environment._jax_distributed_up:
            # jax.distributed.initialize may only run once per process; init/finalize
            # cycles of the Environment must not re-run it.
            self._distributed_init_with_retry(
                coordinator_address, num_processes, process_id
            )
            Environment._jax_distributed_up = True
        self.config = Config.from_env()
        set_log_level(self.config.log_level)
        sysinfo.auto_config(self.config)
        # fail-fast validation (MLSLError): contradictory settings — an
        # MLSL_ALGO name outside the registry, nonsensical knob ranges — are
        # init-time errors, not latent dispatch failures
        self.config.validate()
        # (re)apply the recovery-ladder breaker knobs: breakers are
        # process-wide and keep their STATE across an Environment rebuild
        # (subsystem health must survive recovery cycles), but adopt the
        # freshly validated thresholds
        from mlsl_tpu import supervisor

        supervisor.configure(self.config)
        if devices is not None:
            self.devices = tuple(devices)
        else:
            # elastic-mesh registry (mlsl_tpu.elastic): after a shrink, a
            # recovery/factory rebuild with no explicit device list must
            # adopt the survivor world, not silently re-inflate to the full
            # one — the registry outlives Environment teardown by design
            from mlsl_tpu import elastic as elastic_mod

            self.devices = (
                elastic_mod.active_devices() or tuple(jax.devices())
            )
        # the persistent XLA cache must be armed BEFORE the tuner sweep: the
        # sweep compiles every eligible algorithm x size x shape program, and
        # on real chips those compiles are the tens-of-seconds cost the cache
        # exists to amortize across restarts
        self._apply_compile_cache()
        # autotuner hook: MLSL_TUNE=1 sweeps and persists a profile on the
        # live mesh; MLSL_TUNE_PROFILE loads one (stale fingerprints rejected
        # with a warning, missing/corrupt files raise). Sets
        # config.tuned_profile, which comm/algos.select consults, and applies
        # tuned chunk/bucket/priority knobs (explicit env always wins).
        from mlsl_tpu import tuner

        tuner.init_profile(self.config, self.devices)
        # telemetry plane (obs/metrics.py + obs/serve.py): arm the registry
        # when MLSL_METRICS or a scrape port asks for it, and start the
        # /metrics + /healthz + /statusz daemon thread on MLSL_METRICS_PORT.
        # Both are process-wide and idempotent (the tracer contract): a
        # recovery teardown/rebuild cycle keeps the series history and the
        # scrape surface alive mid-incident.
        if self.config.metrics or self.config.metrics_port:
            from mlsl_tpu.obs import metrics as obs_metrics

            obs_metrics.enable(every=self.config.metrics_every,
                               retention=self.config.metrics_retention)
        if self.config.metrics_port:
            from mlsl_tpu.obs import serve as obs_serve

            obs_serve.start_server(self.config.metrics_port)
        # pod control plane (mlsl_tpu.control): when the config names a
        # control world, join it — membership/heartbeat over a stdlib TCP
        # channel separate from the JAX collective fabric. Process-wide and
        # idempotent like the telemetry plane: pod membership must survive
        # an Environment rebuild mid-recovery.
        if self.config.control_addrs or (
            self.config.control_port and self.config.control_world
        ):
            from mlsl_tpu import control as control_mod

            control_mod.ensure_started(self.config)
        self.dispatcher = Dispatcher(self.config)
        self._initialized = True
        self._init_pid = os.getpid()
        if self.quant_params is not None:
            try:
                # a pre-init SetQuantizationParams is applied now that config
                # exists; if the deferred codec can no longer load, unwind so a
                # retried init() re-attempts it instead of silently proceeding
                # with the built-in codec
                self.set_quantization_params(self.quant_params)
            except Exception:
                self._initialized = False
                self._init_pid = None
                self.dispatcher.shutdown()
                self.dispatcher = None
                raise
        self._dump_config()
        return self

    @staticmethod
    def _distributed_init_with_retry(
        coordinator_address: str,
        num_processes: Optional[int],
        process_id: Optional[int],
    ) -> None:
        """jax.distributed.initialize with MLSL_DIST_INIT_RETRIES backoff.

        The known gloo TCP preamble race (KNOWN_FAILURES.md) and plain
        coordinator-not-up-yet races surface here as RuntimeError/OSError
        during the coordination-service handshake. Retrying INSIDE init —
        with a best-effort shutdown between attempts so the client can
        rebind — is the library-side fix that let tests/test_multiprocess.py
        drop its test-side retry-on-SIGABRT wrapper. Only the handshake is
        retryable; a failure after the world is up propagates (that is the
        control plane's job, not init's)."""
        import time as _time

        from mlsl_tpu.config import _env_float, _env_int
        from mlsl_tpu.log import log_warning

        retries = max(0, _env_int("MLSL_DIST_INIT_RETRIES", 3))
        backoff_s = max(0.0, _env_float("MLSL_DIST_INIT_BACKOFF_S", 0.5))
        for attempt in range(retries + 1):
            if attempt:
                _time.sleep(backoff_s * (2 ** (attempt - 1)))
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                )
                return
            except (RuntimeError, OSError) as e:
                if attempt >= retries:
                    raise
                log_warning(
                    "jax.distributed.initialize failed (attempt %d/%d, "
                    "retrying in %.2gs): %s: %s",
                    attempt + 1, retries + 1,
                    backoff_s * (2 ** attempt), type(e).__name__, e,
                )
                try:
                    jax.distributed.shutdown()
                except Exception:  # mlsl-lint: disable=A205 -- half-
                    pass  # initialized client: nothing to unwind

    _jax_cache_defaults = None  # knob values before our first mutation

    def _apply_compile_cache(self) -> None:
        """Persistent XLA compilation cache: pre-lowered Session collectives and
        jitted train steps reload from disk on warm restarts instead of
        recompiling (first compiles cost tens of seconds on real chips).
        Thresholds are zeroed while enabled so every program is cached — the
        cache exists to eliminate recompiles, not just the largest ones. The
        toggle is symmetric: an init() without MLSL_COMPILE_CACHE_DIR restores
        the pre-mutation knob values, so 'empty = off' holds across
        init/finalize cycles in one process."""
        if Environment._jax_cache_defaults is None:
            Environment._jax_cache_defaults = (
                jax.config.jax_compilation_cache_dir,
                jax.config.jax_persistent_cache_min_compile_time_secs,
                jax.config.jax_persistent_cache_min_entry_size_bytes,
            )
        if self.config.compile_cache_dir:
            jax.config.update("jax_compilation_cache_dir",
                              self.config.compile_cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        else:
            d, t, s = Environment._jax_cache_defaults
            jax.config.update("jax_compilation_cache_dir", d)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", t)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", s)

    def _dump_config(self) -> None:
        """One-time config/world dump at init (the reference's rank-0 env-var dump,
        src/comm_ep.cpp:1701-1739), at INFO level."""
        from mlsl_tpu.log import log_info

        if jax.process_index() != 0:  # rank-0 only, like the reference
            return
        si = sysinfo.probe()
        log_info(
            "mlsl_tpu init: platform=%s kind=%s devices=%d hosts=%d",
            si.platform, si.device_kind, len(self.devices), si.num_hosts,
        )
        for field, value in sorted(vars(self.config).items()):
            log_info("  config %s = %r", field, value)

    def finalize(self) -> None:
        # Fork-safety: a forked child must not tear down the parent's state
        # (reference initPid guard, src/mlsl.cpp:720-724).
        if not self._initialized or os.getpid() != self._init_pid:
            return
        if self.dispatcher is not None:
            self.dispatcher.shutdown()
        for s in self._sessions:
            s._invalidate()
        self._sessions.clear()
        self._distributions.clear()
        self._initialized = False
        Environment._instance = None

    # -- world introspection ---------------------------------------------

    def get_process_count(self) -> int:
        mlsl_assert(self._initialized, "Environment not initialized")
        return len(self.devices)

    def get_process_idx(self) -> int:
        """Single-controller SPMD: the controller is logical rank 0. Per-device rank
        math lives on Distribution (process_idx_of)."""
        return 0

    # -- configuration (reference src/mlsl.cpp:620-682) -------------------

    def configure(self, conf_str: str) -> None:
        """Color-based restriction of the world (reference Configure("color=N"),
        src/mlsl.cpp:620-647: MPI ranks with the same color form the new global group).

        Single-controller translation: 'color=N' (one value) keeps the full device set
        (every device shares the controller's color — identical to the reference when
        all ranks pass the same color). 'color=c0,c1,...' (one value per device)
        restricts subsequently created Distributions to the devices whose color equals
        the first listed color.
        """
        conf_str = conf_str.strip()
        mlsl_assert(
            conf_str.startswith("color="),
            "unsupported configuration string: %s",
            conf_str,
        )
        values = [int(v) for v in conf_str.split("=", 1)[1].split(",")]
        if len(values) == 1:
            self._global_colors = tuple(values * len(self.devices))
            return
        mlsl_assert(
            len(values) == len(self.devices),
            "color list length %d != device count %d",
            len(values),
            len(self.devices),
        )
        self._global_colors = tuple(values)
        self.devices = tuple(
            d for d, c in zip(self.devices, values) if c == values[0]
        )

    # -- factories --------------------------------------------------------

    def create_distribution(
        self,
        data_parts: int,
        model_parts: int,
        devices: Optional[Sequence[jax.Device]] = None,
        seq_parts: int = 1,
    ):
        from mlsl_tpu.core.distribution import Distribution

        mlsl_assert(self._initialized, "Environment not initialized")
        d = Distribution(
            self,
            data_parts,
            model_parts,
            devices=devices or self.devices,
            seq_parts=seq_parts,
        )
        self._distributions.append(d)
        return d

    def create_distribution_with_colors(self, data_color_per_rank, model_color_per_rank):
        from mlsl_tpu.core.distribution import Distribution

        mlsl_assert(self._initialized, "Environment not initialized")
        d = Distribution(
            self,
            None,
            None,
            devices=self.devices,
            data_colors=tuple(data_color_per_rank),
            model_colors=tuple(model_color_per_rank),
        )
        self._distributions.append(d)
        return d

    def delete_distribution(self, dist) -> None:
        if dist in self._distributions:
            self._distributions.remove(dist)

    def create_session(self, phase_type=None):
        from mlsl_tpu.core.session import Session
        from mlsl_tpu.types import PhaseType

        mlsl_assert(self._initialized, "Environment not initialized")
        s = Session(self, phase_type if phase_type is not None else PhaseType.TRAIN)
        self._sessions.append(s)
        return s

    def delete_session(self, session) -> None:
        if session in self._sessions:
            session._invalidate()
            self._sessions.remove(session)

    # -- memory (reference Alloc/Free -> EPLIB_memalign shm; here device arrays) --

    def alloc(self, count: int, data_type: DataType = DataType.FLOAT):
        """Allocate a zeroed host-side buffer; collectives accept device arrays
        directly, so this exists for API parity and test convenience."""
        return np.zeros((count,), dtype=jnp_dtype(data_type))

    def free(self, buf) -> None:  # noqa: ARG002 - parity no-op (GC owns memory)
        return None

    # -- generic request completion (reference src/mlsl.cpp:784-796) ------

    def wait(self, req: CommRequest):
        out = req.wait()
        self.request_storage.remove(req)
        return out

    def test(self, req: CommRequest):
        done, out = req.test()
        if done:
            self.request_storage.remove(req)
        return done, out

    # -- quantization (reference src/mlsl.cpp:798) ------------------------

    def set_quantization_params(self, params: QuantParams) -> None:
        """Select the codec for CT_QUANTIZATION collectives (reference
        src/mlsl.cpp:798 -> quant_load, quant/quant.c:96-133). Callable fields
        register a jittable user codec; lib_path dlopens the reference's library
        contract (failing loudly if it cannot be honored); otherwise the
        built-in Pallas int8 kernels are used with the given block geometry.

        Before init() the request is recorded and applied at init time (the
        reference likewise defers: quant params submitted pre-Init reach the
        servers on EPLIB_init). State mutates only after a codec loads, so a
        failed lib_path leaves the previous registration fully active."""
        from mlsl_tpu.comm import codec as codec_mod
        from mlsl_tpu.log import mlsl_assert

        codec = None
        if getattr(params, "compress_fn", None) is not None:
            mlsl_assert(
                getattr(params, "decompress_fn", None) is not None,
                "compress_fn requires decompress_fn",
            )
            codec = codec_mod.CustomCodec(
                compress=params.compress_fn,
                decompress=params.decompress_fn,
                reduce=getattr(params, "reduce_sum_fn", None),
            )
        elif params.lib_path:
            # raises MLSLError on open/resolve failure — never silently ignored
            codec = codec_mod.load_library_codec(params)

        self.quant_params = params
        if self.config is not None:
            if params.elem_in_block:
                self.config.quant_block_elems = int(params.elem_in_block)
            self.config.custom_codec = codec

    def get_quantization_params(self) -> Optional[QuantParams]:
        return self.quant_params

    def get_version(self) -> str:
        from mlsl_tpu import __version__

        return __version__

    # PascalCase parity aliases (reference include/mlsl.hpp:799-915)
    GetVersion = get_version
    GetEnv = get_env
    Init = init
    Finalize = finalize
    GetProcessCount = get_process_count
    GetProcessIdx = get_process_idx
    Configure = configure
    CreateDistribution = create_distribution
    CreateDistributionWithColors = create_distribution_with_colors
    DeleteDistribution = delete_distribution
    CreateSession = create_session
    DeleteSession = delete_session
    Alloc = alloc
    Free = free
    Wait = wait
    Test = test
    SetQuantizationParams = set_quantization_params
    GetQuantizationParams = get_quantization_params
