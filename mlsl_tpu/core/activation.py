"""Activations, CommBlockInfo pack/unpack layouts, and the five peer-connection cases.

Mirrors the reference ActivationImpl (src/mlsl_impl.cpp:36-347):

- feature-map partitioning: inputs and non-CC outputs hold globalFmCount/modelParts
  feature maps; a CC (matmul/conv-style) output holds ALL feature maps as partial sums
  and needs a cross-model reduction (needReduce, :47-51);
- InitPeerConnection picks one of five topology cases for each graph edge
  (:139-241) — ReduceScatter+AllGather within one grid, AllReduce into a pure-data
  grid, mixed-grid ReduceScatter (redistribution), or AlltoAll in either direction;
- BIPack*/BIUnpack* compute the CommBlockInfo block layout that maps the rank-local
  activation tensor (localMb, localFm, fmSize) to/from the wire buffer (:243-347).

TPU translation: the "comm buffer" is a distributed jax.Array of the packed layout; the
collectives are the cached shard_map programs from mlsl_tpu.comm; pack/unpack are
vectorized jnp gathers usable both host-side (parity with the reference's user-side
PackBuffer, tests/examples/mlsl_test/mlsl_test.cpp:214-254) and fused under jit.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from mlsl_tpu.comm.request import CommDesc, CommRequest, ComputeType
from mlsl_tpu.log import mlsl_assert, log_debug
from mlsl_tpu.types import DataType, OpType, dtype_size


@dataclasses.dataclass(frozen=True)
class CommBlockInfo:
    """One pack/unpack block (reference include/mlsl.hpp:177-204)."""

    mb_offset: int
    mb_count: int
    fm_offset: int
    fm_count: int
    fm_size: int
    data_type: DataType
    buf_offset: int  # element offset into the wire buffer

    # accessor parity (reference mlsl.py get_mb_offset etc. / C++ GetMbOffset)
    def get_mb_offset(self):
        return self.mb_offset

    def get_mb_count(self):
        return self.mb_count

    def get_fm_offset(self):
        return self.fm_offset

    def get_fm_count(self):
        return self.fm_count

    def get_fm_size(self):
        return self.fm_size

    def get_data_type(self):
        return self.data_type

    def get_buf_offset(self):
        return self.buf_offset

    GetMbOffset = get_mb_offset
    GetMbCount = get_mb_count
    GetFmOffset = get_fm_offset
    GetFmCount = get_fm_count
    GetFmSize = get_fm_size
    GetDataType = get_data_type
    GetBufOffset = get_buf_offset


def pack_local(act_local, blocks: List[CommBlockInfo], local_mb: int, local_fm: int, fm_size: int):
    """Pack a local activation (localMb, localFm, fmSize) into the wire layout.

    Vectorized equivalent of the reference test's PackBuffer loop
    (tests/examples/mlsl_test/mlsl_test.cpp:214-233).
    """
    xp = jnp if not isinstance(act_local, np.ndarray) else np
    a = act_local.reshape(local_mb, local_fm, fm_size)
    total = sum(b.mb_count * b.fm_count * b.fm_size for b in blocks)
    out = xp.zeros((total,), dtype=a.dtype)
    for b in blocks:
        chunk = a[
            b.mb_offset : b.mb_offset + b.mb_count,
            b.fm_offset : b.fm_offset + b.fm_count,
            : b.fm_size,
        ].reshape(-1)
        if xp is np:
            out[b.buf_offset : b.buf_offset + chunk.size] = chunk
        else:
            out = out.at[b.buf_offset : b.buf_offset + chunk.size].set(chunk)
    return out


def unpack_local(wire, blocks: List[CommBlockInfo], local_mb: int, local_fm: int, fm_size: int):
    """Inverse of pack_local: wire layout -> (localMb, localFm, fmSize)."""
    xp = jnp if not isinstance(wire, np.ndarray) else np
    a = xp.zeros((local_mb, local_fm, fm_size), dtype=wire.dtype)
    for b in blocks:
        n = b.mb_count * b.fm_count * b.fm_size
        chunk = wire[b.buf_offset : b.buf_offset + n].reshape(
            b.mb_count, b.fm_count, b.fm_size
        )
        if xp is np:
            a[
                b.mb_offset : b.mb_offset + b.mb_count,
                b.fm_offset : b.fm_offset + b.fm_count,
                : b.fm_size,
            ] = chunk
        else:
            a = a.at[
                b.mb_offset : b.mb_offset + b.mb_count,
                b.fm_offset : b.fm_offset + b.fm_count,
                : b.fm_size,
            ].set(chunk)
    return a


class Activation:
    """An operation's input or output activation handle
    (reference include/mlsl.hpp:210-268, ActivationImpl src/mlsl_impl.cpp:36-66)."""

    def __init__(self, op, reg, is_input: bool, index: int):
        self.op = op
        self.is_input = is_input
        self.act_index = index
        self.dist = op.distribution
        self.global_fm_count = reg.count
        self.fm_size = reg.size
        self.data_type = DataType(reg.data_type)
        self.need_comm = False
        self.peer_act: Optional["Activation"] = None
        self.comm_req: Optional[CommRequest] = None
        self.pack_blocks: List[CommBlockInfo] = []
        self.unpack_blocks: List[CommBlockInfo] = []
        self.tmp_buf_offset = 0

        model_size = self.dist.get_process_count_model()
        if (not is_input) and op.op_type == OpType.CC:
            # CC outputs hold partial sums over the full fm range
            # (reference src/mlsl_impl.cpp:44-51).
            self.local_fm_count = self.global_fm_count
            self.global_fm_offset_fn = lambda model_idx: 0
            self.need_reduce = model_size > 1
        else:
            mlsl_assert(
                self.global_fm_count % model_size == 0,
                "feature-map count %d not divisible by model parts %d",
                self.global_fm_count,
                model_size,
            )
            self.local_fm_count = self.global_fm_count // model_size
            self.global_fm_offset_fn = lambda model_idx: self.local_fm_count * model_idx
            self.need_reduce = False

    # GetGlobalFmOffset needs the rank; controller-side takes model_idx explicitly.
    def get_global_fm_offset(self, model_idx: int = 0) -> int:
        return self.global_fm_offset_fn(model_idx)

    def get_global_fm_count(self) -> int:
        return self.global_fm_count

    def get_local_fm_count(self) -> int:
        return self.local_fm_count

    def get_fm_size(self) -> int:
        return self.fm_size

    def get_data_type(self) -> DataType:
        return self.data_type

    def get_pack_block_count(self) -> int:
        return len(self.pack_blocks)

    def get_pack_block(self, idx: int) -> CommBlockInfo:
        return self.pack_blocks[idx]

    def get_unpack_block_count(self) -> int:
        return len(self.unpack_blocks)

    def get_unpack_block(self, idx: int) -> CommBlockInfo:
        return self.unpack_blocks[idx]

    # -- graph wiring -----------------------------------------------------

    def set_peer(self, act: Optional["Activation"]) -> None:
        if act is None:
            self.peer_act = None
            self.need_comm = False
            return
        mlsl_assert(
            act.global_fm_count * act.fm_size == self.global_fm_count * self.fm_size,
            "prev output activation size must match current input activation size",
        )
        mlsl_assert(self.is_input != act.is_input, "input-output doesn't pair")
        mlsl_assert(self.data_type == act.data_type, "datatype must match")
        mlsl_assert(
            self.peer_act is None or self.peer_act is act, "peer can be set only once"
        )
        mlsl_assert(
            act.peer_act is None or act.peer_act is self,
            "peer activation is already paired with another edge",
        )
        self.peer_act = act
        act.peer_act = self

    # -- the five cases (reference src/mlsl_impl.cpp:139-241) --------------

    def init_peer_connection(self) -> None:
        if self.peer_act is None:
            return
        out_act = self.peer_act if self.is_input else self
        in_act = self if self.is_input else self.peer_act
        if out_act.comm_req is not None or in_act.comm_req is not None:
            return  # already connected from the other side
        out_dist = out_act.dist
        in_dist = in_act.dist
        world = out_dist.get_process_count_global()

        if world > 1 and (out_act.need_reduce or out_dist is not in_dist):
            out_act.need_comm = True
            in_act.need_comm = True
        if not out_act.need_comm:
            return

        env = out_act.op.session.env
        out_model = out_dist.get_process_count_model()
        in_model = in_dist.get_process_count_model()
        out_data = out_dist.get_process_count_data()
        in_data = in_dist.get_process_count_data()
        dt = out_act.data_type
        esize = dtype_size(dt)

        def mk(kind, group, **kw):
            # op-attributed name: trace tracks / watchdog descriptors / the
            # overlap report's span-derived stalls key on the '<op>/' prefix
            req = CommRequest(
                CommDesc(kind, group, kw.pop("count"), dt, **kw), env.dispatcher,
                name=f"{out_act.op.name}/{kind}",
            )
            req.setup()
            return req

        if out_act.need_reduce and out_dist is in_dist:
            log_debug("peer connection case 1 (ReduceScatter fwd / AllGather bwd)")
            n = in_act.local_fm_count * self.op.get_local_minibatch_size() * in_act.fm_size
            out_act.comm_req = mk(
                "reduce_scatter",
                in_dist.model_group,
                count=n * in_model,
                compute_type=ComputeType.FPROP,
                op=0,
                recv_count=n,
            )
            out_act._bi_pack_reduce_scatter()
            in_act._bi_unpack_reduce_scatter()
            in_act.comm_req = mk(
                "allgather",
                in_dist.model_group,
                count=n,
                compute_type=ComputeType.BPROP,
            )
            in_act._bi_pack_allgather()
            out_act._bi_unpack_allgather()
        elif (
            out_act.need_reduce
            and in_model == 1
            and out_data == in_data
        ):
            log_debug("peer connection case 2 (AllReduce fwd / no bwd comm)")
            n = (
                out_act.local_fm_count
                * out_act.op.get_local_minibatch_size()
                * out_act.fm_size
            )
            out_act.comm_req = mk(
                "allreduce",
                out_dist.model_group,
                count=n,
                compute_type=ComputeType.FPROP,
                op=0,
            )
            out_act._bi_pack_allreduce()
            in_act._bi_unpack_allreduce()
            in_act.comm_req = None  # reference: empty request (no ops)
        elif (
            out_act.need_reduce
            and in_model == 1
            and in_data % out_data == 0
            and in_data == out_model * out_data
        ):
            log_debug("peer connection case 3 (mixed-grid ReduceScatter/AllGather)")
            n = in_act.local_fm_count * in_act.op.get_local_minibatch_size() * in_act.fm_size
            out_act.comm_req = mk(
                "reduce_scatter",
                out_dist.model_group,
                count=n * out_model,
                compute_type=ComputeType.FPROP,
                op=0,
                recv_count=n,
            )
            out_act._bi_pack_reduce_scatter2()
            in_act._bi_unpack_reduce_scatter()
            in_act.comm_req = mk(
                "allgather",
                out_dist.model_group,
                count=n,
                compute_type=ComputeType.BPROP,
            )
            in_act._bi_pack_allgather()
            out_act._bi_unpack_allgather2()
        elif (not out_act.need_reduce) and out_model == 1:
            log_debug("peer connection case 4 (AlltoAll over in model group)")
            n = in_act.local_fm_count * out_act.op.get_local_minibatch_size() * in_act.fm_size
            out_act.comm_req = mk(
                "alltoall",
                in_dist.model_group,
                count=n,
                compute_type=ComputeType.FPROP,
            )
            out_act._bi_build_alltoall(in_act)
            in_act.comm_req = mk(
                "alltoall",
                in_dist.model_group,
                count=n,
                compute_type=ComputeType.BPROP,
            )
            in_act._bi_build_alltoall(out_act)
        elif (not out_act.need_reduce) and in_model == 1:
            log_debug("peer connection case 5 (AlltoAll over out model group)")
            n = out_act.local_fm_count * in_act.op.get_local_minibatch_size() * out_act.fm_size
            out_act.comm_req = mk(
                "alltoall",
                out_dist.model_group,
                count=n,
                compute_type=ComputeType.FPROP,
            )
            out_act._bi_build_alltoall(in_act)
            in_act.comm_req = mk(
                "alltoall",
                out_dist.model_group,
                count=n,
                compute_type=ComputeType.BPROP,
            )
            in_act._bi_build_alltoall(out_act)
        else:
            mlsl_assert(False, "this activation topology case is not supported yet")

    # -- block-layout math (reference src/mlsl_impl.cpp:243-347) ----------

    def _bi_pack_reduce_scatter(self):
        model_parts = self.dist.get_process_count_model()
        local_mb = self.op.get_local_minibatch_size()
        fm = self.local_fm_count // model_parts
        self.pack_blocks = [
            CommBlockInfo(0, local_mb, i * fm, fm, self.fm_size, self.data_type,
                          i * local_mb * fm * self.fm_size)
            for i in range(model_parts)
        ]
        self.tmp_buf_offset = model_parts * local_mb * fm * self.fm_size

    def _bi_pack_reduce_scatter2(self):
        model_parts = self.dist.get_process_count_model()
        local_mb = self.op.get_local_minibatch_size() // model_parts
        fm = self.local_fm_count
        self.pack_blocks = [
            CommBlockInfo(i * local_mb, local_mb, 0, fm, self.fm_size, self.data_type,
                          i * local_mb * fm * self.fm_size)
            for i in range(model_parts)
        ]
        self.tmp_buf_offset = model_parts * local_mb * fm * self.fm_size

    def _bi_unpack_reduce_scatter(self):
        self.unpack_blocks = [
            CommBlockInfo(0, self.op.get_local_minibatch_size(), 0,
                          self.local_fm_count, self.fm_size, self.data_type, 0)
        ]

    def _bi_pack_allreduce(self):
        local_mb = self.op.get_local_minibatch_size()
        self.pack_blocks = [
            CommBlockInfo(0, local_mb, 0, self.local_fm_count, self.fm_size,
                          self.data_type, 0)
        ]
        self.tmp_buf_offset = local_mb * self.local_fm_count * self.fm_size

    def _bi_unpack_allreduce(self):
        self.unpack_blocks = [
            CommBlockInfo(0, self.op.get_local_minibatch_size(), 0,
                          self.local_fm_count, self.fm_size, self.data_type, 0)
        ]

    def _bi_pack_allgather(self):
        # Per-rank buf offset depends on the rank's model index; offset 0 on the wire —
        # the gather concatenation provides the placement (the reference needed the
        # explicit fmIdx offset because MPI allgather writes into a shared recv buffer,
        # src/mlsl_impl.cpp:287-294; group-rank ordering is identical).
        local_mb = self.op.get_local_minibatch_size()
        self.pack_blocks = [
            CommBlockInfo(0, local_mb, 0, self.local_fm_count, self.fm_size,
                          self.data_type, 0)
        ]

    def _bi_unpack_allgather(self):
        model_parts = self.dist.get_process_count_model()
        local_mb = self.op.get_local_minibatch_size()
        fm = self.local_fm_count // model_parts
        self.unpack_blocks = [
            CommBlockInfo(0, local_mb, i * fm, fm, self.fm_size, self.data_type,
                          i * local_mb * fm * self.fm_size)
            for i in range(model_parts)
        ]

    def _bi_unpack_allgather2(self):
        model_parts = self.dist.get_process_count_model()
        local_mb = self.op.get_local_minibatch_size() // model_parts
        fm = self.local_fm_count
        self.unpack_blocks = [
            CommBlockInfo(i * local_mb, local_mb, 0, fm, self.fm_size, self.data_type,
                          i * local_mb * fm * self.fm_size)
            for i in range(model_parts)
        ]

    def _bi_build_alltoall(self, other: "Activation"):
        """Blocked AlltoAll layout for redistribution (reference :313-347)."""
        out_act = self
        in_act = other
        out_model = out_act.dist.get_process_count_model()
        in_model = in_act.dist.get_process_count_model()
        mlsl_assert(
            out_model == 1 or in_model == 1, "one of the model group sizes should be 1"
        )
        local_mb = min(
            out_act.op.get_local_minibatch_size(), in_act.op.get_local_minibatch_size()
        )
        fmx = min(
            out_act.local_fm_count * out_act.fm_size,
            in_act.local_fm_count * in_act.fm_size,
        )
        my_fm = fmx // self.fm_size
        blocks = []
        idx = 0
        for i in range(0, self.op.get_local_minibatch_size(), local_mb):
            for j in range(0, self.local_fm_count, my_fm):
                blocks.append(
                    CommBlockInfo(i, local_mb, j, my_fm, self.fm_size, self.data_type,
                                  idx * local_mb * fmx)
                )
                idx += 1
        if self.is_input:
            self.unpack_blocks = blocks
        else:
            self.pack_blocks = blocks
        group = in_act.dist.model_group if out_model == 1 else out_act.dist.model_group
        self.tmp_buf_offset = group.size * local_mb * fmx

    # -- runtime ----------------------------------------------------------

    def get_comm_buf_size(self) -> int:
        """Required wire-buffer element count for this activation's collective
        (reference Activation::GetCommBuf sizing; buffers are functional here, so
        this is the size the packed distributed buffer must have)."""
        if self.comm_req is None:
            return 0
        return self.comm_req.desc.count

    def get_comm_buf(self):
        """The most recent communication result for this activation's request, or
        None (the reference returns the staging buffer; functional arrays make the
        last result the analog)."""
        if self.comm_req is None:
            return None
        return self.comm_req._result

    def start_comm(self, buf) -> None:
        """Dispatch this activation's collective on the packed distributed buffer
        (reference ActivationImpl::StartComm src/mlsl_impl.cpp:354-369)."""
        self.op.session._stat_event(self, "start")
        if self.need_comm and self.comm_req is not None:
            self.comm_req.start(buf)
        self.op.session._stat_event(self, "start_done")

    def wait_comm(self):
        """Wait on the PEER's request (reference invariant: the output owns FPROP, the
        input owns BPROP; WaitComm always completes the peer's transfer,
        src/mlsl_impl.cpp:377-380). Returns the received distributed buffer or None."""
        self.op.session._stat_event(self, "wait")
        out = None
        if self.need_comm and self.peer_act is not None and self.peer_act.comm_req is not None:
            if self.peer_act.comm_req.is_started:
                out = self.peer_act.comm_req.wait()
            else:
                out = self.peer_act.comm_req._result
        self.op.session._stat_event(self, "wait_done")
        return out

    # PascalCase parity aliases
    GetGlobalFmCount = get_global_fm_count
    GetGlobalFmOffset = get_global_fm_offset
    GetLocalFmCount = get_local_fm_count
    GetFmSize = get_fm_size
    GetDataType = get_data_type
    GetPackBlockCount = get_pack_block_count
    GetPackBlock = get_pack_block
    GetUnpackBlockCount = get_unpack_block_count
    GetUnpackBlock = get_unpack_block
    GetCommBufSize = get_comm_buf_size
    GetCommBuf = get_comm_buf
    StartComm = start_comm
    WaitComm = wait_comm
