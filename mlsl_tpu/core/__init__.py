"""Public API layer: Environment / Session / Operation / Distribution / Statistics."""
