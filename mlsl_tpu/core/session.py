"""Session, OperationRegInfo, Operation: the graph registration and commit path.

Mirrors the reference (include/mlsl.hpp:510-798, src/mlsl_impl.cpp:540-600,
src/mlsl_impl.hpp:941-1097): a Session collects Operations sharing a global minibatch
size; each Operation is registered from an OperationRegInfo (activation shapes +
parameter sets) against a Distribution; SetPrev/SetNext wire graph edges; Commit
finalizes every edge (picks the peer-connection case, builds the collectives) and runs
the isolation benchmark when statistics are enabled.

The TPU "Commit = compile" analog: all CommRequests are built over cached jitted
shard_map programs at commit time, so the training loop only re-dispatches compiled
executables (the reference likewise builds all CommRequests once and reuses them,
src/mlsl_impl.hpp:1024-1071).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from mlsl_tpu.core.activation import Activation
from mlsl_tpu.core.parameter_set import ParameterSet
from mlsl_tpu.core.stats import Statistics
from mlsl_tpu.log import log_debug, mlsl_assert
from mlsl_tpu.types import CompressionType, DataType, OpType, PhaseType


@dataclasses.dataclass
class _RegEntry:
    count: int
    size: int
    data_type: DataType
    distributed_update: bool = False
    compression: CompressionType = CompressionType.NONE


class OperationRegInfo:
    """Shape registration for one Operation (reference include/mlsl.hpp:510-556)."""

    def __init__(self, op_type: OpType):
        self.op_type = OpType(op_type)
        self.name = ""
        self.inputs: List[_RegEntry] = []
        self.outputs: List[_RegEntry] = []
        self.parameter_sets: List[_RegEntry] = []

    def set_name(self, name: str) -> None:
        self.name = name

    def add_input(self, count: int, size: int, data_type=DataType.FLOAT) -> int:
        self.inputs.append(_RegEntry(int(count), int(size), DataType(data_type)))
        return len(self.inputs) - 1

    def add_output(self, count: int, size: int, data_type=DataType.FLOAT) -> int:
        self.outputs.append(_RegEntry(int(count), int(size), DataType(data_type)))
        return len(self.outputs) - 1

    def add_parameter_set(
        self,
        kernel_count: int,
        kernel_size: int,
        data_type=DataType.FLOAT,
        distributed_update: bool = False,
        compression_type=CompressionType.NONE,
    ) -> int:
        self.parameter_sets.append(
            _RegEntry(
                int(kernel_count),
                int(kernel_size),
                DataType(data_type),
                bool(distributed_update),
                CompressionType(compression_type),
            )
        )
        return len(self.parameter_sets) - 1

    def validate(self) -> None:
        if self.op_type == OpType.DATA:
            mlsl_assert(not self.inputs, "DATA op cannot have inputs")
        if self.op_type == OpType.EVAL:
            mlsl_assert(not self.outputs, "EVAL op cannot have outputs")

    # PascalCase parity aliases
    SetName = set_name
    AddInput = add_input
    AddOutput = add_output
    AddParameterSet = add_parameter_set


class Operation:
    """One graph node (reference include/mlsl.hpp:564-645, OperationImpl
    src/mlsl_impl.hpp:941-1097)."""

    def __init__(self, reg: OperationRegInfo, session: "Session", distribution, op_idx: int):
        reg.validate()
        self.session = session
        self.distribution = None
        self._reg = reg
        self.op_type = reg.op_type
        self.name = reg.name or f"op{op_idx}"
        self.op_idx = op_idx
        self.inputs: List[Activation] = []
        self.outputs: List[Activation] = []
        self.parameter_sets: List[ParameterSet] = []
        if distribution is not None:
            self.set_distribution(distribution)

    def set_distribution(self, distribution) -> None:
        """Bind (or late-bind) the parallelism layout. The reference allows
        AddOperation(regInfo, NULL) followed by Operation::SetDistribution
        (include/mlsl.hpp:765-767, :574); activations and parameter sets are
        derived here because their partitioning depends on the grid."""
        mlsl_assert(
            self.distribution is None, "distribution can be set only once"
        )
        mlsl_assert(
            not getattr(distribution, "is_ragged", False),
            "operations require equal-sized color groups: the minibatch/kernel "
            "partitioning assumes a uniform group size (ragged partitions "
            "support Distribution collectives only)",
        )
        self.distribution = distribution
        reg = self._reg

        data_size = distribution.get_process_count_data()
        global_mb = self.session.global_minibatch_size
        mlsl_assert(
            global_mb % data_size == 0,
            "global minibatch %d not divisible by data parts %d",
            global_mb,
            data_size,
        )
        self.global_minibatch_size = global_mb
        self.local_minibatch_size = global_mb // data_size

        self.inputs = [Activation(self, r, True, i) for i, r in enumerate(reg.inputs)]
        self.outputs = [Activation(self, r, False, i) for i, r in enumerate(reg.outputs)]
        self.parameter_sets = [
            ParameterSet(self, r, i) for i, r in enumerate(reg.parameter_sets)
        ]

    # -- introspection -----------------------------------------------------

    def get_op_type(self) -> OpType:
        return self.op_type

    def get_name(self) -> str:
        return self.name

    def get_distribution(self):
        return self.distribution

    def get_session(self):
        return self.session

    def get_global_minibatch_size(self) -> int:
        return self.global_minibatch_size

    def get_local_minibatch_size(self) -> int:
        return self.local_minibatch_size

    def get_global_minibatch_offset(self, data_idx: int = 0) -> int:
        return self.local_minibatch_size * data_idx

    def get_input_count(self) -> int:
        return len(self.inputs)

    def get_input(self, idx: int) -> Activation:
        return self.inputs[idx]

    def get_output_count(self) -> int:
        return len(self.outputs)

    def get_output(self, idx: int) -> Activation:
        return self.outputs[idx]

    def get_parameter_set_count(self) -> int:
        return len(self.parameter_sets)

    def has_parameter_sets(self) -> bool:
        return bool(self.parameter_sets)

    def get_parameter_set(self, idx: int) -> ParameterSet:
        return self.parameter_sets[idx]

    # -- graph wiring (reference src/mlsl_impl.cpp:68-113) -----------------

    def set_prev(self, prev: Optional["Operation"], input_idx: int, prev_out_idx: int) -> None:
        act = self.inputs[input_idx]
        if prev is None:
            act.set_peer(None)
            return
        mlsl_assert(prev.session is self.session, "different sessions")
        prev.outputs[prev_out_idx].set_peer(act)

    def set_next(self, nxt: Optional["Operation"], output_idx: int, next_in_idx: int) -> None:
        act = self.outputs[output_idx]
        if nxt is None:
            act.set_peer(None)
            return
        mlsl_assert(nxt.session is self.session, "different sessions")
        act.set_peer(nxt.inputs[next_in_idx])

    # PascalCase parity aliases
    GetOpType = get_op_type
    GetName = get_name
    GetDistribution = get_distribution
    GetSession = get_session
    GetGlobalMinibatchSize = get_global_minibatch_size
    GetLocalMinibatchSize = get_local_minibatch_size
    GetGlobalMinibatchOffset = get_global_minibatch_offset
    GetInputCount = get_input_count
    GetInput = get_input
    GetOutputCount = get_output_count
    GetOutput = get_output
    GetParameterSetCount = get_parameter_set_count
    GetParameterSet = get_parameter_set
    HasParameterSets = has_parameter_sets
    SetDistribution = set_distribution
    SetPrev = set_prev
    SetNext = set_next


class Session:
    """A collection of Operations with one global minibatch size
    (reference include/mlsl.hpp:731-797)."""

    def __init__(self, env, phase_type: PhaseType = PhaseType.TRAIN):
        self.env = env
        self.phase_type = PhaseType(phase_type)
        self.global_minibatch_size = 0
        self.operations: List[Operation] = []
        self.stats = Statistics(self)
        self._committed = False
        self._valid = True

    def _invalidate(self):
        self._valid = False

    def set_global_minibatch_size(self, size: int) -> None:
        mlsl_assert(size > 0, "global minibatch size must be positive")
        self.global_minibatch_size = int(size)

    def get_global_minibatch_size(self) -> int:
        return self.global_minibatch_size

    def get_phase_type(self) -> PhaseType:
        return self.phase_type

    def create_operation_reg_info(self, op_type: OpType) -> OperationRegInfo:
        return OperationRegInfo(op_type)

    def delete_operation_reg_info(self, reg: OperationRegInfo) -> None:
        return None

    def add_operation(self, reg: OperationRegInfo, distribution=None) -> int:
        """Register an operation. distribution may be None (reference
        AddOperation(regInfo, NULL)) and bound later with
        Operation.set_distribution — it must be bound before Commit."""
        mlsl_assert(self.global_minibatch_size > 0, "set global minibatch size first")
        mlsl_assert(
            distribution is None or not getattr(distribution, "is_ragged", False),
            "operations require equal-sized color groups: the minibatch/kernel "
            "partitioning assumes a uniform group size (ragged partitions "
            "support Distribution collectives only)",
        )
        op = Operation(reg, self, distribution, len(self.operations))
        self.operations.append(op)
        return len(self.operations) - 1

    # reference mlsl.py exposes both spellings
    add_operation_with_distribution = add_operation

    def remove_operations(self) -> None:
        self.operations.clear()
        self._committed = False

    def get_operation_count(self) -> int:
        return len(self.operations)

    def get_operation(self, idx: int) -> Operation:
        return self.operations[idx]

    def get_stats(self) -> Statistics:
        return self.stats

    def commit(self) -> None:
        """Finalize all graph edges and build the collectives
        (reference SessionImpl::Commit src/mlsl_impl.cpp:567-578)."""
        for op in self.operations:
            mlsl_assert(
                op.distribution is not None,
                "operation %s has no distribution bound at Commit", op.name,
            )
        for op in self.operations:
            for act in op.outputs:
                act.init_peer_connection()
            for act in op.inputs:
                act.init_peer_connection()
        self._committed = True
        cfg = self.env.config
        if cfg is not None and getattr(cfg, "tune_codec", False):
            # MLSL_TUNE_CODEC=1: measure per-set gradient sensitivity and
            # assign codec x block against the convergence (NSR) budget —
            # BEFORE buckets form, so they partition on the calibrated
            # codecs (tuner/calibrate.py; docs/TUNING.md §22)
            from mlsl_tpu.tuner.calibrate import calibrate_session

            calibrate_session(self)
        if cfg is not None and cfg.grad_bucket_mb > 0:
            from mlsl_tpu.core.bucketing import build_buckets

            build_buckets(self, cfg.grad_bucket_mb)
        if cfg is not None and getattr(cfg, "verify", False):
            # MLSL_VERIFY=1: statically verify the collective plan NOW —
            # after buckets formed (their geometry is checked) and before
            # the precompile warm spends compile time on a plan the
            # verifier may reject (mlsl_tpu/analysis/plan.py; severity
            # behavior under MLSL_VERIFY_SEVERITY)
            from mlsl_tpu.analysis.plan import run_commit_verify
            from mlsl_tpu.analysis.protocol import run_commit_protocol_check

            run_commit_verify(self)
            # same gate, second pass: exhaustively explore the control-plane
            # membership/drain and elastic shrink/grow protocol models
            # (deadlock-freedom, no dual coordinator, no lost drain-ack) —
            # memoized process-wide, so repeated commits pay once
            # (mlsl_tpu/analysis/protocol.py, A15x)
            run_commit_protocol_check(self)
        if cfg is not None and cfg.precompile:
            self.precompile_collectives()
        self.stats.initialize()
        if cfg is not None and cfg.enable_stats:
            self.stats.collect_isolation_stats()

    def precompile_collectives(self) -> int:
        """AOT-warm every collective program this session's committed graph
        can dispatch — activation edges, per-layer gradient/increment
        requests (plain, chunked, quant-ring), and the coalesced GradBucket
        programs (pack, collective, unpack) — by executing each once on zero
        buffers, so step 0 of the training loop contains no collective
        compilation (run automatically at Commit under MLSL_PRECOMPILE=1).

        Idempotent across sessions: programs already warmed under the same
        plan key (the collectives-cache identity: kind, group, dtype, count,
        compression) are skipped via collectives._plan_cache, which
        collectives.clear_cache() clears together with the program cache.
        Returns the number of programs run."""
        from mlsl_tpu.comm.collectives import _group_key, _plan_cache

        n = 0

        from mlsl_tpu.types import CompressionType

        cfg = self.env.config

        def warm_req(req):
            nonlocal n
            if req is None or not req.is_setup:
                return
            d = req.desc
            # compressed programs are parameterized by codec geometry the
            # desc does not carry (quant_ring/sparse cache by it): a plan
            # entry recorded under one block size / ratio / custom codec must
            # not suppress warming a program built under another
            codec_key = ()
            if d.compression != CompressionType.NONE:
                codec_key = (cfg.quant_block_elems, cfg.topk_ratio,
                             id(cfg.custom_codec))
            # pallas-ring variant identity: a slot-geometry or direction
            # change compiles a DIFFERENT kernel, and a plan entry recorded
            # under the old geometry must not skip re-warming it
            pallas_key = ()
            if req.algo in ("pallas_ring", "pallas_ring2d"):
                pallas_key = (
                    int(getattr(cfg, "pallas_ring_slots", 2)),
                    bool(getattr(cfg, "pallas_ring_bidir", False)),
                )
            elif req.algo == "pallas_rhd":
                # the rhd kernel's only compile-time knob is slot depth
                pallas_key = (int(getattr(cfg, "pallas_ring_slots", 2)),)
            elif req.algo == "pallas_a2a":
                # wire-codec identity: toggling the int8 codec (or its block
                # grid) compiles a DIFFERENT kernel
                from mlsl_tpu.ops import a2a_kernels

                pallas_key = (
                    int(getattr(cfg, "pallas_ring_slots", 2)),
                    int(getattr(cfg, "quant_block_elems", 256)),
                    bool(a2a_kernels.quant_enabled(cfg)),
                )
            elif req.algo == "hier":
                # two-tier variant identity: a DCN-codec or tier-shape
                # change compiles a DIFFERENT program (comm/algos/hier.py),
                # and a stale plan entry must not skip re-warming it
                import os

                pallas_key = (
                    str(getattr(cfg, "hier_dcn_codec", "int8")),
                    os.environ.get("MLSL_MESH_TIERS", ""),
                )
            # the algorithm identity is part of the plan key: a profile (or
            # MLSL_ALGO) switching a request from 'lax' to 'rhd' between
            # sessions compiles a DIFFERENT program, and a stale plan entry
            # recorded under the old algorithm must not skip warming it
            key = (
                "req", d.kind, _group_key(d.group), int(d.data_type), d.count,
                int(d.compression), d.recv_count,
                None if d.op is None else int(d.op), d.root,
                len(req._chunk_slices), codec_key, pallas_key, req.algo,
            )
            if key in _plan_cache:
                return
            n += req.precompile()
            _plan_cache[key] = True

        buckets: dict = {}
        for op in self.operations:
            for act in op.inputs + op.outputs:
                warm_req(act.comm_req)
            for ps in op.parameter_sets:
                warm_req(ps.grad_req)
                warm_req(ps.inc_req)
                for b in (ps.bucket, ps.inc_bucket):
                    if b is not None:
                        buckets[id(b)] = b
        # buckets warm per INSTANCE (GradBucket.precompile is idempotent on
        # itself): their pack/unpack are per-instance jit closures, so a
        # shape-identity plan entry would skip a same-shaped sibling whose
        # caches are cold. Only the bucket's underlying collective comes from
        # the shared module caches — re-warming it costs one cheap execution.
        for b in buckets.values():
            n += b.precompile()
        if n:
            log_debug("precompile: %d collective program(s) warmed at commit", n)
        return n

    # -- statistics plumbing ----------------------------------------------

    def _stat_event(self, entity, action: str, is_param: bool = False, is_increment: bool = False):
        # Gate on started, not the env flag: MLSL_STATS drives the default via
        # initialize(), but Statistics.start() must also work programmatically
        # (reference Statistics::Start, include/mlsl.hpp:662) — bench.py turns
        # accounting on for a few un-timed steps to emit the overlap fraction.
        if self.stats.is_started():
            self.stats.update(entity, action, is_param, is_increment)

    # PascalCase parity aliases
    SetGlobalMinibatchSize = set_global_minibatch_size
    GetGlobalMinibatchSize = get_global_minibatch_size
    GetPhaseType = get_phase_type
    CreateOperationRegInfo = create_operation_reg_info
    DeleteOperationRegInfo = delete_operation_reg_info
    AddOperation = add_operation
    RemoveOperations = remove_operations
    GetOperationCount = get_operation_count
    GetOperation = get_operation
    GetStats = get_stats
    Commit = commit
    PrecompileCollectives = precompile_collectives
