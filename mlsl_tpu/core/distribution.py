"""Distribution: the data x model process grid and its 11 collectives + Barrier.

Mirrors the reference Distribution (include/mlsl.hpp:350-504) and DistributionImpl's
grid construction (src/mlsl_impl.hpp:174-305). The grid math reproduces the reference's
color formulas exactly:

    lSize = dataParts * modelParts ; lId = p % lSize ; iR = p / lSize
    dataIdx(p)  = lId / modelParts      (index within the data group)
    modelIdx(p) = lId % modelParts      (index within the model group)

so the model axis is minor. On TPU the grid IS a jax.sharding.Mesh of shape
(replica, data, model); subgroup collectives lower onto the ICI rings of the named axes.

Buffers: each collective takes a "distributed buffer" — a global jax.Array of shape
(R, D, S, M, n) whose (r, d, s, m) slice is that rank's local buffer — and returns a
CommRequest already started (the reference returns CommReq* from each call too,
completed via Environment.Wait/Test). Helpers shard_buffer/make_buffer build them.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax

from mlsl_tpu.comm.mesh import (
    GRID_AXES,
    Topology,
    ProcessGroup,
    DATA_AXIS,
    SEQ_AXIS,
    MODEL_AXIS,
)
from mlsl_tpu.comm.request import CommDesc, CommRequest
from mlsl_tpu.log import mlsl_assert
from mlsl_tpu.types import (
    DataType, GroupType, ReductionType, dtype_size, jnp_dtype,
)


class Distribution:
    def __init__(
        self,
        env,
        data_parts: Optional[int],
        model_parts: Optional[int],
        devices: Sequence[jax.Device],
        data_colors: Optional[Tuple[int, ...]] = None,
        model_colors: Optional[Tuple[int, ...]] = None,
        seq_parts: int = 1,
    ):
        self.env = env
        self._colors_mode = data_colors is not None

        if self._colors_mode:
            # Color-based construction (reference src/mlsl_impl.hpp:268-280):
            # group sizes are derived from the color assignment.
            n = len(devices)
            mlsl_assert(
                len(data_colors) == n and len(model_colors) == n,
                "color arrays must have one entry per device (%d)",
                n,
            )
            from collections import Counter

            # Unequal partitions are allowed, as with MPI_Comm_split (reference
            # src/comm_ep.cpp:1821-1827): parts are the MAX group size, and
            # size-dependent results on smaller groups are zero-padded to it
            # (see comm/collectives._make_ragged_body for which kinds support it).
            # Ragged distributions carry collectives only — the operation graph's
            # minibatch partitioning needs uniform group sizes (see
            # Session.add_operation).
            self.data_parts = max(Counter(data_colors).values())
            self.model_parts = max(Counter(model_colors).values())
            self.is_ragged = (
                len(set(Counter(data_colors).values())) > 1
                or len(set(Counter(model_colors).values())) > 1
            )
            self.seq_parts = 1
            # The mesh is flat (N, 1, 1, 1); groups are pure color partitions.
            self.topology = Topology(1, 1, devices=devices)
            self.data_group = ProcessGroup(self.topology, (), colors=tuple(data_colors))
            self.model_group = ProcessGroup(
                self.topology, (), colors=tuple(model_colors)
            )
            self.seq_group = ProcessGroup(self.topology, ())
            self.global_group = ProcessGroup(self.topology, GRID_AXES)
            self.grad_group = self.data_group
            # Logical replica count is 1 in colors mode (reference
            # src/mlsl_impl.hpp:268-273); the Topology's (N,1,1) mesh shape is a
            # storage layout, not a replica structure — size buffers via
            # world_shape/make_buffer, never from replica_count.
            self.replica_count = 1
        else:
            self.topology = Topology(
                data_parts, model_parts, devices=devices, seq_parts=seq_parts
            )
            self.data_parts = data_parts
            self.model_parts = model_parts
            self.seq_parts = seq_parts
            self.replica_count = self.topology.replica_count
            self.data_group = (
                ProcessGroup(self.topology, (DATA_AXIS,))
                if data_parts > 1
                else ProcessGroup(self.topology, ())
            )
            self.model_group = (
                ProcessGroup(self.topology, (MODEL_AXIS,))
                if model_parts > 1
                else ProcessGroup(self.topology, ())
            )
            self.seq_group = (
                ProcessGroup(self.topology, (SEQ_AXIS,))
                if seq_parts > 1
                else ProcessGroup(self.topology, ())
            )
            self.global_group = ProcessGroup(self.topology, GRID_AXES)
            # Parameter gradients sum over BOTH batch shards and sequence shards
            # (sequence parallelism looks like data parallelism to the parameters).
            grad_axes = tuple(
                a
                for a, n in ((DATA_AXIS, data_parts), (SEQ_AXIS, seq_parts))
                if n > 1
            )
            self.grad_group = ProcessGroup(self.topology, grad_axes)
        self._self_group = ProcessGroup(self.topology, ())

    # -- introspection (reference include/mlsl.hpp:360-373) ---------------

    def _group(self, gt: GroupType) -> ProcessGroup:
        gt = GroupType(gt)
        if gt == GroupType.DATA:
            return self.data_group
        if gt == GroupType.MODEL:
            return self.model_group
        if gt == GroupType.SEQ:
            return self.seq_group
        return self.global_group

    def get_process_count(self, group_type: GroupType) -> int:
        g = self._group(group_type)
        return 1 if g.is_self else g.size

    def get_process_idx(self, group_type: GroupType, global_idx: int = 0) -> int:
        """Member index of world-rank ``global_idx`` within the group. The reference's
        per-rank GetProcessIdx maps to this with the rank made explicit (SPMD
        single-controller has no implicit 'my rank')."""
        g = self._group(group_type)
        return 0 if g.is_self else g.group_idx_of(global_idx)

    def get_process_count_data(self) -> int:
        return self.get_process_count(GroupType.DATA)

    def get_process_count_model(self) -> int:
        return self.get_process_count(GroupType.MODEL)

    def get_process_count_global(self) -> int:
        return self.topology.world_size

    def get_data_parts(self) -> int:
        return self.data_parts

    def get_model_parts(self) -> int:
        return self.model_parts

    def get_seq_parts(self) -> int:
        return self.seq_parts

    # -- buffer helpers ----------------------------------------------------

    @property
    def world_shape(self) -> Tuple[int, int, int, int]:
        return self.topology.grid_shape

    def make_buffer(self, per_rank_fn, count: int, data_type=DataType.FLOAT):
        """Build a distributed buffer from a function global_rank -> np.ndarray(count)."""
        shape = self.world_shape
        n = int(np.prod(shape))
        buf = np.stack(
            [per_rank_fn(p) for p in range(n)], axis=0
        ).reshape(*shape, count).astype(jnp_dtype(data_type))
        return self.topology.shard_buffer(buf)

    def shard_buffer(self, array) -> jax.Array:
        """Place an (R, D, S, M, ...) host array onto the mesh."""
        return self.topology.shard_buffer(np.asarray(array))

    def local_part(self, buf, global_idx: int):
        """Rank-local slice of a distributed buffer (host-side, for tests/inspection)."""
        r, d, s, m = self.topology.coords(global_idx)
        return np.asarray(buf)[r, d, s, m]

    # -- collectives (reference include/mlsl.hpp:375-503) -----------------

    def _start(self, desc: CommDesc, buf) -> CommRequest:
        req = CommRequest(desc, self.env.dispatcher)
        req.setup()
        req.start(buf)
        self.env.request_storage.register(req)
        return req

    def bcast(self, buffer, count, data_type, root_idx, group_type) -> CommRequest:
        return self._start(
            CommDesc(
                "bcast",
                self._group(group_type),
                int(count),
                DataType(data_type),
                root=int(root_idx),
            ),
            buffer,
        )

    def reduce(
        self, send_buffer, count, data_type, red_type, root_idx, group_type
    ) -> CommRequest:
        return self._start(
            CommDesc(
                "reduce",
                self._group(group_type),
                int(count),
                DataType(data_type),
                op=ReductionType(red_type),
                root=int(root_idx),
            ),
            send_buffer,
        )

    def all_reduce(self, send_buffer, count, data_type, red_type, group_type,
                   compression=None) -> CommRequest:
        """compression (optional CompressionType) routes the reduction through
        the registered codec — the built-in Pallas int8 block ring or a
        user-pluggable codec from set_quantization_params (reference: quantized
        allreduce swaps in MPI_QUANT_OP, src/comm_ep.cpp:946-950)."""
        from mlsl_tpu.types import CompressionType

        return self._start(
            CommDesc(
                "allreduce",
                self._group(group_type),
                int(count),
                DataType(data_type),
                op=ReductionType(red_type),
                compression=(CompressionType(compression)
                             if compression is not None
                             else CompressionType.NONE),
            ),
            send_buffer,
        )

    def all_to_all(self, send_buffer, send_count, data_type, group_type) -> CommRequest:
        g = self._group(group_type)
        return self._start(
            CommDesc("alltoall", g, int(send_count), DataType(data_type)),
            send_buffer,
        )

    def all_to_allv(
        self,
        send_buffer,
        send_counts,
        send_offsets,
        recv_counts,
        recv_offsets,
        data_type,
        group_type,
    ) -> CommRequest:
        g = self._group(group_type)
        s = np.asarray(send_counts, dtype=int)
        count = int(s.sum(axis=-1).max()) if s.ndim else int(s)

        def _tup(a):
            if a is None:
                return None
            a = np.asarray(a, dtype=int)
            if a.ndim == 1:
                return tuple(int(v) for v in a)
            return tuple(tuple(int(v) for v in row) for row in a)

        return self._start(
            CommDesc(
                "alltoallv",
                g,
                count,
                DataType(data_type),
                send_counts=_tup(send_counts),
                send_offsets=_tup(send_offsets),
                recv_counts=_tup(recv_counts),
                recv_offsets=_tup(recv_offsets),
            ),
            send_buffer,
        )

    def gather(self, send_buffer, send_count, data_type, root_idx, group_type) -> CommRequest:
        """Device-side rooted gather. SPMD buffers are rank-uniform, so the
        result buffer spans (G * send_count) on EVERY member — an HBM superset
        over MPI's root-only delivery (reference src/comm_ep.cpp:1011-1120)
        that is structural to single-program shard_map (docs/DESIGN.md,
        'Rooted gather and the memory contract'). Above
        MLSL_GATHER_DEVICE_LIMIT_MB (per-device output bytes) it is rejected
        in favor of gather_to_host, which has no device footprint at all."""
        g = self._group(group_type)
        gsize = 1 if g.is_self else g.size
        cfg = getattr(self.env, "config", None)
        limit = getattr(cfg, "gather_device_limit_mb", 0) if cfg else 0
        out_bytes = gsize * int(send_count) * dtype_size(DataType(data_type))
        mlsl_assert(
            limit <= 0 or out_bytes <= limit * 1024 * 1024,
            "gather output (%d MiB per device; rank-uniform SPMD buffers "
            "replicate the concatenation on every member) exceeds "
            "MLSL_GATHER_DEVICE_LIMIT_MB=%d — use gather_to_host for "
            "root-delivered results with no device footprint",
            out_bytes >> 20, limit,
        )
        return self._start(
            CommDesc(
                "gather",
                g,
                int(send_count),
                DataType(data_type),
                root=int(root_idx),
            ),
            send_buffer,
        )

    def gather_to_host(self, send_buffer, send_count, data_type, root_idx,
                       group_type) -> dict:
        """Rooted gather with HOST delivery: {root_world_rank: np.ndarray(G*n)}
        per group instance.

        The TPU-native rooted contract: in a single-controller SPMD program a
        rooted result is consumed by the controller (or written back to one
        rank's user buffer, as the compat layer does), so the concatenation is
        assembled on the host from the already-distributed blocks — ZERO
        device-side wire traffic and ZERO extra HBM, strictly less data motion
        than the reference's network gather (src/comm_ep.cpp:1011-1120). The
        device path (``gather``) stays available for results that feed device
        computation, at the documented rank-uniform HBM cost. Works on ragged
        color groups too (host assembly needs no padding).

        Multi-process: needs other hosts' shards, so (like every MPI gather)
        EVERY process must call it; remote blocks ride one DCN all-gather to
        each host — the same G*n the reference's network gather moves
        (src/comm_ep.cpp:1011-1120), still with zero HBM superset."""
        g = self._group(group_type)
        world = self.topology.world_size
        if getattr(send_buffer, "is_fully_addressable", True):
            host = np.asarray(send_buffer)
        else:
            from jax.experimental import multihost_utils

            host = multihost_utils.process_allgather(send_buffer, tiled=True)
        host = np.asarray(host).reshape(world, -1)[:, : int(send_count)]
        if g.is_self:
            return {p: host[p].copy() for p in range(world)}
        if g.colors is not None:
            rows = [g.member_world_ranks(c) for c in sorted(set(g.colors))]
        else:
            from mlsl_tpu.comm.collectives import _axis_groups_tbl

            rows = list(_axis_groups_tbl(g))
        out = {}
        for row in rows:
            mlsl_assert(
                int(root_idx) < len(row),
                "root member index %d out of range for group of size %d",
                int(root_idx), len(row),
            )
            root_w = int(row[int(root_idx)])
            out[root_w] = np.concatenate([host[q] for q in row])
        return out

    def all_gather(self, send_buffer, send_count, data_type, group_type) -> CommRequest:
        return self._start(
            CommDesc(
                "allgather",
                self._group(group_type),
                int(send_count),
                DataType(data_type),
            ),
            send_buffer,
        )

    def all_gatherv(
        self, send_buffer, send_count, recv_counts, data_type, group_type
    ) -> CommRequest:
        return self._start(
            CommDesc(
                "allgatherv",
                self._group(group_type),
                int(send_count),
                DataType(data_type),
                recv_counts=tuple(recv_counts),
            ),
            send_buffer,
        )

    def scatter(self, send_buffer, recv_count, data_type, root_idx, group_type) -> CommRequest:
        g = self._group(group_type)
        return self._start(
            CommDesc(
                "scatter",
                g,
                int(recv_count) * (1 if g.is_self else g.size),
                DataType(data_type),
                root=int(root_idx),
                recv_count=int(recv_count),
            ),
            send_buffer,
        )

    def reduce_scatter(
        self, send_buffer, recv_count, data_type, red_type, group_type
    ) -> CommRequest:
        g = self._group(group_type)
        return self._start(
            CommDesc(
                "reduce_scatter",
                g,
                int(recv_count) * (1 if g.is_self else g.size),
                DataType(data_type),
                op=ReductionType(red_type),
                recv_count=int(recv_count),
            ),
            send_buffer,
        )

    def send_recv_list(self, buffer, count, data_type, pairs, group_type) -> CommRequest:
        """Point-to-point exchange list: each (src, dst) member pair moves ``count``
        elements; non-recipients get zeros. Implements the reference's SendRecvList
        CommOp (src/comm.hpp:212-248, declared there but never built) via
        lax.ppermute — the pipeline-parallel boundary-transfer primitive."""
        g = self._group(group_type)
        gsize = 1 if g.is_self else g.size
        srcs = [int(s) for s, _ in pairs]
        dsts = [int(d) for _, d in pairs]
        for s, d in zip(srcs, dsts):
            mlsl_assert(
                0 <= s < gsize and 0 <= d < gsize,
                "SendRecvList pair (%d, %d) out of range for group size %d",
                s, d, gsize,
            )
        # ppermute (the fast path) requires unique sources and destinations;
        # enforce the same contract on every path so semantics never depend on
        # the group's shape.
        mlsl_assert(
            len(set(srcs)) == len(srcs) and len(set(dsts)) == len(dsts),
            "SendRecvList sources and destinations must be unique",
        )
        return self._start(
            CommDesc(
                "sendrecv",
                g,
                int(count),
                DataType(data_type),
                pairs=tuple(zip(srcs, dsts)),
            ),
            buffer,
        )

    def barrier(self, group_type) -> None:
        g = self._group(group_type)
        req = CommRequest(
            CommDesc("barrier", g, 1, DataType.FLOAT), self.env.dispatcher
        )
        req.setup()
        token = self.topology.shard_buffer(
            np.ones((*self.world_shape, 1), dtype=np.float32)
        )
        req.start(token)
        req.wait()

    # reference-style PascalCase aliases (API parity with include/mlsl.hpp) ----
    GetProcessCount = get_process_count
    GetProcessIdx = get_process_idx
    Bcast = bcast
    Reduce = reduce
    AllReduce = all_reduce
    AlltoAll = all_to_all
    AlltoAllv = all_to_allv
    Gather = gather
    GatherToHost = gather_to_host
    AllGather = all_gather
    AllGatherv = all_gatherv
    Scatter = scatter
    ReduceScatter = reduce_scatter
    SendRecvList = send_recv_list
    Barrier = barrier
