"""Statistics: online comm/compute accounting and the isolation benchmark.

Mirrors the reference Statistics engine (include/mlsl.hpp:651-726,
src/mlsl_impl_stats.cpp):

- Online accounting: every Start/Wait/Test on any entity emits an event pair; the time
  since the previous event is attributed to *compute* on the pre-event and to *comm* on
  the post-event, and bytes are attributed on Start (reference UpdateStats
  :564-668). "Cycles" are reported as nanoseconds (TPU has no rdtsc visible to the
  host; the unit is documented).

- Isolation benchmark at Commit: every registered comm request is replayed
  ISOLATION_ITERS times (first ISOLATION_SKIP discarded) with compute off, using zero
  buffers, giving the pure-communication time per iteration (reference
  CollectIsolationStats :387-562, iters/skip hardcoded :48-49). This doubles as the
  algbw-vs-size measurement harness used by bench.py.

- Table printer to mlsl_stats.log (reference :226-363).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax

from mlsl_tpu.types import dtype_size, jnp_dtype

ISOLATION_ITERS = 10
ISOLATION_SKIP = 4
STATS_OUTPUT_FILE = "mlsl_stats.log"


class _Slot:
    __slots__ = ("bytes", "comm_ns", "comp_ns", "events")

    def __init__(self):
        self.bytes = 0
        self.comm_ns = 0
        self.comp_ns = 0
        self.events = 0


def _entity_key(entity, is_param: bool, is_increment: bool) -> Tuple:
    if is_param:
        kind = "INC" if is_increment else "GRAD"
        return (kind, entity.param_index)
    kind = "IA" if entity.is_input else "OA"
    return (kind, entity.act_index)


class Statistics:
    def __init__(self, session):
        self.session = session
        self._started = False
        self._last_event_ns: Optional[int] = None
        self._slots: Dict[Tuple[int, Tuple], _Slot] = {}
        self._isolation_ns: Dict[int, int] = {}   # op_idx -> per-iteration comm ns
        self._isolation_bytes: Dict[int, int] = {}

    # -- lifecycle ---------------------------------------------------------

    def is_enabled(self) -> bool:
        cfg = self.session.env.config
        return bool(cfg and cfg.enable_stats)

    def is_started(self) -> bool:
        return self._started

    def initialize(self) -> None:
        self._slots.clear()
        if self.is_enabled():
            self._started = True
            self._last_event_ns = time.perf_counter_ns()

    def start(self) -> None:
        self._started = True
        self._last_event_ns = time.perf_counter_ns()

    def stop(self) -> None:
        self._started = False

    def reset(self) -> None:
        self._slots.clear()
        self._last_event_ns = time.perf_counter_ns()

    # -- online accounting -------------------------------------------------

    def _slot(self, op_idx: int, key: Tuple) -> _Slot:
        s = self._slots.get((op_idx, key))
        if s is None:
            s = _Slot()
            self._slots[(op_idx, key)] = s
        return s

    def update(self, entity, action: str, is_param: bool, is_increment: bool) -> None:
        """Pre-events ('start','wait','test') attribute elapsed time to compute;
        post-events ('*_done') attribute it to comm; bytes counted on start.

        Peer-op redirection (reference UpdateStats src/mlsl_impl_stats.cpp:564-668):
        WaitComm on an activation completes the PEER's transfer, so its comm time is
        charged to the peer's (op, entity) slot."""
        if not self._started:
            return
        now = time.perf_counter_ns()
        delta = now - (self._last_event_ns or now)
        self._last_event_ns = now
        target = entity
        if (
            not is_param
            and action in ("wait", "wait_done")
            and getattr(entity, "peer_act", None) is not None
        ):
            target = entity.peer_act
        op_idx = target.op.op_idx
        slot = self._slot(op_idx, _entity_key(target, is_param, is_increment))
        if action.endswith("_done"):
            slot.comm_ns += delta
        else:
            slot.comp_ns += delta
        if action == "start":
            req = _entity_request(entity, is_param, is_increment)
            if req is not None:
                slot.bytes += req.desc.payload_bytes()
        slot.events += 1

    # -- isolation benchmark ----------------------------------------------

    def collect_isolation_stats(self) -> None:
        """Replay every registered comm with compute off (reference :387-562)."""
        for op in self.session.operations:
            total_ns = 0
            total_bytes = 0
            for req in _op_requests(op):
                ns, nbytes = isolation_time_request(req)
                total_ns += ns
                total_bytes += nbytes
            self._isolation_ns[op.op_idx] = total_ns
            self._isolation_bytes[op.op_idx] = total_bytes

    # -- queries (reference include/mlsl.hpp:680-725) ----------------------

    def get_isolation_comm_cycles(self, op_idx: int) -> int:
        return self._isolation_ns.get(op_idx, 0)

    def get_comm_size(self, op_idx: int) -> int:
        return sum(s.bytes for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_comm_cycles(self, op_idx: int) -> int:
        return sum(s.comm_ns for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_compute_cycles(self, op_idx: int) -> int:
        return sum(s.comp_ns for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_total_isolation_comm_cycles(self) -> int:
        return sum(self._isolation_ns.values())

    def get_total_comm_size(self) -> int:
        return sum(s.bytes for s in self._slots.values())

    def get_total_comm_cycles(self) -> int:
        return sum(s.comm_ns for s in self._slots.values())

    def get_total_compute_cycles(self) -> int:
        return sum(s.comp_ns for s in self._slots.values())

    # -- printer (reference :226-363) --------------------------------------

    def print_(self, path: str = STATS_OUTPUT_FILE) -> str:
        lines = []
        mb = max(self.session.global_minibatch_size, 1)
        lines.append(
            f"{'op':<16} {'entity':<8} {'KB':>12} {'comm Kns/img':>14} "
            f"{'comp Kns/img':>14} {'events':>8}"
        )
        for (op_idx, key), slot in sorted(self._slots.items()):
            op = self.session.operations[op_idx]
            lines.append(
                f"{op.name:<16} {key[0] + str(key[1]):<8} "
                f"{slot.bytes / 1024.0:>12.1f} {slot.comm_ns / 1e3 / mb:>14.2f} "
                f"{slot.comp_ns / 1e3 / mb:>14.2f} {slot.events:>8}"
            )
        for op_idx, ns in sorted(self._isolation_ns.items()):
            op = self.session.operations[op_idx]
            lines.append(
                f"{op.name:<16} {'ISOLATE':<8} "
                f"{self._isolation_bytes.get(op_idx, 0) / 1024.0:>12.1f} "
                f"{ns / 1e3 / mb:>14.2f} {'-':>14} {'-':>8}"
            )
        text = "\n".join(lines) + "\n"
        try:
            with open(path, "a") as f:
                f.write(text)
        except OSError:
            pass
        return text

    def trace(self, log_dir: str):
        """Device-level profiler trace context (the jax.profiler complement to the
        host-side byte/time accounting; view in TensorBoard/Perfetto). Usage:

            with session.get_stats().trace("/tmp/trace"):
                trainer.step(batch)
        """
        import jax

        return jax.profiler.trace(log_dir)

    # PascalCase parity aliases
    Start = start
    Stop = stop
    Reset = reset
    IsStarted = is_started
    IsEnabled = is_enabled
    Print = print_
    GetIsolationCommCycles = get_isolation_comm_cycles
    GetCommSize = get_comm_size
    GetCommCycles = get_comm_cycles
    GetComputeCycles = get_compute_cycles
    GetTotalIsolationCommCycles = get_total_isolation_comm_cycles
    GetTotalCommSize = get_total_comm_size
    GetTotalCommCycles = get_total_comm_cycles
    GetTotalComputeCycles = get_total_compute_cycles


# -- helpers -----------------------------------------------------------------


def _entity_request(entity, is_param: bool, is_increment: bool):
    if is_param:
        return entity.inc_req if is_increment else entity.grad_req
    return entity.comm_req


def _op_requests(op) -> List:
    reqs = []
    for act in op.inputs + op.outputs:
        if act.comm_req is not None:
            reqs.append(act.comm_req)
    for ps in op.parameter_sets:
        if ps.grad_req is not None:
            reqs.append(ps.grad_req)
        if ps.inc_req is not None:
            reqs.append(ps.inc_req)
    return reqs


def isolation_time_request(req) -> Tuple[int, int]:
    """(per-iteration ns, payload bytes) for one request, measured in isolation."""
    d = req.desc
    topo = d.group.topology
    buf = topo.shard_buffer(
        np.zeros((*topo.grid_shape, d.count), dtype=jnp_dtype(d.data_type))
    )
    times = []
    for i in range(ISOLATION_ITERS):
        t0 = time.perf_counter_ns()
        req.start(buf)
        req.wait()
        times.append(time.perf_counter_ns() - t0)
    good = times[ISOLATION_SKIP:]
    return int(sum(good) / max(len(good), 1)), d.payload_bytes()
