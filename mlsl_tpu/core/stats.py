"""Statistics: online comm/compute accounting and the isolation benchmark.

Mirrors the reference Statistics engine (include/mlsl.hpp:651-726,
src/mlsl_impl_stats.cpp):

- Online accounting: every Start/Wait/Test on any entity emits an event pair; the time
  since the previous event is attributed to *compute* on the pre-event and to *comm* on
  the post-event, and bytes are attributed on Start (reference UpdateStats
  :564-668). "Cycles" are reported as nanoseconds (TPU has no rdtsc visible to the
  host; the unit is documented).

- Isolation benchmark at Commit: every registered comm request is replayed
  ISOLATION_ITERS times (first ISOLATION_SKIP discarded) with compute off, using zero
  buffers, giving the pure-communication time per iteration (reference
  CollectIsolationStats :387-562, iters/skip hardcoded :48-49). This doubles as the
  algbw-vs-size measurement harness used by bench.py.

- Table printer to mlsl_stats.log (reference :226-363).
"""

from __future__ import annotations

import collections
import contextlib
import os
import time
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np
import jax

from mlsl_tpu.log import log_warning
from mlsl_tpu.obs import tracer as obs
from mlsl_tpu.types import jnp_dtype

ISOLATION_ITERS = 10
ISOLATION_SKIP = 4
STATS_OUTPUT_FILE = "mlsl_stats.log"


def stats_path(name: str = STATS_OUTPUT_FILE) -> str:
    """Where the stats log lands: ``MLSL_STATS_DIR`` (default CWD, the
    reference's behavior). Read per call, not at import — tests route it to a
    tmp dir and long-lived processes may re-point it between phases."""
    d = os.environ.get("MLSL_STATS_DIR")
    return os.path.join(d, name) if d else name

# Watchdog event record: every request the watchdog declared stuck, with its
# descriptor and how long it had been in flight. Process-wide (the watchdog
# fires from the request layer, which has no Session handle); bounded so a
# recurrently flaky interconnect cannot grow memory across recoveries — the
# full history lives in STATS_OUTPUT_FILE, appended per event below.
WATCHDOG_EVENTS: Deque[dict] = collections.deque(maxlen=256)


def record_watchdog_event(descriptor: str, phase: str, waited_s: float) -> None:
    """Called by CommRequest._watchdog_trip just before it raises
    MLSLTimeoutError."""
    evt = {
        "descriptor": descriptor,
        "phase": phase,
        "waited_s": waited_s,
        "at": time.time(),
    }
    WATCHDOG_EVENTS.append(evt)
    log_warning(
        "watchdog: request stuck in %s for %.2fs: %s", phase, waited_s, descriptor
    )
    if obs._tracer is not None:
        # flight recorder: dump the trailing window of spans around the stall
        # (the stuck epoch plus margin) so the timeout report carries the
        # timeline that led to it — the stuck request's own watchdog.trip
        # instant is already in the ring (CommRequest._watchdog_trip)
        from mlsl_tpu.obs import export as obs_export

        path = obs_export.flight_record(
            window_s=max(2 * waited_s, 30.0),
            reason=f"watchdog {phase}: {descriptor}",
        )
        if path:
            evt["flight_record"] = path
            log_warning("watchdog flight record written: %s", path)
    prof = _profile_on_trip(descriptor)
    if prof:
        evt["device_profile"] = prof
    try:
        with open(stats_path(), "a") as f:
            f.write(
                f"{'WATCHDOG':<16} {phase:<8} waited {waited_s:>10.2f} s  "
                f"{descriptor}\n"
            )
    except OSError:
        pass


#: how long the on-trip device profile samples the wedged state (seconds):
#: long enough for the profiler to catch the in-flight executable / idle
#: devices, short enough that the trip still raises promptly
PROFILE_ON_TRIP_WINDOW_S = 0.25


def _profile_on_trip(reason: str) -> Optional[str]:
    """``MLSL_PROFILE_ON_TRIP=1``: capture a short jax.profiler device trace
    of the wedged state, next to the flight record — the host timeline says
    WHERE the wait stuck, the device profile says what (if anything) the
    chips were doing under it. Best-effort by contract: a profiler failure
    (already active, unsupported backend) must never replace the
    MLSLTimeoutError the watchdog exists to raise."""
    v = (os.environ.get("MLSL_PROFILE_ON_TRIP") or "").strip().lower()
    if v in ("", "0", "false", "no", "off"):
        return None
    out_dir = os.path.join(
        obs.trace_dir(), f"profile-trip-{time.time_ns() // 1_000_000}"
    )
    try:
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(PROFILE_ON_TRIP_WINDOW_S)
        finally:
            jax.profiler.stop_trace()
    except Exception as e:  # profiler busy/unsupported: keep the trip primary
        log_warning(
            "MLSL_PROFILE_ON_TRIP capture failed (%s: %s); continuing with "
            "the host flight record only (%s)", type(e).__name__, e, reason,
        )
        return None
    log_warning("watchdog device profile written: %s", out_dir)
    return out_dir


# Bucket-round accounting (core/bucketing.py): process-wide like the watchdog
# record — buckets fire from the request layer with no Session handle. The
# aggregate counters are the tracked signal (printed by Statistics.print_ into
# STATS_OUTPUT_FILE); the bounded event ring keeps the recent per-round detail
# for diagnosis without growing memory on a long run.
BUCKET_EVENTS: Deque[dict] = collections.deque(maxlen=256)
BUCKET_COUNTERS: Dict[str, int] = {
    "rounds_dispatched": 0,   # full rounds served by one coalesced dispatch
    "rounds_fallback": 0,     # early-Wait rounds degraded to individual reqs
    "member_abandons": 0,     # members restarted mid-flight (ran individually)
    "bytes_coalesced": 0,     # member payload bytes carried by bucket rounds
    "wire_bytes_saved": 0,    # est. wire bytes compression saved vs f32 rounds
}


def record_bucket_round(
    event: str, kind: str, members: int = 0, coalesced: int = 0,
    wire_saved: int = 0,
) -> None:
    """Called by GradBucket at every round transition (dispatch / early-Wait
    fallback / member-restart abandon)."""
    if event == "dispatched":
        BUCKET_COUNTERS["rounds_dispatched"] += 1
        BUCKET_COUNTERS["bytes_coalesced"] += coalesced
        BUCKET_COUNTERS["wire_bytes_saved"] += wire_saved
    elif event == "fallback":
        BUCKET_COUNTERS["rounds_fallback"] += 1
    else:  # abandon
        BUCKET_COUNTERS["member_abandons"] += max(members, 1)
    BUCKET_EVENTS.append(
        {"event": event, "kind": kind, "members": members, "at": time.time()}
    )
    if obs._tracer is not None:
        # round transitions on the comm timeline (the dispatched round's
        # pack+Start duration is recorded by GradBucket itself)
        obs._tracer.instant(f"bucket.{event}", "bucket", kind=kind,
                            members=members)


def reset_bucket_counters() -> None:
    for k in BUCKET_COUNTERS:
        BUCKET_COUNTERS[k] = 0
    BUCKET_EVENTS.clear()


# Degradation-ladder accounting (mlsl_tpu.supervisor): breaker transitions,
# degraded dispatches, comm retries, and supervised recoveries — process-wide
# like the watchdog record (breakers fire from the request layer with no
# Session handle). Breaker transitions append a DEGRADE line to
# STATS_OUTPUT_FILE immediately (cold path — trips are rare by construction);
# per-dispatch fallbacks and retries only bump counters + the obs timeline
# (an OPEN breaker degrades every dispatch, and a file append per layer per
# step would be the new bottleneck). Statistics.print_ renders the counter
# totals as the DEGRADE summary line.
DEGRADE_EVENTS: Deque[dict] = collections.deque(maxlen=256)
DEGRADE_COUNTERS: Dict[str, int] = {
    "breaker_trips": 0,     # closed/half_open -> open transitions
    "breaker_probes": 0,    # open -> half_open probe admissions
    "breaker_resets": 0,    # half_open -> closed (healthy path re-engaged)
    "comm_retries": 0,      # rung-2 transient retries (dispatch + wait)
    "recoveries": 0,        # rung-4 supervised checkpoint restarts
}
#: degraded dispatches per subsystem (quant->plain, bucket->individual,
#: algo->lax, tracer->no-op)
DEGRADE_FALLBACKS: Dict[str, int] = {}


def record_degrade(subsystem: str, event: str, detail: str = "") -> None:
    """One ladder event: ``event`` is a breaker transition ('trip' /
    'probe' / 'reset'), a degraded dispatch ('fallback'), or a supervised
    restart ('recover'). Called by supervisor.CircuitBreaker and the
    degraded call sites."""
    if event == "trip":
        DEGRADE_COUNTERS["breaker_trips"] += 1
    elif event == "probe":
        DEGRADE_COUNTERS["breaker_probes"] += 1
    elif event == "reset":
        DEGRADE_COUNTERS["breaker_resets"] += 1
    elif event == "recover":
        DEGRADE_COUNTERS["recoveries"] += 1
    elif event == "codec_demote":
        # guardrail demotion (mlsl_tpu.codecs): counted in its own family
        # (CODEC_COUNTERS, via record_codec_demotion) — here it only joins
        # the event deque + DEGRADE file line, not the fallback counter
        pass
    else:  # fallback: one dispatch served by the degraded path
        DEGRADE_FALLBACKS[subsystem] = DEGRADE_FALLBACKS.get(subsystem, 0) + 1
    DEGRADE_EVENTS.append(
        {"subsystem": subsystem, "event": event, "detail": detail,
         "at": time.time()}
    )
    if obs._tracer is not None:
        # trip/reset instants bracket the degraded interval on the timeline;
        # fallback instants attribute each degraded dispatch
        name = f"breaker.{event}" if event != "fallback" else "degrade.fallback"
        obs._tracer.instant(name, "degrade", subsystem=subsystem,
                            detail=detail or None)
    if event in ("trip", "probe", "reset", "recover", "codec_demote"):
        try:
            with open(stats_path(), "a") as f:
                f.write(
                    f"{'DEGRADE':<16} {event.upper():<8} {subsystem:<10} "
                    f"{detail}\n"
                )
        except OSError:
            pass


# Integrity-sentinel accounting (mlsl_tpu.sentinel): gate screens/fires and
# consistency audits — process-wide like the degrade counters (the sentinel
# fires from the trainer with no Session handle). Statistics.print_ renders
# the totals as the SENTINEL line in mlsl_stats.log; gate fires and audit
# mismatches also land on the obs timeline as integrity.* instants (emitted
# by the sentinel itself, which owns the step/reason context).
SENTINEL_COUNTERS: Dict[str, int] = {
    "screened": 0,        # steps the quality gate inspected
    "gate_warn": 0,       # gate fired with response 'warn' (run continued)
    "gate_skip": 0,       # gate fired with response 'skip_step'
    "gate_rollback": 0,   # gate fired with response 'rollback' (raised)
    "audits": 0,          # cross-replica consistency audits run
    "audit_mismatch": 0,  # audits that found replica divergence
    "verified_saves": 0,  # checkpoints saved with a passing fingerprint
    "reaudits": 0,        # post-restore re-audits (rollback verification)
}


def record_sentinel(event: str) -> None:
    """One sentinel event: 'screened', 'gate_<response>', 'audits',
    'audit_mismatch', 'verified_saves', or 'reaudits'."""
    SENTINEL_COUNTERS[event] += 1


def reset_sentinel_counters() -> None:
    for k in SENTINEL_COUNTERS:
        SENTINEL_COUNTERS[k] = 0


# Codec-lab accounting (mlsl_tpu.codecs): per-codec wire bytes (compressed
# image of each started round's payload — the codec-comparable bandwidth
# signal) and the calibration/guardrail event counters. Process-wide like the
# degrade counters: the guardrail fires from the sentinel with no Session
# handle. Demotions additionally keep a bounded attribution list (which
# request, which codec, why) — the post-mortem answer to "who turned my VQ
# off", mirrored into supervisor.status()["codecs"].
CODEC_WIRE_BYTES: Dict[str, int] = {}
CODEC_COUNTERS: Dict[str, int] = {
    "calibrations": 0,     # calibration passes run (Session.commit)
    "assignments": 0,      # ParameterSets routed to a calibrated codec
    "guard_breaches": 0,   # sentinel loss z-score breaches while guarded
    "demotions": 0,        # guardrail demotions to int8
}
CODEC_DEMOTIONS: List[str] = []
_CODEC_DEMOTIONS_MAX = 64


def record_codec(event: str) -> None:
    """One codec-lab event: a key of CODEC_COUNTERS."""
    CODEC_COUNTERS[event] += 1


def record_codec_wire(codec: str, nbytes: int) -> None:
    """One started compressed round: ``nbytes`` of wire image under
    ``codec`` (called from CommRequest.start — one dict upsert)."""
    CODEC_WIRE_BYTES[codec] = CODEC_WIRE_BYTES.get(codec, 0) + int(nbytes)


def record_codec_demotion(request: str, codec: str, reason: str) -> None:
    """Guardrail demotion attribution: bump the counter, keep the bounded
    attribution row, and cut the DEGRADE ladder line (codec_demote)."""
    CODEC_COUNTERS["demotions"] += 1
    if len(CODEC_DEMOTIONS) < _CODEC_DEMOTIONS_MAX:
        CODEC_DEMOTIONS.append(f"{request}: {codec} -> int8 ({reason})")
    record_degrade("quant", "codec_demote", f"{request} {codec}->int8 {reason}")


def reset_codec_counters() -> None:
    for k in CODEC_COUNTERS:
        CODEC_COUNTERS[k] = 0
    CODEC_WIRE_BYTES.clear()
    CODEC_DEMOTIONS.clear()


# Elastic-mesh accounting (mlsl_tpu.elastic): device losses routed to the
# reshard rung, shrink/grow cycles, and the re-admission audit verdicts —
# process-wide like the degrade counters (the coordinator outlives every
# Environment rebuild it performs). Cold events (a reshard is rarer than a
# breaker trip) append an immediate ELASTIC line to mlsl_stats.log, the same
# contract as DEGRADE transitions; Statistics.print_ renders the totals.
ELASTIC_COUNTERS: Dict[str, int] = {
    "device_losses": 0,     # DEVICE_LOSS faults reaching the coordinator
    "shrinks": 0,           # successful shrink reshard cycles
    "grows": 0,             # successful grow (re-admission) cycles
    "grow_abandons": 0,     # grows abandoned on persistent divergence
    "admits": 0,            # replicas admitted on a passing fingerprint audit
    "admit_rejects": 0,     # admission audits that found divergence
    "resyncs": 0,           # rejected copies re-broadcast from survivors
    "reshard_buffers": 0,   # ZeRO-1 state buffers moved by reshard plans
    "restart_fallbacks": 0,  # losses escalated to checkpoint restart
}


def record_elastic(event: str, detail: str = "", n: int = 1) -> None:
    """One elastic-mesh event (see ELASTIC_COUNTERS keys). Events that mark
    a topology change or an admission verdict get an immediate ELASTIC line
    in mlsl_stats.log; per-buffer accounting only bumps the counter."""
    ELASTIC_COUNTERS[event] += n
    # every event is cold (topology change / admission verdict) except the
    # per-buffer accounting — state the exception so a new counter cannot
    # silently fall out of the immediate-line contract
    if event != "reshard_buffers":
        try:
            with open(stats_path(), "a") as f:
                f.write(
                    f"{'ELASTIC':<16} {event.upper():<16} {detail}\n"
                )
        except OSError:
            pass


def reset_elastic_counters() -> None:
    for k in ELASTIC_COUNTERS:
        ELASTIC_COUNTERS[k] = 0


# Buffer-checker accounting (mlsl_tpu.checker): how many buffers CHKP
# inspected, how many violated the contract, and how many device syncs the
# batched CHKP_VALUES finiteness path actually paid (the point of batching:
# value_checks >> value_syncs on a multi-request round).
CHKP_COUNTERS: Dict[str, int] = {
    "checks": 0,        # buffers validated (shape/dtype/sharding tier)
    "violations": 0,    # checks that raised (any tier)
    "value_checks": 0,  # finiteness verdicts queued (CHKP_VALUES)
    "value_syncs": 0,   # device syncs paid to resolve queued verdicts
}


def record_chkp(event: str, n: int = 1) -> None:
    CHKP_COUNTERS[event] += n


def reset_chkp_counters() -> None:
    for k in CHKP_COUNTERS:
        CHKP_COUNTERS[k] = 0


# Static-analysis accounting (mlsl_tpu.analysis): verifier/linter runs and
# their finding counts. Process-wide like the other event families (the
# verifier fires from Session.commit, which may run for several sessions in
# one process); each run also appends an immediate ANALYSIS line below.
ANALYSIS_COUNTERS: Dict[str, int] = {
    "runs": 0,       # verify/lint passes completed
    "errors": 0,     # error-severity findings across all runs
    "warnings": 0,   # warn-severity findings across all runs
}


def record_analysis(kind: str, errors: int, warnings: int,
                    codes: List[str], duration_s: float = 0.0) -> None:
    """One finished static-analysis pass (called by analysis.diagnostics
    .record): counters plus an immediate ANALYSIS line in the stats log —
    the verifier's verdict belongs next to the DEGRADE/WATCHDOG history it
    exists to prevent."""
    ANALYSIS_COUNTERS["runs"] += 1
    ANALYSIS_COUNTERS["errors"] += int(errors)
    ANALYSIS_COUNTERS["warnings"] += int(warnings)
    verdict = "FAIL" if errors else "PASS"
    try:
        with open(stats_path(), "a") as f:
            f.write(
                f"{'ANALYSIS':<16} {kind:<8} {verdict:<5} "
                f"errors={errors} warnings={warnings} "
                f"dt={duration_s * 1e3:.2f}ms"
                + (f"  codes={','.join(codes)}" if codes else "") + "\n"
            )
    except OSError:
        pass


def reset_analysis_counters() -> None:
    for k in ANALYSIS_COUNTERS:
        ANALYSIS_COUNTERS[k] = 0


# Straggler-sentinel accounting (mlsl_tpu.obs.straggler): cross-replica
# skew audits, confirmed-straggler flags, and elastic sheds — process-wide
# like the degrade counters (the sentinel is fed from the trainer with no
# Session handle). Flags and sheds are cold (a confirmed straggler is rarer
# than a breaker trip) and append an immediate STRAGGLER line, the DEGRADE
# transition contract; per-audit bookkeeping only bumps the counter.
STRAGGLER_COUNTERS: Dict[str, int] = {
    "audits": 0,          # cross-replica comparisons run
    "flags": 0,           # confirmed stragglers (sustained skew) flagged
    "sheds": 0,           # flagged replicas handed to the elastic coordinator
    "shed_fallbacks": 0,  # shed handoffs the coordinator refused/failed
}


def record_straggler(event: str, detail: str = "") -> None:
    """One straggler-sentinel event (see STRAGGLER_COUNTERS keys)."""
    STRAGGLER_COUNTERS[event] += 1
    if event != "audits":  # audits are the per-interval heartbeat, not news
        try:
            with open(stats_path(), "a") as f:
                f.write(f"{'STRAGGLER':<16} {event.upper():<8} {detail}\n")
        except OSError:
            pass


def reset_straggler_counters() -> None:
    for k in STRAGGLER_COUNTERS:
        STRAGGLER_COUNTERS[k] = 0


# Pod-control-plane accounting (mlsl_tpu.control): heartbeat traffic,
# membership detection/commit, election, and drain coordination —
# process-wide like the other families (pod membership outlives every
# Environment rebuild). Heartbeat traffic is the hot path (every interval x
# every peer) and only bumps counters; everything else is a cold membership
# event and appends an immediate CONTROL line, the DEGRADE transition
# contract — the acceptance story ("who noticed the death, who committed
# the epoch, who ordered the drain") must be readable from mlsl_stats.log.
CONTROL_COUNTERS: Dict[str, int] = {
    "heartbeats_sent": 0,   # frames sent (hot: counter only)
    "heartbeats_recv": 0,   # frames received (hot: counter only)
    "send_failures": 0,     # control-channel sends that failed (hot)
    "deaths_detected": 0,   # peers locally declared dead (miss budget)
    "epochs_committed": 0,  # membership/drain epochs applied (fenced)
    "stale_rejected": 0,    # stale-epoch / deposed-leader orders rejected
    "elections": 0,         # leadership changes observed
    "notices": 0,           # preemption notices submitted locally
    "drain_decisions": 0,   # pod-wide drain verdicts made (leader only)
    "drains_executed": 0,   # local drain executions completed
    "evicted": 0,           # this rank declared dead by the pod (partition)
}

_CONTROL_HOT = ("heartbeats_sent", "heartbeats_recv", "send_failures")


def record_control(event: str, detail: str = "", line: bool = True,
                   count: bool = True) -> None:
    """One control-plane event (see CONTROL_COUNTERS keys)."""
    if count:
        CONTROL_COUNTERS[event] += 1
    if line and event not in _CONTROL_HOT:
        try:
            with open(stats_path(), "a") as f:
                f.write(f"{'CONTROL':<16} {event.upper():<16} {detail}\n")
        except OSError:
            pass


def reset_control_counters() -> None:
    for k in CONTROL_COUNTERS:
        CONTROL_COUNTERS[k] = 0


# Runtime lock-witness accounting (mlsl_tpu.analysis.witness,
# MLSL_LOCK_WITNESS=1): the dynamic half of the A21x concurrency suite.
# Acquisitions are the hot path (every witnessed critical section) and only
# bump the counter; edges/cycles/over-budget holds are cold findings and
# append an immediate LOCKWITNESS line — a witnessed order cycle must be
# readable from mlsl_stats.log next to the CONTROL story it would deadlock.
LOCKWITNESS_COUNTERS: Dict[str, int] = {
    "acquisitions": 0,       # witnessed acquisitions (hot: counter only)
    "edges_observed": 0,     # distinct acquisition-order edges seen
    "cycles_detected": 0,    # runtime lock-order cycles (potential deadlock)
    "over_budget_holds": 0,  # holds past MLSL_LOCK_WITNESS_BUDGET_MS
}

_LOCKWITNESS_HOT = ("acquisitions",)


def record_lock_witness(event: str, detail: str = "") -> None:
    """One lock-witness event (see LOCKWITNESS_COUNTERS keys)."""
    LOCKWITNESS_COUNTERS[event] += 1
    if event not in _LOCKWITNESS_HOT:
        try:
            with open(stats_path(), "a") as f:
                f.write(f"{'LOCKWITNESS':<16} {event.upper():<16} {detail}\n")
        except OSError:
            pass


def reset_lock_witness_counters() -> None:
    for k in LOCKWITNESS_COUNTERS:
        LOCKWITNESS_COUNTERS[k] = 0


def record_comm_retry(phase: str, request: str, error: BaseException,
                      attempt: int, delay_s: float) -> None:
    """One rung-2 retry of a transient dispatch/wait failure (called by
    CommRequest before it backs off)."""
    DEGRADE_COUNTERS["comm_retries"] += 1
    if obs._tracer is not None:
        obs._tracer.instant(f"{phase}.retry", "degrade", request=request,
                            attempt=attempt, delay_s=round(delay_s, 4),
                            error=repr(error))


def reset_degrade_counters() -> None:
    for k in DEGRADE_COUNTERS:
        DEGRADE_COUNTERS[k] = 0
    DEGRADE_FALLBACKS.clear()
    DEGRADE_EVENTS.clear()


# Feed-pipeline accounting (mlsl_tpu.data): process-wide like the bucket
# counters — the feed stages batches from a loader worker thread with no
# Session handle. Wire bytes are what actually crossed the h2d link;
# bytes_saved is the full-width f32 baseline minus that; stall_ms is time the
# TRAINING LOOP blocked on an empty prefetch queue (the number the pipeline
# exists to drive to zero); producer_wait_ms is healthy backpressure (the
# worker waiting for a free slot). Statistics.print_ renders the totals as
# the FEED line in mlsl_stats.log.
FEED_COUNTERS: Dict[str, float] = {
    "batches_staged": 0,     # batches that crossed the h2d link
    "wire_bytes": 0,         # bytes actually shipped (payload + scales)
    "bytes_saved": 0,        # f32-baseline bytes minus wire bytes
    "cache_hits": 0,         # batches served from the HBM cache (no h2d)
    "cache_misses": 0,
    "cache_rejects": 0,      # batches the cache budget refused to pin
    "stall_ms": 0.0,         # consumer blocked on an empty prefetch queue
    "producer_wait_ms": 0.0,  # worker blocked on a full queue (backpressure)
    "retries": 0,            # TRANSIENT source-read retries (rung 2)
}


def record_feed_stage(wire_bytes: int, full_bytes: int) -> None:
    """One batch staged over the wire (called by FeedCodec.stage; the
    h2d.transfer span is recorded there too)."""
    FEED_COUNTERS["batches_staged"] += 1
    FEED_COUNTERS["wire_bytes"] += wire_bytes
    FEED_COUNTERS["bytes_saved"] += max(0, full_bytes - wire_bytes)


def record_feed_cache(event: str) -> None:
    """One cache lookup outcome: 'hit' / 'miss' / 'reject'."""
    key = "cache_misses" if event == "miss" else f"cache_{event}s"
    FEED_COUNTERS[key] += 1


def record_feed_stall(ms: float) -> None:
    """Consumer blocked on the prefetch queue for ``ms`` (AsyncLoader)."""
    FEED_COUNTERS["stall_ms"] += ms


def record_feed_wait(ms: float) -> None:
    """Producer backpressure wait (AsyncLoader worker, full queue)."""
    FEED_COUNTERS["producer_wait_ms"] += ms


def record_feed_retry() -> None:
    """One TRANSIENT source-read retry (MLSL_FEED_RETRIES)."""
    FEED_COUNTERS["retries"] += 1


def reset_feed_counters() -> None:
    for k in FEED_COUNTERS:
        FEED_COUNTERS[k] = 0 if isinstance(FEED_COUNTERS[k], int) else 0.0


# Serving-engine accounting (mlsl_tpu.serve): process-wide like the feed
# counters — the engine admits requests from caller threads with no Session
# handle. Admission outcomes, decode progress, KV paging churn, and SLA
# ladder transitions; Statistics.print_ renders the totals as the SERVE line
# in mlsl_stats.log, and obs/metrics.sample_families snapshots them onto
# /metrics as mlsl_serve_* gauges.
SERVE_COUNTERS: Dict[str, float] = {
    "admitted": 0,        # requests accepted into the admission queue
    "rejected": 0,        # 429-style admission rejections (ladder rung 3)
    "completed": 0,       # sequences that finished (eos or max_tokens)
    "failed": 0,          # sequences abandoned by a non-retryable fault
    "prefills": 0,        # prefill programs launched
    "decode_steps": 0,    # iteration-level decode steps over the batch
    "tokens_out": 0,      # total generated tokens across all sequences
    "retries": 0,         # TRANSIENT decode-step retries (rung 2)
    "kv_pages_alloc": 0,  # KV pages taken off the free-list
    "kv_pages_freed": 0,  # KV pages returned on retirement
    "kv_evictions": 0,    # sequences evicted to reclaim pages under pressure
    "kv_rejects": 0,      # admissions refused for want of KV pages
    "shed_batch": 0,      # SLA ladder: batch-size sheds (rung 1)
    "shed_precision": 0,  # SLA ladder: KV-precision sheds (rung 2)
    "shed_admission": 0,  # SLA ladder: admission-shedding entries (rung 3)
    "recoveries": 0,      # ladder steps back toward healthy
}


def record_serve(event: str, n: int = 1) -> None:
    """One serving-engine event (see SERVE_COUNTERS keys)."""
    SERVE_COUNTERS[event] += n


def record_serve_shed(rung: str, detail: str = "") -> None:
    """One SLA-ladder transition ('batch' / 'precision' / 'admission' /
    'recovery'): counted, and appended as an immediate SERVE line — the
    degraded-not-down story must be readable from mlsl_stats.log."""
    key = "recoveries" if rung == "recovery" else f"shed_{rung}"
    SERVE_COUNTERS[key] += 1
    try:
        with open(stats_path(), "a") as f:
            f.write(f"{'SERVE':<16} {rung.upper():<10} {detail}\n")
    except OSError:
        pass


def reset_serve_counters() -> None:
    for k in SERVE_COUNTERS:
        SERVE_COUNTERS[k] = 0 if isinstance(SERVE_COUNTERS[k], int) else 0.0


# Per-algorithm dispatch accounting (comm/algos): process-wide like the
# bucket counters — dispatch fires at the request layer with no Session
# handle. Key = (kind, algorithm name); value = launches. The point: traces
# and stats must attribute wire time to the ALGORITHM that ran, or a tuned
# profile's effect is invisible in the logs it was tuned from.
ALGO_COUNTERS: Dict[Tuple[str, str], int] = {}


def record_algo_dispatch(kind: str, algo: str) -> None:
    """One collective launch under ``algo`` (called by CommRequest._dispatch
    on the hot path: a dict upsert, no allocation beyond the first key)."""
    key = (kind, algo)
    ALGO_COUNTERS[key] = ALGO_COUNTERS.get(key, 0) + 1


def reset_algo_counters() -> None:
    ALGO_COUNTERS.clear()


# Compiled-overlap engine accounting (comm/overlap.py): the in-graph rounds
# never construct a CommRequest, so their attribution lands here (and, per
# algorithm, in ALGO_COUNTERS — the ALGO line covers host AND in-graph
# launches). Process-wide like the other dispatch-layer counters.
OVERLAP_COUNTERS: Dict[str, int] = {
    "steps": 0,          # compiled-overlap steps dispatched
    "split_steps": 0,    # of which ran the two-program (sentinel-gated) split
    "units": 0,          # in-graph reduction units dispatched (cumulative)
    "rounds": 0,         # in-graph collective phases (ppermute rounds etc.)
    "bytes": 0,          # logical gradient bytes reduced in-graph
}


def record_overlap_step(units: int, rounds: int, nbytes: int, *,
                        split: bool = False,
                        breakdown: Optional[Dict[Tuple[str, str], int]] = None
                        ) -> None:
    """One compiled-overlap step: bulk attribution for all of its in-graph
    rounds (a handful of dict upserts per STEP, not per layer — the
    dispatch-floor budget the engine exists to protect). ``breakdown`` maps
    (kind, algo) -> unit count and feeds the shared ALGO table."""
    OVERLAP_COUNTERS["steps"] += 1
    if split:
        OVERLAP_COUNTERS["split_steps"] += 1
    OVERLAP_COUNTERS["units"] += units
    OVERLAP_COUNTERS["rounds"] += rounds
    OVERLAP_COUNTERS["bytes"] += nbytes
    if breakdown:
        for key, n in breakdown.items():
            ALGO_COUNTERS[key] = ALGO_COUNTERS.get(key, 0) + n


def reset_overlap_counters() -> None:
    for k in OVERLAP_COUNTERS:
        OVERLAP_COUNTERS[k] = 0


#: jax monitoring event fired once per XLA backend compilation — the
#: compile-count probe behind the MLSL_PRECOMPILE acceptance check.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


@contextlib.contextmanager
def count_backend_compiles():
    """Count XLA backend compilations inside the block: yields a one-element
    list whose [0] is the running count. Used to verify AOT precompilation
    (Session.precompile_collectives / MLSL_PRECOMPILE) actually removed
    compile stalls from the timed path — a warmed step must count 0.

    Cleanup is unconditional (the ``finally`` runs on exception paths too) and
    VERIFIED: a listener left behind by a failing test body would keep
    counting other tests' compiles forever, so if jax's private unregister
    hook has moved we excise the callback from the registry list directly and
    warn rather than silently leaking."""
    from jax._src import monitoring

    n = [0]

    def _listener(event, duration=0.0, **kw):  # noqa: ARG001
        if event == BACKEND_COMPILE_EVENT:
            n[0] += 1

    monitoring.register_event_duration_secs_listener(_listener)
    try:
        yield n
    finally:
        _remove_duration_listener(monitoring, _listener)


def _remove_duration_listener(monitoring, listener) -> None:
    """Best-effort unregister via the jax API, then verify against the
    registry itself and fall back to direct excision — never leave the
    listener installed."""
    try:
        monitoring._unregister_event_duration_listener_by_callback(listener)
    except Exception:  # mlsl-lint: disable=A205 -- jax internals moved;
        pass           # the verify below still runs
    for attr in (
        "_event_duration_secs_listeners",  # current jax registry list
        "event_duration_secs_listeners",
    ):
        reg = getattr(monitoring, attr, None)
        if isinstance(reg, list) and listener in reg:
            try:
                reg.remove(listener)
            except ValueError:
                pass
    for reg in (
        getattr(monitoring, "_event_duration_secs_listeners", None),
        getattr(monitoring, "event_duration_secs_listeners", None),
    ):
        if isinstance(reg, list) and listener in reg:  # pragma: no cover
            log_warning(
                "count_backend_compiles could not unregister its jax "
                "monitoring listener; later compile counts will be inflated"
            )


class _Slot:
    __slots__ = ("bytes", "comm_ns", "comp_ns", "events", "starts")

    def __init__(self):
        self.bytes = 0
        self.comm_ns = 0
        self.comp_ns = 0
        self.events = 0
        self.starts = 0


def _entity_key(entity, is_param: bool, is_increment: bool) -> Tuple:
    if is_param:
        kind = "INC" if is_increment else "GRAD"
        return (kind, entity.param_index)
    kind = "IA" if entity.is_input else "OA"
    return (kind, entity.act_index)


class Statistics:
    def __init__(self, session):
        self.session = session
        self._started = False
        self._last_event_ns: Optional[int] = None
        self._slots: Dict[Tuple[int, Tuple], _Slot] = {}
        self._isolation_ns: Dict[int, int] = {}   # op_idx -> per-iteration comm ns
        self._isolation_bytes: Dict[int, int] = {}
        # (op_idx, entity_key) -> per-iteration comm ns, for the overlap report
        self._isolation_slot_ns: Dict[Tuple[int, Tuple], int] = {}

    # -- lifecycle ---------------------------------------------------------

    def is_enabled(self) -> bool:
        cfg = self.session.env.config
        return bool(cfg and cfg.enable_stats)

    def is_started(self) -> bool:
        return self._started

    def initialize(self) -> None:
        self._slots.clear()
        if self.is_enabled():
            self._started = True
            self._last_event_ns = time.perf_counter_ns()

    def start(self) -> None:
        self._started = True
        self._last_event_ns = time.perf_counter_ns()

    def stop(self) -> None:
        self._started = False

    def reset(self) -> None:
        self._slots.clear()
        self._last_event_ns = time.perf_counter_ns()

    # -- online accounting -------------------------------------------------

    def _slot(self, op_idx: int, key: Tuple) -> _Slot:
        s = self._slots.get((op_idx, key))
        if s is None:
            s = _Slot()
            self._slots[(op_idx, key)] = s
        return s

    def update(self, entity, action: str, is_param: bool, is_increment: bool) -> None:
        """Pre-events ('start','wait','test') attribute elapsed time to compute;
        post-events ('*_done') attribute it to comm; bytes counted on start.

        Peer-op redirection (reference UpdateStats src/mlsl_impl_stats.cpp:564-668):
        WaitComm on an activation completes the PEER's transfer, so its comm time is
        charged to the peer's (op, entity) slot."""
        if not self._started:
            return
        now = time.perf_counter_ns()
        delta = now - (self._last_event_ns or now)
        self._last_event_ns = now
        target = entity
        if (
            not is_param
            and action in ("wait", "wait_done")
            and getattr(entity, "peer_act", None) is not None
        ):
            target = entity.peer_act
        op_idx = target.op.op_idx
        slot = self._slot(op_idx, _entity_key(target, is_param, is_increment))
        if action.endswith("_done"):
            slot.comm_ns += delta
        else:
            slot.comp_ns += delta
        if action == "start":
            slot.starts += 1
            req = _entity_request(entity, is_param, is_increment)
            if req is not None:
                slot.bytes += req.desc.payload_bytes()
        slot.events += 1

    # -- isolation benchmark ----------------------------------------------

    def collect_isolation_stats(self) -> None:
        """Replay every registered comm with compute off (reference :387-562)."""
        for op in self.session.operations:
            total_ns = 0
            total_bytes = 0
            for key, req in _op_request_slots(op):
                ns, nbytes = isolation_time_request(req)
                total_ns += ns
                total_bytes += nbytes
                self._isolation_slot_ns[(op.op_idx, key)] = ns
            self._isolation_ns[op.op_idx] = total_ns
            self._isolation_bytes[op.op_idx] = total_bytes

    # -- overlap quantification --------------------------------------------

    def overlap_report(self) -> dict:
        """Hidden vs exposed communication time — how much comm actually hides
        behind compute, the entire point of the async Start/Wait engine
        (reference: eplib's newest-first allreduce exists to maximize this,
        eplib/allreduce_pr.c:76-79; the comp/comm attribution intent is
        src/mlsl_impl_stats.cpp:564-668).

        Per (op, entity) slot that was replayed in isolation AND started online:
          true comm time  = isolation ns/iter x observed Start count
          exposed time    = online comm ns (host blocked inside Start/Wait/Test)
          hidden time     = max(0, true - exposed)
          overlap_fraction = hidden / true
        Requires collect_isolation_stats() (run at Commit when stats are enabled,
        or callable explicitly) plus at least one accounted step."""
        ops: Dict[str, dict] = {}
        tot_iso = tot_exposed = 0
        for op_idx, iso, exposed in self._overlap_slots():
            name = self.session.operations[op_idx].name
            ent = ops.setdefault(name, {"iso_ns": 0, "exposed_ns": 0})
            ent["iso_ns"] += iso
            ent["exposed_ns"] += exposed
            tot_iso += iso
            tot_exposed += exposed
        for ent in ops.values():
            ent["hidden_ns"] = max(0, ent["iso_ns"] - ent["exposed_ns"])
            ent["overlap_fraction"] = ent["hidden_ns"] / ent["iso_ns"]
        total = {
            "iso_ns": tot_iso,
            "exposed_ns": tot_exposed,
            "hidden_ns": max(0, tot_iso - tot_exposed),
            "overlap_fraction": (
                max(0, tot_iso - tot_exposed) / tot_iso if tot_iso > 0 else None
            ),
        }
        rep = {"ops": ops, "total": total}
        tr = obs._tracer
        if tr is not None:
            # span-derived attribution (tracing on): per-op p50/p95 wait-stall
            # from the tracer's 'wait' spans — requests are named '<op>/...'
            # (core/parameter_set.py), so overlap loss maps to specific ops
            # instead of one aggregate number
            stalls = tr.wait_stall_durations()
            all_durs: List[int] = []
            for name, ent in ops.items():
                durs: List[int] = []
                for key, d in stalls.items():
                    if key.startswith(name + "/"):
                        durs.extend(d)
                if durs:
                    durs.sort()
                    ent["wait_spans"] = len(durs)
                    ent["wait_stall_p50_ms"] = (
                        obs._percentile(durs, 50) / 1e6
                    )
                    ent["wait_stall_p95_ms"] = (
                        obs._percentile(durs, 95) / 1e6
                    )
                all_durs.extend(durs)
            if all_durs:
                all_durs.sort()
                total["wait_spans"] = len(all_durs)
                total["wait_stall_p50_ms"] = obs._percentile(all_durs, 50) / 1e6
                total["wait_stall_p95_ms"] = obs._percentile(all_durs, 95) / 1e6
        return rep

    def _overlap_slots(self):
        """(op_idx, true_comm_ns, exposed_ns) per qualifying slot — the ONE
        copy of the overlap accounting rules, shared by overlap_report and
        get_overlap_fraction so the printed table and the C API agree."""
        for (oi, key), iso_per_iter in self._isolation_slot_ns.items():
            slot = self._slots.get((oi, key))
            if slot is None or slot.starts == 0 or iso_per_iter <= 0:
                continue
            yield oi, iso_per_iter * slot.starts, slot.comm_ns

    def get_overlap_fraction(self, op_idx: Optional[int] = None) -> Optional[float]:
        """Fraction of pure-comm time hidden behind compute — session total, or
        one operation's with ``op_idx`` (keyed by index, robust to duplicate op
        names). None until isolation stats and an accounted step exist, or for
        an op with no replayed comm."""
        iso = exposed = 0
        for oi, slot_iso, slot_exposed in self._overlap_slots():
            if op_idx is not None and oi != op_idx:
                continue
            iso += slot_iso
            exposed += slot_exposed
        return None if iso == 0 else max(0, iso - exposed) / iso

    # -- queries (reference include/mlsl.hpp:680-725) ----------------------

    def get_isolation_comm_cycles(self, op_idx: int) -> int:
        return self._isolation_ns.get(op_idx, 0)

    def get_comm_size(self, op_idx: int) -> int:
        return sum(s.bytes for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_comm_cycles(self, op_idx: int) -> int:
        return sum(s.comm_ns for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_compute_cycles(self, op_idx: int) -> int:
        return sum(s.comp_ns for (oi, _), s in self._slots.items() if oi == op_idx)

    def get_total_isolation_comm_cycles(self) -> int:
        return sum(self._isolation_ns.values())

    def get_total_comm_size(self) -> int:
        return sum(s.bytes for s in self._slots.values())

    def get_total_comm_cycles(self) -> int:
        return sum(s.comm_ns for s in self._slots.values())

    def get_total_compute_cycles(self) -> int:
        return sum(s.comp_ns for s in self._slots.values())

    # -- printer (reference :226-363) --------------------------------------

    def print_(self, path: Optional[str] = None) -> str:
        if path is None:
            path = stats_path()  # MLSL_STATS_DIR routing, resolved per call
        lines = []
        mb = max(self.session.global_minibatch_size, 1)
        lines.append(
            f"{'op':<16} {'entity':<8} {'KB':>12} {'comm Kns/img':>14} "
            f"{'comp Kns/img':>14} {'events':>8}"
        )
        for (op_idx, key), slot in sorted(self._slots.items()):
            op = self.session.operations[op_idx]
            lines.append(
                f"{op.name:<16} {key[0] + str(key[1]):<8} "
                f"{slot.bytes / 1024.0:>12.1f} {slot.comm_ns / 1e3 / mb:>14.2f} "
                f"{slot.comp_ns / 1e3 / mb:>14.2f} {slot.events:>8}"
            )
        for op_idx, ns in sorted(self._isolation_ns.items()):
            op = self.session.operations[op_idx]
            lines.append(
                f"{op.name:<16} {'ISOLATE':<8} "
                f"{self._isolation_bytes.get(op_idx, 0) / 1024.0:>12.1f} "
                f"{ns / 1e3 / mb:>14.2f} {'-':>14} {'-':>8}"
            )
        rep = self.overlap_report()
        if rep["total"]["overlap_fraction"] is not None:
            lines.append(
                f"{'OVERLAP':<16} {'TOTAL':<8} hidden "
                f"{rep['total']['hidden_ns'] / 1e3:>10.1f} Kns / iso "
                f"{rep['total']['iso_ns'] / 1e3:>10.1f} Kns = "
                f"{rep['total']['overlap_fraction']:.3f}"
            )
            for name, ent in sorted(rep["ops"].items()):
                lines.append(
                    f"{name:<16} {'OVERLAP':<8} hidden "
                    f"{ent['hidden_ns'] / 1e3:>10.1f} Kns / iso "
                    f"{ent['iso_ns'] / 1e3:>10.1f} Kns = "
                    f"{ent['overlap_fraction']:.3f}"
                )
        c = BUCKET_COUNTERS
        if c["rounds_dispatched"] or c["rounds_fallback"] or c["member_abandons"]:
            bucket_line = (
                f"{'BUCKET':<16} {'ROUNDS':<8} dispatched {c['rounds_dispatched']} "
                f"fallback {c['rounds_fallback']} abandoned {c['member_abandons']} "
                f"coalesced {c['bytes_coalesced'] / 1024.0:.1f} KB "
                f"wire_saved {c['wire_bytes_saved'] / 1024.0:.1f} KB"
            )
            tr = obs._tracer
            if tr is not None:
                # span-derived: wait-stall distribution over the bucket
                # requests' 'wait' spans (named 'bucket-<kind>[NxM]')
                durs = [
                    d
                    for key, ds in tr.wait_stall_durations().items()
                    if key.startswith("bucket-")
                    for d in ds
                ]
                if durs:
                    durs.sort()
                    bucket_line += (
                        f" wait_p50 {obs._percentile(durs, 50) / 1e6:.2f} ms"
                        f" wait_p95 {obs._percentile(durs, 95) / 1e6:.2f} ms"
                    )
            lines.append(bucket_line)
        fc = FEED_COUNTERS
        if (fc["batches_staged"] or fc["cache_hits"] or fc["cache_misses"]
                or fc["stall_ms"] or fc["retries"]):
            # stall/retries alone must also surface the line: a plain
            # AsyncLoader (no wire path) that stalled the training loop is
            # exactly the input-bound run this line exists to expose
            # the feed line: how many bytes the wire codecs + HBM cache kept
            # off the h2d link, and whether the training loop ever waited on
            # its input (stall) — one grep ('FEED') answers "is this run
            # input-bound"
            staged = max(int(fc["batches_staged"]), 1)
            lines.append(
                f"{'FEED':<16} {'PIPELINE':<8} "
                f"staged {int(fc['batches_staged'])} "
                f"wire {fc['wire_bytes'] / 1e6:.1f} MB "
                f"({fc['wire_bytes'] / 1e6 / staged:.2f} MB/batch) "
                f"saved {fc['bytes_saved'] / 1e6:.1f} MB "
                f"cache {int(fc['cache_hits'])}h/{int(fc['cache_misses'])}m/"
                f"{int(fc['cache_rejects'])}r "
                f"stall {fc['stall_ms']:.1f} ms "
                f"bp_wait {fc['producer_wait_ms']:.1f} ms "
                f"retries {int(fc['retries'])}"
            )
        if ALGO_COUNTERS:
            # per-algorithm dispatch attribution (comm/algos): which program
            # family actually carried each collective kind this run
            parts = [
                f"{kind}:{algo}={n}"
                for (kind, algo), n in sorted(ALGO_COUNTERS.items())
            ]
            lines.append(
                f"{'ALGO':<16} {'DISPATCH':<8} " + " ".join(parts)
            )
        oc = OVERLAP_COUNTERS
        if oc["steps"]:
            # the compiled-overlap story: how many steps rode the in-graph
            # schedule, how many of those split for the sentinel gate, and
            # the in-graph round/byte volume — one grep ('OVERLAP ENGINE')
            # answers "did the compiled path actually carry this run"
            lines.append(
                f"{'OVERLAP':<16} {'ENGINE':<8} "
                f"steps {oc['steps']} (split {oc['split_steps']}) "
                f"units {oc['units']} rounds {oc['rounds']} "
                f"bytes {oc['bytes'] / 1e6:.1f} MB"
            )
        sc = SENTINEL_COUNTERS
        if any(sc.values()):
            # the integrity story: how many steps the gate screened, what it
            # fired, and whether the consistency audit ever saw replicas
            # diverge — one grep ('SENTINEL') answers "did this run's state
            # stay trustworthy"
            lines.append(
                f"{'SENTINEL':<16} {'GATE':<8} "
                f"screened {sc['screened']} "
                f"warn {sc['gate_warn']} skip {sc['gate_skip']} "
                f"rollback {sc['gate_rollback']} audits {sc['audits']} "
                f"mismatch {sc['audit_mismatch']} "
                f"verified_saves {sc['verified_saves']} "
                f"reaudits {sc['reaudits']}"
            )
        ec = ELASTIC_COUNTERS
        if any(ec.values()):
            # the elastic story: how many device losses the run absorbed by
            # rescaling instead of restarting, and whether every returning
            # replica passed its admission audit — one grep ('ELASTIC')
            # answers "did capacity churn cost this run a restart"
            lines.append(
                f"{'ELASTIC':<16} {'MESH':<8} "
                f"losses {ec['device_losses']} "
                f"shrinks {ec['shrinks']} grows {ec['grows']} "
                f"abandons {ec['grow_abandons']} "
                f"admits {ec['admits']} rejects {ec['admit_rejects']} "
                f"resyncs {ec['resyncs']} "
                f"reshard_buffers {ec['reshard_buffers']} "
                f"restart_fallbacks {ec['restart_fallbacks']}"
            )
        gc = STRAGGLER_COUNTERS
        if any(gc.values()):
            # the straggler story: how many skew audits ran, which replicas
            # were confirmed slow, and whether any were shed — one grep
            # ('STRAGGLER') answers "did one replica tax this run"
            lines.append(
                f"{'STRAGGLER':<16} {'SKEW':<8} "
                f"audits {gc['audits']} flags {gc['flags']} "
                f"sheds {gc['sheds']} "
                f"shed_fallbacks {gc['shed_fallbacks']}"
            )
        cc = CONTROL_COUNTERS
        if any(cc.values()):
            # the pod story: detection -> one fenced epoch -> drain — one
            # grep ('CONTROL') answers "did the pod agree on what happened"
            lines.append(
                f"{'CONTROL':<16} {'POD':<8} "
                f"hb_sent {cc['heartbeats_sent']} "
                f"hb_recv {cc['heartbeats_recv']} "
                f"send_failures {cc['send_failures']} "
                f"deaths {cc['deaths_detected']} "
                f"epochs {cc['epochs_committed']} "
                f"stale_rejected {cc['stale_rejected']} "
                f"elections {cc['elections']} notices {cc['notices']} "
                f"drain_decisions {cc['drain_decisions']} "
                f"drains {cc['drains_executed']} evicted {cc['evicted']}"
            )
        vc = SERVE_COUNTERS
        if any(vc.values()):
            # the serving story: admission vs rejection, decode progress,
            # KV paging churn, and every SLA shed — one grep ('SERVE')
            # answers "did this engine stay inside its SLO, and at what cost"
            lines.append(
                f"{'SERVE':<16} {'ENGINE':<10} "
                f"admitted {int(vc['admitted'])} "
                f"rejected {int(vc['rejected'])} "
                f"completed {int(vc['completed'])} "
                f"failed {int(vc['failed'])} "
                f"tokens {int(vc['tokens_out'])} "
                f"steps {int(vc['decode_steps'])} "
                f"retries {int(vc['retries'])} "
                f"kv {int(vc['kv_pages_alloc'])}a/{int(vc['kv_pages_freed'])}f/"
                f"{int(vc['kv_evictions'])}e/{int(vc['kv_rejects'])}r "
                f"sheds {int(vc['shed_batch'])}b/{int(vc['shed_precision'])}p/"
                f"{int(vc['shed_admission'])}a "
                f"recoveries {int(vc['recoveries'])}"
            )
        kc = CHKP_COUNTERS
        if any(kc.values()):
            lines.append(
                f"{'CHKP':<16} {'BUFFERS':<8} checks {kc['checks']} "
                f"violations {kc['violations']} "
                f"value_checks {kc['value_checks']} "
                f"value_syncs {kc['value_syncs']}"
            )
        xc = CODEC_COUNTERS
        if any(xc.values()) or CODEC_WIRE_BYTES:
            # the codec-lab story: which codecs carried how many compressed
            # bytes, whether a calibration ran, and every guardrail demotion
            # — one grep ('CODEC') answers "what was on the wire, and did
            # the autotuner's choice survive the sentinel"
            wire = " ".join(
                f"{name}={n}" for name, n in sorted(CODEC_WIRE_BYTES.items())
            )
            lines.append(
                f"{'CODEC':<16} {'LAB':<8} "
                f"calibrations {xc['calibrations']} "
                f"assignments {xc['assignments']} "
                f"breaches {xc['guard_breaches']} "
                f"demotions {xc['demotions']}"
                + (f" wire_bytes {wire}" if wire else "")
            )
            for row in CODEC_DEMOTIONS:
                lines.append(f"{'CODEC':<16} {'DEMOTE':<8} {row}")
        dc = DEGRADE_COUNTERS
        if any(dc.values()) or DEGRADE_FALLBACKS:
            # the ladder summary: every trip/probe/reset, retry, degraded
            # dispatch, and supervised recovery of this run, plus the live
            # breaker states — one grep ('DEGRADE') answers "did this run
            # ever leave the healthy path, and is it back on it"
            from mlsl_tpu import supervisor  # lazy: supervisor imports stats

            states = " ".join(
                f"{name}:{st['state']}"
                for name, st in supervisor.status().items()
                # 'analysis' is verdict-shaped, not breaker-shaped — it has
                # its own ANALYSIS line above, so the ladder summary skips it
                if "state" in st
                and (st["state"] == "tripped" if name == "sentinel"
                     # elastic's healthy vocabulary is 'full', which never
                     # equals CLOSED — list it only when actually shrunk
                     else st["state"] == "shrunk" if name == "elastic"
                     # straggler's healthy vocabulary is 'off'/'watching'
                     # (the elastic lesson): list only when flagged
                     else st["state"] == "flagged" if name == "straggler"
                     # control's healthy vocabulary is 'off'/'member'/
                     # 'leader': list only when the pod actually lost
                     # members (or this rank was evicted by it)
                     else bool(st.get("dead")) or st.get("evicted")
                     if name == "control"
                     # serve's healthy vocabulary is 'off'/'healthy': list
                     # only when the SLA ladder actually shed a rung
                     else st["state"] not in ("off", "healthy")
                     if name == "serve"
                     else st.get("trips") or st["state"] != supervisor.CLOSED)
            )
            fb = " ".join(
                f"{name}={n}" for name, n in sorted(DEGRADE_FALLBACKS.items())
            )
            lines.append(
                f"{'DEGRADE':<16} {'LADDER':<8} retries {dc['comm_retries']} "
                f"trips {dc['breaker_trips']} probes {dc['breaker_probes']} "
                f"resets {dc['breaker_resets']} "
                f"recoveries {dc['recoveries']}"
                + (f" fallbacks {fb}" if fb else "")
                + (f" breakers {states}" if states else "")
            )
        text = "\n".join(lines) + "\n"
        try:
            with open(path, "a") as f:
                f.write(text)
        except OSError:
            pass
        return text

    def trace(self, log_dir: str):
        """Device-level profiler trace context (the jax.profiler complement to the
        host-side byte/time accounting; view in TensorBoard/Perfetto). Usage:

            with session.get_stats().trace("/tmp/trace"):
                trainer.step(batch)
        """
        import jax

        return jax.profiler.trace(log_dir)

    # PascalCase parity aliases
    Start = start
    Stop = stop
    Reset = reset
    IsStarted = is_started
    IsEnabled = is_enabled
    Print = print_
    GetIsolationCommCycles = get_isolation_comm_cycles
    GetCommSize = get_comm_size
    GetCommCycles = get_comm_cycles
    GetComputeCycles = get_compute_cycles
    GetTotalIsolationCommCycles = get_total_isolation_comm_cycles
    GetTotalCommSize = get_total_comm_size
    GetTotalCommCycles = get_total_comm_cycles
    GetTotalComputeCycles = get_total_compute_cycles
    OverlapReport = overlap_report
    GetOverlapFraction = get_overlap_fraction


# -- helpers -----------------------------------------------------------------


def _entity_request(entity, is_param: bool, is_increment: bool):
    if is_param:
        return entity.inc_req if is_increment else entity.grad_req
    return entity.comm_req


def _op_request_slots(op) -> List[Tuple[Tuple, object]]:
    """(entity_key, request) pairs for every registered comm of one operation,
    keyed the same way as the online-accounting slots so the isolation replay
    and the live Start/Wait attribution line up per entity."""
    out = []
    for act in op.inputs + op.outputs:
        if act.comm_req is not None:
            out.append((("IA" if act.is_input else "OA", act.act_index), act.comm_req))
    for ps in op.parameter_sets:
        if ps.grad_req is not None:
            out.append((("GRAD", ps.param_index), ps.grad_req))
        if ps.inc_req is not None:
            out.append((("INC", ps.param_index), ps.inc_req))
    return out


def isolation_time_request(req) -> Tuple[int, int]:
    """(per-iteration ns, payload bytes) for one request, measured in isolation."""
    d = req.desc
    topo = d.group.topology
    buf = topo.shard_buffer(
        np.zeros((*topo.grid_shape, d.count), dtype=jnp_dtype(d.data_type))
    )
    times = []
    for i in range(ISOLATION_ITERS):
        t0 = time.perf_counter_ns()
        req.start(buf)
        req.wait()
        times.append(time.perf_counter_ns() - t0)
    good = times[ISOLATION_SKIP:]
    return int(sum(good) / max(len(good), 1)), d.payload_bytes()
