"""Process-wide fault injection: named sites threaded through the real stack.

The reference is fail-stop (SURVEY.md §5.3: MLSL_ASSERT -> Finalize + _exit(1));
its recovery story is untestable because there is nothing to test. Here every
layer that can fail in production — request dispatch, collective launch, the
quantized codec round-trip, checkpoint IO, data prefetch — passes a named
injection *site*, and this registry decides whether that pass raises, stalls,
hangs, or rots bytes. Tests (tests/test_chaos.py) and the ``MLSL_CHAOS`` env
var arm faults without touching the code under test, so the recovery paths in
``mlsl_tpu.resilience`` are exercised as a matrix rather than one happy path.

Sites (see ``SITES``) are compiled into the registry, not discovered, so a
typo in a plan is an error instead of a fault that never fires.

Python API::

    chaos.plan("checkpoint.save", "error", exc=OSError, after=2, times=1)
    with chaos.injected("request.wait", "delay", seconds=0.1):
        ...
    chaos.clear()

Env config (comma-separated)::

    MLSL_CHAOS="request.wait:error@6,collective.dispatch:hang=30,data.prefetch:delay=0.05x*"

Grammar per entry: ``site:kind[=value][@after][xN][%p]`` — *value* is the
exception name for ``error`` (oserror, runtimeerror, mlslerror, ...),
seconds for ``delay``/``hang``, or the corruption magnitude for ``silent``
(``train.params:silent`` flips a bit, ``train.grads:silent=nan`` poisons an
element — applied by the call site via sentinel.corrupt_silent, never
raising); ``@after`` skips the first N hits; ``xN``
fires at most N times (default 1; ``x*`` = unlimited); ``%p`` makes each
eligible hit fire with probability *p* (e.g.
``collective.dispatch:errorx*%0.05`` — a 5% flaky dispatch; ``%p`` is the
trailing suffix, after ``xN``), so randomized
soak runs need no hand-scheduled ``@after`` budgets. At the ``device.lost``
site an ``error`` plan raises :class:`MLSLDeviceLossError` by default
(``device.lost:error[@after][xN][%p]`` — the elastic-mesh fault; docs
DESIGN.md "Elastic mesh"). The fire decisions
come from a module RNG seeded by ``MLSL_CHAOS_SEED`` (or :func:`seed`), so
a probabilistic soak replays exactly.

Hot-path contract: instrumented code guards with ``if chaos._plans:`` (one
dict truthiness test when idle) or calls ``inject`` directly (one call + one
check). Nothing else happens until a plan is armed.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from mlsl_tpu.log import (
    MLSLCorruptionError,
    MLSLDeviceLossError,
    MLSLError,
    log_info,
    log_warning,
)


class ChaosError(RuntimeError):
    """Default injected fault: recoverable (RuntimeError) by FaultTolerantLoop."""


#: Every legal injection site and where it lives in the stack.
SITES: Dict[str, str] = {
    "request.start": "CommRequest.start (comm/request.py): before dispatch",
    "request.wait": "CommRequest.wait (comm/request.py): before completion wait",
    "request.test": "CommRequest.test (comm/request.py): before completion poll",
    "collective.dispatch": "compiled collective invocation (comm/collectives.py)",
    "codec.roundtrip": "quantized ring codec round-trip (comm/quant_ring.py)",
    "checkpoint.save": "CheckpointManager.save (checkpoint.py); supports bitrot",
    "checkpoint.restore": "CheckpointManager.restore (checkpoint.py)",
    "data.prefetch": "feed batch read (data/: AsyncLoader worker and "
                     "DeviceFeed source reads; bitrot rots the encoded "
                     "wire payload through the codec + cache paths)",
    # SILENT corruption sites (models/train.py): the fired plan is returned
    # and the trainer applies the corruption via sentinel.corrupt_silent —
    # state/payload is flipped or perturbed WITHOUT raising, the class of
    # fault only the integrity sentinel (mlsl_tpu.sentinel) can catch. The
    # per-layer graph path applies them; the no-comm fused shortcut has no
    # gradient boundary to corrupt (and an armed sentinel gate disables it).
    "train.params": "trainer parameters at step entry (models/train.py); "
                    "silent corrupts ONE replica's copy (audit quarry)",
    "train.opt_state": "optimizer state at step entry (models/train.py); "
                       "silent corrupts one replica/shard copy",
    "train.grads": "local gradients before the quality gate and gradient "
                   "comm (models/train.py); silent=nan/inf poisons an "
                   "element the gate's nonfinite screen must catch",
    # Elastic-mesh fault (comm/collectives.py dispatch + mlsl_tpu/elastic.py
    # admission): an 'error' plan raises MLSLDeviceLossError (the default
    # exception at THIS site) — routed to the elastic reshard rung when a
    # coordinator is armed, to checkpoint restart otherwise. A 'silent' plan
    # is consulted by ElasticCoordinator.grow: it corrupts the REJOINING
    # replica's copy of the params so the sentinel admission audit has
    # something to reject (the re-admission quarry).
    "device.lost": "device/slice loss at collective dispatch "
                   "(comm/collectives.py) and at elastic re-admission "
                   "(elastic.py grow; silent corrupts the rejoining copy)",
    # Pod-control-plane faults (control/plane.py): fired on the SENDER's
    # heartbeat/notice paths — error = frame lost, delay = late frame,
    # hang = wedged sender. A lost heartbeat feeds the PEER's miss
    # accounting (which is the machinery under test); a lost/delayed
    # notice degrades to retry-next-tick, never to a lost drain.
    "control.heartbeat": "heartbeat fan-out tick (control/plane.py): one "
                         "inject per peer send; error drops the frame, "
                         "delay/hang stall the sender into a miss",
    "control.notice": "preemption-notice delivery and drain-order "
                      "broadcast (control/plane.py): error/delay/hang "
                      "model a lost notice, a late drain order, and a "
                      "partitioned leader",
    # Serving-engine faults (serve/engine.py): fired inside the scheduler
    # loop. admit fires per admission attempt (error = a request the
    # engine must reject-not-crash); decode fires per decode step on the
    # in-flight batch — error/delay/hang model a failed, late, and wedged
    # decode program, the tail-latency quarry the SLA ladder must absorb
    # (degraded throughput, never lost availability). An error classified
    # DEVICE_LOSS models replica loss mid-serve.
    "serve.admit": "admission attempt (serve/engine.py): error = a "
                   "request the engine must fail closed, not crash on",
    "serve.decode": "decode step over the in-flight batch "
                    "(serve/engine.py): error/delay/hang = failed, "
                    "late, wedged decode; DEVICE_LOSS = replica loss",
}

KINDS = ("error", "delay", "hang", "bitrot", "silent")

_EXC_NAMES = {
    "chaoserror": ChaosError,
    "runtimeerror": RuntimeError,
    "mlslerror": MLSLError,
    "corruptionerror": MLSLCorruptionError,
    "devicelosserror": MLSLDeviceLossError,
    "oserror": OSError,
    "ioerror": OSError,
    "valueerror": ValueError,
    "timeouterror": TimeoutError,
}

# Probabilistic-fire RNG (the %p grammar). Module-level and seedable so a
# randomized soak run is replayable: MLSL_CHAOS_SEED=42 (or seed(42)) makes
# the same fault schedule fire against the same workload.
_rng = random.Random(
    int(os.environ["MLSL_CHAOS_SEED"])
    if os.environ.get("MLSL_CHAOS_SEED") else None
)


def seed(n: Optional[int]) -> None:
    """Re-seed the probabilistic-fire RNG (tests / soak reproducibility)."""
    _rng.seed(n)


@dataclasses.dataclass
class Plan:
    """One armed fault. ``after`` hits are skipped, then it fires ``times``
    times (None = unlimited). ``hits``/``fires`` are the observable counters."""

    site: str
    kind: str = "error"
    exc: type = ChaosError
    seconds: float = 0.1
    after: int = 0
    times: Optional[int] = 1
    prob: float = 1.0
    #: 'silent' corruption magnitude: None = flip one random bit in one
    #: element; a finite value adds mag * (|x| + 1); nan/inf overwrite the
    #: element (the applier is sentinel.corrupt_silent)
    mag: Optional[float] = None
    hits: int = 0
    fires: int = 0
    cancelled: bool = False

    def _should_fire(self) -> bool:
        # caller holds _lock
        self.hits += 1
        if self.cancelled or self.hits <= self.after:
            return False
        if self.times is not None and self.fires >= self.times:
            return False
        if self.prob < 1.0 and _rng.random() >= self.prob:
            # probabilistic plan (%p): an eligible hit that rolled a miss —
            # counts as a hit, never as a fire, and never burns `times`
            return False
        self.fires += 1
        return True


_lock = threading.Lock()
_plans: Dict[str, List[Plan]] = {}  # site -> armed plans (empty dict = idle)


def plan(
    site: str,
    kind: str = "error",
    exc: Optional[type] = None,
    seconds: float = 0.1,
    after: int = 0,
    times: Optional[int] = 1,
    prob: float = 1.0,
    mag: Optional[float] = None,
) -> Plan:
    """Arm a fault at ``site``. Returns the Plan (counters readable by tests).
    ``prob`` < 1 makes each eligible hit fire with that probability (the
    ``%p`` grammar — randomized soak faults with no hand-scheduled
    budgets); pair it with ``times=None`` for an indefinitely flaky site.
    ``mag`` applies to ``kind='silent'`` only (see Plan.mag)."""
    if site not in SITES:
        raise ValueError(f"unknown chaos site {site!r}; known: {sorted(SITES)}")
    if kind not in KINDS:
        raise ValueError(f"unknown chaos kind {kind!r}; known: {KINDS}")
    if not 0.0 < prob <= 1.0:
        raise ValueError(f"chaos probability must be in (0, 1] (got {prob!r})")
    if exc is None:
        # per-site semantic default (None = caller named nothing, so an
        # EXPLICIT exc=ChaosError still wins for cross-class tests): a lost
        # device IS a device-loss error — grammar
        # `device.lost:error[@after][xN][%p]` carries no exception name
        exc = MLSLDeviceLossError if site == "device.lost" else ChaosError
    p = Plan(site=site, kind=kind, exc=exc, seconds=seconds, after=after,
             times=times, prob=prob, mag=mag)
    with _lock:
        _plans.setdefault(site, []).append(p)
    log_info("chaos armed: %s %s after=%d times=%s prob=%s",
             site, kind, after, times, prob)
    return p


class injected:
    """Context manager: arm a plan on entry, remove it (and wake any hang) on
    exit. ``with chaos.injected("request.wait", "delay", seconds=0.1): ...``"""

    def __init__(self, site: str, kind: str = "error", **kw):
        self._args = (site, kind)
        self._kw = kw
        self.plan: Optional[Plan] = None

    def __enter__(self) -> Plan:
        self.plan = plan(*self._args, **self._kw)
        return self.plan

    def __exit__(self, *exc) -> None:
        remove(self.plan)


def remove(p: Plan) -> None:
    p.cancelled = True
    with _lock:
        site_plans = _plans.get(p.site)
        if site_plans is not None:
            try:
                site_plans.remove(p)
            except ValueError:
                pass
            if not site_plans:
                del _plans[p.site]


def clear() -> None:
    """Disarm everything and wake any in-progress hang sleeps."""
    with _lock:
        for plans_ in _plans.values():
            for p in plans_:
                p.cancelled = True
        _plans.clear()


def active() -> bool:
    return bool(_plans)


def inject(site: str, kinds: Optional[Tuple[str, ...]] = None,
           **ctx) -> Optional[Plan]:
    """Pass ``site``. No-op (one dict check) unless a plan is armed there.

    ``error`` raises the plan's exception, ``delay`` sleeps, ``hang`` sleeps
    until its duration elapses or the plan is cancelled (clear()/remove()).
    Site-specific kinds (``bitrot``, ``silent``) don't act here — the fired
    Plan is returned and the call site applies the effect (checkpoint.py
    corrupts the committed files; models/train.py corrupts live state via
    sentinel.corrupt_silent). ``ctx`` is free-form, logged for diagnosis.

    ``kinds`` restricts which plan kinds this pass may fire (and therefore
    consume): a site with two consumers — collective dispatch fires
    ``device.lost`` error-shaped loss, elastic grow applies the ``silent``
    rejoiner corruption — must not burn the other consumer's ``times``
    budget. A plan whose kind is filtered out stays armed, untouched.
    """
    if not _plans:
        return None
    site_plans = _plans.get(site)
    if not site_plans:
        return None
    fired: Optional[Plan] = None
    for p in list(site_plans):
        if kinds is not None and p.kind not in kinds:
            continue
        with _lock:
            go = p._should_fire()
        if not go:
            continue
        log_warning("chaos fired: %s %s (hit %d) ctx=%s", site, p.kind, p.hits, ctx)
        from mlsl_tpu.obs import tracer as _obs  # lazy: cold (fired) path only

        if _obs._tracer is not None:
            # injections land on the comm timeline so a trace of a chaos run
            # shows WHERE the fault hit relative to the spans it perturbed
            _obs._tracer.instant("chaos.fired", "chaos", site=site,
                                 kind=p.kind, hit=p.hits)
        if p.kind == "error":
            raise p.exc(f"chaos injected at {site} (hit {p.hits})")
        if p.kind == "delay":
            time.sleep(p.seconds)
        elif p.kind == "hang":
            end = time.monotonic() + p.seconds
            while time.monotonic() < end and not p.cancelled:
                time.sleep(0.01)
        fired = p
    return fired


def refresh_from_env(spec: Optional[str] = None) -> List[Plan]:
    """(Re)arm plans from ``MLSL_CHAOS`` (or an explicit spec). Replaces any
    previously env-armed plans; API-armed plans are cleared too — the env spec
    is authoritative when used."""
    if spec is None:
        spec = os.environ.get("MLSL_CHAOS", "")
    s = os.environ.get("MLSL_CHAOS_SEED")
    if s:
        # re-arming from the env restarts the reproducible fault schedule
        _rng.seed(int(s))
    clear()
    out = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        out.append(plan(**_parse_entry(entry)))
    return out


def _parse_entry(entry: str) -> dict:
    """``site:kind[=value][@after][xN][%p]`` -> plan() kwargs."""
    site, sep, rest = entry.partition(":")
    if not sep:
        raise ValueError(f"bad MLSL_CHAOS entry {entry!r}: expected site:kind[...]")
    kw: dict = {"site": site}
    if "%" in rest:
        rest, _, pr = rest.rpartition("%")
        kw["prob"] = float(pr)
    times: Optional[int] = 1
    if "x" in rest:
        rest, _, t = rest.rpartition("x")
        times = None if t == "*" else int(t)
    kw["times"] = times
    if "@" in rest:
        rest, _, a = rest.partition("@")
        kw["after"] = int(a)
    kind, _, value = rest.partition("=")
    kw["kind"] = kind
    if value:
        if kind == "error":
            try:
                kw["exc"] = _EXC_NAMES[value.lower()]
            except KeyError:
                raise ValueError(
                    f"unknown exception {value!r} in MLSL_CHAOS entry {entry!r}; "
                    f"known: {sorted(_EXC_NAMES)}"
                ) from None
        elif kind == "silent":
            # silent corruption magnitude ('nan'/'inf' accepted — they
            # overwrite the element); no value = flip one random bit
            kw["mag"] = float(value)
        else:
            kw["seconds"] = float(value)
    return kw


# Arm from the environment at import: instrumented modules import this module,
# so MLSL_CHAOS=... on the launch command works with no code changes.
if os.environ.get("MLSL_CHAOS"):
    refresh_from_env()
