"""Asynchronous data loading: background prefetch onto the device mesh.

TPU-native equivalent of the reference's endpoint-server file-IO offload
(ENABLE_FILEIO, eplib/eplib.h:51-58 fopen/fread_nb/fwait: a second command ring lets
the server stream files into shared memory while the trainer computes). Here the
"server" is a background thread pool and the "shared memory" is device HBM: batches
are read/produced, sharded onto the mesh, and transferred ahead of use so the
training loop never blocks on input.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, Optional

import numpy as np
import jax

from mlsl_tpu import chaos
from mlsl_tpu.log import log_warning


class AsyncLoader:
    """Wraps a host batch source with prefetch-to-device.

    source: iterator/callable yielding host batches (any pytree of np arrays);
    place: fn(host_batch) -> device batch (e.g. trainer.shard_batch);
    depth: number of batches kept in flight (double buffering = 2).
    """

    def __init__(self, source, place: Callable, depth: int = 2):
        self._source = iter(source) if not callable(source) else None
        self._source_fn = source if callable(source) else None
        self._place = place
        self._depth = max(1, depth)
        self._q: queue.Queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._done = False
        self._exc: Optional[BaseException] = None
        self._batches = 0  # descriptor for the join-timeout warning in close()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name=f"mlsl-prefetch-{id(self):x}"
        )
        self._thread.start()

    def _next_host_batch(self):
        if self._source_fn is not None:
            return self._source_fn()
        return next(self._source)

    def _worker(self):
        try:
            while not self._stop.is_set():
                if chaos._plans:
                    chaos.inject("data.prefetch", batch=self._batches)
                try:
                    host = self._next_host_batch()
                except StopIteration:
                    self._q.put(_SENTINEL)
                    return
                self._batches += 1
                # device_put dispatches the transfer asynchronously; holding the
                # resulting arrays in the queue keeps `depth` transfers in flight
                dev = self._place(*host) if isinstance(host, tuple) else self._place(host)
                self._q.put(dev)
        except BaseException as e:  # surface worker failures to the consumer
            self._exc = e
            self._q.put(_SENTINEL)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            # stay exhausted instead of blocking on an empty queue forever
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        item = self._q.get()
        if item is _SENTINEL:
            self._done = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def close(self) -> None:
        self._stop.set()
        # drain so the worker is not blocked on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
        if self._thread.is_alive():
            # The worker is wedged in the source or the device transfer —
            # abandoning it silently would hide the leak until HBM or file
            # handles run out.
            log_warning(
                "prefetch thread %s still alive after 5s join "
                "(was serving batch %d); abandoning it",
                self._thread.name,
                self._batches,
            )


_SENTINEL = object()


def file_source(paths, epochs: Optional[int] = 1):
    """Stream (x, y) batches from ``.npz`` files (keys 'x' and 'y') — the
    analog of the reference's endpoint-server file reads (EPLIB_fopen/fread_nb,
    eplib/eplib.h:51-58): the AsyncLoader's worker thread performs the disk
    read AND the host->device transfer while the trainer computes, so the
    training loop never blocks on IO. ``epochs=None`` cycles forever."""
    paths = list(paths)  # a one-shot iterable must survive multiple epochs
    e = 0
    while epochs is None or e < epochs:
        for p in paths:
            with np.load(p) as z:
                yield z["x"], z["y"]
        e += 1


def synthetic_source(batch: int, shape, num_classes: int, seed: int = 0,
                     steps: Optional[int] = None, dtype=np.float32):
    """Deterministic synthetic (x, y) batches (the reference tests likewise use
    generated algebraic data rather than real datasets). Pass
    dtype=ml_dtypes.bfloat16 to cast on the host: models that immediately
    cast inputs to bf16 on device see identical math, and the host->device
    transfer halves — on the tunneled bench that transfer is the pipeline
    bottleneck (~26 MB/s effective; BENCH_MEASURED round-5 pipeline rows)."""
    rng = np.random.default_rng(seed)
    produced = 0
    while steps is None or produced < steps:
        x = rng.normal(size=(batch, *shape)).astype(dtype)
        y = rng.integers(0, num_classes, size=(batch,)).astype(np.int32)
        produced += 1
        yield x, y
