"""Recovery supervisor: error taxonomy, retry policy, and circuit breakers.

PR 1 gave the stack fault *detection* (chaos sites, the request watchdog,
verified checkpoints); until now the only *responses* were "raise" and
"restart the whole loop from a checkpoint" (resilience.FaultTolerantLoop).
This module closes the loop from detection to proportionate response — a
four-rung escalation ladder:

1. **Classify** (:func:`classify`): every exception at an instrumented site
   maps to an :class:`ErrorClass` that selects the recovery policy. A
   transient ``OSError`` is not a corrupted codec is not a caller bug.
2. **Retry** (``MLSL_COMM_RETRIES`` / ``MLSL_COMM_RETRY_BACKOFF_S``):
   TRANSIENT failures of collective dispatch/wait retry in place with
   exponential backoff + jitter (:func:`jittered_backoff`) — the
   generalization of PR 1's checkpoint-save retry to ``comm/request.py``.
3. **Degrade** (:class:`CircuitBreaker`): PERSISTENT/CORRUPTION failures
   (and exhausted retries) count against a per-subsystem breaker. After
   ``MLSL_BREAKER_THRESHOLD`` classified failures inside a sliding
   ``MLSL_BREAKER_WINDOW_S`` window the breaker trips OPEN and the subsystem
   falls back to its always-correct path instead of dying: the quantized
   ring to the plain allreduce (error-feedback residual flushed), coalesced
   buckets to individual requests, a tuned algorithm to ``'lax'``, the trace
   exporter to a no-op. After ``MLSL_BREAKER_COOLDOWN_S`` the breaker goes
   HALF_OPEN and lets the healthy path probe; one success re-closes it, one
   failure re-opens.
4. **Restart** (resilience.FaultTolerantLoop): only what rungs 1-3 could not
   absorb reaches checkpoint recovery, bounded by ``MLSL_RESTART_BUDGET``
   across the run, and finally abort-with-flight-record.

Breakers are process-wide (like the chaos registry and the watchdog event
record): subsystem health must SURVIVE a FaultTolerantLoop teardown/rebuild
cycle, or a poisoned codec would re-trip identically after every recovery
and the ladder could never escalate past rung 4's first rung. Knobs are
(re)applied from :class:`mlsl_tpu.config.Config` at ``Environment.init``
via :func:`configure`; tests reset state with :func:`reset`.

Hot-path contract (mirrors ``chaos._plans`` / ``obs._tracer``): a closed
breaker's ``allow()`` is one lock-free attribute compare; uninstrumented
requests hold no breaker at all (``CommRequest._breaker is None``).
"""

from __future__ import annotations

import collections
import enum
import os
import random
import time
from typing import Deque, Dict, Optional

from mlsl_tpu.analysis import witness
from mlsl_tpu.log import (
    MLSLCorruptionError,
    MLSLDeviceLossError,
    MLSLError,
    MLSLTimeoutError,
    log_warning,
)


class ErrorClass(enum.Enum):
    """Recovery policy classes for the taxonomy table (rung 1)."""

    #: flaky IO / timing: retry in place with backoff (rung 2)
    TRANSIENT = "transient"
    #: data integrity (bitrot, codec round-trip mismatch): the producing
    #: subsystem is suspect — count against its breaker and degrade (rung 3)
    CORRUPTION = "corruption"
    #: dispatch/compile/device failure: breaker-countable, and recoverable by
    #: checkpoint restart when no breaker owns the site (rung 3 then 4)
    PERSISTENT = "persistent"
    #: capacity left the world (preemption, ICI neighbor loss, the chaos
    #: ``device.lost`` site): never retried in place, never breaker-absorbed
    #: — the device is *gone*, so a fallback dispatch on the same mesh only
    #: masks the loss. Routed to the elastic reshard rung
    #: (mlsl_tpu.elastic: re-derive the mesh among survivors, re-shard
    #: ZeRO-1 state live); checkpoint restart is the fallback when no
    #: coordinator is armed or the capacity budget is exhausted.
    DEVICE_LOSS = "device_loss"
    #: caller bugs and resource exhaustion: surface immediately — retrying a
    #: ValueError or degrading around a MemoryError only hides the real fault
    FATAL = "fatal"


# Ordered (exception type, class) table: first isinstance match wins, so
# subclasses must precede their bases (MLSLTimeoutError < MLSLError <
# RuntimeError; TimeoutError < OSError). MLSLTimeoutError is deliberately
# PERSISTENT, not TRANSIENT: the watchdog already waited out a full timeout
# budget — re-arming an identical wait would double the stall, so a wedged
# request escalates straight past the retry rung.
_TAXONOMY = (
    (MLSLCorruptionError, ErrorClass.CORRUPTION),
    (MLSLDeviceLossError, ErrorClass.DEVICE_LOSS),
    (MLSLTimeoutError, ErrorClass.PERSISTENT),
    (MLSLError, ErrorClass.PERSISTENT),
    (TimeoutError, ErrorClass.TRANSIENT),
    (ConnectionError, ErrorClass.TRANSIENT),
    (OSError, ErrorClass.TRANSIENT),
    (MemoryError, ErrorClass.FATAL),
    (ArithmeticError, ErrorClass.CORRUPTION),  # FloatingPointError etc.
    (RuntimeError, ErrorClass.PERSISTENT),     # XlaRuntimeError, ChaosError
)


def classify(exc: BaseException) -> ErrorClass:
    """Map an exception to its recovery policy class.

    Anything outside the table — ValueError, TypeError, KeyboardInterrupt,
    unknown library exceptions — is FATAL: the ladder only absorbs failure
    modes it understands."""
    for typ, cls in _TAXONOMY:
        if isinstance(exc, typ):
            return cls
    return ErrorClass.FATAL


# -- retry policy (rung 2) ----------------------------------------------------

# process-wide jitter source; seedable for reproducible soaks (shared with
# nothing else — chaos has its own RNG for fault scheduling)
_rng = random.Random()


def jittered_backoff(base_s: float, attempt: int,
                     rng: Optional[random.Random] = None) -> float:
    """Delay before retry ``attempt`` (0-based): ``base * 2**attempt``
    scaled by a uniform jitter in [0.5, 1.5) so a fleet of workers retrying
    the same transient fault does not re-collide in lockstep. Bounds are part
    of the contract (tests pin them): 0.5*base*2^a <= delay < 1.5*base*2^a."""
    r = rng if rng is not None else _rng
    return base_s * (2.0 ** attempt) * (0.5 + r.random())


# -- circuit breakers (rung 3) ------------------------------------------------

#: breaker states
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

#: the subsystems the ladder knows how to degrade (breakers are created on
#: demand, but status()/reset() always report the full set)
SUBSYSTEMS = ("quant", "bucket", "algo", "tracer")

# module defaults, overridden by configure() at Environment.init
_DEFAULT_THRESHOLD = int(os.environ.get("MLSL_BREAKER_THRESHOLD") or 3)
_DEFAULT_WINDOW_S = float(os.environ.get("MLSL_BREAKER_WINDOW_S") or 30.0)
_DEFAULT_COOLDOWN_S = float(os.environ.get("MLSL_BREAKER_COOLDOWN_S") or 10.0)


class CircuitBreaker:
    """closed -> open -> half_open -> closed, with a sliding failure window.

    - CLOSED: healthy. ``record_failure`` appends a timestamp; when
      ``threshold`` failures land inside the trailing ``window_s`` the
      breaker trips OPEN (the tripping call site degrades that very
      dispatch, so the Nth failure is served by the fallback, not raised).
    - OPEN: ``allow()`` is False — call sites skip the subsystem and run its
      degraded path. After ``cooldown_s`` the next ``allow()`` transitions
      to HALF_OPEN and returns True (the probe).
    - HALF_OPEN: the healthy path runs. One ``record_success`` re-closes
      (window cleared); one ``record_failure`` re-opens with a fresh
      cooldown.

    All transitions are recorded via core/stats.record_degrade (DEGRADE
    lines in mlsl_stats.log + breaker.* instants on the obs timeline).
    """

    def __init__(self, name: str, threshold: Optional[int] = None,
                 window_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None):
        self.name = name
        self.threshold = _DEFAULT_THRESHOLD if threshold is None else threshold
        self.window_s = _DEFAULT_WINDOW_S if window_s is None else window_s
        self.cooldown_s = (
            _DEFAULT_COOLDOWN_S if cooldown_s is None else cooldown_s
        )
        self._state = CLOSED
        self._failures: Deque[float] = collections.deque()
        self._opened_at = 0.0
        self._trips = 0
        self._last_error: Optional[str] = None
        self._lock = witness.named_lock(f"supervisor.breaker.{name}")

    # -- hot-path query ----------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def allow(self) -> bool:
        """May the healthy path run now? One attribute compare while CLOSED
        (the only state a healthy run ever sees); the OPEN->HALF_OPEN
        transition happens here, on the first call past the cooldown."""
        if self._state == CLOSED:
            return True
        with self._lock:
            if self._state == OPEN:
                if time.monotonic() - self._opened_at < self.cooldown_s:
                    return False
                self._state = HALF_OPEN
            # HALF_OPEN: let the probe(s) through; the first recorded
            # outcome decides (a multi-member bucket round is one probe)
        if self._state == HALF_OPEN:
            self._record("probe")
        return True

    # -- transitions -------------------------------------------------------

    def record_failure(self, error: Optional[BaseException] = None) -> bool:
        """One classified failure of the subsystem. Returns True when the
        breaker is OPEN afterwards (the call site should degrade)."""
        now = time.monotonic()
        with self._lock:
            if error is not None:
                self._last_error = f"{type(error).__name__}: {error}"
            if self._state == HALF_OPEN:
                # failed probe: straight back to OPEN, fresh cooldown
                self._state = OPEN
                self._opened_at = now
                self._trips += 1
                tripped = True
            else:
                self._failures.append(now)
                self._prune_locked(now)
                if self._state == CLOSED and len(self._failures) >= self.threshold:
                    self._state = OPEN
                    self._opened_at = now
                    self._trips += 1
                    tripped = True
                else:
                    tripped = False
            is_open = self._state == OPEN
        if tripped:
            self._record("trip")
        return is_open

    def record_success(self) -> None:
        """One healthy-path success. Meaningful in HALF_OPEN (closes the
        breaker); in CLOSED it is a no-op so call sites may report success
        unconditionally."""
        if self._state == CLOSED:
            return
        with self._lock:
            if self._state != HALF_OPEN:
                return  # OPEN: a stale success from before the trip
            self._state = CLOSED
            self._failures.clear()
        self._record("reset")

    def reset(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._failures.clear()
            self._opened_at = 0.0
            self._trips = 0
            self._last_error = None

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._failures and self._failures[0] < cutoff:
            self._failures.popleft()

    def _record(self, event: str) -> None:
        # lazy: core.stats imports jax; the breaker itself must stay
        # importable from anywhere in the stack
        from mlsl_tpu.core import stats as stats_mod

        stats_mod.record_degrade(self.name, event, detail=self._last_error or "")
        if event == "trip":
            log_warning(
                "circuit breaker %r tripped OPEN (%d failures in %.0fs "
                "window; cooldown %.1fs; last: %s): subsystem degrades to "
                "its fallback path",
                self.name, len(self._failures) or self.threshold,
                self.window_s, self.cooldown_s, self._last_error,
            )

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "failures_in_window": len(self._failures),
                "threshold": self.threshold,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                "trips": self._trips,
                "last_error": self._last_error,
            }


# -- registry ----------------------------------------------------------------

_breakers: Dict[str, CircuitBreaker] = {}
_registry_lock = witness.named_lock("supervisor.registry")


def breaker(name: str) -> CircuitBreaker:
    """The process-wide breaker for ``name`` (created on first use with the
    configured defaults)."""
    br = _breakers.get(name)
    if br is None:
        with _registry_lock:
            br = _breakers.get(name)
            if br is None:
                br = CircuitBreaker(name)
                _breakers[name] = br
    return br


def degraded(name: str) -> bool:
    """Is ``name`` currently running its fallback path? (False for a breaker
    that was never created — no failure ever recorded.)"""
    br = _breakers.get(name)
    return br is not None and br.state != CLOSED


def configure(config=None, threshold: Optional[int] = None,
              window_s: Optional[float] = None,
              cooldown_s: Optional[float] = None) -> None:
    """(Re)apply breaker knobs — from a Config (Environment.init) or
    explicitly (tests). Existing breakers keep their STATE (health survives
    an Environment rebuild) but adopt the new thresholds."""
    global _DEFAULT_THRESHOLD, _DEFAULT_WINDOW_S, _DEFAULT_COOLDOWN_S
    if config is not None:
        threshold = getattr(config, "breaker_threshold", threshold)
        window_s = getattr(config, "breaker_window_s", window_s)
        cooldown_s = getattr(config, "breaker_cooldown_s", cooldown_s)
    if threshold is not None:
        _DEFAULT_THRESHOLD = int(threshold)
    if window_s is not None:
        _DEFAULT_WINDOW_S = float(window_s)
    if cooldown_s is not None:
        _DEFAULT_COOLDOWN_S = float(cooldown_s)
    with _registry_lock:
        for br in _breakers.values():
            if threshold is not None:
                br.threshold = int(threshold)
            if window_s is not None:
                br.window_s = float(window_s)
            if cooldown_s is not None:
                br.cooldown_s = float(cooldown_s)


def status() -> Dict[str, dict]:
    """Per-subsystem breaker status (subsystems never touched report a
    virgin closed breaker), plus the integrity sentinel's state — surfaced
    by FaultTolerantLoop's abort log and importable for dashboards."""
    out = {}
    for name in sorted(set(SUBSYSTEMS) | set(_breakers)):
        br = _breakers.get(name)
        out[name] = br.status() if br is not None else {
            "state": CLOSED, "failures_in_window": 0, "trips": 0,
        }
    # lazy: sentinel sits above the comm stack (imports jax/stats); the
    # breaker machinery must stay importable from anywhere below it
    from mlsl_tpu import sentinel as _sentinel

    out["sentinel"] = _sentinel.status()
    # static-analysis verdicts (mlsl_tpu.analysis): the last MLSL_VERIFY
    # plan verdict and lint run, so dashboards see whether the committed
    # plan passed verification (lazy + dependency-light for the same
    # reason as the sentinel)
    from mlsl_tpu.analysis import diagnostics as _analysis

    out["analysis"] = _analysis.status()
    # elastic-mesh state (mlsl_tpu.elastic): active vs full world size,
    # capacity budget remaining, and the last reshard verdict — the
    # "capacity budget" half of the ladder's last rung (lazy for the same
    # reason as the sentinel: elastic sits above the comm stack)
    from mlsl_tpu import elastic as _elastic

    out["elastic"] = _elastic.status()
    # telemetry plane (mlsl_tpu.obs): the straggler sentinel's skew verdicts
    # and the metrics registry summary — this dict IS the /healthz body
    # (obs/serve.py), so everything here must stay JSON-serializable
    # (round-trip pinned by tests/test_metrics.py)
    from mlsl_tpu.obs import metrics as _metrics
    from mlsl_tpu.obs import straggler as _straggler

    out["straggler"] = _straggler.status()
    out["metrics"] = _metrics.status()
    # pod control plane (mlsl_tpu.control): membership epoch, leadership,
    # survivor set and heartbeat ages — {"state": "off"} when this process
    # is not a pod member. Same JSON-serializability contract as above:
    # this dict rides heartbeat frames AND the /healthz body.
    from mlsl_tpu import control as _control

    out["control"] = _control.status()
    # serving engine (mlsl_tpu.serve): the SLA governor's ladder rung, queue
    # pressure, and shed counts — {"state": "off"} when no engine is live.
    # Same JSON-serializability contract: this dict IS the /healthz body.
    from mlsl_tpu import serve as _serve

    out["serve"] = _serve.status()
    # codec lab (mlsl_tpu.codecs): registered codecs, the guardrail's
    # breach streak and guarded sets, per-codec wire bytes, and the
    # demotion attribution trail — same JSON-serializability contract.
    from mlsl_tpu import codecs as _codecs

    out["codecs"] = _codecs.status()
    return out


def reset() -> None:
    """Close every breaker and clear its history (tests; a production run
    never resets — health carries across recovery cycles by design)."""
    with _registry_lock:
        for br in _breakers.values():
            br.reset()
